"""Core telemetry instrument: hierarchical spans + counters/gauges/histograms.

Design points:

* **Ambient activation.**  ``with Telemetry(...)`` installs the instance as
  the process-wide active instrument; ``current()`` returns it (or None)
  from any depth of the stack.  Deep call sites — the kmeans kernel wrapper,
  the ``StageTimer`` shim — instrument themselves against ``current()`` so
  no handle threads through every layer, and an inactive process pays one
  ``is None`` check.
* **Hierarchical spans.**  Each thread carries its own span stack
  (``threading.local``), so a producer thread's spans nest under its own
  root rather than corrupting the main thread's tree.  Durations use
  ``time.perf_counter`` (monotonic); wall timestamps use ``time.time``.
  Span events are emitted on *exit* (children before parents in the
  stream); the ``id``/``parent`` fields let readers rebuild the tree.
* **In-memory aggregates.**  Counters/gauges/histograms also accumulate on
  the instance, so in-process consumers (tests, the pipeline summary)
  read final values without re-parsing the stream.
* **Recompile detector.**  ``record_kernel_call(kernel, signature)`` keeps
  one *process-level* set of seen abstract signatures per kernel — the
  same lifetime as jax's compilation caches — and bumps
  ``jit.recompiles.<kernel>`` only on a first-seen signature, so a
  repeated same-shape call counts zero and a shape change counts one.
"""

from __future__ import annotations

import os
import sys
import threading
import time

from .sink import JsonlSink

__all__ = ["Telemetry", "Span", "current", "run_metadata",
           "HIST_BUCKETS", "bucket_counts"]

#: Log-spaced bucket ladder shared by every ``histogram_bulk`` producer
#: and consumer: ``10^(k/4)`` for k in -32..40 (1e-8 .. 1e10, ~78% step).
#: Fixed — not per-histogram — so counts from any stream merge by upper
#: bound, and the Prometheus export is a stable cumulative histogram.
#: Values above the top bucket land in +Inf; values <= the bottom bound
#: (including 0 and negatives) land in the first bucket.
HIST_BUCKETS: tuple[float, ...] = tuple(10.0 ** (k / 4.0)
                                        for k in range(-32, 41))

#: Raw per-key sample cap of ``Telemetry.histogram``: past it the list is
#: decimated 2:1 (uniform stride), keeping p50/p95 digests stable while
#: bounding memory — the scalability trap ``histogram_bulk`` exists to
#: avoid entirely on high-volume paths.
HIST_RAW_CAP = 8192

#: Bucketing cap of one ``histogram_bulk`` call: past it the buckets are
#: computed on a uniform 1-in-stride subsample and the counts scaled
#: back by the stride, so the per-call cost is O(cap) no matter how many
#: samples a window produces (a million routed reads cost the same as
#: 32k).  Percentile error from a 32k uniform subsample is far below the
#: ladder's own ~78% bucket resolution; min/max stay exact.
HIST_BULK_SAMPLE_CAP = 32768


def bucket_counts(values) -> "tuple[list, int, float, float, float]":
    """(sparse ``[le, count]`` pairs, count, sum, min, max) of ``values``
    on the ``HIST_BUCKETS`` ladder; the overflow bucket's ``le`` is the
    JSON-safe string ``"+Inf"``.  ``count`` is always the EXACT sample
    count (it must reconcile with exact counters like
    ``serve.reads_routed``); above ``HIST_BULK_SAMPLE_CAP`` samples the
    per-bucket split comes from a uniform subsample scaled back up with
    largest-remainder rounding, so ``sum(bucket counts) == count`` still
    holds exactly.  ``sum`` scales with the subsample; min/max are
    exact."""
    import numpy as np

    v = np.asarray(values, dtype=np.float64).ravel()
    n = int(v.size)
    if n == 0:
        return [], 0, 0.0, 0.0, 0.0
    vmin, vmax = float(v.min()), float(v.max())
    sub = v
    if n > HIST_BULK_SAMPLE_CAP:
        stride = -(-n // HIST_BULK_SAMPLE_CAP)  # ceil div
        sub = v[::stride]
    ladder = np.asarray(HIST_BUCKETS)
    idx = np.searchsorted(ladder, sub, side="left")
    counts = np.bincount(idx, minlength=len(HIST_BUCKETS) + 1)
    total = float(n) / float(sub.size)
    if sub.size != n:
        # Scale the subsample split to the exact n: floor, then hand the
        # leftover units to the largest fractional remainders
        # (deterministic tie-break by bucket index via argsort kind).
        scaled = counts * total
        floors = np.floor(scaled).astype(np.int64)
        short = n - int(floors.sum())
        if short > 0:
            order = np.argsort(-(scaled - floors), kind="stable")[:short]
            floors[order] += 1
        counts = floors
    sparse: list = []
    for i in np.flatnonzero(counts):
        le = "+Inf" if i == len(HIST_BUCKETS) else float(ladder[i])
        sparse.append([le, int(counts[i])])
    return (sparse, n, float(sub.sum()) * total, vmin, vmax)

#: Active instrument (module-global, not a contextvar: worker threads must
#: see the same instrument as the thread that activated it).
_ACTIVE: list["Telemetry"] = []

#: Process-level seen-signature registry per wrapped kernel.  Lives at
#: module scope — not per-Telemetry — because it mirrors the lifetime of
#: the process's actual compilation caches (ops/kmeans_jax._build_kmeans
#: is ``lru_cache``d for the life of the process).
_KERNEL_SIGS: dict[str, set] = {}


def current() -> "Telemetry | None":
    """The active instrument, or None when telemetry is off."""
    return _ACTIVE[-1] if _ACTIVE else None


def run_metadata() -> dict:
    """Environment stamp making emitted artifacts comparable across
    machines: interpreter, numpy, and — when jax is already loaded —
    jax version, backend, device count, and the x64 flag.  Never *imports*
    jax itself: a numpy-backend run must not pay (or fail) the import."""
    meta: dict = {
        "python": sys.version.split()[0],
        "platform": sys.platform,
    }
    np = sys.modules.get("numpy")
    if np is not None:
        meta["numpy"] = getattr(np, "__version__", None)
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            devices = jax.devices()
            meta.update({
                "jax": jax.__version__,
                "jax_backend": jax.default_backend(),
                "jax_device_count": len(devices),
                "jax_device_kind": devices[0].device_kind if devices
                else None,
                "jax_enable_x64": bool(jax.config.jax_enable_x64),
            })
        except Exception:  # pragma: no cover - partially initialized jax
            meta["jax"] = getattr(jax, "__version__", None)
    return meta


class Span:
    """One timed region; context manager handed out by ``Telemetry.span``."""

    __slots__ = ("tel", "name", "attrs", "id", "parent", "t_wall", "_t0",
                 "dur")

    def __init__(self, tel: "Telemetry", name: str, attrs: dict):
        self.tel = tel
        self.name = name
        self.attrs = attrs
        self.id = tel._next_id()
        self.parent: int | None = None
        self.dur = 0.0

    def __enter__(self) -> "Span":
        stack = self.tel._stack()
        self.parent = stack[-1].id if stack else None
        stack.append(self)
        self.t_wall = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.dur = time.perf_counter() - self._t0
        stack = self.tel._stack()
        if stack and stack[-1] is self:
            stack.pop()
        event = {
            "kind": "span",
            "name": self.name,
            "id": self.id,
            "parent": self.parent,
            "t": self.t_wall,
            "dur": self.dur,
        }
        if self.attrs:
            event["attrs"] = self.attrs
        self.tel._emit(event)
        if self.tel.device_memory:
            from .jaxtools import device_memory_gauges

            device_memory_gauges(self.tel, stage=self.name)


class Telemetry:
    """The instrument.  ``with Telemetry(sink=JsonlSink(path)):`` activates
    it; everything instrumented against ``obs.current()`` then emits."""

    def __init__(self, sink: JsonlSink | None = None, *,
                 kmeans_trace: bool = True, device_memory: bool = False,
                 xprof: bool = True, audit: bool = True,
                 meta: bool = True):
        self.sink = sink
        #: Unique per-instrument id stamped on every event: span ids and
        #: trace-call numbers restart per process, and the sink appends —
        #: readers disambiguate runs sharing one file by this field.
        self.run_id = f"{os.getpid():x}-{time.monotonic_ns():x}"
        #: Emit per-Lloyd-iteration convergence records from the kmeans
        #: kernels (ops/kmeans_jax.py carries them in the while_loop state;
        #: ops/kmeans_np.py computes them inline).
        self.kmeans_trace = kmeans_trace
        #: Sample jax.local_devices() memory_stats at every span exit.
        self.device_memory = device_memory
        #: Capture XLA cost/memory analysis + compile wall-clock per kernel
        #: signature (obs/xprof.py) at the wrapped kernel entry points.
        self.xprof = xprof
        #: Emit per-window decision-quality audit events from the online
        #: controller (obs/audit.py wired in control/controller.py).
        self.audit = audit
        self._meta = meta
        self.counters: dict[str, float] = {}
        self.gauges: dict[str, float] = {}
        self.histograms: dict[str, list[float]] = {}
        #: Decimation stride per raw-histogram key (HIST_RAW_CAP contract):
        #: sample i is retained iff i % stride == 0; doubling the stride
        #: halves the kept list, so percentiles stay a uniform subsample.
        self._hist_stride: dict[str, int] = {}
        self._hist_seen: dict[str, int] = {}
        #: Bucketed aggregates from ``histogram_bulk``:
        #: name -> {"count", "sum", "min", "max", "buckets": {le: count}}.
        self.hist_buckets: dict[str, dict] = {}
        self._local = threading.local()
        self._id_lock = threading.Lock()
        self._ids = 0
        self._agg_lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------
    def __enter__(self) -> "Telemetry":
        _ACTIVE.append(self)
        if self._meta:
            self._emit({"kind": "meta", "t": time.time(),
                        "run": run_metadata()})
        return self

    def __exit__(self, *exc) -> None:
        if self in _ACTIVE:
            _ACTIVE.remove(self)
        if self._meta:
            # Second stamp at exit: activation happens before the command
            # imports jax, so the entry stamp lacks the jax fields
            # (backend, device kind — what the roofline peak lookup needs).
            # Readers take the LAST meta event; a killed run keeps the
            # entry stamp.
            self._emit({"kind": "meta", "t": time.time(),
                        "run": run_metadata()})
        if self.sink is not None:
            self.sink.close()

    # -- plumbing ----------------------------------------------------------
    def _stack(self) -> list[Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _next_id(self) -> int:
        with self._id_lock:
            self._ids += 1
            return self._ids

    def _emit(self, event: dict) -> None:
        if self.sink is not None:
            event.setdefault("run", self.run_id)
            self.sink.emit(event)

    def current_span_id(self) -> int | None:
        stack = self._stack()
        return stack[-1].id if stack else None

    # -- instruments -------------------------------------------------------
    def span(self, name: str, **attrs) -> Span:
        return Span(self, name, attrs)

    def emit_span(self, name: str, dur: float, *, parent: int | None
                  = None, **attrs) -> int:
        """Emit one ALREADY-MEASURED region as a span event (same schema
        as :class:`Span`, same id allocator, same sink) and return its
        id.  The decision tracer uses this to land retrospective spans —
        a stage whose duration was measured elsewhere (the controller's
        per-stage clock, the daemon's reconciled segments) still joins
        the span forest under the caller's chosen parent (default: the
        currently open span)."""
        sid = self._next_id()
        if parent is None:
            parent = self.current_span_id()
        event = {
            "kind": "span",
            "name": name,
            "id": sid,
            "parent": parent,
            "t": time.time(),
            "dur": float(dur),
        }
        if attrs:
            event["attrs"] = attrs
        self._emit(event)
        return sid

    def counter_inc(self, name: str, delta: float = 1.0) -> float:
        with self._agg_lock:
            value = self.counters.get(name, 0.0) + float(delta)
            self.counters[name] = value
        self._emit({"kind": "counter", "name": name, "t": time.time(),
                    "delta": float(delta), "value": value})
        return value

    def gauge(self, name: str, value: float) -> None:
        with self._agg_lock:
            self.gauges[name] = float(value)
        self._emit({"kind": "gauge", "name": name, "t": time.time(),
                    "value": float(value)})

    def histogram(self, name: str, value: float) -> None:
        """One sample: emits a ``hist`` event and keeps a BOUNDED raw list
        (uniform 2:1 decimation past ``HIST_RAW_CAP`` — p50/p95 of a
        uniform subsample track the full stream).  High-volume producers
        (thousands of samples per call) should use ``histogram_bulk``:
        one bucketed event instead of one per sample."""
        with self._agg_lock:
            lst = self.histograms.setdefault(name, [])
            seen = self._hist_seen.get(name, 0)
            stride = self._hist_stride.get(name, 1)
            if seen % stride == 0:
                lst.append(float(value))
                if len(lst) >= HIST_RAW_CAP:
                    del lst[1::2]
                    self._hist_stride[name] = stride * 2
            self._hist_seen[name] = seen + 1
        self._emit({"kind": "hist", "name": name, "t": time.time(),
                    "value": float(value)})

    def histogram_bulk(self, name: str, values) -> None:
        """A batch of samples as ONE event: counts on the fixed log-spaced
        ``HIST_BUCKETS`` ladder plus count/sum/min/max, emitted as a
        single ``hist_bulk`` line and merged into the in-memory
        ``hist_buckets`` aggregate.  The serving layer's per-window
        latency samples (potentially millions) ride this path — per-key
        memory and stream volume stay O(buckets), not O(samples)."""
        sparse, n, total, vmin, vmax = bucket_counts(values)
        if n == 0:
            return
        with self._agg_lock:
            agg = self.hist_buckets.setdefault(
                name, {"count": 0, "sum": 0.0, "min": vmin, "max": vmax,
                       "buckets": {}})
            agg["count"] += n
            agg["sum"] += total
            agg["min"] = min(agg["min"], vmin)
            agg["max"] = max(agg["max"], vmax)
            for le, c in sparse:
                key = float("inf") if le == "+Inf" else float(le)
                agg["buckets"][key] = agg["buckets"].get(key, 0) + c
        self._emit({"kind": "hist_bulk", "name": name, "t": time.time(),
                    "count": n, "sum": total, "min": vmin, "max": vmax,
                    "buckets": sparse})

    # -- jax kernel hooks --------------------------------------------------
    def record_kernel_call(self, kernel: str, signature,
                           compiled: bool | None = None) -> bool:
        """Count a wrapped-kernel call and its recompiles.

        ``compiled`` is the wrapper's authoritative signal (e.g. an
        lru_cache miss delta around the program build — exact even when
        the kernel was compiled before telemetry activated).  When the
        wrapper has no such signal, a first-seen abstract-aval
        ``signature`` (shape/dtype/static-config tuple) in this process
        stands in.  Returns True when the call is counted as compiling."""
        seen = _KERNEL_SIGS.setdefault(kernel, set())
        new = signature not in seen
        if new:
            seen.add(signature)
        if compiled is not None:
            new = compiled
        self.counter_inc(f"jit.calls.{kernel}")
        if new:
            self.counter_inc(f"jit.recompiles.{kernel}")
        return new

    def emit_kmeans_trace(self, kernel: str, *, inertia, shift,
                          **attrs) -> None:
        """Per-Lloyd-iteration convergence records (one event per step),
        plus the ``kmeans.iterations`` histogram for p50/p95 over calls."""
        call = int(self.counter_inc("kmeans.trace_calls"))
        span = self.current_span_id()
        n_iter = len(shift)
        for i in range(n_iter):
            self._emit({
                "kind": "kmeans_iter", "kernel": kernel, "call": call,
                "span": span, "step": i,
                "inertia": None if inertia is None else float(inertia[i]),
                "shift": float(shift[i]),
                **attrs,
            })
        self.histogram("kmeans.iterations", float(n_iter))
