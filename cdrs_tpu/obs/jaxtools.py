"""jax-facing telemetry helpers: aval signatures + device memory gauges.

Kept apart from telemetry.py so the core instrument never imports jax (a
base numpy-only install can produce and read telemetry).
"""

from __future__ import annotations

__all__ = ["aval_signature", "device_memory_gauges"]


def aval_signature(*arrays, static=()) -> tuple:
    """Hashable signature of a call's abstract values: (shape, dtype) per
    array plus the static-argument tuple — the same information jax keys
    its compilation caches on, so a first-seen signature marks a compile.
    Accepts numpy arrays, jax arrays, and tracers alike (anything with
    ``.shape``/``.dtype``)."""
    parts = []
    for a in arrays:
        shape = tuple(getattr(a, "shape", ()))
        dtype = str(getattr(a, "dtype", type(a).__name__))
        parts.append((shape, dtype))
    return tuple(parts) + (tuple(static),)


def device_memory_gauges(tel, stage: str | None = None) -> None:
    """Gauge ``device.mem.bytes_in_use{.<stage>}`` per local device.

    ``memory_stats()`` is backend-dependent (None on CPU, populated on
    TPU); absent stats emit nothing — the gauges are strictly additive
    information, never a failure source."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:  # pragma: no cover - no jax / no backend
        return
    suffix = f".{stage}" if stage else ""
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:  # pragma: no cover - backend without the API
            continue
        if not stats:
            continue
        in_use = stats.get("bytes_in_use")
        if in_use is not None:
            tel.gauge(f"device.mem.bytes_in_use.d{d.id}{suffix}",
                      float(in_use))
        peak = stats.get("peak_bytes_in_use")
        if peak is not None:
            tel.gauge(f"device.mem.peak_bytes_in_use.d{d.id}{suffix}",
                      float(peak))
