"""Prometheus text-format rendering — ONE renderer for every surface.

The exposition logic used to live inside ``metrics_cli`` (the
``cdrs metrics export --format prometheus`` textfile path).  The live
operational plane (obs/httpz.py: the daemon's in-process ``/metrics``
endpoint) must emit the SAME format with the SAME name sanitization and
the SAME type/sample line shapes, so the renderer moved here and both
surfaces consume it — the textfile export is now a thin wrapper
(``metrics_cli`` re-exports :func:`prometheus_lines` unchanged, golden-
tested byte-for-byte in tests/test_httpz.py).

Every exposition additionally carries two meta series
(:func:`meta_lines`):

* ``cdrs_process_start_time_seconds`` — the standard Prometheus
  process-start gauge.  The repo's counters are process-lifetime
  cumulative and **reset on daemon restart/resume** (a resumed daemon's
  ``windows_processed`` restarts at zero even though ``epoch_id``
  continues); ``rate()``/``increase()`` handle that reset correctly
  *only* when the scraper can see the restart, which is exactly what
  this gauge publishes.  Documented in ARCHITECTURE "Live operational
  plane".
* ``cdrs_build_info`` — the conventional constant-``1`` info gauge
  (version label), so dashboards can join metrics to the code that
  produced them.

:func:`lint` is the promtool-style format check CI and the tests run
against live scrapes: TYPE-before-samples, valid metric/label syntax,
parseable values, no duplicate TYPE declarations.
"""

from __future__ import annotations

import re
import time

from .aggregate import final_counters, merge_hist_buckets, percentile

__all__ = ["prom_name", "prometheus_lines", "meta_lines", "lint",
           "counter_lines", "gauge_lines", "summary_lines",
           "histogram_lines", "alerts_lines", "PROCESS_START_TIME"]

#: Wall-clock (unix) seconds this process started observing — stamped at
#: first import of the telemetry layer, which every producing surface
#: (daemon, CLI exporter) does during startup.  The honest value for
#: ``cdrs_process_start_time_seconds`` at exposition resolution.
PROCESS_START_TIME = time.time()

_VERSION = None


def _build_version() -> str:
    global _VERSION
    if _VERSION is None:
        try:
            from importlib.metadata import version

            _VERSION = version("cdrs-tpu")
        except Exception:
            _VERSION = "unknown"
    return _VERSION


def prom_name(name: str, prefix: str = "cdrs_") -> str:
    """Sanitize an event name into a valid Prometheus metric name.

    Valid names match ``[a-zA-Z_:][a-zA-Z0-9_:]*``: every other character
    maps to ``_``, and a digit-leading result is escaped with ``_`` so the
    name stays valid even with an empty prefix (exporters that strip or
    configure away the ``cdrs_`` namespace)."""
    s = re.sub(r"[^a-zA-Z0-9_]", "_", name)
    full = prefix + s
    if full and full[0].isdigit():
        full = "_" + full
    return full


def _labels(labels: dict | None) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels.items())
    return "{" + inner + "}"


# -- primitive renderers (shared by every surface) ---------------------------


def counter_lines(name: str, value: float,
                  labels: dict | None = None) -> list[str]:
    m = prom_name(name)
    return [f"# TYPE {m} counter", f"{m}{_labels(labels)} {value:g}"]


def gauge_lines(name: str, value: float,
                labels: dict | None = None) -> list[str]:
    m = prom_name(name)
    return [f"# TYPE {m} gauge", f"{m}{_labels(labels)} {value:g}"]


def summary_lines(name: str, values: list[float]) -> list[str]:
    """Prometheus summary over raw samples: the textfile export's p50/p95
    quantile convention, shared verbatim by the live endpoint."""
    m = prom_name(name)
    return [
        f"# TYPE {m} summary",
        f'{m}{{quantile="0.5"}} {percentile(values, 0.5):g}',
        f'{m}{{quantile="0.95"}} {percentile(values, 0.95):g}',
        f"{m}_sum {sum(values):g}",
        f"{m}_count {len(values)}",
    ]


def histogram_lines(name: str, agg: dict) -> list[str]:
    """Native Prometheus histogram from a merged ``hist_bulk`` aggregate
    (cumulative le buckets over the fixed ladder, closed by +Inf)."""
    m = prom_name(name)
    lines = [f"# TYPE {m} histogram"]
    cum = 0
    for le in sorted(k for k in agg["buckets"] if k != float("inf")):
        cum += agg["buckets"][le]
        lines.append(f'{m}_bucket{{le="{le:g}"}} {cum}')
    lines += [
        f'{m}_bucket{{le="+Inf"}} {agg["count"]}',
        f"{m}_sum {agg['sum']:g}",
        f"{m}_count {agg['count']}",
    ]
    return lines


def alerts_lines(firing: list[dict]) -> list[str]:
    """Prometheus-convention ``ALERTS`` gauges (what Alertmanager-side
    rules export): one series per currently-firing alert.  ``firing``
    rows need ``name`` and ``severity`` (the alert-engine result /
    transition shape)."""
    if not firing:
        return []
    lines = ["# TYPE ALERTS gauge"]
    for r in firing:
        lines.append(
            f'ALERTS{{alertname="{r["name"]}",'
            f'alertstate="firing",'
            f'severity="{r["severity"]}"}} 1')
    return lines


def meta_lines(start_time: float | None = None,
               version: str | None = None) -> list[str]:
    """The two meta series every exposition carries (module docstring:
    restart visibility for ``rate()`` + build provenance).  ``start_time``
    defaults to this process's observed start; tests inject a fixed value
    for byte-stable assertions."""
    st = PROCESS_START_TIME if start_time is None else float(start_time)
    ver = _build_version() if version is None else version
    return [
        "# TYPE cdrs_process_start_time_seconds gauge",
        f"cdrs_process_start_time_seconds {st:.3f}",
        "# TYPE cdrs_build_info gauge",
        f'cdrs_build_info{{version="{ver}"}} 1',
    ]


# -- the stream renderer (the historical textfile exposition) ----------------


def prometheus_lines(events: list[dict]) -> list[str]:
    """Prometheus textfile exposition of the stream's final aggregates.

    Byte-for-byte the exposition ``cdrs metrics export`` has always
    produced (golden-tested); surfaces append :func:`meta_lines` on top."""
    lines: list[str] = []
    counters = final_counters(events)
    gauges: dict[str, float] = {}
    hists: dict[str, list[float]] = {}
    bulk: dict[str, dict] = {}
    for e in events:
        kind = e.get("kind")
        if kind == "gauge":
            gauges[e["name"]] = e["value"]
        elif kind == "hist":
            hists.setdefault(e["name"], []).append(float(e["value"]))
        elif kind == "hist_bulk":
            merge_hist_buckets(bulk.setdefault(e["name"], {}), e)
        elif kind == "span":
            hists.setdefault(f"span.{e['name']}.seconds", []).append(
                float(e.get("dur", 0.0)))
    for name in sorted(counters):
        lines += counter_lines(name, counters[name])
    for name in sorted(gauges):
        lines += gauge_lines(name, gauges[name])
    for name in sorted(hists):
        lines += summary_lines(name, hists[name])
    for name in sorted(bulk):
        lines += histogram_lines(name, bulk[name])
    from .aggregate import dedup_windows
    from .alerts import evaluate_records

    windows = dedup_windows(events)
    if windows:
        firing = [r for r in evaluate_records(windows) if r["firing"]]
        lines += alerts_lines(firing)
    return lines


# -- format lint (promtool-style) --------------------------------------------

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?P<labels>\{[^{}]*\})?"
    r" (?P<value>\S+)$")
_LABEL_RE = re.compile(
    r'^[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"$')


def _base_name(name: str) -> str:
    """A sample's family name: summary/histogram component suffixes map
    back to the declared metric."""
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix):
            return name[: -len(suffix)]
    return name


def lint(text: str) -> list[str]:
    """Promtool-style format check of one exposition.

    Returns a list of error strings (empty = clean): every sample line
    must parse (name, optional well-formed labels, float value), every
    sample's family must have a TYPE declared BEFORE it, no family may
    declare TYPE twice, and the exposition must end with a newline.
    """
    errors: list[str] = []
    if text and not text.endswith("\n"):
        errors.append("exposition must end with a newline")
    typed: dict[str, str] = {}
    for i, line in enumerate(text.splitlines(), start=1):
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 2 and parts[1] == "TYPE":
                if len(parts) != 4:
                    errors.append(f"line {i}: malformed TYPE comment")
                    continue
                _, _, name, mtype = parts
                if not _NAME_RE.match(name):
                    errors.append(f"line {i}: invalid metric name "
                                  f"{name!r}")
                if mtype not in ("counter", "gauge", "summary",
                                 "histogram", "untyped"):
                    errors.append(f"line {i}: unknown type {mtype!r}")
                if name in typed:
                    errors.append(f"line {i}: duplicate TYPE for {name}")
                typed[name] = mtype
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            errors.append(f"line {i}: unparseable sample {line!r}")
            continue
        labels = m.group("labels")
        if labels:
            for pair in _split_labels(labels[1:-1]):
                if pair and not _LABEL_RE.match(pair):
                    errors.append(f"line {i}: bad label {pair!r}")
        try:
            float(m.group("value"))
        except ValueError:
            errors.append(f"line {i}: non-numeric value "
                          f"{m.group('value')!r}")
        fam = _base_name(m.group("name"))
        if fam not in typed and m.group("name") not in typed:
            errors.append(f"line {i}: sample {m.group('name')} has no "
                          f"preceding TYPE")
    return errors


def _split_labels(inner: str) -> list[str]:
    """Split ``k="v",k2="v2"`` on commas outside quotes."""
    out, buf, in_q, esc = [], [], False, False
    for ch in inner:
        if esc:
            buf.append(ch)
            esc = False
            continue
        if ch == "\\":
            buf.append(ch)
            esc = True
            continue
        if ch == '"':
            in_q = not in_q
        if ch == "," and not in_q:
            out.append("".join(buf))
            buf = []
        else:
            buf.append(ch)
    if buf:
        out.append("".join(buf))
    return out
