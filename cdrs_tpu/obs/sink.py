"""JSONL event sink — thread-safe, line-buffered, append-only.

The contract the controller's kill/resume semantics need (control/
controller.py): the file is opened in append mode, every event is exactly
one line written with a single ``write()`` call under a lock and flushed
immediately, and a crashed writer leaves at worst a repeated tail —
consumers take the last record per logical key (e.g. window index).  A
torn final line (the process died mid-``write``) is skipped by
``read_events`` rather than poisoning the stream.
"""

from __future__ import annotations

import json
import os
import threading

__all__ = ["JsonlSink", "read_events"]


class JsonlSink:
    """Append one JSON object per line; safe to share across threads."""

    def __init__(self, path: str):
        self.path = path
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._lock = threading.Lock()
        self._f = open(path, "a")

    def emit(self, event: dict) -> None:
        # One write() + flush per event: the line lands atomically from the
        # point of view of a tailing reader, and a kill between events loses
        # nothing already emitted.
        line = json.dumps(event, default=_coerce) + "\n"
        with self._lock:
            if self._f is None:
                return  # emitted after close (e.g. a late worker thread)
            self._f.write(line)
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    def __enter__(self) -> "JsonlSink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _coerce(obj):
    """JSON fallback for numpy scalars/arrays without importing numpy.

    ``tolist`` first: arrays need it, and on numpy scalars it returns the
    python scalar (``item`` would raise on a size > 1 array)."""
    fn = getattr(obj, "tolist", None)
    if callable(fn):
        return fn()
    return str(obj)


def read_events(path: str) -> list[dict]:
    """Parse a telemetry JSONL stream; a torn final line is skipped."""
    events: list[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                # Torn tail from a killed writer — by the sink's contract
                # only the final line can be affected.
                continue
    return events
