"""JSONL event sink — thread-safe, line-buffered, append-only.

The contract the controller's kill/resume semantics need (control/
controller.py): the file is opened in append mode, every event is exactly
one line written with a single ``write()`` call under a lock and flushed
immediately, and a crashed writer leaves at worst a repeated tail —
consumers take the last record per logical key (e.g. window index).  A
torn final line (the process died mid-``write``) is skipped by
``read_events`` rather than poisoning the stream.

**Rotation (opt-in).**  ``JsonlSink(path, max_bytes=N)`` caps the live
file: when the next line would push it past ``max_bytes`` the file
rotates shift-style (``path`` -> ``path.1``, ``path.1`` -> ``path.2``,
...; larger suffix = older), so a 100M-file controller soak cannot grow
one unbounded file.  A line is never split across files, and a single
line larger than ``max_bytes`` still lands whole.  ``read_events`` and
``iter_events`` read the rotated set oldest-first, so consumers see ONE
logically contiguous stream; ``iter_events(follow=True)`` additionally
drains the just-rotated ``path.1`` tail when a rotation lands between
polls (best-effort: more than one rotation inside a single poll interval
can skip the middle file — size the cap so a poll interval spans far
less than one file's worth of events).
"""

from __future__ import annotations

import json
import os
import threading

__all__ = ["JsonlSink", "read_events", "iter_events", "rotated_paths"]


class JsonlSink:
    """Append one JSON object per line; safe to share across threads."""

    def __init__(self, path: str, max_bytes: int | None = None):
        self.path = path
        if max_bytes is not None and max_bytes <= 0:
            raise ValueError(f"max_bytes must be > 0, got {max_bytes}")
        self.max_bytes = max_bytes
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._lock = threading.Lock()
        # Explicit encoding: telemetry must round-trip identically across
        # platform default encodings (read_events/iter_events match).
        self._f = open(path, "a", encoding="utf-8")
        self._size = self._f.tell()

    def _rotate(self) -> None:
        """Shift-rotate under the held lock: close, bump every existing
        suffix up by one (highest first), move the live file to ``.1``,
        reopen fresh."""
        self._f.close()
        n = 1
        while os.path.exists(f"{self.path}.{n}"):
            n += 1
        for i in range(n - 1, 0, -1):
            os.replace(f"{self.path}.{i}", f"{self.path}.{i + 1}")
        os.replace(self.path, f"{self.path}.1")
        self._f = open(self.path, "a", encoding="utf-8")
        self._size = 0

    def emit(self, event: dict) -> None:
        # One write() + flush per event: the line lands atomically from the
        # point of view of a tailing reader, and a kill between events loses
        # nothing already emitted.
        # ensure_ascii=False writes real UTF-8 (the file's pinned encoding)
        # instead of \uXXXX escapes — half the bytes on non-ASCII names.
        line = json.dumps(event, default=_coerce, ensure_ascii=False) + "\n"
        with self._lock:
            if self._f is None:
                return  # emitted after close (e.g. a late worker thread)
            if (self.max_bytes is not None and self._size > 0
                    and self._size + len(line.encode("utf-8"))
                    > self.max_bytes):
                self._rotate()
            self._f.write(line)
            self._f.flush()
            self._size += len(line.encode("utf-8"))

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    def __enter__(self) -> "JsonlSink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _coerce(obj):
    """JSON fallback for numpy scalars/arrays without importing numpy.

    ``tolist`` first: arrays need it, and on numpy scalars it returns the
    python scalar (``item`` would raise on a size > 1 array)."""
    fn = getattr(obj, "tolist", None)
    if callable(fn):
        return fn()
    return str(obj)


def rotated_paths(path: str) -> list[str]:
    """The rotated predecessors of ``path``, oldest first (``path.N`` ..
    ``path.1``) — exactly the order that makes ``rotated + [path]`` one
    logically contiguous stream.  Empty when no rotation ever happened,
    so non-rotating streams read exactly as before."""
    out = []
    n = 1
    while os.path.exists(f"{path}.{n}"):
        out.append(f"{path}.{n}")
        n += 1
    out.reverse()
    return out


def _read_one(path: str) -> list[dict]:
    events: list[dict] = []
    # errors="replace": a writer killed mid-write can tear a multi-byte
    # UTF-8 character; the mangled line then fails JSON parsing and is
    # skipped like any other torn tail, instead of UnicodeDecodeError
    # poisoning the whole stream.
    with open(path, encoding="utf-8", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                # Torn tail from a killed writer — by the sink's contract
                # only the final line can be affected.
                continue
    return events


def read_events(path: str) -> list[dict]:
    """Parse a telemetry JSONL stream (rotated predecessors included,
    oldest first); a torn final line is skipped."""
    events: list[dict] = []
    for p in rotated_paths(path):
        events.extend(_read_one(p))
    events.extend(_read_one(path))
    return events


def iter_events(path: str, *, follow: bool = False, poll: float = 0.5,
                stop=None):
    """Incrementally yield events from a (possibly still-growing) stream.

    The live-tailing counterpart of :func:`read_events` (``cdrs metrics
    watch``): reads whatever the file currently holds, yields each complete
    line's event, and — with ``follow=True`` — sleeps ``poll`` seconds and
    continues from the same offset when the writer appends more.  A partial
    final line (the writer is mid-``write``, or the process died there) is
    buffered until its newline arrives, so a tailing consumer never parses a
    torn record; the file is read in BINARY and only complete lines are
    decoded, so a poll landing inside a multi-byte UTF-8 character buffers
    the raw bytes instead of mangling them (text-mode ``read()`` would
    flush U+FFFD at EOF).  Without ``follow`` a torn tail is skipped
    exactly like ``read_events``.  ``stop`` is an optional zero-argument
    callable checked once per poll round — return True to end a follow
    loop cleanly (tests, bounded watch sessions).  A missing file under
    ``follow`` is waited for, not raised: the watcher may start before the
    controller.

    Rotated predecessors (``JsonlSink(max_bytes=...)``) are yielded first,
    oldest to newest; when a rotation lands BETWEEN polls of a follow
    session (the live file shrank and a ``.1`` now holds the old bytes),
    the old file's unread tail is drained from ``.1`` before the fresh
    file — best-effort single-step recovery (see module docstring).
    """
    import time as _time

    for p in rotated_paths(path):
        yield from _read_one(p)
    buf = b""
    pos = 0
    while True:
        try:
            with open(path, "rb") as f:
                if os.fstat(f.fileno()).st_size < pos:
                    # Shrunk: either truncated/recreated (rm + fresh
                    # producer) or rotated under a max_bytes sink.  If a
                    # rotation moved our bytes to ``.1``, drain its
                    # unread tail first; then restart at the top of the
                    # new live file.
                    prev = path + ".1"
                    drained = False
                    try:
                        if os.path.getsize(prev) >= pos:
                            with open(prev, "rb") as pf:
                                pf.seek(pos)
                                buf += pf.read()
                            drained = True
                    except OSError:
                        pass
                    if not drained:
                        # Plain truncation: the old bytes are gone, and
                        # any buffered partial line died with them.
                        buf = b""
                    pos = 0
                f.seek(pos)
                chunk = f.read()
                pos = f.tell()
        except FileNotFoundError:
            if not follow:
                raise
            chunk = b""
        buf += chunk
        while True:
            nl = buf.find(b"\n")
            if nl < 0:
                break
            raw, buf = buf[:nl], buf[nl + 1:]
            line = raw.decode("utf-8", errors="replace").strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except json.JSONDecodeError:
                continue  # corrupt mid-stream line: skip, keep tailing
        if not follow:
            return
        if stop is not None and stop():
            return
        _time.sleep(poll)
