"""JSONL event sink — thread-safe, line-buffered, append-only.

The contract the controller's kill/resume semantics need (control/
controller.py): the file is opened in append mode, every event is exactly
one line written with a single ``write()`` call under a lock and flushed
immediately, and a crashed writer leaves at worst a repeated tail —
consumers take the last record per logical key (e.g. window index).  A
torn final line (the process died mid-``write``) is skipped by
``read_events`` rather than poisoning the stream.
"""

from __future__ import annotations

import json
import os
import threading

__all__ = ["JsonlSink", "read_events", "iter_events"]


class JsonlSink:
    """Append one JSON object per line; safe to share across threads."""

    def __init__(self, path: str):
        self.path = path
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._lock = threading.Lock()
        # Explicit encoding: telemetry must round-trip identically across
        # platform default encodings (read_events/iter_events match).
        self._f = open(path, "a", encoding="utf-8")

    def emit(self, event: dict) -> None:
        # One write() + flush per event: the line lands atomically from the
        # point of view of a tailing reader, and a kill between events loses
        # nothing already emitted.
        # ensure_ascii=False writes real UTF-8 (the file's pinned encoding)
        # instead of \uXXXX escapes — half the bytes on non-ASCII names.
        line = json.dumps(event, default=_coerce, ensure_ascii=False) + "\n"
        with self._lock:
            if self._f is None:
                return  # emitted after close (e.g. a late worker thread)
            self._f.write(line)
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    def __enter__(self) -> "JsonlSink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _coerce(obj):
    """JSON fallback for numpy scalars/arrays without importing numpy.

    ``tolist`` first: arrays need it, and on numpy scalars it returns the
    python scalar (``item`` would raise on a size > 1 array)."""
    fn = getattr(obj, "tolist", None)
    if callable(fn):
        return fn()
    return str(obj)


def read_events(path: str) -> list[dict]:
    """Parse a telemetry JSONL stream; a torn final line is skipped."""
    events: list[dict] = []
    # errors="replace": a writer killed mid-write can tear a multi-byte
    # UTF-8 character; the mangled line then fails JSON parsing and is
    # skipped like any other torn tail, instead of UnicodeDecodeError
    # poisoning the whole stream.
    with open(path, encoding="utf-8", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                # Torn tail from a killed writer — by the sink's contract
                # only the final line can be affected.
                continue
    return events


def iter_events(path: str, *, follow: bool = False, poll: float = 0.5,
                stop=None):
    """Incrementally yield events from a (possibly still-growing) stream.

    The live-tailing counterpart of :func:`read_events` (``cdrs metrics
    watch``): reads whatever the file currently holds, yields each complete
    line's event, and — with ``follow=True`` — sleeps ``poll`` seconds and
    continues from the same offset when the writer appends more.  A partial
    final line (the writer is mid-``write``, or the process died there) is
    buffered until its newline arrives, so a tailing consumer never parses a
    torn record; the file is read in BINARY and only complete lines are
    decoded, so a poll landing inside a multi-byte UTF-8 character buffers
    the raw bytes instead of mangling them (text-mode ``read()`` would
    flush U+FFFD at EOF).  Without ``follow`` a torn tail is skipped
    exactly like ``read_events``.  ``stop`` is an optional zero-argument
    callable checked once per poll round — return True to end a follow
    loop cleanly (tests, bounded watch sessions).  A missing file under
    ``follow`` is waited for, not raised: the watcher may start before the
    controller.
    """
    import time as _time

    buf = b""
    pos = 0
    while True:
        try:
            with open(path, "rb") as f:
                if os.fstat(f.fileno()).st_size < pos:
                    # Truncated or recreated (rm + fresh producer): the
                    # old offset points past EOF and would read b""
                    # forever — restart from the top of the new stream.
                    pos = 0
                    buf = b""
                f.seek(pos)
                chunk = f.read()
                pos = f.tell()
        except FileNotFoundError:
            if not follow:
                raise
            chunk = b""
        buf += chunk
        while True:
            nl = buf.find(b"\n")
            if nl < 0:
                break
            raw, buf = buf[:nl], buf[nl + 1:]
            line = raw.decode("utf-8", errors="replace").strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except json.JSONDecodeError:
                continue  # corrupt mid-stream line: skip, keep tailing
        if not follow:
            return
        if stop is not None and stop():
            return
        _time.sleep(poll)
