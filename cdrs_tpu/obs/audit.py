"""Decision-quality audit — per-window "is the controller's output good?".

The controller's window records (control/controller.py) account for what it
*did* (folds, drifts, re-clusters, moves); this auditor scores what it
*decided*, per window, from quantities the loop already holds:

* **Clustering quality** — a simplified silhouette (per point: distance to
  its own accepted centroid vs the nearest other centroid; one (n, k)
  distance block, the same cost class as the drift detector that already
  runs every window) and a Davies-Bouldin index over the same block
  (per-cluster mean dispersion vs centroid separation; lower is better).
  Both are centroid-based proxies of their exact forms — the O(n²)
  pairwise silhouette is not a per-window quantity at any real n.
* **Population health** — normalized entropy of the per-category population
  (0 = everything in one category, 1 = uniform) and the total-variation
  distance against the PREVIOUS window's fractions (the drift detector's TV
  is against the last accepted model; this one sees window-to-window churn
  even between re-clusters).
* **Cost/benefit** — the applied plan's replication byte cost
  (Σ rf·size_bytes) and its delta vs the previous window, next to the
  window's measured locality hit ratio (cluster/evaluate.py replay).

Threshold-based anomaly flags turn the metrics into verdicts:

* ``drift_no_gain`` — a re-cluster ran this window and silhouette still
  dropped by more than ``silhouette_drop`` vs the previous audited window:
  the drift alarm fired but acting on it bought nothing (tuning signal for
  ``drift_threshold``).
* ``budget_saturated`` — the migration byte/file budget deferred moves
  ``budget_windows`` windows running: the backlog is structurally larger
  than the budget lets through (churn cap too tight, or the plan is
  flapping).
* ``locality_regressed`` — the window's applied moves measurably lowered
  the replayed locality (before/after gap beyond ``locality_drop``).
* ``durability_lost`` — fault mode (control + faults/): the window ended
  with files at ZERO live replicas; reads of them fail until a crashed
  holder recovers.
* ``repair_backlogged`` — the repair backlog stayed non-empty
  ``repair_backlog_windows`` windows running: nodes are failing faster
  than the churn budget lets the re-replicator heal.
* ``domain_diversity_violated`` — files whose reachable replicas all sit
  in ONE failure domain while a second domain is available
  (``correlated_risk`` > 0): a single rack/switch failure away from
  unavailability, the exact gap domain-aware placement exists to close.
* ``partition_stalled_repairs`` — repairs were deferred this window
  because every copy source is stranded behind a network partition; the
  backlog cannot drain until the partition heals.
* ``corruption_detected`` — integrity mode (control + faults corruption):
  this window's scrub scan, verified reads, or repair source checks
  caught silently rotten copies and quarantined them — the audit-trail
  proof the integrity layer, not luck, kept rot off the wire.
* ``scrub_starved`` — the background scrubber ran out of its (shared)
  byte allowance before finishing the window's verification quota: the
  scan cadence — and therefore the detection-latency bound — is
  slipping behind the configured rate.
* ``hotspot_recluster`` — serve mode (control + serve/): this window's
  re-cluster was triggered by the HOTSPOT detector, not feature drift — a
  flash crowd the cumulative fold had not yet surfaced.  The flag is the
  audit-trail proof that the serving feedback path, not drift, acted.
* ``slo_burning`` — serve mode: the window consumed more than its share
  of the read-latency error budget (``slo_burn`` > 1): reads over the
  SLO target plus unavailable reads exceeded ``1 - availability``.

One ``{"kind": "audit", ...}`` event per window rides the same JSONL stream
as everything else, plus ``audit.*`` gauges (silhouette, entropy, byte
cost) and an ``audit.flags.<name>`` counter per raised flag.  The auditor
is pure observation: it never touches the plan, and with telemetry off (or
``Telemetry(audit=False)``) the controller skips it entirely.  Its
window-to-window carry (previous fractions/silhouette/flag streaks) is
deliberately NOT checkpointed — a resumed controller restarts the audit
baseline at its first processed window; the plan sequence, which IS
checkpoint-covered, is unaffected.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

__all__ = ["AuditConfig", "DecisionAuditor", "silhouette_db_proxy"]


@dataclass(frozen=True)
class AuditConfig:
    """Anomaly thresholds (see module docstring for flag semantics)."""

    #: Silhouette drop (absolute, silhouette is in [-1, 1]) vs the previous
    #: audited window that makes a same-window re-cluster "no gain".
    silhouette_drop: float = 0.02
    #: Consecutive windows with budget-deferred moves before the budget
    #: counts as saturated.
    budget_windows: int = 3
    #: Before/after locality gap (absolute ratio points) that flags a
    #: window's applied moves as a regression.
    locality_drop: float = 0.01
    #: Consecutive windows with a non-empty repair backlog (fault mode,
    #: faults/repair.py) before the repair pipeline counts as backlogged —
    #: the churn budget is structurally too tight to re-replicate as fast
    #: as nodes fail.
    repair_backlog_windows: int = 3
    #: Row cap for the silhouette/Davies-Bouldin geometry (deterministic
    #: stride sample; None = all rows).  The metrics are means over rows,
    #: so a few thousand samples pin them to the third decimal while
    #: keeping the per-window audit cost flat in n — the audit must stay
    #: inside the telemetry budget at any population size.
    sample_rows: int | None = 4096


def silhouette_db_proxy(X: np.ndarray, centroids: np.ndarray,
                        labels: np.ndarray | None = None
                        ) -> tuple[float, float]:
    """(simplified silhouette, Davies-Bouldin) of X under ``centroids``.

    One (n, k) squared-distance block serves both: silhouette compares each
    point's own-centroid distance with its nearest-other-centroid distance;
    Davies-Bouldin compares per-cluster mean dispersion with centroid
    separation.  ``labels`` defaults to the nearest-centroid assignment
    (the accepted model's own rule).  Degenerate inputs (k < 2, or all
    points on one centroid) return (0.0, inf-free 0.0) rather than raising —
    the auditor records, it does not crash the control loop.
    """
    X = np.asarray(X, dtype=np.float64)
    c = np.asarray(centroids, dtype=np.float64)
    n, k = X.shape[0], c.shape[0]
    if n == 0 or k < 2:
        return 0.0, 0.0
    # ‖x−c‖² via the matmul expansion; clamp the cancellation negatives.
    d2 = np.maximum(
        (X * X).sum(1)[:, None] - 2.0 * (X @ c.T) + (c * c).sum(1)[None, :],
        0.0)
    if labels is None:
        labels = np.argmin(d2, axis=1)
    else:
        labels = np.asarray(labels)
    # Only the own-centroid and nearest-other distances are needed per row:
    # square-root two (n,) vectors, never the (n, k) block.
    rows = np.arange(n)
    own = np.sqrt(d2[rows, labels])
    d2[rows, labels] = np.inf       # d2 is local; no copy needed
    other = np.sqrt(d2.min(axis=1))
    denom = np.maximum(np.maximum(own, other), 1e-12)
    sil = float(np.mean((other - own) / denom))

    counts = np.bincount(labels, minlength=k).astype(np.float64)
    disp = np.bincount(labels, weights=own, minlength=k)
    disp = np.where(counts > 0, disp / np.maximum(counts, 1.0), 0.0)
    cd = np.sqrt(np.maximum(
        (c * c).sum(1)[:, None] - 2.0 * (c @ c.T) + (c * c).sum(1)[None, :],
        0.0))
    np.fill_diagonal(cd, np.inf)
    nonempty = counts > 0
    if nonempty.sum() < 2:
        return sil, 0.0
    # R_ij = (S_i + S_j) / M_ij over nonempty pairs; DB = mean_i max_j R_ij.
    r = (disp[:, None] + disp[None, :]) / np.maximum(cd, 1e-12)
    r[:, ~nonempty] = -np.inf
    per_i = r.max(axis=1)[nonempty]
    db = float(np.mean(np.where(np.isfinite(per_i), per_i, 0.0)))
    return sil, db


class DecisionAuditor:
    """Carries window-to-window audit state for one controller instance."""

    def __init__(self, sizes: np.ndarray, n_categories: int,
                 cfg: AuditConfig | None = None):
        self._sizes = np.asarray(sizes, dtype=np.int64)
        self._n_categories = int(n_categories)
        self.cfg = cfg or AuditConfig()
        self._prev_fractions: np.ndarray | None = None
        self._prev_silhouette: float | None = None
        self._prev_byte_cost: int | None = None
        self._budget_streak = 0
        self._repair_streak = 0

    def audit_window(self, tel, *, window: int, rec: dict,
                     X: np.ndarray | None,
                     centroids: np.ndarray | None,
                     rf: np.ndarray, cat: np.ndarray) -> dict | None:
        """Score one processed window and emit the audit event through
        ``tel``.  ``X`` is the window's feature snapshot when the loop
        already computed one (drift/re-cluster ran); None skips the
        geometry metrics but still audits population/cost/flags.  Returns
        the audit record (also appended to the stream)."""
        import time

        cfg = self.cfg
        event: dict = {"kind": "audit", "window": int(window),
                       "t": time.time()}

        sil = db = None
        if X is not None and centroids is not None and len(centroids) >= 2:
            cap = cfg.sample_rows
            if cap is not None and len(X) > cap:
                # Deterministic stride sample: same rows every window, so
                # the window-to-window silhouette TREND (what the flags
                # compare) carries no sampling jitter.
                X = X[::max(1, len(X) // cap)][:cap]
            sil, db = silhouette_db_proxy(X, centroids)
            event["silhouette"] = sil
            event["davies_bouldin"] = db

        planned = cat >= 0
        frac = np.bincount(cat[planned].astype(np.int64),
                           minlength=self._n_categories).astype(np.float64)
        total = max(int(planned.sum()), 1)
        frac /= total
        nz = frac[frac > 0]
        # + 0.0 normalizes the -0.0 a one-category population produces.
        entropy = float(-(nz * np.log(nz)).sum() /
                        np.log(max(self._n_categories, 2)) + 0.0)
        event["category_entropy"] = entropy
        event["category_fractions"] = [round(float(f), 6) for f in frac]
        if self._prev_fractions is not None:
            event["population_tv"] = float(
                0.5 * np.abs(frac - self._prev_fractions).sum())

        byte_cost = int((rf.astype(np.int64) * self._sizes).sum())
        event["replication_bytes"] = byte_cost
        if self._prev_byte_cost is not None:
            event["replication_bytes_delta"] = byte_cost - self._prev_byte_cost

        if rec.get("locality_after") is not None:
            event["locality"] = rec["locality_after"]

        flags: list[str] = []
        if (rec.get("recluster") and sil is not None
                and self._prev_silhouette is not None
                and sil < self._prev_silhouette - cfg.silhouette_drop):
            flags.append("drift_no_gain")
        if rec.get("deferred_budget"):
            self._budget_streak += 1
        else:
            self._budget_streak = 0
        if self._budget_streak >= cfg.budget_windows:
            flags.append("budget_saturated")
        before, after = rec.get("locality_before"), rec.get("locality_after")
        if (before is not None and after is not None
                and after < before - cfg.locality_drop):
            flags.append("locality_regressed")
        dur = rec.get("durability")
        if dur is not None:
            event["durability"] = {
                k: dur.get(k, 0) for k in
                ("under_replicated", "at_risk", "lost", "unreachable",
                 "correlated_risk")}
            if dur["lost"]:
                flags.append("durability_lost")
            if dur.get("correlated_risk"):
                flags.append("domain_diversity_violated")
        if rec.get("repair_deferred_partition"):
            flags.append("partition_stalled_repairs")
        integ = rec.get("integrity")
        if integ is not None:
            event["integrity"] = {
                k: integ.get(k, 0) for k in
                ("corrupt_copies", "true_lost", "detected_scrub",
                 "detected_read", "detected_repair")}
            if (integ.get("detected_scrub", 0)
                    + integ.get("detected_read", 0)
                    + integ.get("detected_repair", 0)):
                flags.append("corruption_detected")
        if (rec.get("scrub") or {}).get("starved"):
            flags.append("scrub_starved")
        if rec.get("recluster_trigger") == "hotspot":
            flags.append("hotspot_recluster")
        if rec.get("latency_p99_ms") is not None:
            event["latency_p99_ms"] = rec["latency_p99_ms"]
            event["slo_burn"] = rec.get("slo_burn")
            if (rec.get("slo_burn") or 0.0) > 1.0:
                flags.append("slo_burning")
        if rec.get("repair_backlog"):
            self._repair_streak += 1
        else:
            self._repair_streak = 0
        if self._repair_streak >= cfg.repair_backlog_windows:
            flags.append("repair_backlogged")
        event["flags"] = flags

        self._prev_fractions = frac
        if sil is not None:
            self._prev_silhouette = sil
        self._prev_byte_cost = byte_cost

        tel._emit(event)
        if sil is not None:
            tel.gauge("audit.silhouette", sil)
            tel.gauge("audit.davies_bouldin", db)
        tel.gauge("audit.category_entropy", entropy)
        tel.gauge("audit.replication_bytes", float(byte_cost))
        for f in flags:
            tel.counter_inc(f"audit.flags.{f}")
        return event
