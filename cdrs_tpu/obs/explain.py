"""``cdrs explain`` — decision provenance, reconstructed offline.

The controller's core artifact is a *decision* (a weighted
directional-deviation score mapping clusters to replication categories,
realized as a placement by a pure hash chooser), yet until now nothing
could answer "why is file X on nodes {a,b,c}", "why did it move in
window t", or "why is category C scored Hot".  Because placement is a
recomputable pure function (placement_fn/, the CRUSH posture) and every
admitted move is cause-tagged by the controller (``lineage`` events +
the per-window ``causes`` record), the full story reconstructs offline
from the metrics JSONL + a checkpoint — no live process needed:

* ``explain file ID`` — re-derive the chooser's slot-by-slot reasoning
  (:func:`placement_fn.explain_placement`: candidate priorities,
  domain-count keys, the rule that picked each slot — asserted equal to
  ``compute_placement``, so the narration cannot drift from the
  decision), report the checkpoint's exception-overlay deviation for
  the file if any, and list its cause-tagged move history from the
  lineage stream.
* ``explain category NAME`` — decompose the directional-deviation score
  into per-feature signed contributions vs the cluster centroid
  (``ops.scoring_np.score_table_terms`` — the paper's Table-2 math,
  feature by feature, reconciling exactly with the score).
* ``explain window W`` — rank which signals crossed their thresholds
  that window (drift, hotspot, SLO burn, durability tiers, integrity)
  and decompose the window's traffic by cause against the shared churn
  budget, plus the alert transitions the window caused.

Every line of output is deterministic for a given stream/checkpoint
(no wall clock), so explanations are golden-stable and diffable.
"""

from __future__ import annotations

import argparse
import json
import sys

__all__ = ["main", "explain_file", "explain_category", "explain_window",
           "file_history"]

#: Cause vocabulary stamped by the controller (control/controller.py).
CAUSES = ("drift", "hotspot", "conversion", "repair",
          "correlated_rebalance", "elastic_rebalance", "epoch_diff")


# -- shared loading ----------------------------------------------------------


def _load_events(path: str):
    from .sink import read_events

    try:
        events = read_events(path)
    except OSError as e:
        print(f"error: cannot read {path}: {e}", file=sys.stderr)
        return None
    if not events:
        print(f"error: {path}: no telemetry events (missing, empty, or "
              f"corrupt stream)", file=sys.stderr)
        return None
    return events


def _load_checkpoint(path: str):
    from ..utils.checkpoint import CheckpointError, load_state

    try:
        return load_state(path)
    except (OSError, CheckpointError) as e:
        print(f"error: cannot load checkpoint {path}: {e}",
              file=sys.stderr)
        return None


def _resolve_topology(args, manifest):
    """The chaos-CLI topology resolution (--topology JSON|FILE, --racks
    SPEC, default flat) against the manifest's node set."""
    from ..cluster import ClusterTopology

    if getattr(args, "topology", None):
        text = args.topology
        if not text.lstrip().startswith("{"):
            with open(text, encoding="utf-8") as f:
                text = f.read()
        return ClusterTopology.from_hierarchy(json.loads(text))
    if getattr(args, "racks", None):
        return ClusterTopology.from_rack_spec(manifest.nodes, args.racks)
    return ClusterTopology(nodes=tuple(manifest.nodes))


# -- file --------------------------------------------------------------------


def file_history(events: list[dict], fid: int) -> list[dict]:
    """The file's cause-tagged move history from the lineage stream:
    one entry per lineage batch naming the file, window-ordered, joined
    with that window's record context (trigger, plan hash).  A batch
    whose id list was truncated (LINEAGE_ID_CAP) cannot testify either
    way — those windows are reported via the ``truncated`` flag so
    absence of evidence is never presented as evidence of absence."""
    from .aggregate import dedup_windows

    recs = {r.get("window"): r for r in dedup_windows(events)}
    hist: list[dict] = []
    truncated: list[int] = []
    seen = set()
    for e in events:
        if e.get("kind") != "lineage":
            continue
        w = e.get("window")
        if e.get("truncated") and w not in truncated:
            truncated.append(w)
        if int(fid) not in (e.get("file_ids") or ()):
            continue
        key = (w, e.get("cause"))
        if key in seen:  # crash-repeated tail: last-wins like windows
            continue
        seen.add(key)
        rec = recs.get(w) or {}
        hist.append({
            "window": w,
            "cause": e.get("cause"),
            "batch_files": e.get("files"),
            "batch_bytes": e.get("bytes"),
            "recluster_trigger": rec.get("recluster_trigger"),
            "plan_hash": rec.get("plan_hash"),
        })
    hist.sort(key=lambda h: (h["window"] is None, h["window"]))
    return hist if not truncated else hist + [
        {"window": w, "cause": "(lineage id list truncated — counts "
                               "exact, membership unknown)"}
        for w in sorted(truncated)]


def explain_file(fid: int, *, manifest, topology, rf: int,
                 seed: int = 0, local: bool = False,
                 events: list[dict] | None = None,
                 checkpoint=None) -> dict:
    """The full story of one file: chooser narration + exception overlay
    + cause-tagged history.  ``checkpoint`` is a ``(arrays, meta)`` pair
    from utils/checkpoint.load_state (optional)."""
    import numpy as np

    from ..placement_fn import explain_placement, primary_on_topology

    if not 0 <= int(fid) < len(manifest):
        raise ValueError(
            f"file id {fid} out of range (manifest has "
            f"{len(manifest)} files)")
    primary = primary_on_topology(manifest.nodes,
                                  manifest.primary_node_id,
                                  topology)[int(fid)]
    out: dict = {
        "file": int(fid),
        "path": str(manifest.paths[int(fid)]),
        "size_bytes": int(manifest.size_bytes[int(fid)]),
        "trace": explain_placement(int(fid), int(rf), int(primary),
                                   topology, seed, local=bool(local)),
    }
    if checkpoint is not None:
        arrays, meta = checkpoint
        out["placement_mode"] = meta.get("placement", "materialized")
        if "current_rf" in arrays:
            out["target_rf"] = int(arrays["current_rf"][int(fid)])
        if "current_cat" in arrays:
            from ..config import CATEGORIES

            c = int(arrays["current_cat"][int(fid)])
            out["category"] = CATEGORIES[c] if c >= 0 else "Unplanned"
        exc_fids = arrays.get("fault_fn_exc_fids")
        if exc_fids is not None:
            hit = np.flatnonzero(np.asarray(exc_fids) == int(fid))
            if hit.size:
                row = np.asarray(arrays["fault_fn_exc_rows"])[hit[0]]
                out["exception_row"] = [int(x) for x in row if x >= 0]
            else:
                out["exception_row"] = None
            out["exceptions_total"] = int(np.asarray(exc_fids).size)
    if events is not None:
        out["history"] = file_history(events, int(fid))
        from .aggregate import dedup_windows

        recs = dedup_windows(events)
        stamps = [r.get("placement") for r in recs
                  if isinstance(r.get("placement"), dict)]
        if stamps:
            out["stream_placement"] = stamps[-1]
    return out


def render_file(d: dict, out) -> None:
    print(f"file {d['file']} ({d['path']}, {d['size_bytes']} bytes)",
          file=out)
    if "category" in d:
        line = f"  decided category: {d['category']}"
        if "target_rf" in d:
            line += f", target shards {d['target_rf']}"
        print(line, file=out)
    tr = d["trace"]
    nodes = [s["node_name"] for s in tr["slots"]]
    print(f"  computed placement (seed {tr['seed']}, rf {tr['rf']}"
          + (", region-local" if tr["local"] else "")
          + f"): {nodes}", file=out)
    for s in tr["slots"]:
        line = f"    slot {s['slot']}: {s['node_name']} — {s['rule']}"
        if "key" in s:
            k = s["key"]
            line += (f" (region copies {k['top_count']}, rack copies "
                     f"{k['base_count']}, priority {k['priority']})")
        print(line, file=out)
        for c in s.get("candidates", ()):
            if "masked" in c:
                print(f"      {c['name']:<8} [{c['domain']}] — "
                      f"{c['masked']}", file=out)
            else:
                extra = ""
                if "top_count" in c:
                    extra = (f" region={c['top_count']} "
                             f"rack={c['base_count']}")
                print(f"      {c['name']:<8} [{c['domain']}] "
                      f"priority={c['priority']}{extra}", file=out)
    if "exception_row" in d:
        if d["exception_row"] is not None:
            print(f"  exception overlay: DEVIATES from the computed "
                  f"base — current row {d['exception_row']} "
                  f"(one of {d['exceptions_total']} standing "
                  f"exceptions)", file=out)
        else:
            print(f"  exception overlay: on the computed base "
                  f"({d.get('exceptions_total', 0)} standing "
                  f"exceptions elsewhere)", file=out)
    if "history" in d:
        if d["history"]:
            print("  move history (cause-tagged):", file=out)
            for h in d["history"]:
                extra = ""
                if h.get("recluster_trigger"):
                    extra = f" (trigger: {h['recluster_trigger']})"
                if h.get("plan_hash"):
                    extra += f" plan {h['plan_hash']}"
                print(f"    window {h['window']}: {h['cause']}{extra}",
                      file=out)
        else:
            print("  move history: no cause-tagged moves in the stream",
                  file=out)


# -- category ----------------------------------------------------------------


def explain_category(name: str, centroids, category_idx, scoring_cfg,
                     fractions=None) -> dict:
    """Per-feature decomposition of the directional-deviation score for
    every cluster the accepted model mapped to ``name``.

    ``centroids`` is the accepted model's (k, d) block (the cluster
    representative in normalized feature space); the contributions are
    ``score_table_terms`` rows — the feature-axis sum IS the score the
    tie-broken argmax decided on, so the table reconciles exactly."""
    import numpy as np

    from ..config import CATEGORIES
    from ..ops.scoring_np import score_table_terms

    if name not in CATEGORIES:
        raise ValueError(
            f"unknown category {name!r} (want one of {CATEGORIES})")
    ci = CATEGORIES.index(name)
    cent = np.asarray(centroids, dtype=np.float64)
    terms = score_table_terms(cent, scoring_cfg)       # (k, C, d)
    scores = terms.sum(axis=2)                         # (k, C)
    gmed = np.asarray([scoring_cfg.global_medians[f]
                       for f in scoring_cfg.features], dtype=np.float64)
    W = np.asarray(scoring_cfg.weight_matrix(), dtype=np.float64)
    D = np.asarray(scoring_cfg.direction_matrix(), dtype=np.float64)
    cat_idx = np.asarray(category_idx)
    members = np.flatnonzero(cat_idx == ci)
    clusters = []
    for c in members:
        row = scores[c]
        others = np.delete(row, ci)
        runner = float(others.max()) if others.size else 0.0
        feats = []
        for j, f in enumerate(scoring_cfg.features):
            delta = float(cent[c, j] - gmed[j])
            contrib = float(terms[c, ci, j])
            feats.append({
                "feature": f,
                "centroid": round(float(cent[c, j]), 6),
                "global_median": round(float(gmed[j]), 6),
                "delta": round(delta, 6),
                "direction": int(D[ci, j]),
                "weight": round(float(W[ci, j]), 6),
                "contribution": round(contrib, 6),
                "gated_out": contrib == 0.0 and W[ci, j] != 0.0,
            })
        feats.sort(key=lambda r: -r["contribution"])
        clusters.append({
            "cluster": int(c),
            "score": round(float(row[ci]), 6),
            "runner_up_score": round(runner, 6),
            "margin": round(float(row[ci]) - runner, 6),
            "scores_all": {cat: round(float(row[i]), 6)
                           for i, cat in enumerate(CATEGORIES)},
            "features": feats,
        })
    out = {"category": name,
           "rf": scoring_cfg.replication_factors.get(name),
           "clusters_total": int(cent.shape[0]),
           "clusters": clusters}
    if fractions is not None:
        out["population_fraction"] = round(float(
            np.asarray(fractions)[ci]), 6)
    return out


def render_category(d: dict, out) -> None:
    line = (f"category {d['category']} (rf {d['rf']}): "
            f"{len(d['clusters'])} of {d['clusters_total']} clusters")
    if d.get("population_fraction") is not None:
        line += f", {d['population_fraction']:.1%} of files"
    print(line, file=out)
    if not d["clusters"]:
        print("  no cluster currently maps to this category", file=out)
        return
    for c in d["clusters"]:
        note = ""
        if c["margin"] < 0:
            # The DECISION scored cluster medians over the window's
            # feature table; this decomposition scores the checkpointed
            # centroid (the only cluster representative a snapshot
            # carries).  A negative margin means the two representatives
            # disagree — flag it rather than present proxy as truth.
            note = (" [centroid proxy disagrees with the accepted "
                    "decision (which scored cluster medians); read the "
                    "rows as directional]")
        print(f"  cluster {c['cluster']}: score {c['score']} "
              f"(runner-up {c['runner_up_score']}, margin "
              f"{c['margin']}){note}", file=out)
        for f in c["features"]:
            sign = "+" if f["delta"] >= 0 else ""
            want = {1: "wants high", -1: "wants low",
                    0: "direction-free"}[f["direction"]]
            gate = " [GATED OUT: direction/band mismatch]" \
                if f["gated_out"] else ""
            print(f"    {f['feature']:<22} delta {sign}{f['delta']:g} "
                  f"x weight {f['weight']:g} ({want}) -> "
                  f"+{f['contribution']:g}{gate}", file=out)


# -- window ------------------------------------------------------------------


def explain_window(events: list[dict], w: int) -> dict:
    """One window's story: which signals crossed, what traffic each
    cause consumed, and the alert transitions the window caused."""
    from .aggregate import dedup_windows
    from .alerts import evaluate_records

    recs = dedup_windows(events)
    by_w = {r.get("window"): r for r in recs}
    if int(w) not in by_w:
        have = [r.get("window") for r in recs]
        raise ValueError(
            f"no window {w} in the stream (windows "
            f"{min(have)}..{max(have)})" if have
            else f"no window records in the stream")
    rec = by_w[int(w)]

    signals = []

    def sig(name, value, crossed, detail=""):
        if value is None:
            return
        signals.append({"signal": name, "value": value,
                        "crossed": bool(crossed), "detail": detail})

    trig = rec.get("recluster_trigger")
    sig("drift", rec.get("drift"), trig == "drift",
        "re-cluster trigger" if trig == "drift" else "")
    sig("hotspot", rec.get("hotspot_score"), trig == "hotspot",
        "re-cluster trigger" if trig == "hotspot" else "")
    sig("slo_burn", rec.get("slo_burn"),
        (rec.get("slo_burn") or 0.0) > 1.0, "error budget exceeded"
        if (rec.get("slo_burn") or 0.0) > 1.0 else "")
    dur = rec.get("durability") or {}
    for key in ("lost", "at_risk", "under_replicated", "unreachable",
                "correlated_risk"):
        if key in dur:
            sig(f"durability.{key}", dur[key], dur[key] > 0)
    integ = rec.get("integrity") or {}
    for key in ("true_lost", "corrupt_copies"):
        if key in integ:
            sig(f"integrity.{key}", integ[key], integ[key] > 0)
    if (rec.get("scrub") or {}).get("starved") is not None:
        sig("scrub.starved", int(bool(rec["scrub"]["starved"])),
            bool(rec["scrub"]["starved"]))
    if rec.get("reads_unavailable") is not None:
        sig("reads_unavailable", rec.get("reads_unavailable"),
            (rec.get("reads_unavailable") or 0) > 0)
    # Crossed first (the ranked verdict), then by magnitude.
    signals.sort(key=lambda s: (not s["crossed"], -float(s["value"])))

    causes = dict(rec.get("causes") or {})
    scrub_b = (rec.get("scrub") or {}).get("bytes", 0)
    traffic = {k: dict(v) for k, v in causes.items()}
    if scrub_b:
        traffic["scrub"] = {"files": (rec.get("scrub") or {}).get(
            "files_verified", 0), "bytes": int(scrub_b)}
    total = sum(v.get("bytes", 0) for v in traffic.values())
    for v in traffic.values():
        v["share"] = round(v.get("bytes", 0) / total, 4) if total else 0.0

    upto = [r for r in recs if r.get("window") is not None
            and r["window"] <= int(w)]
    verdicts = evaluate_records(upto)
    transitions = [t for r in verdicts
                   for t in r["transitions"] if t.get("window") == int(w)]
    firing = sorted(r["name"] for r in verdicts if r["firing"])
    return {
        "window": int(w),
        "n_events": rec.get("n_events"),
        "recluster": rec.get("recluster"),
        "recluster_trigger": trig,
        "recluster_mode": rec.get("recluster_mode"),
        "plan_hash": rec.get("plan_hash"),
        "fault_events": list(rec.get("fault_events") or ()),
        "signals": signals,
        "traffic": traffic,
        "traffic_bytes_total": int(total),
        "repair_bytes": rec.get("repair_bytes", 0),
        "bytes_migrated": rec.get("bytes_migrated", 0),
        "alert_transitions": transitions,
        "alerts_firing_after": firing,
    }


def render_window(d: dict, out) -> None:
    head = (f"window {d['window']}: {d['n_events']} events, "
            f"recluster={bool(d['recluster'])}")
    if d["recluster_trigger"]:
        head += (f" (trigger {d['recluster_trigger']}, mode "
                 f"{d['recluster_mode']})")
    print(head, file=out)
    if d["fault_events"]:
        print(f"  fault events: {', '.join(d['fault_events'])}",
              file=out)
    print("  signals (crossed first):", file=out)
    for s in d["signals"]:
        mark = "CROSSED" if s["crossed"] else "quiet"
        detail = f" — {s['detail']}" if s["detail"] else ""
        print(f"    {s['signal']:<26} {s['value']:<12g} "
              f"[{mark}]{detail}", file=out)
    if d["traffic"]:
        print(f"  churn traffic by cause "
              f"({d['traffic_bytes_total']} bytes total):", file=out)
        for cause in sorted(d["traffic"],
                            key=lambda c: -d["traffic"][c]["bytes"]):
            v = d["traffic"][cause]
            print(f"    {cause:<22} {v['bytes']:>12} bytes "
                  f"({v['share']:.1%}), {v.get('files', 0)} files",
                  file=out)
    else:
        print("  churn traffic by cause: none (no admitted moves)",
              file=out)
    for t in d["alert_transitions"]:
        print(f"  alert {t['state'].upper()}: {t['alert']} "
              f"[{t['severity']}]", file=out)
    if d["alerts_firing_after"]:
        print(f"  alerts firing after this window: "
              f"{', '.join(d['alerts_firing_after'])}", file=out)
    if d["plan_hash"]:
        print(f"  plan hash: {d['plan_hash']}", file=out)


# -- CLI ---------------------------------------------------------------------


def _scoring_from(args):
    from ..config import ScoringConfig

    spec = getattr(args, "scoring_config", None)
    if spec == "validated":
        from ..config import validated_scoring_config

        return validated_scoring_config()
    if spec:
        from ..config import load_scoring_config

        return load_scoring_config(spec)
    return ScoringConfig()


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="cdrs explain",
        description="decision provenance: why a file lives where it "
                    "does, why a category scored what it did, what a "
                    "window's signals and traffic were")
    sub = parser.add_subparsers(dest="what", required=True)

    p = sub.add_parser("file", help="slot-by-slot chooser narration + "
                                    "exception overlay + cause-tagged "
                                    "move history")
    p.add_argument("id", type=int, help="file id (manifest row)")
    p.add_argument("--manifest", required=True)
    p.add_argument("--metrics", default=None, metavar="JSONL",
                   help="telemetry stream: adds the lineage move history")
    p.add_argument("--checkpoint", default=None, metavar="NPZ",
                   help="controller snapshot: adds the decided "
                        "category/rf and the exception-overlay row")
    p.add_argument("--topology", default=None, metavar="JSON|FILE")
    p.add_argument("--racks", default=None, metavar="SPEC")
    p.add_argument("--rf", type=int, default=2,
                   help="shard count to narrate when no --checkpoint "
                        "supplies the decided one")
    p.add_argument("--seed", type=int, default=0,
                   help="placement seed (the controller uses 0)")
    p.add_argument("--local", action="store_true",
                   help="narrate the region-local (locality-pinned) "
                        "variant")

    p = sub.add_parser("category", help="per-feature decomposition of "
                                        "the directional-deviation "
                                        "score (Table-2 math)")
    p.add_argument("name", help="category name (e.g. Hot, Archival)")
    p.add_argument("--checkpoint", required=True, metavar="NPZ",
                   help="controller snapshot carrying the accepted "
                        "model (centroids + cluster categories)")
    p.add_argument("--scoring_config", default=None,
                   metavar="JSON|validated")

    p = sub.add_parser("window", help="signals crossed, traffic by "
                                      "cause, alert transitions")
    p.add_argument("index", type=int, help="window index")
    p.add_argument("--metrics", required=True, metavar="JSONL")

    args = parser.parse_args(argv)
    out = sys.stdout

    try:
        if args.what == "file":
            from ..io.events import Manifest

            manifest = Manifest.read_csv(args.manifest)
            if not 0 <= args.id < len(manifest):
                # Before ANY checkpoint array is indexed: an
                # out-of-range id must be the clean one-liner, not a
                # numpy IndexError traceback.
                print(f"error: file id {args.id} out of range "
                      f"(manifest has {len(manifest)} files)",
                      file=sys.stderr)
                return 2
            try:
                topology = _resolve_topology(args, manifest)
            except (ValueError, OSError) as e:
                print(f"error: bad topology: {e}", file=sys.stderr)
                return 2
            events = None
            if args.metrics:
                events = _load_events(args.metrics)
                if events is None:
                    return 1
            checkpoint = None
            rf = args.rf
            if args.checkpoint:
                checkpoint = _load_checkpoint(args.checkpoint)
                if checkpoint is None:
                    return 1
                mode = checkpoint[1].get("placement", "materialized")
                if mode == "materialized":
                    print("error: checkpoint was written in "
                          "'materialized' placement mode — only the "
                          "hash modes ('functional'/"
                          "'materialized_hash') are a pure function "
                          "the chooser can narrate; re-run with "
                          "--placement materialized_hash or drop "
                          "--checkpoint to narrate the hash chooser "
                          "hypothetically", file=sys.stderr)
                    return 2
                if "current_rf" in checkpoint[0]:
                    rf = int(checkpoint[0]["current_rf"][args.id])
            d = explain_file(args.id, manifest=manifest,
                             topology=topology, rf=rf, seed=args.seed,
                             local=args.local, events=events,
                             checkpoint=checkpoint)
            render_file(d, out)
            return 0
        if args.what == "category":
            loaded = _load_checkpoint(args.checkpoint)
            if loaded is None:
                return 1
            arrays, meta = loaded
            if "accepted_centroids" not in arrays:
                print(f"error: checkpoint {args.checkpoint} carries no "
                      f"accepted model yet (no window re-clustered "
                      f"before the snapshot)", file=sys.stderr)
                return 2
            d = explain_category(
                args.name, arrays["accepted_centroids"],
                arrays["accepted_category_idx"], _scoring_from(args),
                fractions=arrays.get("accepted_fractions"))
            render_category(d, out)
            return 0
        # window
        events = _load_events(args.metrics)
        if events is None:
            return 1
        d = explain_window(events, args.index)
        render_window(d, out)
        return 0
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
