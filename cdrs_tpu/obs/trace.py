"""End-to-end decision tracing: the daemon's causal flight recorder.

Aggregate histograms explain averages; only a per-decision causal trace
explains a p99 (Sigelman et al., "Dapper", and Dean & Barroso, "The
Tail at Scale" — PAPERS.md).  This module gives every streaming-daemon
decision exactly that story:

* A :class:`TraceContext` is minted for every ingested event batch at
  the tailer boundary (``daemon/core.StreamDaemon._batches`` — the
  ingest timestamp is taken by the tailer itself, as close to the read
  as possible) and carried through the window carve into the decision.
* Each processed window emits ONE compact ``decision_trace`` event into
  the same JSONL sink as the window records: the trace id, the exact
  per-stage segment durations, the published epoch, and the ingest
  cursor — the stage sums every decision keeps.
* **Reconciliation is exact by construction**: segments are integer
  nanoseconds measured as consecutive deltas of ONE monotonic clock
  (``time.perf_counter_ns``), so they telescope — their sum equals the
  measured event-to-decision total bit-for-bit, the same discipline as
  the PR-15 ``causes`` digest reconciling migrated bytes.  Consumers
  (:func:`cdrs_tpu.obs.aggregate.critical_path_digest`, the scenario
  harness, CI) *assert* it rather than trust it.
* **Tail-sampled exemplars**: only the ``trace_exemplars`` slowest
  decisions seen so far keep a FULL span tree (the coarse segments plus
  the controller's per-stage breakdown, embedded in the event); the
  rest keep the stage sums alone, so steady-state overhead stays inside
  the repo's 1.05x telemetry budget (data/telemetry_overhead_r17.json).

The ``cdrs trace`` CLI (:func:`main`) reads the stream back:
``list`` tabulates decisions slowest-first, ``show`` renders one
decision's span tree with the epoch/lineage it produced (composing
with ``cdrs explain window``), and ``export`` emits deterministic
Chrome/Perfetto ``trace_event`` JSON — ``--canonical`` zeroes the
wall-clock fields so double runs are byte-identical (the CI check).

Span/segment schema of one ``decision_trace`` event::

    {"kind": "decision_trace", "trace": "d000007", "window": 7,
     "total_ns": 41823992,
     "segments_ns": {"tail": 92, "decide": 41_0.., "observe": ...,
                     "publish": ...},          # sum == total_ns, exact
     "ref_ns": <perf_counter_ns at segment origin>,
     "n_events": 1204, "epoch_id": 8, "map_epoch_id": 8,
     "plan_hash": "…", "batch": {"offset": 16384, "skip": 0},
     "exemplar": true,                         # only the N slowest
     "spans": [{"name": "decision", "parent": null, "dur_ns": …}, …]}

``tail`` is the carve/queue wait the daemon itself can control: the
delta from the later of (closing batch ingested, previous decision
finished) to decision start — a backlog replay does not double-charge
earlier decisions' service time to later windows.  The trace id is the
window index (``d%06d``): deterministic, and a SIGTERM/checkpoint/
resume stitch mints the SAME lineage for a re-decided window, so
consumers dedup last-wins exactly like window records.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from dataclasses import dataclass

__all__ = ["TraceContext", "mint_batch", "decision_trace_id",
           "build_span_tree", "chrome_trace", "main"]

#: Fixed segment order for rendering/export (deterministic output).
SEGMENT_ORDER = ("tail", "decide", "observe", "publish", "minibatch")

#: Controller stage order inside the ``decide`` segment (the
#: ``rec["seconds"]`` keys, pipeline order; "total" is the phase sum and
#: never a stage).
STAGE_ORDER = ("fold", "hotspot", "drift", "recluster", "faults",
               "repair", "rebalance", "scrub", "schedule", "serve",
               "evaluate", "plan")


@dataclass
class TraceContext:
    """Span context of one ingested event batch, minted at the tailer.

    ``offset``/``skip`` name the batch's resumable cursor position (byte
    offset of its block for binary logs, global event index for feeds);
    ``ingest_ns`` is ``time.perf_counter_ns()`` taken when the batch was
    read — the causal origin of every decision the batch closes."""

    offset: int
    skip: int
    ingest_ns: int


def mint_batch(offset: int, skip: int,
               ingest_ns: int | None = None) -> TraceContext:
    """Mint the per-batch context (``ingest_ns`` defaults to *now*; the
    tailer passes its own stamp, taken before any slicing work)."""
    return TraceContext(int(offset), int(skip),
                        int(ingest_ns if ingest_ns is not None
                            else time.perf_counter_ns()))


def decision_trace_id(window: int) -> str:
    """The decision's trace id.  Window indices identify decisions
    one-to-one (the carver's grid), so the id is deterministic across
    double runs AND across a checkpoint/resume stitch — a resumed
    decision references the same trace lineage, never an orphan."""
    return f"d{int(window):06d}"


def build_span_tree(decision: dict, window_rec: dict | None = None
                    ) -> list[dict]:
    """The decision's full span tree as a flat parent-indexed list.

    Row 0 is the root; each row is ``{"name", "parent": index|None,
    "dur_ns": int}``.  Coarse segments come from the reconciled
    ``segments_ns``; when the stream also carries the decision's window
    record, its ``rec["seconds"]`` stage breakdown nests under the
    ``decide`` segment (durations scaled to the decide segment so the
    tree's levels each sum to their parent).  Exemplar events embed
    exactly this tree at emit time; for the rest it is rebuilt here —
    same shape, same math."""
    if decision.get("spans"):
        return list(decision["spans"])
    segs = decision.get("segments_ns") or {}
    rows = [{"name": "decision", "parent": None,
             "dur_ns": int(decision.get("total_ns", 0))}]
    decide_idx = None
    for name in SEGMENT_ORDER:
        if name not in segs:
            continue
        rows.append({"name": name, "parent": 0,
                     "dur_ns": int(segs[name])})
        if name == "decide":
            decide_idx = len(rows) - 1
    secs = (window_rec or {}).get("seconds")
    if decide_idx is not None and isinstance(secs, dict):
        stage_sum = sum(float(secs[k]) for k in STAGE_ORDER if k in secs)
        decide_ns = int(segs.get("decide", 0))
        if stage_sum > 0 and decide_ns > 0:
            for k in STAGE_ORDER:
                if k in secs:
                    rows.append({
                        "name": f"controller.{k}", "parent": decide_idx,
                        "dur_ns": int(round(float(secs[k]) / stage_sum
                                            * decide_ns))})
    return rows


# -- readers ------------------------------------------------------------------


def _load_events(path: str):
    from .sink import read_events

    try:
        events = read_events(path)
    except OSError as e:
        raise SystemExit(f"error: cannot read metrics stream {path!r}: "
                         f"{e.strerror or e}")
    if not events:
        raise SystemExit(f"error: no telemetry events in {path!r} "
                         f"(empty or not a metrics JSONL stream)")
    return events


def _decisions_and_windows(events):
    from .aggregate import dedup_windows

    decisions = dedup_windows(events, "decision_trace")
    if not decisions:
        raise SystemExit(
            "error: stream carries no decision_trace events — produce "
            "one with `cdrs daemon ... --metrics FILE` (tracing rides "
            "the metrics sink)")
    windows = {w.get("window"): w for w in dedup_windows(events)}
    return decisions, windows


def _fmt_ns(ns: int) -> str:
    s = ns / 1e9
    if s >= 1.0:
        return f"{s:.3f}s"
    if s >= 1e-3:
        return f"{s * 1e3:.2f}ms"
    return f"{s * 1e6:.1f}us"


def _reconcile(d: dict) -> bool:
    segs = d.get("segments_ns") or {}
    return sum(int(v) for v in segs.values()) == int(d.get("total_ns", -1))


# -- subcommands --------------------------------------------------------------


def list_decisions(events, out=None, limit: int | None = None) -> None:
    """Slowest-first table of every traced decision (stage attribution
    at a glance; the reconciliation column is asserted, not assumed)."""
    out = out or sys.stdout
    decisions, _ = _decisions_and_windows(events)
    rows = sorted(decisions,
                  key=lambda d: -int(d.get("total_ns", 0)))
    if limit:
        rows = rows[:limit]
    print(f"{'trace':<10} {'window':>6} {'total':>10} {'top stage':>18} "
          f"{'epoch':>6} {'ok':>3} {'ex':>3}", file=out)
    for d in rows:
        segs = d.get("segments_ns") or {}
        top = max(segs, key=segs.get) if segs else "?"
        print(f"{d.get('trace', '?'):<10} {d.get('window'):>6} "
              f"{_fmt_ns(int(d.get('total_ns', 0))):>10} "
              f"{top + ' ' + _fmt_ns(int(segs.get(top, 0))):>18} "
              f"{d.get('epoch_id', '—'):>6} "
              f"{'y' if _reconcile(d) else 'N':>3} "
              f"{'*' if d.get('exemplar') else '':>3}", file=out)


def show_decision(events, which: str | None = None, out=None) -> None:
    """One decision's span tree, stage durations, and the epoch/lineage
    ids it produced.  ``which`` is a window index or a trace id
    (``d000007``); omitted, the SLOWEST decision is shown (the one
    ``trace list`` ranks first).  Composes with ``cdrs explain
    window``: the footer names the command that reconstructs the full
    decision story."""
    out = out or sys.stdout
    decisions, windows = _decisions_and_windows(events)
    if which is None:
        slowest = max(decisions, key=lambda d: int(d.get("total_ns", 0)))
        w = int(slowest.get("window", -1))
    else:
        key = which.lstrip("d").lstrip("0") or "0"
        try:
            w = int(key)
        except ValueError:
            raise SystemExit(f"error: {which!r} is not a window index "
                             f"or trace id (want e.g. 7 or d000007)")
    match = [d for d in decisions if int(d.get("window", -1)) == w]
    if not match:
        have = [int(d.get("window", -1)) for d in decisions]
        raise SystemExit(f"error: no traced decision for window {w} "
                         f"(stream has windows "
                         f"{min(have)}..{max(have)})")
    d = match[0]
    rec = windows.get(w)
    ok = _reconcile(d)
    print(f"decision {d.get('trace')}  window {w}  "
          f"total {_fmt_ns(int(d.get('total_ns', 0)))}  "
          f"events {d.get('n_events')}  "
          f"{'reconciled' if ok else 'RECONCILIATION BROKEN'}"
          f"{'  [exemplar]' if d.get('exemplar') else ''}", file=out)
    tree = build_span_tree(d, rec)
    total = max(1, int(d.get("total_ns", 1)))
    children: dict = {}
    for i, row in enumerate(tree):
        children.setdefault(row.get("parent"), []).append(i)

    def render(idx: int, depth: int) -> None:
        row = tree[idx]
        dur = int(row.get("dur_ns", 0))
        print(f"  {'  ' * depth}{row['name']:<{28 - 2 * depth}} "
              f"{_fmt_ns(dur):>10}  {dur / total:>6.1%}", file=out)
        for c in children.get(idx, ()):
            render(c, depth + 1)

    for root in children.get(None, ()):
        render(root, 0)
    if d.get("epoch_id") is not None:
        print(f"  -> published epoch {d['epoch_id']} "
              f"(map revision {d.get('map_epoch_id')}, "
              f"plan {str(d.get('plan_hash', ''))[:16]})", file=out)
    causes = (rec or {}).get("causes") or {}
    for name in sorted(causes):
        c = causes[name]
        print(f"  -> lineage {name}: {c.get('files', 0)} files / "
              f"{c.get('bytes', 0)} bytes", file=out)
    batch = d.get("batch") or {}
    if batch:
        print(f"  ingested from cursor offset={batch.get('offset')} "
              f"skip={batch.get('skip')}", file=out)
    print(f"  (full story: cdrs explain window {w} --metrics <stream>)",
          file=out)


def chrome_trace(events, canonical: bool = False) -> dict:
    """Deterministic Chrome/Perfetto ``trace_event`` JSON.

    One complete (``ph: "X"``) event per decision and per stage, ordered
    by (window, fixed stage order) with fixed pid/tid — the only run-
    varying fields are the wall-clock ``ts``/``dur`` microseconds.
    ``canonical=True`` zeroes those, making double runs byte-identical
    (the CI byte-stability check runs ``cmp`` on two canonical
    exports)."""
    decisions, windows = _decisions_and_windows(events)
    decisions = sorted(decisions, key=lambda d: int(d.get("window", 0)))
    base = min((int(d.get("ref_ns", 0)) for d in decisions), default=0)
    out = [{"ph": "M", "pid": 1, "tid": 1, "name": "process_name",
            "args": {"name": "cdrs daemon"}}]
    for d in decisions:
        w = int(d.get("window", 0))
        t0 = (int(d.get("ref_ns", 0)) - base) / 1e3
        total = int(d.get("total_ns", 0)) / 1e3
        args = {"trace": d.get("trace"), "window": w,
                "n_events": d.get("n_events"),
                "epoch_id": d.get("epoch_id"),
                "reconciled": _reconcile(d)}
        out.append({"ph": "X", "pid": 1, "tid": 1, "cat": "decision",
                    "name": f"decision w{w}", "ts": t0, "dur": total,
                    "args": args})
        cursor = t0
        segs = d.get("segments_ns") or {}
        for name in SEGMENT_ORDER:
            if name not in segs:
                continue
            dur = int(segs[name]) / 1e3
            out.append({"ph": "X", "pid": 1, "tid": 1, "cat": "segment",
                        "name": name, "ts": cursor, "dur": dur,
                        "args": {"window": w}})
            if name == "decide":
                tree = build_span_tree(d, windows.get(w))
                sub = cursor
                for row in tree:
                    if not str(row["name"]).startswith("controller."):
                        continue
                    sdur = int(row.get("dur_ns", 0)) / 1e3
                    out.append({"ph": "X", "pid": 1, "tid": 1,
                                "cat": "stage", "name": row["name"],
                                "ts": sub, "dur": sdur,
                                "args": {"window": w}})
                    sub += sdur
            cursor += dur
    if canonical:
        for ev in out:
            if "ts" in ev:
                ev["ts"] = 0.0
                ev["dur"] = 0.0
    return {"displayTimeUnit": "ms", "traceEvents": out}


def export_trace(events, out_path: str | None, out=None,
                 canonical: bool = False) -> None:
    out = out or sys.stdout
    doc = chrome_trace(events, canonical=canonical)
    text = json.dumps(doc, sort_keys=True,
                      separators=(",", ":")) + "\n"
    if out_path:
        with open(out_path, "w") as f:
            f.write(text)
        print(f"wrote {len(doc['traceEvents'])} trace events to "
              f"{out_path}", file=out)
    else:
        out.write(text)


# -- CLI ----------------------------------------------------------------------


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="cdrs trace",
        description="per-decision causal traces of the streaming daemon "
                    "(read back from the metrics JSONL stream)")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("list", help="every traced decision, slowest "
                                    "first, with stage attribution")
    p.add_argument("file", help="metrics JSONL stream")
    p.add_argument("--limit", type=int, default=None,
                   help="show only the N slowest")

    p = sub.add_parser("show", help="one decision's span tree, stage "
                                    "durations and epoch/lineage ids")
    p.add_argument("file", help="metrics JSONL stream")
    p.add_argument("which", nargs="?", default=None,
                   help="window index or trace id (d000007); default = "
                        "the slowest decision")

    p = sub.add_parser("export", help="Chrome/Perfetto trace_event JSON "
                                      "(chrome://tracing, ui.perfetto."
                                      "dev)")
    p.add_argument("file", help="metrics JSONL stream")
    p.add_argument("--out", default=None, help="output path (default "
                                               "stdout)")
    p.add_argument("--canonical", action="store_true",
                   help="zero the wall-clock ts/dur fields: double runs "
                        "become byte-identical (the CI stability check)")

    args = parser.parse_args(argv)
    events = _load_events(args.file)
    try:
        if args.cmd == "list":
            list_decisions(events, limit=args.limit)
        elif args.cmd == "show":
            show_decision(events, args.which)
        elif args.cmd == "export":
            export_trace(events, args.out, canonical=args.canonical)
    except BrokenPipeError:
        # `cdrs trace ... | head` closing the pipe is a clean exit, not
        # a traceback (the metrics_cli idiom).
        try:
            sys.stdout.close()
        except Exception:
            pass
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
