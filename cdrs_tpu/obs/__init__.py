"""Unified telemetry layer (spans, counters/gauges/histograms, JSONL sink).

One subsystem for every observability question the framework previously
answered with ad-hoc means — the per-stage ``StageTimer``s and flat
``MetricsLog`` dict (utils/logging.py, now thin shims over this layer), the
controller's inline JSONL writer (control/controller.py), and nothing at all
for the questions that mattered most at speed: did ``kmeans_jax_full``
recompile?  How many Lloyd iterations did each re-cluster take?  Where did
the wall-clock go inside a window?

Pieces:

* ``Telemetry`` (telemetry.py) — hierarchical spans (nested timers with
  attributes, monotonic clocks), counters, gauges, histograms; activates as
  the ambient instrument via a context manager so call sites deep in the
  stack (``ops/kmeans_jax.py``) emit without threading a handle through
  every layer.
* ``JsonlSink`` (sink.py) — thread-safe line-buffered append; each event is
  one ``write()`` call under a lock, so the stream stays parseable under
  the controller's kill/resume semantics (consumers take the last record
  per key).
* ``jaxtools`` — the JIT recompile detector (abstract-aval signature per
  wrapped kernel; counter increments on a first-seen signature) and
  optional ``jax.local_devices()`` memory-stats gauges.
* ``metrics_cli`` — the ``cdrs metrics`` subcommand: ``summarize`` (span
  wall-clock tree, p50/p95 histograms, convergence traces), ``tail``,
  ``export --format prometheus``, ``watch``, and ``alerts``.
* ``alerts`` — declarative streaming AlertRules (thresholds, SRE
  burn-rate pairs over the SloSpec error budget, staleness) evaluated
  incrementally over the event stream; shared by the CLI, watch, the
  HTML report, the Prometheus export and the scenario harness.
* ``explain`` — decision provenance: the ``cdrs explain`` offline
  reconstruction of placement choices (slot-by-slot chooser narration),
  category scores (per-feature Table-2 decomposition) and window
  stories (signals crossed, traffic by cause, alert transitions).

The core imports neither jax nor pandas: a base install can produce and
read telemetry.
"""

from .sink import JsonlSink, iter_events, read_events
from .telemetry import Span, Telemetry, current, run_metadata

__all__ = [
    "JsonlSink",
    "Span",
    "Telemetry",
    "current",
    "iter_events",
    "read_events",
    "run_metadata",
]
