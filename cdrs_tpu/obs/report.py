"""Static HTML report of a telemetry stream — ``cdrs metrics report``.

One self-contained file (inline CSS, inline SVG, zero external requests) a
reviewer can open from a bench artifact directory or attach to a PR: the
span wall-clock tree with duration bars, counters/gauges with sparklines of
every observation, histogram p50/p95, the XLA cost/roofline table
(obs/xprof.py captures), the per-window decision-quality audit timeline
with anomaly flags (obs/audit.py), and the controller window digest.  All
aggregation comes from obs/aggregate.py — the HTML agrees with ``cdrs
metrics summarize`` by construction.

Rendering is **deterministic for a given event stream** (dict iteration is
sorted, floats are rounded, no generation timestamp is stamped), which is
what lets tests/test_observatory.py golden-file the output.

Visual conventions follow the repo-neutral dataviz defaults: single-hue
marks for data (blue series ramp), text in ink tokens rather than series
color, status colors reserved for audit flags and always paired with a
text label, light and dark mode both selected from the same palette.
"""

from __future__ import annotations

import html as _html

from .aggregate import (
    bucket_percentile,
    collect,
    fmt_bytes,
    ordered_span_paths,
    percentile,
    roofline_rows,
    serve_digest,
    storage_digest,
)

__all__ = ["render_html"]

_CSS = """
:root {
  color-scheme: light;
  --surface-1: #fcfcfb; --surface-2: #f1f0ee;
  --text-primary: #0b0b0b; --text-secondary: #52514e;
  --grid: #e3e2df;
  --series-1: #2a78d6; --series-1-soft: #cde2fb;
  --status-good: #0ca30c; --status-serious: #ec835a;
  --status-critical: #d03b3b;
}
@media (prefers-color-scheme: dark) {
  :root:where(:not([data-theme="light"])) {
    color-scheme: dark;
    --surface-1: #1a1a19; --surface-2: #242422;
    --text-primary: #ffffff; --text-secondary: #c3c2b7;
    --grid: #3a3936;
    --series-1: #3987e5; --series-1-soft: #1c5cab;
  }
}
body { background: var(--surface-1); color: var(--text-primary);
  font: 14px/1.45 system-ui, sans-serif; margin: 2rem auto;
  max-width: 72rem; padding: 0 1rem; }
h1 { font-size: 1.3rem; } h2 { font-size: 1.05rem; margin-top: 2rem; }
table { border-collapse: collapse; width: 100%; }
th, td { text-align: left; padding: .3rem .6rem;
  border-bottom: 1px solid var(--grid); font-variant-numeric: tabular-nums; }
th { color: var(--text-secondary); font-weight: 600; }
td.num, th.num { text-align: right; }
.tiles { display: flex; flex-wrap: wrap; gap: .8rem; margin: 1rem 0; }
.tile { background: var(--surface-2); border-radius: 8px;
  padding: .6rem 1rem; min-width: 8rem; }
.tile .v { font-size: 1.4rem; font-weight: 650; }
.tile .l { color: var(--text-secondary); font-size: .8rem; }
.bar { display: inline-block; height: 8px; border-radius: 0 4px 4px 0;
  background: var(--series-1); vertical-align: middle; }
.spark polyline { fill: none; stroke: var(--series-1); stroke-width: 2; }
.spark { vertical-align: middle; }
.indent { color: var(--text-secondary); }
.flag { font-weight: 600; }
.flag.serious { color: var(--status-serious); }
.flag.critical { color: var(--status-critical); }
.ok { color: var(--status-good); }
.muted { color: var(--text-secondary); }
code { background: var(--surface-2); padding: 0 .25rem; border-radius: 4px; }
"""


def _esc(x) -> str:
    return _html.escape(str(x))


def _fmt(v, digits: int = 4) -> str:
    if v is None:
        return "—"
    if isinstance(v, float):
        return f"{v:.{digits}g}"
    return str(v)


def _fmt_bytes(b) -> str:
    # \u202f: narrow no-break space keeps value+unit on one line in cells.
    return fmt_bytes(b, sep="\u202f")


def _sparkline(values: list[float], width: int = 120, height: int = 26
               ) -> str:
    """Inline single-series SVG sparkline (2px line, no axes; the row's
    text cells carry the numbers).  Hover shows the min/max range."""
    vs = [float(v) for v in values]
    if len(vs) < 2:
        return '<span class="muted">—</span>'
    lo, hi = min(vs), max(vs)
    span = (hi - lo) or 1.0
    pad = 2
    pts = []
    for i, v in enumerate(vs):
        x = pad + i * (width - 2 * pad) / (len(vs) - 1)
        y = height - pad - (v - lo) / span * (height - 2 * pad)
        pts.append(f"{x:.1f},{y:.1f}")
    return (f'<svg class="spark" width="{width}" height="{height}" '
            f'role="img" aria-label="{len(vs)} samples, '
            f'{_fmt(lo)} to {_fmt(hi)}">'
            f'<title>{len(vs)} samples, min {_fmt(lo)}, max {_fmt(hi)}'
            f'</title><polyline points="{" ".join(pts)}"/></svg>')


def _tiles(digest: dict, n_events: int) -> str:
    windows = digest["windows"]
    audits = digest["audits"]
    tiles = [("events in stream", f"{n_events}")]
    if windows:
        tiles.append(("controller windows", f"{len(windows)}"))
        tiles.append(("reclusters",
                      f"{sum(1 for w in windows if w.get('recluster'))}"))
        tiles.append(("bytes migrated", _fmt_bytes(
            sum(int(w.get("bytes_migrated", 0)) for w in windows))))
        dur = [w for w in windows if w.get("durability")]
        if dur:
            tiles.append(("max files lost",
                          f"{max(w['durability']['lost'] for w in dur)}"))
            tiles.append(("repair bytes", _fmt_bytes(
                sum(int(w.get("repair_bytes", 0)) for w in windows))))
    if audits:
        flagged = sum(1 for a in audits if a.get("flags"))
        tiles.append(("flagged windows", f"{flagged}"))
        sils = [a["silhouette"] for a in audits
                if a.get("silhouette") is not None]
        if sils:
            tiles.append(("final silhouette", _fmt(sils[-1], 3)))
    sd = serve_digest(windows)
    if sd is not None:
        tiles.append(("reads routed", f"{sd['reads_routed']}"))
        p99 = sd["latency_p99_ms_last"]
        tiles.append(("p99 latency (last)",
                      "—" if p99 is None else f"{p99:g} ms"))
        tiles.append(("SLO burn (max)", _fmt(sd["slo_burn_max"], 3)))
    if digest["xla"]:
        tiles.append(("XLA programs captured", f"{len(digest['xla'])}"))
    cells = "".join(
        f'<div class="tile"><div class="v">{_esc(v)}</div>'
        f'<div class="l">{_esc(label)}</div></div>'
        for label, v in tiles)
    return f'<div class="tiles">{cells}</div>'


def _span_section(digest: dict) -> str:
    agg = digest["spans"]
    if not agg:
        return ""
    total = max((n["total"] for n in agg.values()), default=0.0) or 1.0
    rows = []
    for path in ordered_span_paths(agg):
        node = agg[path]
        indent = "&nbsp;" * 4 * (len(path) - 1)
        bar_w = max(2, int(round(160 * node["total"] / total)))
        calls = f' <span class="muted">&times;{node["count"]}</span>' \
            if node["count"] > 1 else ""
        rows.append(
            f'<tr><td>{indent}<span class="indent"></span>'
            f'{_esc(path[-1])}{calls}</td>'
            f'<td class="num">{node["total"]:.3f} s</td>'
            f'<td><span class="bar" style="width:{bar_w}px"></span></td>'
            f"</tr>")
    return ("<h2>Span tree (wall-clock, aggregated)</h2><table>"
            "<tr><th>span</th><th class=num>total</th><th></th></tr>"
            + "".join(rows) + "</table>")


def _counter_section(digest: dict) -> str:
    counters = digest["counters"]
    if not counters:
        return ""
    rows = "".join(
        f"<tr><td><code>{_esc(n)}</code></td>"
        f'<td class="num">{counters[n]:g}</td></tr>'
        for n in sorted(counters))
    return ("<h2>Counters</h2><table><tr><th>counter</th>"
            "<th class=num>value</th></tr>" + rows + "</table>")


def _gauge_section(digest: dict) -> str:
    gauges = digest["gauges"]
    if not gauges:
        return ""
    rows = []
    for name in sorted(gauges):
        series = digest["gauge_series"].get(name, [])
        rows.append(
            f"<tr><td><code>{_esc(name)}</code></td>"
            f'<td class="num">{gauges[name]:g}</td>'
            f"<td>{_sparkline(series)}</td></tr>")
    return ("<h2>Gauges</h2><table><tr><th>gauge</th><th class=num>last"
            "</th><th>trend</th></tr>" + "".join(rows) + "</table>")


def _hist_section(digest: dict) -> str:
    hists = digest["hists"]
    buckets = digest.get("hist_buckets", {})
    if not hists and not buckets:
        return ""
    rows = []
    for name in sorted(hists):
        vs = hists[name]
        rows.append(
            f"<tr><td><code>{_esc(name)}</code></td>"
            f'<td class="num">{len(vs)}</td>'
            f'<td class="num">{percentile(vs, 0.5):g}</td>'
            f'<td class="num">{percentile(vs, 0.95):g}</td>'
            f'<td class="num">{max(vs):g}</td>'
            f"<td>{_sparkline(vs)}</td></tr>")
    # Bucketed (hist_bulk) names: percentiles are bucket upper bounds
    # (the ~ marks the ladder's 10^(1/4) resolution).
    for name in sorted(buckets):
        agg = buckets[name]
        rows.append(
            f"<tr><td><code>{_esc(name)}</code></td>"
            f'<td class="num">{agg["count"]}</td>'
            f'<td class="num">~{_fmt(bucket_percentile(agg, 0.5))}</td>'
            f'<td class="num">~{_fmt(bucket_percentile(agg, 0.95))}</td>'
            f'<td class="num">{agg["max"]:g}</td>'
            f'<td><span class="muted">bucketed</span></td></tr>')
    return ("<h2>Histograms</h2><table><tr><th>histogram</th>"
            "<th class=num>n</th><th class=num>p50</th><th class=num>p95"
            "</th><th class=num>max</th><th>observations</th></tr>"
            + "".join(rows) + "</table>")


def _xla_section(digest: dict) -> str:
    rows_data = roofline_rows(digest)
    if not rows_data:
        return ""
    have_peaks = any("peak_fraction" in r for r in rows_data)
    head = ("<tr><th>kernel</th><th class=num>flops</th>"
            "<th class=num>bytes</th><th class=num>intensity (f/B)</th>"
            "<th class=num>temp</th><th class=num>compile</th>"
            "<th class=num>exec</th><th class=num>achieved GF/s</th>"
            + ("<th class=num>% of attainable</th><th>bound</th>"
               if have_peaks else "") + "</tr>")
    rows = []
    for r in rows_data:
        cells = [
            f"<td><code>{_esc(r['kernel'])}</code></td>",
            f'<td class="num">{_fmt(r.get("flops"))}</td>',
            f'<td class="num">{_fmt_bytes(r.get("bytes_accessed"))}</td>',
            f'<td class="num">{_fmt(r.get("intensity"), 3)}</td>',
            f'<td class="num">{_fmt_bytes(r.get("temp_bytes"))}</td>',
            f'<td class="num">{_fmt(r.get("compile_seconds"), 3)}'
            f' s</td>',
            f'<td class="num">{_fmt(r.get("exec_seconds"), 3)} s</td>',
            f'<td class="num">{_fmt(r.get("gflops"), 3)}</td>',
        ]
        if have_peaks:
            pf = r.get("peak_fraction")
            cells.append(f'<td class="num">'
                         f'{_fmt(100 * pf, 3) if pf is not None else "—"}'
                         f"</td>")
            cells.append(f"<td>{_esc(r.get('bound', '—'))}</td>")
        rows.append("<tr>" + "".join(cells) + "</tr>")
    note = ("" if have_peaks else
            '<p class="muted">No known chip peaks in the stream metadata — '
            "attainable-fraction columns omitted (pass --peak_flops/"
            "--peak_gbps to <code>cdrs metrics summarize</code> for the "
            "text view).</p>")
    return ("<h2>XLA kernel costs (roofline)</h2><table>" + head
            + "".join(rows) + "</table>" + note)


def _audit_flag_html(flags: list[str]) -> str:
    if not flags:
        return '<span class="ok">✓ clean</span>'
    spans = [f'<span class="flag {"critical" if f == "drift_no_gain" else "serious"}">'  # noqa: E501
             f"⚠ {_esc(f)}</span>" for f in flags]
    return " ".join(spans)


def _audit_section(digest: dict) -> str:
    audits = digest["audits"]
    if not audits:
        return ""
    sils = [a.get("silhouette") for a in audits]
    sil_series = [s for s in sils if s is not None]
    spark = (f"<p>silhouette trend {_sparkline(sil_series)}</p>"
             if len(sil_series) >= 2 else "")
    rows = []
    for a in audits:
        rows.append(
            f"<tr><td>{_esc(a.get('window'))}</td>"
            f'<td class="num">{_fmt(a.get("silhouette"), 3)}</td>'
            f'<td class="num">{_fmt(a.get("davies_bouldin"), 3)}</td>'
            f'<td class="num">{_fmt(a.get("category_entropy"), 3)}</td>'
            f'<td class="num">{_fmt(a.get("population_tv"), 3)}</td>'
            f'<td class="num">{_fmt(a.get("locality"), 3)}</td>'
            f'<td class="num">'
            f'{_fmt_bytes(a.get("replication_bytes"))}</td>'
            f"<td>{_audit_flag_html(a.get('flags', []))}</td></tr>")
    return ("<h2>Decision-quality audit timeline</h2>" + spark
            + "<table><tr><th>window</th><th class=num>silhouette</th>"
            "<th class=num>Davies-Bouldin</th><th class=num>entropy</th>"
            "<th class=num>pop. TV</th><th class=num>locality</th>"
            "<th class=num>repl. bytes</th><th>flags</th></tr>"
            + "".join(rows) + "</table>")


def _alerts_section(digest: dict) -> str:
    """Streaming-alert timeline: the default AlertRules (obs/alerts.py)
    evaluated over the stream's window records — fired alerts with their
    firing/resolved spans.  Absent when nothing fired, so quiet streams
    render unchanged."""
    from .alerts import evaluate_records, firing_spans

    windows = digest["windows"]
    if not windows:
        return ""
    res = [r for r in evaluate_records(windows) if r["fired"]]
    if not res:
        return ""
    rows = []
    for r in res:
        spans = [f"w{a} → w{b}" if b is not None
                 else f"w{a} → still firing"
                 for a, b in firing_spans(r["transitions"])]
        state = ('<span class="flag critical">⚠ firing</span>'
                 if r["firing"] else '<span class="ok">✓ resolved</span>')
        rows.append(
            f"<tr><td>{_esc(r['name'])}</td>"
            f"<td>{_esc(r['severity'])}</td>"
            f"<td>{state}</td>"
            f"<td>{_esc('; '.join(spans))}</td></tr>")
    return ("<h2>Alerts</h2>"
            "<table><tr><th>alert</th><th>severity</th><th>state</th>"
            "<th>spans</th></tr>" + "".join(rows) + "</table>")


def _window_section(digest: dict) -> str:
    windows = digest["windows"]
    if not windows:
        return ""
    rows = []
    for w in windows:
        rows.append(
            f"<tr><td>{_esc(w.get('window'))}</td>"
            f'<td class="num">{_fmt(w.get("n_events"))}</td>'
            f'<td class="num">{_fmt(w.get("drift"), 3)}</td>'
            f"<td>{_esc(w.get('recluster_mode') or '—')}</td>"
            f'<td class="num">{_fmt(w.get("moves_applied"))}</td>'
            f'<td class="num">{_fmt_bytes(w.get("bytes_migrated"))}</td>'
            f'<td class="num">{_fmt(w.get("locality_after"), 3)}</td>'
            f"</tr>")
    return ("<h2>Controller windows</h2><table><tr><th>window</th>"
            "<th class=num>events</th><th class=num>drift</th>"
            "<th>recluster</th><th class=num>moves</th>"
            "<th class=num>migrated</th><th class=num>locality</th></tr>"
            + "".join(rows) + "</table>")


def _durability_section(digest: dict) -> str:
    """Fault-mode timeline (window records carrying ``durability``):
    tiers per window, repair traffic, fault events.  Absent for streams
    without fault accounting — pre-fault reports render unchanged."""
    windows = [w for w in digest["windows"] if w.get("durability")]
    if not windows:
        return ""
    # Length-normalized unavailability (see metrics_cli._render_durability
    # for the n_reads/n_events fallback rationale).
    unavail = sum(int(w.get("unavailable_reads", 0)) for w in windows)
    note = ""
    if unavail:
        reads = sum(int(w.get("n_reads", 0)) for w in windows)
        denom = reads or sum(int(w.get("n_events", 0)) for w in windows)
        frac = f" (fraction {_fmt(unavail / denom, 3)})" if denom else ""
        note = (f'<p class="muted">{unavail} reads hit unreadable files'
                f"{frac}</p>")
    rows = []
    for w in windows:
        d = w["durability"]
        faults = ", ".join(w.get("fault_events") or ()) or "—"
        rows.append(
            f"<tr><td>{_esc(w.get('window'))}</td>"
            f"<td><code>{_esc(faults)}</code></td>"
            f'<td class="num">{_fmt(d.get("nodes_up"))}</td>'
            f'<td class="num">{_fmt(d.get("lost"))}</td>'
            f'<td class="num">{_fmt(d.get("at_risk"))}</td>'
            f'<td class="num">{_fmt(d.get("under_replicated"))}</td>'
            f'<td class="num">{_fmt(w.get("repair_moves"))}</td>'
            f'<td class="num">{_fmt_bytes(w.get("repair_bytes"))}</td>'
            f'<td class="num">{_fmt(w.get("repair_backlog"))}</td>'
            f"</tr>")
    return ("<h2>Durability (fault mode)</h2>" + note
            + "<table><tr><th>window</th>"
            "<th>fault events</th><th class=num>nodes up</th>"
            "<th class=num>lost</th><th class=num>at risk</th>"
            "<th class=num>under-repl.</th><th class=num>repairs</th>"
            "<th class=num>repair bytes</th><th class=num>backlog</th>"
            "</tr>" + "".join(rows) + "</table>")


def _integrity_section(digest: dict) -> str:
    """Silent-corruption vs detection timeline (window records carrying
    ``integrity`` — a corrupt-fault / scrub-enabled run): ground-truth
    rot and true losses the blind durability tiers cannot see, the
    per-path detection totals, and the scrub scan's progress.  Absent
    for pre-integrity streams — older reports render unchanged."""
    from .aggregate import integrity_digest

    d = integrity_digest(digest["windows"])
    if d is None:
        return ""
    tiles = "".join(
        f'<div class="tile"><div class="v">{v}</div>'
        f'<div class="l">{_esc(label)}</div></div>'
        for label, v in (
            ("corrupt copies (max)", _fmt(d["corrupt_copies_max"])),
            ("true losses (max)", _fmt(d["true_lost_max"])),
            ("detected", _fmt(d["detected_total"])),
            ("corrupt reads served", _fmt(d["corrupt_reads_served"])),
            ("scrub read", _fmt_bytes(d["scrub_bytes_total"])),
        ))
    note = (f'<p class="muted">detections: scrub {d["detected_scrub"]}, '
            f'read {d["detected_read"]}, repair {d["detected_repair"]}'
            + (f' · scrub starved {d["scrub_starved_windows"]} windows'
               if d["scrub_starved_windows"] else "") + "</p>")
    rows = []
    for w in digest["windows"]:
        integ = w.get("integrity")
        if integ is None:
            continue
        sc = w.get("scrub") or {}
        rows.append(
            f"<tr><td>{_esc(w.get('window'))}</td>"
            f'<td class="num">{_fmt(integ.get("corrupt_copies"))}</td>'
            f'<td class="num">{_fmt(integ.get("true_lost"))}</td>'
            f'<td class="num">{_fmt(integ.get("detected_scrub"))}</td>'
            f'<td class="num">{_fmt(integ.get("detected_read"))}</td>'
            f'<td class="num">{_fmt(integ.get("detected_repair"))}</td>'
            f'<td class="num">{_fmt(w.get("reads_corrupt_served"))}</td>'
            f'<td class="num">{_fmt_bytes(sc.get("bytes"))}</td>'
            f"<td>{'⚠ starved' if sc.get('starved') else '—'}</td></tr>")
    return ("<h2>Data integrity (silent corruption)</h2>"
            f'<div class="tiles">{tiles}</div>' + note
            + "<table><tr><th>window</th><th class=num>corrupt</th>"
            "<th class=num>true lost</th><th class=num>det. scrub</th>"
            "<th class=num>det. read</th><th class=num>det. repair</th>"
            "<th class=num>served rotten</th><th class=num>scrub bytes"
            "</th><th>scrub</th></tr>" + "".join(rows) + "</table>")


def _storage_section(digest: dict) -> str:
    """Tier/byte-cost digest (window records carrying ``storage`` — a
    ``ControllerConfig.storage`` run): stored vs raw bytes, overhead
    ratio, per-tier split, EC stripe count.  Absent for pre-storage
    streams — older reports render unchanged."""
    sd = storage_digest(digest["windows"])
    if sd is None:
        return ""
    tiles = "".join(
        f'<div class="tile"><div class="v">{v}</div>'
        f'<div class="l">{_esc(label)}</div></div>'
        for label, v in (
            ("stored bytes", _fmt_bytes(sd["bytes_stored_final"])),
            ("raw bytes", _fmt_bytes(sd["bytes_raw"])),
            ("overhead", f'{_fmt(sd["overhead_ratio_final"], 4)}×'),
            ("cost units", _fmt(sd["cost_units_final"], 5)),
            ("EC files", _fmt(sd["ec_files_final"])),
        ))
    rows = "".join(
        f"<tr><td>{_esc(t)}</td>"
        f'<td class="num">{_fmt_bytes(b)}</td></tr>'
        for t, b in sorted(sd["per_tier_bytes_final"].items()))
    cat_rows = "".join(
        f"<tr><td>{_esc(c)}</td>"
        f'<td class="num">{_fmt_bytes(b)}</td></tr>'
        for c, b in sorted(sd["per_category_bytes_final"].items()))
    return ("<h2>Storage (tiers &amp; erasure coding)</h2>"
            f'<div class="tiles">{tiles}</div>'
            "<table><tr><th>tier</th><th class=num>stored</th></tr>"
            + rows + "</table>"
            "<table><tr><th>category</th><th class=num>stored</th></tr>"
            + cat_rows + "</table>")


def _serve_section(digest: dict) -> str:
    """Read-path SLO timeline (serving window records from a
    ``ControllerConfig.serve`` / ``cdrs serve`` run): per-window latency
    percentiles, utilization, SLO burn, unavailable fraction, hotspots.
    Absent for pre-serve streams — older reports render unchanged."""
    sd = serve_digest(digest["windows"])
    if sd is None:
        return ""
    sw = [w for w in digest["windows"]
          if w.get("reads_routed") is not None]
    p99s = [float(w["latency_p99_ms"]) for w in sw
            if w.get("latency_p99_ms") is not None]
    spark = (f"<p>p99 latency trend {_sparkline(p99s)} · unavailable "
             f"fraction {_fmt(sd['unavailable_fraction'], 3)} · hotspot "
             f"windows {sd['hotspot_windows']} · hotspot-triggered "
             f"reclusters {sd['hotspot_reclusters']}</p>"
             if len(p99s) >= 2 else "")
    rows = []
    for w in sw:
        hot = w.get("hotspot_files") or ()
        hot_s = ", ".join(str(f) for f in hot) if hot else "—"
        trig = w.get("recluster_trigger")
        rows.append(
            f"<tr><td>{_esc(w.get('window'))}</td>"
            f'<td class="num">{_fmt(w.get("reads_routed"))}</td>'
            f'<td class="num">{_fmt(w.get("reads_unavailable"))}</td>'
            f'<td class="num">{_fmt(w.get("latency_p50_ms"), 3)}</td>'
            f'<td class="num">{_fmt(w.get("latency_p99_ms"), 3)}</td>'
            f'<td class="num">{_fmt(w.get("utilization_max"), 3)}</td>'
            f'<td class="num">{_fmt(w.get("slo_burn"), 3)}</td>'
            f"<td>{_esc(hot_s)}</td>"
            f"<td>{_esc(trig) if trig else '—'}</td></tr>")
    return ("<h2>Serving (read-path SLO)</h2>" + spark
            + "<table><tr><th>window</th><th class=num>routed</th>"
            "<th class=num>unavail.</th><th class=num>p50 ms</th>"
            "<th class=num>p99 ms</th><th class=num>util. max</th>"
            "<th class=num>SLO burn</th><th>hotspots</th><th>trigger</th>"
            "</tr>" + "".join(rows) + "</table>")


def _critical_path_section(digest: dict) -> str:
    """Decision critical-path attribution (``decision_trace`` records
    from a traced daemon run — obs/trace.py): event-to-decision tail,
    time-weighted stage shares, exemplar decisions.  Absent for
    untraced streams — older reports render unchanged."""
    from .aggregate import critical_path_digest, daemon_digest

    cp = critical_path_digest(digest.get("decisions") or [],
                              digest.get("windows") or [])
    if cp is None:
        return ""
    dd = daemon_digest(digest.get("decisions") or [],
                       digest.get("epoch_pins") or []) or {}
    recon = ("reconciled" if cp["reconciled"]
             else f"RECONCILIATION BROKEN ×{cp['reconcile_mismatches']}")
    tiles = "".join(
        f'<div class="tile"><div class="v">{v}</div>'
        f'<div class="l">{_esc(label)}</div></div>'
        for label, v in (
            ("traced decisions", _fmt(cp["decisions"])),
            ("epochs published", _fmt(dd.get("epochs_published"))),
            ("epochs pinned", _fmt(dd.get("epochs_pinned"))),
            ("decision p50", f"{cp['total_p50_seconds']:.4g}s"),
            ("decision p99", f"{cp['total_p99_seconds']:.4g}s"),
            ("segments", _esc(recon)),
        ))
    rows = "".join(
        f"<tr><td><code>{_esc(k)}</code></td>"
        f'<td class="num">{v:.1%}</td></tr>'
        for k, v in cp["stage_shares"].items() if v >= 0.001)
    ex = "".join(
        f"<tr><td><code>{_esc(e['trace'])}</code></td>"
        f'<td class="num">{_esc(e["window"])}</td>'
        f'<td class="num">{e["total_seconds"]:.4g}s</td></tr>'
        for e in cp["exemplars"][:8])
    ex_tbl = ("<h3>Exemplars (full span trees kept)</h3>"
              "<table><tr><th>trace</th><th class=num>window</th>"
              "<th class=num>total</th></tr>" + ex + "</table>"
              if ex else "")
    return ("<h2>Decision critical path</h2>"
            f'<div class="tiles">{tiles}</div>'
            "<table><tr><th>stage</th><th class=num>share of "
            "event-to-decision time</th></tr>" + rows + "</table>"
            + ex_tbl)


def _trace_section(digest: dict) -> str:
    traces = digest["traces"]
    if not traces:
        return ""
    rows = []
    for i, key in enumerate(sorted(traces), start=1):
        steps = sorted(traces[key], key=lambda e: e["step"])
        first, last = steps[0], steps[-1]
        inertias = [e["inertia"] for e in steps
                    if e.get("inertia") is not None]
        rows.append(
            f"<tr><td>{i}</td><td><code>{_esc(first.get('kernel', '?'))}"
            f"</code></td><td>{_esc(first.get('backend', '?'))}</td>"
            f'<td class="num">{_esc(first.get("k", "?"))}</td>'
            f'<td class="num">{len(steps)}</td>'
            f'<td class="num">{_fmt(last.get("shift"), 3)}</td>'
            f"<td>{_sparkline(inertias)}</td></tr>")
    return ("<h2>KMeans convergence traces</h2><table><tr><th>call</th>"
            "<th>kernel</th><th>backend</th><th class=num>k</th>"
            "<th class=num>iterations</th><th class=num>final shift</th>"
            "<th>inertia</th></tr>" + "".join(rows) + "</table>")


def _meta_section(digest: dict) -> str:
    meta = digest["meta"]
    if not meta:
        return ""
    items = " · ".join(f"{_esc(k)}=<code>{_esc(v)}</code>"
                       for k, v in sorted(meta.items()))
    return f'<p class="muted">{items}</p>'


def render_html(events: list[dict], title: str = "cdrs telemetry report"
                ) -> str:
    """The whole report as one self-contained HTML string."""
    digest = collect(events)
    body = (
        f"<h1>{_esc(title)}</h1>"
        + _meta_section(digest)
        + _tiles(digest, len(events))
        + _span_section(digest)
        + _xla_section(digest)
        + _audit_section(digest)
        + _alerts_section(digest)
        + _serve_section(digest)
        + _storage_section(digest)
        + _durability_section(digest)
        + _integrity_section(digest)
        + _critical_path_section(digest)
        + _window_section(digest)
        + _trace_section(digest)
        + _gauge_section(digest)
        + _hist_section(digest)
        + _counter_section(digest)
    )
    return ("<!doctype html><html><head><meta charset='utf-8'>"
            f"<title>{_esc(title)}</title>"
            "<meta name='viewport' content='width=device-width, "
            "initial-scale=1'>"
            f"<style>{_CSS}</style></head><body>{body}</body></html>")
