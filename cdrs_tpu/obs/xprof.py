"""XLA cost capture — per-kernel flops/bytes/memory + compile wall-clock.

The telemetry layer (PR 2) answers *where the wall-clock went*; this module
answers *what the hardware was asked to do*.  When an instrument with
``xprof`` enabled is active, the wrapped kernel entry points
(``ops/kmeans_jax.kmeans_jax_full``, ``ops/scoring_jax.classify_jax``,
``features/jax_backend.compute_features_jax``) route their program build
through :func:`instrumented_call`, which — once per (kernel, abstract
signature) —

* lowers and compiles the program explicitly (``jit.lower(...).compile()``)
  with the lowering and compile phases individually wall-clocked,
* reads XLA's own ``cost_analysis()`` (flops, bytes accessed, transcendental
  count — the numbers the roofline model needs) and
  ``memory_analysis()`` (argument/output/temp/code bytes — the numbers an
  HBM budget needs),
* emits one ``{"kind": "xla", "event": "compile", ...}`` telemetry event
  plus an ``xla.compiles.<kernel>`` counter and an ``xla.compile.seconds``
  histogram,
* times the first execution of the compiled program (one deliberate
  ``block_until_ready`` — diagnostic mode pays one sync per signature) and
  emits ``{"kind": "xla", "event": "exec", ...}`` with the achieved
  seconds, from which ``cdrs metrics summarize|report`` derive achieved
  FLOP/s and bytes/s for the roofline table.

Steady-state calls reuse the AOT-compiled executable, so telemetry-on runs
compile each program exactly once (same as telemetry-off); the only repeated
cost is Python dispatch instead of jit's C++ fast path — noise next to any
kernel this module is worth pointing at.  Every capture step is
fail-soft: an XLA backend without the analysis APIs falls back to the plain
jit call and never raises.

Roofline peaks for known TPU generations live in :data:`DEVICE_PEAKS`
(per-chip dense peak FLOP/s at the native matmul precision and HBM
bandwidth, from published specs); ``cdrs metrics summarize --peak_flops /
--peak_gbps`` overrides them for unlisted hardware.
"""

from __future__ import annotations

import threading
import time

from .telemetry import current

__all__ = [
    "instrumented_call",
    "clear_cache",
    "DEVICE_PEAKS",
    "resolve_peaks",
]

#: Per-chip (peak dense FLOP/s, peak HBM bytes/s) for device kinds jax
#: reports; the roofline lines in ``cdrs metrics`` use these when the
#: stream's run metadata names a known chip.  bf16/f32 MXU peak — the
#: precision the kernels here issue.
DEVICE_PEAKS: dict[str, tuple[float, float]] = {
    "TPU v4": (275e12, 1228e9),
    "TPU v5 lite": (197e12, 819e9),
    "TPU v5e": (197e12, 819e9),
    "TPU v5p": (459e12, 2765e9),
    "TPU v6 lite": (918e12, 1640e9),
    "TPU v6e": (918e12, 1640e9),
}


def resolve_peaks(device_kind: str | None) -> tuple[float, float] | None:
    """(peak_flops, peak_bytes_per_sec) for a jax ``device_kind``, or None
    when the chip is not in the table (CPU hosts, new hardware)."""
    if not device_kind:
        return None
    return DEVICE_PEAKS.get(device_kind)


#: (kernel, signature) -> AOT-compiled executable, or _FALLBACK when this
#: signature's capture failed once (never retried: a backend without the
#: AOT/analysis APIs would fail identically every call).
_COMPILED: dict[tuple, object] = {}
_FALLBACK = object()
_LOCK = threading.Lock()
#: Per-key capture guard: concurrent first calls must not each pay (and
#: double-report) the multi-second lower+compile.
_INFLIGHT: dict[tuple, threading.Lock] = {}


def clear_cache() -> None:
    """Drop captured executables (tests; mirrors jax.clear_caches)."""
    with _LOCK:
        _COMPILED.clear()
        _INFLIGHT.clear()


def _first_costs(cost) -> dict:
    """Normalize ``cost_analysis()`` output: jax returns a dict from
    ``Lowered`` and a single-element list of dicts from ``Compiled``."""
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return cost or {}


def _cost_event(kernel: str, compiled, lower_s: float, compile_s: float,
                sig_id: int) -> dict:
    event: dict = {
        "kind": "xla",
        "event": "compile",
        "kernel": kernel,
        "sig": sig_id,
        "t": time.time(),
        "lower_seconds": lower_s,
        "compile_seconds": compile_s,
    }
    try:
        cost = _first_costs(compiled.cost_analysis())
        for key, out in (("flops", "flops"),
                         ("bytes accessed", "bytes_accessed"),
                         ("transcendentals", "transcendentals")):
            if key in cost:
                event[out] = float(cost[key])
    except Exception:  # pragma: no cover - backend without the API
        pass
    try:
        mem = compiled.memory_analysis()
        for attr, out in (
            ("argument_size_in_bytes", "argument_bytes"),
            ("output_size_in_bytes", "output_bytes"),
            ("temp_size_in_bytes", "temp_bytes"),
            ("generated_code_size_in_bytes", "generated_code_bytes"),
        ):
            v = getattr(mem, attr, None)
            if v is not None:
                event[out] = int(v)
    except Exception:  # pragma: no cover - backend without the API
        pass
    return event


def _sig_id(kernel: str, signature) -> int:
    """Small stable-by-content id for a signature; events carry this instead
    of the (long, tuple-of-tuples) signature itself.  Content-hashed (not
    ``hash()``, which is salted per process for strings): two processes
    appending to one stream must stamp the identical program with the same
    id, or readers would show duplicate roofline rows per kernel."""
    import hashlib

    digest = hashlib.blake2b(repr((kernel, signature)).encode(),
                             digest_size=4).digest()
    return int.from_bytes(digest, "big")


def instrumented_call(kernel: str, jitted, args: tuple, *, signature,
                      n_static_trailing: int = 0,
                      extra: dict | None = None):
    """Invoke ``jitted(*args)``, capturing XLA cost analysis on the way.

    With no active instrument (or ``xprof`` off) this IS ``jitted(*args)``.
    Otherwise the program for ``signature`` (the caller's hashable abstract
    signature — shapes/dtypes + static config, obs/jaxtools.aval_signature)
    is lowered and compiled explicitly once, its cost/memory analyses are
    emitted as ``xla`` events, its first execution is timed (one
    ``block_until_ready``), and the AOT executable is cached for steady-state
    calls.  ``n_static_trailing`` names how many trailing entries of ``args``
    are jit-static (the AOT executable is invoked without them).
    ``extra`` fields merge into the compile event — callers use it to stamp
    mesh facts XLA's own analyses don't expose (``devices``,
    ``collective_bytes_per_iter``), which the summarize/report digests
    carry into the roofline rows.
    """
    tel = current()
    if tel is None or not getattr(tel, "xprof", False):
        return jitted(*args)
    key = (kernel, signature)
    call_args = args[:len(args) - n_static_trailing] \
        if n_static_trailing else args
    with _LOCK:
        compiled = _COMPILED.get(key)
        guard = _INFLIGHT.setdefault(key, threading.Lock())
    if compiled is None:
        # One capture per key: a concurrent first call waits on the
        # winner instead of paying (and double-reporting) the compile.
        with guard:
            with _LOCK:
                compiled = _COMPILED.get(key)
            if compiled is None:
                return _capture_and_run(key, kernel, signature, jitted,
                                        args, call_args, tel, extra)
    if compiled is _FALLBACK:
        return jitted(*args)
    try:
        return compiled(*call_args)
    except Exception:
        # The aval signature does not capture everything jit's own
        # dispatch does (device placement, sharding context): inputs
        # the AOT executable rejects would have simply recompiled
        # under jit.  Diagnostics must never fail a call jit accepts.
        with _LOCK:
            _COMPILED[key] = _FALLBACK
        return jitted(*args)


def _capture_and_run(key, kernel, signature, jitted, args, call_args, tel,
                     extra=None):
    """Winner path of the per-key capture: lower+compile (wall-clocked),
    emit the cost events, cache the executable, time the first run."""
    sig_id = _sig_id(kernel, signature)
    try:
        t0 = time.perf_counter()
        lowered = jitted.lower(*args)
        t1 = time.perf_counter()
        compiled = lowered.compile()
        t2 = time.perf_counter()
    except Exception:
        with _LOCK:
            _COMPILED[key] = _FALLBACK
        return jitted(*args)
    with _LOCK:
        _COMPILED[key] = compiled
    event = _cost_event(kernel, compiled, t1 - t0, t2 - t1, sig_id)
    if extra:
        event.update(extra)
    tel._emit(event)
    tel.counter_inc(f"xla.compiles.{kernel}")
    tel.histogram("xla.compile.seconds", t2 - t1)

    # First execution, deliberately synchronized: the achieved-seconds
    # sample the roofline summary pairs with the program's flops/bytes.
    import jax

    t0 = time.perf_counter()
    try:
        out = compiled(*call_args)
        out = jax.block_until_ready(out)
    except Exception:
        with _LOCK:
            _COMPILED[key] = _FALLBACK  # same rationale as the hit path
        return jitted(*args)
    exec_s = time.perf_counter() - t0
    tel._emit({"kind": "xla", "event": "exec", "kernel": kernel,
               "sig": sig_id, "t": time.time(), "seconds": exec_s})
    return out
