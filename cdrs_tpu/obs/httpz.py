"""Live operational plane: the daemon's in-process HTTP endpoint.

Every observability surface before this PR was post-hoc file
inspection — ``cdrs metrics summarize|watch|alerts`` and ``cdrs trace``
re-read the JSONL sink after (or while) the daemon writes it.  A daemon
serving reads must be scrapeable and probeable *while it runs* (Dapper /
Tail-at-Scale: production latency debugging happens against live
systems, not log archives), so ``cdrs daemon --http HOST:PORT`` runs
this server in a daemon-owned thread, strictly OFF the decision path:

====================  =======================================================
endpoint              serves
====================  =======================================================
``/metrics``          Prometheus text format (obs/prom.py — the SAME
                      renderer as ``cdrs metrics export``), plus
                      ``cdrs_process_start_time_seconds`` and
                      ``cdrs_build_info``
``/healthz``          200 iff the tailer is making progress (fresh
                      heartbeat) and no page-severity alert is firing
``/readyz``           200 iff a ``PlacementEpoch`` has been published and
                      the daemon is not draining — the epoch-pinned
                      serving contract as a probe
``/statusz``          JSON introspection: epoch id, window index, backlog,
                      firing alerts with streaks, decision p50/p99 from
                      the PR-17 reservoir, per-stage critical-path shares
``/debug/trace``      the tail-sampled slowest-decision exemplars as the
                      same Chrome/Perfetto JSON ``cdrs trace export``
                      emits
====================  =======================================================

**Snapshot-swap contract (no torn reads).**  The daemon never exposes
live mutable state to the server.  Once per processed window it builds
one immutable :class:`ObsSnapshot` and installs it with a single
reference assignment (:meth:`ObsServer.publish`); a request handler
reads ``self.snapshot`` exactly once and renders everything from that
object.  Same discipline as ``EpochPublisher.pin`` — a scrape landing
mid-republication sees either the whole previous snapshot or the whole
next one, never a mixture.  The invariant the concurrency test hammers:
within any one response, ``epochs_published == windows_processed ==
seq`` (a fresh daemon publishes exactly one epoch per processed
window), and ``seq`` is monotone across responses.

Probe semantics: readiness is about *traffic* (an epoch exists to pin;
flips false the moment SIGTERM drain begins so a balancer stops sending
work the daemon will not finish), health is about *liveness + paging*
(the tailer heartbeat goes stale when ingest wedges; a page-severity
alert means the data the daemon serves is in jeopardy).  Both recover
without restart.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import prom

__all__ = ["ObsSnapshot", "ObsServer", "EMPTY_SNAPSHOT"]

#: /statusz keys whose values move with the wall clock (or host timing)
#: on every run — the CI double-run stability check strips exactly these
#: before comparing bytes.  Everything else in /statusz is deterministic
#: for a seeded run.
STATUSZ_WALL_KEYS = ("captured_unix", "uptime_seconds", "decision",
                     "stages")


@dataclass(frozen=True)
class ObsSnapshot:
    """One immutable cut of daemon state, built once per processed
    window (module docstring: the snapshot-swap contract).

    ``decision_seconds`` carries the PR-17 bounded reservoir verbatim so
    ``/metrics`` renders the same summary convention as the textfile
    surface; ``stages`` is the critical-path share table
    ``((stage, seconds, share), ...)``; ``exemplars`` are the retained
    slowest-decision ``decision_trace`` events (span trees embedded)."""

    seq: int = 0
    epoch_id: int | None = None
    window: int | None = None
    windows_processed: int = 0
    events_ingested: int = 0
    epochs_published: int = 0
    checkpoints_written: int = 0
    reclusters: int = 0
    bytes_migrated: int = 0
    traced_decisions: int = 0
    backlog_events: int = 0
    backlog_bytes: int = 0
    lag_bytes: int = 0
    lag_blocks: float = 0.0
    lag_seconds: float = 0.0
    lag_windows: float = 0.0
    brownout_level: int = 0
    brownout_rungs: tuple = ()
    reads_shed: int = 0
    windows_coalesced: int = 0
    decision_seconds: tuple = ()
    decision_p50_seconds: float | None = None
    decision_p99_seconds: float | None = None
    stages: tuple = ()
    alerts: tuple = ()
    exemplars: tuple = ()
    captured_unix: float = field(default_factory=time.time)

    def severe_firing(self) -> tuple:
        """Firing page-severity alert rows — the /healthz trip wire."""
        return tuple(a for a in self.alerts
                     if a.get("firing") and a.get("severity") == "page")


EMPTY_SNAPSHOT = ObsSnapshot(captured_unix=0.0)


def _metrics_text(snap: ObsSnapshot) -> str:
    """The live Prometheus exposition, rendered entirely from one
    snapshot via the shared obs/prom.py primitives."""
    lines: list[str] = []
    counters = {
        "daemon.windows_processed": snap.windows_processed,
        "daemon.events_ingested": snap.events_ingested,
        "daemon.epochs_published": snap.epochs_published,
        "daemon.checkpoints_written": snap.checkpoints_written,
        "daemon.reclusters": snap.reclusters,
        "daemon.bytes_migrated": snap.bytes_migrated,
        "daemon.traced_decisions": snap.traced_decisions,
        "daemon.reads_shed": snap.reads_shed,
        "daemon.windows_coalesced": snap.windows_coalesced,
    }
    for name in sorted(counters):
        lines += prom.counter_lines(name, counters[name])
    gauges = {
        "daemon.backlog_bytes": snap.backlog_bytes,
        "daemon.backlog_events": snap.backlog_events,
        "daemon.brownout_level": snap.brownout_level,
        "daemon.epoch_id": snap.epoch_id or 0,
        "daemon.lag_blocks": snap.lag_blocks,
        "daemon.lag_bytes": snap.lag_bytes,
        "daemon.lag_seconds": snap.lag_seconds,
        "daemon.lag_windows": snap.lag_windows,
        "daemon.window": snap.window if snap.window is not None else -1,
        "obs.snapshot_seq": snap.seq,
    }
    for name in sorted(gauges):
        lines += prom.gauge_lines(name, gauges[name])
    if snap.decision_seconds:
        lines += prom.summary_lines("daemon.decision.seconds",
                                    list(snap.decision_seconds))
    for stage, _seconds, share in snap.stages:
        lines += prom.gauge_lines(f"daemon.stage.{stage}.share", share)
    firing = [a for a in snap.alerts if a.get("firing")]
    lines += prom.alerts_lines(firing)
    lines += prom.meta_lines()
    return "\n".join(lines) + "\n"


def _statusz_json(snap: ObsSnapshot, *, ready: bool, draining: bool,
                  started_unix: float) -> str:
    doc = {
        "seq": snap.seq,
        "captured_unix": snap.captured_unix,
        "uptime_seconds": max(0.0, time.time() - started_unix),
        "ready": ready,
        "draining": draining,
        "epoch_id": snap.epoch_id,
        "window": snap.window,
        "windows_processed": snap.windows_processed,
        "events_ingested": snap.events_ingested,
        "epochs_published": snap.epochs_published,
        "checkpoints_written": snap.checkpoints_written,
        "reclusters": snap.reclusters,
        "bytes_migrated": snap.bytes_migrated,
        "traced_decisions": snap.traced_decisions,
        "backlog": {"events": snap.backlog_events,
                    "bytes": snap.backlog_bytes},
        "lag": {"bytes": snap.lag_bytes,
                "blocks": snap.lag_blocks,
                "seconds": snap.lag_seconds,
                "windows": snap.lag_windows},
        "brownout": {"level": snap.brownout_level,
                     "rungs": list(snap.brownout_rungs),
                     "reads_shed": snap.reads_shed,
                     "windows_coalesced": snap.windows_coalesced},
        "decision": {
            "count": len(snap.decision_seconds),
            "p50_seconds": snap.decision_p50_seconds,
            "p99_seconds": snap.decision_p99_seconds,
        },
        "stages": [{"stage": s, "seconds": sec, "share": share}
                   for s, sec, share in snap.stages],
        "alerts": [dict(a) for a in snap.alerts if a.get("fired")],
    }
    return json.dumps(doc, sort_keys=True, indent=1) + "\n"


def _trace_json(snap: ObsSnapshot) -> str:
    """``/debug/trace``: the exemplar decisions in the exact JSON shape
    ``cdrs trace export`` emits.  Exemplar events embed their span trees,
    so no window-record join is needed; an empty exemplar set is a valid
    empty trace document, not an error."""
    from .trace import chrome_trace

    if not snap.exemplars:
        doc = {"displayTimeUnit": "ms", "traceEvents": []}
    else:
        doc = chrome_trace(list(snap.exemplars))
    return json.dumps(doc, sort_keys=True, separators=(",", ":")) + "\n"


class _Handler(BaseHTTPRequestHandler):
    # The server thread must never write to the daemon's stderr per
    # request (scrapes are periodic; the log would drown the digest).
    def log_message(self, fmt, *args):  # noqa: ARG002
        pass

    def _send(self, status: int, body: str, ctype: str) -> None:
        data = body.encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        if self.command != "HEAD":
            self.wfile.write(data)

    def do_HEAD(self):  # noqa: N802
        self.do_GET()

    def do_GET(self):  # noqa: N802
        obs: ObsServer = self.server.obs  # type: ignore[attr-defined]
        # ONE read of the snapshot reference; everything below renders
        # from this object only (the no-torn-reads contract).
        snap = obs.snapshot
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                self._send(200, _metrics_text(snap),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                ok, reason = obs.health(snap)
                if ok and snap.brownout_level:
                    # Designed degradation is not unhealth: the ladder
                    # shedding load is the daemon WORKING as specified,
                    # so brownout stays 200 — but the body says so, for
                    # humans and for probes that grep.
                    body = (f"ok (degraded: rung {snap.brownout_level} — "
                            f"{','.join(snap.brownout_rungs)})\n")
                elif ok:
                    body = "ok\n"
                else:
                    body = f"unhealthy: {reason}\n"
                self._send(200 if ok else 503, body,
                           "text/plain; charset=utf-8")
            elif path == "/readyz":
                ready, reason = obs.readiness()
                self._send(200 if ready else 503,
                           ("ready\n" if ready
                            else f"unready: {reason}\n"),
                           "text/plain; charset=utf-8")
            elif path == "/statusz":
                self._send(200, _statusz_json(
                    snap, ready=obs.ready, draining=obs.draining,
                    started_unix=obs.started_unix),
                    "application/json; charset=utf-8")
            elif path == "/debug/trace":
                self._send(200, _trace_json(snap),
                           "application/json; charset=utf-8")
            elif path == "/":
                self._send(200, "cdrs daemon: /metrics /healthz /readyz "
                                "/statusz /debug/trace\n",
                           "text/plain; charset=utf-8")
            else:
                self._send(404, f"no such endpoint {path}\n",
                           "text/plain; charset=utf-8")
        except BrokenPipeError:
            pass  # scraper hung up mid-response; nothing to salvage
        except Exception as e:  # pragma: no cover - defensive
            try:
                self._send(500, f"internal error: {e}\n",
                           "text/plain; charset=utf-8")
            except Exception:
                pass


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    obs: ObsServer


class ObsServer:
    """The daemon-owned observability server (module docstring).

    Lifecycle: construct (binds the socket, so a bad address fails fast
    in the foreground, before the daemon loop starts), :meth:`start`
    (serving thread), :meth:`publish` once per processed window,
    :meth:`set_ready` / :meth:`set_draining` at the epoch/drain
    transitions, :meth:`heartbeat` from the tailer's poll loop,
    :meth:`close` on the way out.  ``port=0`` binds an ephemeral port
    (tests); :attr:`url` reports the bound address either way.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 stale_after: float = 30.0):
        self._httpd = _Server((host, port), _Handler)
        self._httpd.obs = self
        self._thread: threading.Thread | None = None
        self.snapshot: ObsSnapshot = EMPTY_SNAPSHOT
        self.ready: bool = False
        self.draining: bool = False
        self.started_unix: float = time.time()
        self.stale_after = float(stale_after)
        self._heartbeat_mono = time.monotonic()

    # -- address ----------------------------------------------------------

    @property
    def address(self) -> tuple[str, int]:
        host, port = self._httpd.server_address[:2]
        return str(host), int(port)

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> ObsServer:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.1},
            name="cdrs-obs-http", daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> ObsServer:
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- daemon-side publication ------------------------------------------

    def publish(self, snapshot: ObsSnapshot) -> None:
        """Install a new immutable snapshot: ONE reference assignment,
        atomic under the GIL — the whole no-torn-reads contract."""
        self.snapshot = snapshot

    def set_ready(self, ready: bool) -> None:
        self.ready = bool(ready)

    def set_draining(self, draining: bool) -> None:
        """Drain begins: readiness drops IMMEDIATELY (single attribute
        stores — safe from a signal handler), before the daemon finishes
        the in-flight window."""
        self.draining = bool(draining)
        if draining:
            self.ready = False

    def heartbeat(self) -> None:
        """Tailer progress stamp, called from the ingest poll loop."""
        self._heartbeat_mono = time.monotonic()

    # -- probe verdicts ----------------------------------------------------

    def readiness(self) -> tuple[bool, str]:
        if self.ready:
            return True, ""
        if self.draining:
            return False, "draining"
        return False, "no placement epoch published yet"

    def health(self, snap: ObsSnapshot | None = None) -> tuple[bool, str]:
        snap = self.snapshot if snap is None else snap
        severe = snap.severe_firing()
        if severe:
            names = ",".join(a.get("name", "?") for a in severe)
            return False, f"severe alert firing: {names}"
        age = time.monotonic() - self._heartbeat_mono
        if age > self.stale_after:
            return False, (f"tailer stalled: no ingest progress for "
                           f"{age:.1f}s (bound {self.stale_after:g}s)")
        return True, ""
