"""Stream aggregation shared by every telemetry consumer.

``cdrs metrics summarize`` (text), ``cdrs metrics report`` (HTML) and
``cdrs metrics watch`` (live terminal) must agree on what a stream *means* —
span-tree aggregation, last-wins window/audit dedup, cross-run counter
summing, roofline arithmetic.  This module is that single meaning; the
consumers only render.

The reader is resilient by construction: unknown ``kind``s are ignored
(forward compatibility) and a torn final line from a killed writer is
skipped upstream (sink contract, obs/sink.py).
"""

from __future__ import annotations

__all__ = ["collect", "span_forest", "ordered_span_paths", "percentile",
           "bucket_percentile", "merge_hist_buckets", "dedup_windows",
           "final_counters", "roofline_rows", "fmt_bytes", "serve_digest",
           "storage_digest", "pacing_digest", "integrity_digest",
           "cells_digest", "coverage_fingerprint", "critical_path_digest",
           "daemon_digest"]


def fmt_bytes(b, sep: str = " ") -> str:
    """Human-readable byte count shared by every renderer (``sep`` is the
    value/unit separator: the HTML report spaces it, the terminal views
    pack it)."""
    if b is None:
        return "—"
    b = float(b)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(b) < 1024 or unit == "GiB":
            return f"{b:.3g}{sep}{unit}"
        b /= 1024
    return f"{b:g}{sep}B"  # pragma: no cover - loop always returns


def percentile(values: list[float], q: float) -> float:
    """Nearest-rank percentile on a sorted copy (no numpy dependency)."""
    s = sorted(values)
    if not s:
        return float("nan")
    idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
    return s[idx]


def _norm_le(le) -> float:
    """Bucket upper bound from its JSON form (``"+Inf"`` -> inf)."""
    return float("inf") if le in ("+Inf", "inf", None) else float(le)


def merge_hist_buckets(target: dict, event: dict) -> None:
    """Fold one ``hist_bulk`` event into a per-name aggregate of shape
    ``{"count", "sum", "min", "max", "buckets": {le: count}}`` (the same
    shape ``Telemetry.hist_buckets`` keeps in-process)."""
    n = int(event.get("count", 0))
    if n <= 0:
        return
    target["count"] = target.get("count", 0) + n
    target["sum"] = target.get("sum", 0.0) + float(event.get("sum", 0.0))
    vmin, vmax = float(event.get("min", 0.0)), float(event.get("max", 0.0))
    target["min"] = vmin if "min" not in target else min(target["min"], vmin)
    target["max"] = vmax if "max" not in target else max(target["max"], vmax)
    buckets = target.setdefault("buckets", {})
    for le, c in event.get("buckets", ()):
        key = _norm_le(le)
        buckets[key] = buckets.get(key, 0) + int(c)


def bucket_percentile(agg: dict, q: float) -> float:
    """Percentile estimate from a bucket aggregate: the upper bound of
    the first bucket whose cumulative count reaches ``q``·total (the
    overflow bucket reports the observed max instead of inf).  Resolution
    is the ladder step (~78%); exact raw samples, when a name has both,
    merge via ``obs.telemetry.bucket_counts`` before calling."""
    total = agg.get("count", 0)
    if not total:
        return float("nan")
    target = max(1, int(round(q * total)))
    cum = 0
    for le in sorted(agg.get("buckets", {})):
        cum += agg["buckets"][le]
        if cum >= target:
            return agg.get("max", le) if le == float("inf") else le
    return agg.get("max", float("nan"))  # pragma: no cover - counts agree


def span_forest(events: list[dict]):
    """Aggregate span events by their name-path.

    Returns ``{path_tuple: {"count": int, "total": float}}`` where the path
    is the chain of span names from the root — repeated spans (e.g. one per
    window) aggregate into one node.  Span ids restart per process, so ids
    are scoped by the event's ``run`` stamp: appended streams from several
    runs aggregate instead of shadowing each other.
    """
    by_id = {(e.get("run"), e["id"]): e for e in events
             if e.get("kind") == "span"}
    agg: dict[tuple, dict] = {}
    for e in by_id.values():
        run = e.get("run")
        path = [e["name"]]
        parent = e.get("parent")
        depth = 0
        while parent is not None and depth < 100:
            pe = by_id.get((run, parent))
            if pe is None:
                break
            path.append(pe["name"])
            parent = pe.get("parent")
            depth += 1
        key = tuple(reversed(path))
        node = agg.setdefault(key, {"count": 0, "total": 0.0})
        node["count"] += 1
        node["total"] += float(e.get("dur", 0.0))
    return agg


def ordered_span_paths(agg) -> list[tuple]:
    """Stable depth-first ordering of a span forest: parents before
    children, siblings by total descending, orphans (parent missing from
    the stream) appended flat."""
    paths = sorted(agg, key=lambda p: (len(p), -agg[p]["total"]))
    ordered: list[tuple] = []

    def add_children(prefix):
        kids = [p for p in paths if len(p) == len(prefix) + 1
                and p[:len(prefix)] == prefix]
        for p in sorted(kids, key=lambda p: -agg[p]["total"]):
            ordered.append(p)
            add_children(p)

    add_children(())
    for p in paths:
        if p not in ordered:
            ordered.append(p)
    return ordered


def dedup_windows(events: list[dict], kind: str = "window") -> list[dict]:
    """Per-window records, last-wins per window index.

    The controller's sink contract (control/controller.py): after a crash
    the append-only tail may repeat the windows between the last snapshot
    and the kill — consumers take the last record per window index.  The
    same contract covers the ``audit`` stream (one record per window)."""
    by_index: dict = {}
    for e in events:
        if e.get("kind") == kind:
            by_index[e.get("window")] = e
    return [by_index[w] for w in sorted(by_index, key=lambda x: (x is None,
                                                                 x))]


def final_counters(events: list[dict]) -> dict[str, float]:
    """Final counter values, summed across runs sharing the stream.

    Each counter event carries its run's *cumulative* value; within one run
    the last event wins, and separate runs (which each restart at zero)
    add.  Caveat: a kill/resume pair counts a crashed run's partial tail in
    both runs' counters — the deduplicated window digest (not the counter
    sums) is the authoritative per-window accounting."""
    per_run: dict[tuple, float] = {}
    for e in events:
        if e.get("kind") == "counter":
            per_run[(e.get("run"), e["name"])] = e["value"]
    totals: dict[str, float] = {}
    for (_, name), v in per_run.items():
        totals[name] = totals.get(name, 0.0) + v
    return totals


def collect(events: list[dict]) -> dict:
    """One structured digest of a telemetry stream.

    Keys: ``spans`` (span forest), ``counters`` (final values),
    ``gauges`` (last value), ``gauge_series`` (every observation, stream
    order), ``hists``, ``hist_buckets`` (merged ``hist_bulk`` aggregates
    per name), ``traces`` ({(run, call): [kmeans_iter events]}),
    ``windows`` / ``audits`` (last-wins per window), ``xla`` (one row per
    (kernel, sig) merging compile and exec events), ``meta`` (last run
    metadata seen).
    """
    gauges: dict[str, float] = {}
    gauge_series: dict[str, list[float]] = {}
    hists: dict[str, list[float]] = {}
    hist_buckets: dict[str, dict] = {}
    traces: dict[tuple, list[dict]] = {}
    xla: dict[tuple, dict] = {}
    meta: dict = {}
    cells: dict[str, dict] = {}
    for e in events:
        kind = e.get("kind")
        if kind == "gauge":
            gauges[e["name"]] = e["value"]
            gauge_series.setdefault(e["name"], []).append(float(e["value"]))
        elif kind == "hist":
            hists.setdefault(e["name"], []).append(float(e["value"]))
        elif kind == "hist_bulk":
            merge_hist_buckets(hist_buckets.setdefault(e["name"], {}), e)
        elif kind == "kmeans_iter":
            traces.setdefault((str(e.get("run")), int(e.get("call", 0))),
                              []).append(e)
        elif kind == "xla":
            row = xla.setdefault((e.get("kernel"), e.get("sig")),
                                 {"kernel": e.get("kernel"),
                                  "sig": e.get("sig")})
            if e.get("event") == "exec":
                # Keep the fastest observed execution: later same-signature
                # captures (fresh process appending to the stream) can only
                # add noise on top of the true cost.
                s = float(e.get("seconds", 0.0))
                if "exec_seconds" not in row or s < row["exec_seconds"]:
                    row["exec_seconds"] = s
            else:
                for key in ("flops", "bytes_accessed", "transcendentals",
                            "argument_bytes", "output_bytes", "temp_bytes",
                            "generated_code_bytes", "lower_seconds",
                            "compile_seconds", "devices",
                            "collective_bytes_per_iter"):
                    if key in e:
                        row[key] = e[key]
        elif kind == "meta" and isinstance(e.get("run"), dict):
            meta = e["run"]
        elif kind == "cell":
            # Scenario-matrix cell records (cdrs scenarios sweep
            # --metrics): last observation per cell name wins, stream
            # order preserved — the rerun-a-failing-cell workflow appends
            # to the same stream.
            cells[str(e.get("cell"))] = e
    return {
        "spans": span_forest(events),
        "counters": final_counters(events),
        "gauges": gauges,
        "gauge_series": gauge_series,
        "hists": hists,
        "hist_buckets": hist_buckets,
        "traces": traces,
        "windows": dedup_windows(events, "window"),
        "audits": dedup_windows(events, "audit"),
        "decisions": dedup_windows(events, "decision_trace"),
        "epoch_pins": _dedup_pins(events),
        "cells": list(cells.values()),
        "xla": [xla[k] for k in sorted(xla, key=lambda t: (str(t[0]),
                                                           str(t[1])))],
        "meta": meta,
    }


def coverage_fingerprint(bits) -> str:
    """The canonical digest of a coverage-bit set (scenario cells'
    ``coverage`` lists — scenarios/harness.py ``coverage_bits``): sha256
    over the sorted newline-joined bits, so any two runs exhibiting the
    same behaviour set hash identically regardless of discovery order.
    The failure-space search keys its corpus (and the
    ``search-s<seed>-<prefix>`` cell names) on this digest."""
    import hashlib

    return hashlib.sha256(
        "\n".join(sorted(set(map(str, bits)))).encode()).hexdigest()


def cells_digest(cells: list[dict]) -> dict | None:
    """Scenario-matrix digest over sweep cell records (``kind: cell`` —
    scenarios/sweep.py).  None when the stream has no cells, so
    non-sweep streams render unchanged everywhere."""
    if not cells:
        return None
    failed = [c for c in cells if not c.get("ok")]
    union = {b for c in cells for b in c.get("coverage") or ()}
    digest = {
        "cells": len(cells),
        "invariants_checked": sum(len(c.get("invariants") or {})
                                  for c in cells),
        "failed": sorted(str(c.get("cell")) for c in failed),
        "failed_invariants": sorted({
            k for c in failed
            for k, v in (c.get("invariants") or {}).items() if not v}),
        "ok": not failed,
        "seconds_total": round(sum(float(c.get("seconds", 0.0))
                                   for c in cells), 3),
    }
    if union:
        digest["coverage_bits"] = len(union)
        digest["fingerprint"] = coverage_fingerprint(union)
    return digest


def serve_digest(windows: list[dict]) -> dict | None:
    """Read-path SLO digest over the serving window records (windows
    carrying ``reads_routed`` — a ``ControllerConfig.serve`` or ``cdrs
    serve`` run).  None when the stream has no serving records, so
    pre-serve streams render unchanged everywhere.  Latency fields are
    None when NO window routed a read (a full-outage run has no latency
    sample — zero would claim a perfect tail); outage windows still
    count toward the unavailable fraction."""
    sw = [w for w in windows if w.get("reads_routed") is not None]
    if not sw:
        return None
    routed = sum(int(w.get("reads_routed", 0)) for w in sw)
    unavail = sum(int(w.get("reads_unavailable", 0)) for w in sw)
    total = routed + unavail
    lat = [w for w in sw if w.get("latency_p99_ms") is not None]
    hot = [w for w in sw if w.get("hotspot_files")]
    last_lat = lat[-1] if lat else {}
    burns = [float(w.get("slo_burn", 0.0)) for w in sw]
    return {
        "windows": len(sw),
        "reads_routed": routed,
        "reads_unavailable": unavail,
        "unavailable_fraction": unavail / total if total else 0.0,
        "latency_p50_ms_last": last_lat.get("latency_p50_ms"),
        "latency_p99_ms_last": last_lat.get("latency_p99_ms"),
        "latency_p99_ms_max": max(
            (float(w["latency_p99_ms"]) for w in lat), default=None),
        "slo_burn_max": max(burns),
        "slo_burn_mean": sum(burns) / len(burns),
        "utilization_max": max(float(w.get("utilization_max", 0.0))
                               for w in sw),
        "hotspot_windows": len(hot),
        "hotspot_files_last": list(hot[-1].get("hotspot_files", ()))
        if hot else [],
        "hotspot_reclusters": sum(
            1 for w in sw if w.get("recluster_trigger") == "hotspot"),
        "locality_last": sw[-1].get("serve_locality"),
        # Integrity layer (0 on rot-free runs): garbage served by the
        # unverified baseline vs detections the verified path redirected.
        "reads_corrupt_served": sum(
            int(w.get("reads_corrupt_served") or 0) for w in sw),
        "reads_corrupt_detected": sum(
            int(w.get("reads_corrupt_detected") or 0) for w in sw),
    }


def storage_digest(windows: list[dict]) -> dict | None:
    """Tier/byte-cost digest over the storage window records (windows
    carrying ``storage`` — a ``ControllerConfig.storage`` run).  None
    when the stream has no storage accounting, so pre-storage streams
    render unchanged everywhere.  The FINAL window is the headline (the
    end state of the run); the max overhead ratio tracks the costliest
    intermediate state (a mid-conversion window can briefly hold both
    shapes of a file)."""
    sw = [w for w in windows if w.get("storage")]
    if not sw:
        return None
    last = sw[-1]["storage"]
    return {
        "windows": len(sw),
        "bytes_raw": last.get("bytes_raw"),
        "bytes_stored_final": last.get("bytes_stored"),
        "overhead_ratio_final": last.get("overhead_ratio"),
        "overhead_ratio_max": max(
            float(w["storage"].get("overhead_ratio", 0.0)) for w in sw),
        "cost_units_final": last.get("cost_units"),
        "ec_files_final": last.get("ec_files"),
        "per_tier_bytes_final": dict(last.get("per_tier_bytes") or {}),
        "per_category_bytes_final": dict(
            last.get("per_category_bytes") or {}),
    }


def integrity_digest(windows: list[dict]) -> dict | None:
    """Data-integrity digest over window records carrying ``integrity``
    (a corrupt-fault or scrub-enabled run — control/controller.py).
    None when the stream has no integrity accounting, so pre-integrity
    streams render unchanged everywhere.  ``corrupt_copies``/``true_lost``
    are GROUND TRUTH the blind durability tiers cannot see; the detection
    totals split by path (scrub scan, verified read, repair source
    check), and ``corrupt_reads_served`` counts the garbage an
    unverified read path put on the wire."""
    iw = [w for w in windows if w.get("integrity")]
    if not iw:
        return None
    last = iw[-1]["integrity"]
    scrubs = [w["scrub"] for w in iw if w.get("scrub")]
    det_scrub = sum(int(w["integrity"].get("detected_scrub", 0))
                    for w in iw)
    det_read = sum(int(w["integrity"].get("detected_read", 0)) for w in iw)
    det_repair = sum(int(w["integrity"].get("detected_repair", 0))
                     for w in iw)
    return {
        "windows": len(iw),
        "corrupt_copies_final": last.get("corrupt_copies", 0),
        "corrupt_copies_max": max(
            int(w["integrity"].get("corrupt_copies", 0)) for w in iw),
        "files_corrupt_final": last.get("files_corrupt", 0),
        "true_lost_final": last.get("true_lost", 0),
        "true_lost_max": max(int(w["integrity"].get("true_lost", 0))
                             for w in iw),
        "detected_scrub": det_scrub,
        "detected_read": det_read,
        "detected_repair": det_repair,
        "detected_total": det_scrub + det_read + det_repair,
        "corrupt_reads_served": sum(
            int(w.get("reads_corrupt_served") or 0) for w in iw),
        "scrub_bytes_total": sum(int(s.get("bytes", 0)) for s in scrubs),
        "scrub_copies_verified": sum(int(s.get("copies_verified", 0))
                                     for s in scrubs),
        "scrub_starved_windows": sum(1 for s in scrubs if s.get("starved")),
    }


def pacing_digest(windows: list[dict]) -> dict | None:
    """End-to-end pacing digest over window records carrying the PR-8
    per-window ``seconds`` dict: windows per second of host wall-clock
    plus the planning slice of it (the SoA control-plane observable).
    None when no window carries timing, so older streams render
    unchanged.  The plan fraction is computed over the windows that
    RECORD a plan slice — a stream resumed across the PR-8 boundary must
    not dilute the fraction with untimed windows."""
    secs = [w["seconds"] for w in windows
            if isinstance(w.get("seconds"), dict)
            and w["seconds"].get("total")]
    total = sum(s["total"] for s in secs)
    if not secs or total <= 0:
        return None
    out = {"windows": len(secs),
           "windows_per_sec": len(secs) / total}
    plan = [float(s["plan"]) for s in secs if "plan" in s]
    if plan:
        plan_total = sum(s["total"] for s in secs if "plan" in s)
        out["plan_p50_seconds"] = percentile(plan, 0.5)
        out["plan_seconds_fraction"] = (sum(plan) / plan_total
                                        if plan_total > 0 else 0.0)
    # Mesh runs stamp devices + the per-Lloyd-iteration collective-bytes
    # estimate on every window record (controller): surface them here so
    # windows/sec reads against mesh size.  Mesh-less streams carry no
    # ``mesh`` key and render unchanged.
    mesh = [w["mesh"] for w in windows if isinstance(w.get("mesh"), dict)]
    if mesh:
        out["devices"] = int(mesh[-1].get("devices", 1))
        out["collective_bytes_per_iter"] = int(
            mesh[-1].get("collective_bytes_per_iter", 0))
    return out


def _dedup_pins(events: list[dict]) -> list[dict]:
    """``epoch_pin`` events, last-wins per epoch id (a crashed run's
    replayed tail may repeat epoch ids — the window-dedup contract,
    applied to the pin stream's natural key)."""
    by_eid: dict = {}
    for e in events:
        if e.get("kind") == "epoch_pin":
            by_eid[e.get("epoch_id")] = e
    return [by_eid[k] for k in sorted(by_eid, key=lambda x: (x is None,
                                                             x))]


def critical_path_digest(decisions: list[dict],
                         windows: list[dict] | None = None) -> dict | None:
    """Critical-path latency attribution over ``decision_trace`` records
    (obs/trace.py — a traced daemon run).  None when the stream has no
    decisions, so untraced streams render unchanged everywhere.

    Every decision's integer-ns segments MUST telescope to its measured
    total (the emitter's one-clock contract); the digest re-checks that
    here and reports any mismatch instead of silently renormalizing —
    the same discipline as the PR-15 ``causes`` byte reconciliation.
    Stage shares are time-weighted across all decisions, with the
    ``decide`` segment expanded into the controller's per-stage seconds
    when the window records are available to join."""
    if not decisions:
        return None
    from .trace import SEGMENT_ORDER, STAGE_ORDER

    mismatches = [d for d in decisions
                  if sum(int(v) for v in
                         (d.get("segments_ns") or {}).values())
                  != int(d.get("total_ns", -1))]
    totals = [int(d.get("total_ns", 0)) / 1e9 for d in decisions]
    grand_ns = sum(int(d.get("total_ns", 0)) for d in decisions)
    by_win = {w.get("window"): w for w in (windows or [])}
    # Time-weighted attribution: coarse daemon segments, with ``decide``
    # split by the joined window's controller stage seconds (scaled so
    # the split still sums to the decide segment exactly in expectation;
    # shares are reporting, the ns reconciliation above is the invariant).
    acc: dict[str, float] = {}
    for d in decisions:
        segs = d.get("segments_ns") or {}
        for name, ns in segs.items():
            if name == "decide":
                w = by_win.get(d.get("window"))
                secs = (w or {}).get("seconds") \
                    if isinstance((w or {}).get("seconds"), dict) else None
                stage_sum = sum(float(secs[k]) for k in secs
                                if k != "total") if secs else 0.0
                if secs and stage_sum > 0:
                    for k, v in secs.items():
                        if k != "total":
                            acc[k] = acc.get(k, 0.0) \
                                + float(v) / stage_sum * int(ns)
                    continue
            acc[name] = acc.get(name, 0.0) + int(ns)
    order = [s for s in SEGMENT_ORDER if s != "decide"] \
        + list(STAGE_ORDER) + ["decide"]
    known = [k for k in order if k in acc] \
        + sorted(k for k in acc if k not in order)
    shares = {k: acc[k] / grand_ns for k in known} if grand_ns else {}
    exemplars = sorted(
        (d for d in decisions if d.get("exemplar")),
        key=lambda d: -int(d.get("total_ns", 0)))
    return {
        "decisions": len(decisions),
        "reconciled": not mismatches,
        "reconcile_mismatches": len(mismatches),
        "total_p50_seconds": percentile(totals, 0.5),
        "total_p99_seconds": percentile(totals, 0.99),
        "stage_shares": shares,
        "exemplars": [{"trace": d.get("trace"),
                       "window": d.get("window"),
                       "total_seconds": int(d.get("total_ns", 0)) / 1e9}
                      for d in exemplars],
    }


def daemon_digest(decisions: list[dict],
                  epoch_pins: list[dict] | None = None) -> dict | None:
    """Streaming-daemon digest over the trace stream: publications,
    serve-path pin coverage, and the event-to-decision latency tail.
    None when the stream has no decisions (a batch run), so non-daemon
    streams render unchanged everywhere.  ``epochs_published`` is the
    max epoch id seen — the daemon-LIFETIME publication sequence, exact
    across checkpoint/resume where counter sums double-count a crashed
    tail."""
    if not decisions:
        return None
    pins = epoch_pins or []
    totals = [int(d.get("total_ns", 0)) / 1e9 for d in decisions]
    p2p = [int(p["publish_to_pin_ns"]) / 1e9 for p in pins
           if p.get("publish_to_pin_ns") is not None]
    return {
        "decisions": len(decisions),
        "epochs_published": max(int(d.get("epoch_id", 0))
                                for d in decisions),
        "epochs_pinned": len(pins),
        "event_to_decision_p50_seconds": percentile(totals, 0.5),
        "event_to_decision_p99_seconds": percentile(totals, 0.99),
        "publish_to_pin_p50_seconds": (percentile(p2p, 0.5)
                                       if p2p else None),
    }


def roofline_rows(digest: dict, peak_flops: float | None = None,
                  peak_gbps: float | None = None) -> list[dict]:
    """Roofline verdict per captured XLA program.

    Each row extends the ``xla`` cost row with ``intensity`` (flops/byte),
    achieved ``gflops``/``gbps`` when an execution sample exists, and —
    when the chip's peaks are known (obs/xprof.DEVICE_PEAKS via the
    stream's run metadata, or the explicit overrides) — the roofline-
    attainable FLOP/s ``min(peak_flops, intensity · peak_bw)``, the
    achieved fraction of it, and the ``bound`` classification
    (memory/compute side of the ridge point).
    """
    from .xprof import resolve_peaks

    peaks = resolve_peaks(digest.get("meta", {}).get("jax_device_kind"))
    # Explicit overrides win per side; the known-chip table fills whichever
    # side was not given (a single --peak_flops on a known chip must not
    # silently disable the whole verdict).
    if peak_flops is None and peaks:
        peak_flops = peaks[0]
    peak_bw = peak_gbps * 1e9 if peak_gbps else (peaks[1] if peaks
                                                 else None)
    rows = []
    for x in digest.get("xla", []):
        row = dict(x)
        flops = x.get("flops")
        bytes_acc = x.get("bytes_accessed")
        if flops and bytes_acc:
            row["intensity"] = flops / bytes_acc
        secs = x.get("exec_seconds")
        if secs and flops:
            row["gflops"] = flops / secs / 1e9
        if secs and bytes_acc:
            row["gbps"] = bytes_acc / secs / 1e9
        if peak_flops and peak_bw and "intensity" in row:
            attainable = min(peak_flops, row["intensity"] * peak_bw)
            row["attainable_gflops"] = attainable / 1e9
            row["bound"] = ("compute" if row["intensity"] * peak_bw
                            >= peak_flops else "memory")
            if "gflops" in row:
                row["peak_fraction"] = row["gflops"] * 1e9 / attainable
        rows.append(row)
    return rows
