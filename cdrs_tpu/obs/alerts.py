"""Streaming alert evaluation over the telemetry event stream.

Yuan et al. (OSDI 2014, PAPERS.md) measured that most catastrophic
distributed-system failures announce themselves in logs long before the
data is gone; in this repo those announcements — durability tiers
degrading, the SLO error budget burning, the scrubber starving, repair
backlogs pinned — already ride the JSONL stream, but until now nobody
watched them until a bench failed.  This module turns the stream into
verdicts: declarative :class:`AlertRule` s evaluated **incrementally**
over window records (one ``observe`` per event — the same shape
``obs.sink.iter_events`` yields, so batch files, live tails and
in-memory controller records all evaluate identically).

Three rule kinds:

* ``threshold`` — a dotted ``field`` path into the window record (or a
  list of paths, summed) compared against ``value``; fires after
  ``for_windows`` CONSECUTIVE windows satisfy the predicate and resolves
  on the first window that does not.  The streak requirement is the
  standard anti-flap guard: one noisy window must not page.
* ``burn_rate`` — the SRE multi-window burn-rate pair over the serve
  layer's :class:`~cdrs_tpu.serve.SloSpec` accounting: ``slo_burn`` is
  already "fraction of the error budget this window consumed", so the
  rule fires when BOTH the short (``short_windows``) and long
  (``long_windows``) trailing means are at/above ``factor``, and
  resolves when the short mean drops below it — the fast window gives
  detection latency, the long window keeps a single spike from paging
  (Google SRE workbook ch. 5, transplanted from wall-clock windows to
  controller windows).  Windows without serving data are skipped, not
  counted as zero.
* ``absence`` — staleness: in a follow session the rule fires when no
  window record arrives for ``stale_seconds`` of wall clock; in batch
  evaluation it fires only when the stream contains NO window records at
  all (a dead producer), so offline verdicts stay deterministic.

Rules round-trip through JSON (``cdrs metrics alerts --rules FILE``);
:func:`default_rules` is the built-in set every surface shares —
``cdrs metrics alerts`` (batch + ``--follow``), the ``watch`` dashboard,
the HTML report's alert section, the Prometheus ``ALERTS`` export, and
the scenario harness's positive-engagement alert invariants (a
designed-bad cell must fire its expected alert; a healthy cell must
stay silent).
"""

from __future__ import annotations

import json
import time
from dataclasses import asdict, dataclass, field

__all__ = ["AlertRule", "AlertEngine", "default_rules", "rules_from_json",
           "evaluate_records", "DEFAULT_RULE_NAMES", "SEVERE_ALERTS"]

_KINDS = ("threshold", "burn_rate", "absence")
_OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b,
}

#: Alerts whose firing means data is (or silently went) missing — the
#: default "must stay silent" set the scenario harness gates healthy
#: cells on.
SEVERE_ALERTS = frozenset({"files_lost", "true_lost"})


@dataclass(frozen=True)
class AlertRule:
    """One declarative rule (see module docstring for kind semantics)."""

    name: str
    kind: str = "threshold"
    #: Dotted path into the window record (``"durability.lost"``), or a
    #: tuple of paths summed (missing components count 0; a record where
    #: EVERY component is missing does not match the rule at all).
    field: str | tuple[str, ...] | None = None
    op: str = ">"
    value: float = 0.0
    #: Consecutive matching windows before a threshold rule fires.
    for_windows: int = 1
    #: Burn-rate pair (window counts, not wall-clock).
    short_windows: int = 1
    long_windows: int = 1
    factor: float = 1.0
    #: Absence rule: wall-clock staleness bound of a follow session.
    stale_seconds: float = 600.0
    #: ``page`` (wake a human) or ``ticket`` (look during business hours).
    severity: str = "ticket"

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(
                f"alert {self.name!r}: unknown kind {self.kind!r} "
                f"(want one of {_KINDS})")
        if self.kind == "threshold":
            if self.field is None:
                raise ValueError(
                    f"alert {self.name!r}: threshold rules need a field")
            if self.op not in _OPS:
                raise ValueError(
                    f"alert {self.name!r}: unknown op {self.op!r} "
                    f"(want one of {sorted(_OPS)})")
            if self.for_windows < 1:
                raise ValueError(
                    f"alert {self.name!r}: for_windows must be >= 1")
        if self.kind == "burn_rate":
            if not 1 <= self.short_windows <= self.long_windows:
                raise ValueError(
                    f"alert {self.name!r}: need 1 <= short_windows <= "
                    f"long_windows, got {self.short_windows}/"
                    f"{self.long_windows}")
            if self.factor <= 0:
                raise ValueError(
                    f"alert {self.name!r}: factor must be > 0")
        if self.kind == "absence" and self.stale_seconds <= 0:
            raise ValueError(
                f"alert {self.name!r}: stale_seconds must be > 0")
        if self.severity not in ("page", "ticket"):
            raise ValueError(
                f"alert {self.name!r}: severity must be 'page' or "
                f"'ticket', got {self.severity!r}")
        if isinstance(self.field, list):
            # JSON delivers lists; the dataclass is hashable/frozen with
            # tuples.
            object.__setattr__(self, "field", tuple(self.field))

    def to_dict(self) -> dict:
        d = asdict(self)
        if isinstance(d["field"], tuple):
            d["field"] = list(d["field"])
        return d


def default_rules() -> tuple[AlertRule, ...]:
    """The built-in ruleset every surface shares.

    Thresholds follow the audit flags' semantics (obs/audit.py) where one
    exists — the alert is the *streaming* form of the same verdict; the
    burn-rate pair follows the SRE fast/slow convention scaled to
    controller windows."""
    R = AlertRule
    return (
        # Data is gone (blind tier) / silently gone (ground truth).
        R("files_lost", field="durability.lost", severity="page"),
        R("true_lost", field="integrity.true_lost", severity="page"),
        # Redundancy below target anywhere: the Yuan-et-al. announcement
        # that precedes loss.
        R("durability_degraded",
          field=("durability.lost", "durability.at_risk",
                 "durability.under_replicated")),
        R("unreachable_stranded", field="durability.unreachable"),
        R("correlated_risk", field="durability.correlated_risk",
          for_windows=2),
        R("repair_backlog", field="repair_backlog", for_windows=3),
        R("budget_saturated", field="deferred_budget", for_windows=3),
        # Decision lag: the daemon has fallen >= 2 windows behind the
        # log head for 2 consecutive windows.  The field only exists on
        # brownout-enabled daemon records, so batch streams and plain
        # controller runs never match (rule not applicable, by the
        # _resolve None contract).
        R("daemon_lagging", field="daemon.lag_windows", value=2.0,
          op=">=", for_windows=2),
        R("scrub_starved", field="scrub.starved", for_windows=2),
        R("corruption_detected",
          field=("integrity.detected_scrub", "integrity.detected_read",
                 "integrity.detected_repair"), severity="page"),
        R("reads_unavailable", field="reads_unavailable",
          severity="page"),
        R("slo_burn_fast", kind="burn_rate", field="slo_burn",
          short_windows=1, long_windows=3, factor=2.0, severity="page"),
        R("slo_burn_slow", kind="burn_rate", field="slo_burn",
          short_windows=2, long_windows=6, factor=1.0),
        # Stage-latency SLOs over the per-window ``seconds`` breakdown
        # (the decision trace's critical-path stages — obs/trace.py): a
        # sustained planning or whole-decision stall is a control-plane
        # regression worth a ticket long before it pages anyone.
        # Thresholds sit far above any healthy windowed run (ci-smoke
        # cells decide in milliseconds) so they only engage on real
        # stalls; the streak is the standard anti-flap guard.
        R("stage_plan_latency", field="seconds.plan", value=2.0,
          for_windows=3),
        R("decision_latency", field="seconds.total", value=10.0,
          for_windows=3),
        R("no_data", kind="absence", stale_seconds=600.0),
    )


DEFAULT_RULE_NAMES: frozenset = frozenset(r.name for r in default_rules())


def rules_from_json(obj) -> tuple[AlertRule, ...]:
    """Rules from a JSON list (the ``--rules FILE`` format: a list of
    :meth:`AlertRule.to_dict` objects; unknown keys error by name)."""
    if isinstance(obj, str):
        obj = json.loads(obj)
    if not isinstance(obj, list):
        raise ValueError("alert rules JSON must be a list of rule objects")
    allowed = {f.name for f in AlertRule.__dataclass_fields__.values()}
    rules = []
    for d in obj:
        unknown = set(d) - allowed
        if unknown:
            raise ValueError(
                f"alert rule {d.get('name', '?')!r}: unknown keys "
                f"{sorted(unknown)}")
        rules.append(AlertRule(**d))
    names = [r.name for r in rules]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate alert rule names in {names}")
    return tuple(rules)


def _resolve(rec: dict, path) -> float | None:
    """Value of a dotted path (or summed tuple of paths) in a window
    record.  None = the record does not carry the field(s) at all — the
    rule is not applicable to this window (a serve rule on a serve-less
    stream must neither fire nor resolve)."""
    if isinstance(path, tuple):
        vals = [_resolve(rec, p) for p in path]
        live = [v for v in vals if v is not None]
        return sum(live) if live else None
    cur = rec
    for part in str(path).split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    if cur is None:
        return None
    if isinstance(cur, bool):
        return 1.0 if cur else 0.0
    try:
        return float(cur)
    except (TypeError, ValueError):
        return None


class _RuleState:
    __slots__ = ("streak", "window_values", "firing", "fired", "since",
                 "transitions")

    def __init__(self):
        self.streak = 0
        self.window_values: list[float] = []
        self.firing = False
        self.fired = False
        self.since: int | None = None
        self.transitions: list[dict] = []


class AlertEngine:
    """Incremental evaluator: feed it events (``observe``), read verdicts
    (``results``).  One instance per stream; state is O(rules)."""

    def __init__(self, rules=None):
        self.rules: tuple[AlertRule, ...] = tuple(rules) \
            if rules is not None else default_rules()
        self._st: dict[str, _RuleState] = {r.name: _RuleState()
                                           for r in self.rules}
        self.windows_seen = 0
        self._last_window_wall: float | None = None

    # -- transitions -------------------------------------------------------
    def _fire(self, rule: AlertRule, st: _RuleState, window,
              value) -> dict:
        st.firing = True
        st.fired = True
        st.since = window
        t = {"alert": rule.name, "state": "firing", "window": window,
             "severity": rule.severity}
        if value is not None:
            t["value"] = round(float(value), 6)
        st.transitions.append(t)
        return t

    def _resolve_alert(self, rule: AlertRule, st: _RuleState,
                       window) -> dict:
        st.firing = False
        t = {"alert": rule.name, "state": "resolved", "window": window,
             "severity": rule.severity}
        st.transitions.append(t)
        return t

    # -- evaluation --------------------------------------------------------
    def observe(self, event: dict) -> list[dict]:
        """Evaluate one stream event; returns the state transitions it
        caused (empty for non-window events)."""
        if event.get("kind") != "window":
            return []
        self.windows_seen += 1
        self._last_window_wall = time.monotonic()
        w = event.get("window")
        out: list[dict] = []
        for rule in self.rules:
            st = self._st[rule.name]
            if rule.kind == "threshold":
                v = _resolve(event, rule.field)
                hit = v is not None and _OPS[rule.op](v, rule.value)
                st.streak = st.streak + 1 if hit else 0
                if not st.firing and st.streak >= rule.for_windows:
                    out.append(self._fire(rule, st, w, v))
                elif st.firing and not hit:
                    out.append(self._resolve_alert(rule, st, w))
            elif rule.kind == "burn_rate":
                v = _resolve(event, rule.field or "slo_burn")
                if v is None:
                    continue  # not a serving window: no burn observation
                st.window_values.append(v)
                del st.window_values[:-rule.long_windows]
                vals = st.window_values
                if len(vals) < rule.long_windows:
                    # Until the long window has real history its mean
                    # would collapse onto the short one and the
                    # anti-spike guard would be vacuous — a stream's
                    # very first hot window must not page.
                    continue
                short = sum(vals[-rule.short_windows:]) / rule.short_windows
                long_ = sum(vals) / len(vals)
                if not st.firing and short >= rule.factor \
                        and long_ >= rule.factor:
                    out.append(self._fire(rule, st, w, short))
                elif st.firing and short < rule.factor:
                    out.append(self._resolve_alert(rule, st, w))
            # absence rules react to the CLOCK, not to window content
            # (arriving data resolves them).
            elif st.firing:
                out.append(self._resolve_alert(rule, st, w))
        return out

    def check_staleness(self, now: float | None = None) -> list[dict]:
        """Follow-mode staleness poll: fire absence rules whose
        ``stale_seconds`` elapsed since the last window record (or since
        this engine started watching, when none arrived yet)."""
        now = time.monotonic() if now is None else now
        if self._last_window_wall is None:
            self._last_window_wall = now
            return []
        out = []
        for rule in self.rules:
            if rule.kind != "absence":
                continue
            st = self._st[rule.name]
            stale = now - self._last_window_wall >= rule.stale_seconds
            if stale and not st.firing:
                out.append(self._fire(rule, st, None,
                                      now - self._last_window_wall))
        return out

    def finish(self) -> list[dict]:
        """End-of-stream (batch mode): absence rules fire iff the stream
        carried no window records at all — a dead or misdirected
        producer, the one staleness verdict batch evaluation can make
        deterministically."""
        out = []
        if self.windows_seen == 0:
            for rule in self.rules:
                st = self._st[rule.name]
                if rule.kind == "absence" and not st.firing:
                    out.append(self._fire(rule, st, None, None))
        return out

    def results(self) -> list[dict]:
        """Per-rule verdicts, rule order: ``{name, severity, kind,
        firing, fired, since, streak, transitions}``."""
        out = []
        for rule in self.rules:
            st = self._st[rule.name]
            out.append({
                "name": rule.name,
                "severity": rule.severity,
                "kind": rule.kind,
                "firing": st.firing,
                "fired": st.fired,
                "since": st.since,
                "streak": st.streak,
                "transitions": list(st.transitions),
            })
        return out


def firing_spans(transitions: list[dict]) -> list[tuple]:
    """Pair each firing transition with its resolution: ``[(start_window,
    end_window | None), ...]`` — ``None`` end = still firing.  The ONE
    fold behind every span rendering (CLI digest, HTML report)."""
    spans: list[tuple] = []
    start = None
    for t in transitions:
        if t["state"] == "firing":
            start = t["window"]
        else:
            spans.append((start, t["window"]))
            start = None
    if start is not None:
        spans.append((start, None))
    return spans


def evaluate_records(records: list[dict], rules=None) -> list[dict]:
    """Batch verdicts over window records (controller ``res.records`` or
    a dedup'd stream): the ONE evaluation the scenario harness, the CLI
    batch mode, ``watch``, the HTML report and the Prometheus export all
    share."""
    eng = AlertEngine(rules)
    for r in records:
        eng.observe(r if r.get("kind") == "window"
                    else {"kind": "window", **r})
    eng.finish()
    return eng.results()
