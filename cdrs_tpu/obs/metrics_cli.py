"""``cdrs metrics`` — human and scraper consumption of telemetry JSONL.

Subcommands:

* ``summarize FILE`` — per-span wall-clock tree (aggregated over repeated
  spans), counters, gauges, histogram p50/p95, kmeans convergence traces,
  XLA cost/roofline lines (obs/xprof.py captures), the decision-quality
  audit digest, an alert digest, and a controller-window digest.
* ``tail FILE [-n N]`` — the last N events, one compact line each.
* ``export FILE --format prometheus [--out FILE]`` — Prometheus textfile
  exposition (node_exporter textfile-collector compatible): counters,
  gauges, histogram summaries, and ``ALERTS`` gauges for firing alerts.
* ``report FILE [-o HTML]`` — self-contained static HTML report
  (obs/report.py): span tree, gauge sparklines, audit timeline, alert
  timeline, roofline table.
* ``watch FILE`` — live terminal view tailing a running producer's stream
  (obs/sink.iter_events), firing/resolved alerts included.
* ``alerts FILE [--follow]`` — evaluate the declarative AlertRules
  (obs/alerts.py: thresholds, SRE burn-rate pairs over the SloSpec error
  budget, staleness) over the stream: batch verdicts with a transition
  timeline, or a live follow session printing transitions as they land.
* ``regress RUN.json`` — compare a fresh bench run against the recorded
  trajectory bands (benchmarks/regress.py); nonzero exit on regression.

The readers are resilient by construction: unknown ``kind``s are ignored
(forward compatibility) and a torn final line from a killed writer is
skipped (sink contract, obs/sink.py); a missing/empty/unparseable stream
is a clean one-line error naming the path, never a traceback.
"""

from __future__ import annotations

import argparse
import json
import sys

from .aggregate import (
    bucket_percentile,
    collect,
    critical_path_digest,
    daemon_digest,
    dedup_windows,
    final_counters,
    fmt_bytes,
    ordered_span_paths,
    pacing_digest,
    percentile,
    roofline_rows,
    serve_digest,
    span_forest,
    storage_digest,
)
from .sink import read_events

__all__ = ["main", "summarize_events", "prometheus_lines"]

# Backwards-compatible aliases (the aggregation moved to obs/aggregate.py).
_percentile = percentile
_span_forest = span_forest
_dedup_windows = dedup_windows
_final_counters = final_counters


# -- summarize ---------------------------------------------------------------


def _render_span_tree(agg, out) -> None:
    for path in ordered_span_paths(agg):
        node = agg[path]
        indent = "  " * (len(path) - 1)
        calls = f" x{node['count']}" if node["count"] > 1 else ""
        print(f"  {indent}{path[-1]:<{max(1, 28 - len(indent))}} "
              f"{node['total']:>9.3f}s{calls}", file=out)


def _fmt_bytes(b) -> str:
    return fmt_bytes(b, sep="")


def _render_roofline(digest, out, peak_flops=None, peak_gbps=None) -> None:
    rows = roofline_rows(digest, peak_flops, peak_gbps)
    if not rows:
        return
    print("\nXLA kernel costs (roofline):", file=out)
    for r in rows:
        parts = [f"  {r['kernel']:<22}"]
        if "flops" in r:
            parts.append(f"flops={r['flops']:.4g}")
        if "bytes_accessed" in r:
            parts.append(f"bytes={_fmt_bytes(r['bytes_accessed'])}")
        if "intensity" in r:
            parts.append(f"I={r['intensity']:.2f}f/B")
        if "temp_bytes" in r:
            parts.append(f"temp={_fmt_bytes(r['temp_bytes'])}")
        if "compile_seconds" in r:
            parts.append(f"compile={r['compile_seconds']:.3g}s")
        if "gflops" in r:
            parts.append(f"achieved={r['gflops']:.3g}GF/s")
        if "peak_fraction" in r:
            parts.append(f"{100 * r['peak_fraction']:.1f}% of "
                         f"{r['attainable_gflops']:.4g}GF/s "
                         f"({r['bound']}-bound)")
        print(" ".join(parts), file=out)


def _render_checkpoint(digest, out) -> None:
    """Checkpoint-size digest (utils/checkpoint.save_state gauges) — the
    observable the functional placement mode's O(exceptions) snapshot
    claim is measured by."""
    g = digest["gauges"]
    if "checkpoint.bytes" not in g:
        return
    saves = int(digest["counters"].get("checkpoint.saves", 0))
    line = (f"\nCheckpoint: last snapshot "
            f"{_fmt_bytes(g['checkpoint.bytes'])}")
    if saves:
        line += f" over {saves} saves"
    secs = g.get("checkpoint.save_seconds")
    if secs is not None:
        line += f", last save {secs:.3f}s"
    print(line, file=out)


def _render_serving(windows: list[dict], out) -> None:
    """Read-path SLO digest (serving window records from a
    ``ControllerConfig.serve`` / ``cdrs serve`` run)."""
    d = serve_digest(windows)
    if d is None:
        return

    def g(v):  # latency fields are None for windows that routed nothing
        return "—" if v is None else f"{v:g}"

    print(f"\nServing: {d['reads_routed']} reads routed over "
          f"{d['windows']} windows "
          f"({d['reads_unavailable']} unavailable, fraction "
          f"{d['unavailable_fraction']:.4g})", file=out)
    print(f"  latency p50 {g(d['latency_p50_ms_last'])} ms, "
          f"p99 {g(d['latency_p99_ms_last'])} ms last window "
          f"(worst-window p99 {g(d['latency_p99_ms_max'])} ms)", file=out)
    line = (f"  SLO burn max {d['slo_burn_max']:.3g} "
            f"(mean {d['slo_burn_mean']:.3g}); "
            f"utilization max {d['utilization_max']:.3g}")
    if d.get("locality_last") is not None:
        line += f"; locality {d['locality_last']:.4g}"
    print(line, file=out)
    if d["hotspot_windows"]:
        print(f"  hotspots: {d['hotspot_windows']} windows fired "
              f"(last files {d['hotspot_files_last']}), "
              f"{d['hotspot_reclusters']} hotspot-triggered reclusters",
              file=out)


def _render_storage(windows: list[dict], out) -> None:
    """Tier/byte-cost digest (storage window records from a
    ``ControllerConfig.storage`` / ``--storage_config`` run)."""
    d = storage_digest(windows)
    if d is None:
        return
    print(f"\nStorage: {_fmt_bytes(d['bytes_stored_final'])} stored for "
          f"{_fmt_bytes(d['bytes_raw'])} raw "
          f"({d['overhead_ratio_final']:g}x, max "
          f"{d['overhead_ratio_max']:g}x; cost "
          f"{d['cost_units_final']:g} units)", file=out)
    tiers = ", ".join(f"{t}={_fmt_bytes(b)}" for t, b in
                      sorted(d["per_tier_bytes_final"].items()))
    line = f"  tiers: {tiers or '—'}"
    if d["ec_files_final"]:
        line += f"; {d['ec_files_final']} erasure-coded files"
    print(line, file=out)


def _render_durability(windows: list[dict], out) -> None:
    """Fault-mode digest: durability tiers, outage span, repair traffic
    (window records from a ``cdrs chaos`` / fault-schedule run)."""
    dur_w = [w for w in windows if w.get("durability")]
    if not dur_w:
        return
    last = dur_w[-1]["durability"]
    lost_max = max(w["durability"]["lost"] for w in dur_w)
    degraded = sum(1 for w in dur_w
                   if w["durability"]["lost"]
                   or w["durability"]["at_risk"]
                   or w["durability"]["under_replicated"])
    rep_bytes = sum(int(w.get("repair_bytes", 0)) for w in windows)
    rep_moves = sum(int(w.get("repair_moves", 0)) for w in windows)
    rep_failed = sum(int(w.get("repair_failed", 0)) for w in windows)
    faults = sum(len(w.get("fault_events") or ()) for w in windows)
    unavail = sum(int(w.get("unavailable_reads", 0)) for w in windows)
    print(f"\nDurability: {faults} fault events over {len(dur_w)} windows, "
          f"{degraded} degraded (max {lost_max} lost)", file=out)
    print(f"  final: {last['lost']} lost / {last['at_risk']} at-risk / "
          f"{last['under_replicated']} under-replicated "
          f"({last['nodes_up']} nodes up)", file=out)
    line = (f"  repair: {rep_moves} replicas, {_fmt_bytes(rep_bytes)}"
            + (f", {rep_failed} failed copies" if rep_failed else ""))
    if unavail:
        # Normalized by the reads actually presented, so runs of
        # different lengths compare: raw counts alone are meaningless
        # across a 5-window smoke and a 500-window soak.  Older streams
        # without per-window ``n_reads`` fall back to the event count (an
        # upper bound on reads — the fraction reads as a floor).
        reads = sum(int(w.get("n_reads", 0)) for w in windows)
        denom = reads or sum(int(w.get("n_events", 0)) for w in windows)
        frac = f" (fraction {unavail / denom:.4g})" if denom else ""
        line += f"; {unavail} reads hit unreadable files{frac}"
    print(line, file=out)
    part_w = sum(1 for w in dur_w
                 if w["durability"].get("nodes_partitioned"))
    stalled = sum(int(w.get("repair_deferred_partition", 0))
                  for w in windows)
    rebal = sum(int(w.get("repair_rebalanced", 0)) for w in windows)
    corr_max = max((w["durability"].get("correlated_risk", 0)
                    for w in dur_w), default=0)
    if part_w or stalled or rebal or corr_max:
        print(f"  domains: {part_w} partitioned windows, {stalled} "
              f"partition-stalled repairs, {rebal} spread rebalances, "
              f"correlated-risk max {corr_max} "
              f"(final {last.get('correlated_risk', 0)})", file=out)


def _render_integrity(windows: list[dict], out) -> None:
    """Integrity digest: silent corruption vs detection (window records
    from a corrupt-fault / scrub-enabled run)."""
    from .aggregate import integrity_digest

    d = integrity_digest(windows)
    if d is None:
        return
    print(f"\nIntegrity: {d['corrupt_copies_max']} corrupt copies max "
          f"(final {d['corrupt_copies_final']}), true losses max "
          f"{d['true_lost_max']} (final {d['true_lost_final']})", file=out)
    print(f"  detected: {d['detected_total']} "
          f"(scrub {d['detected_scrub']}, read {d['detected_read']}, "
          f"repair {d['detected_repair']}); "
          f"{d['corrupt_reads_served']} corrupt reads served", file=out)
    if d["scrub_copies_verified"]:
        line = (f"  scrub: {d['scrub_copies_verified']} copies verified, "
                f"{_fmt_bytes(d['scrub_bytes_total'])} read")
        if d["scrub_starved_windows"]:
            line += f", starved {d['scrub_starved_windows']} windows"
        print(line, file=out)


def _render_cells(cells: list[dict], out) -> None:
    """Scenario-matrix digest (sweep cell records, ``kind: cell``)."""
    from .aggregate import cells_digest

    d = cells_digest(cells)
    if d is None:
        return
    verdict = "all green" if d["ok"] else \
        f"FAILED {len(d['failed'])}: {', '.join(d['failed'])}"
    print(f"\nScenarios: {d['cells']} cells, "
          f"{d['invariants_checked']} invariants checked — {verdict} "
          f"({d['seconds_total']:.1f}s)", file=out)
    if d.get("coverage_bits"):
        print(f"  coverage: {d['coverage_bits']} fingerprint bits "
              f"(fp {d['fingerprint'][:12]})", file=out)
    if d["failed_invariants"]:
        print(f"  failed invariants: "
              f"{', '.join(d['failed_invariants'])}", file=out)


def _render_alerts(windows: list[dict], out) -> None:
    """Alert digest: the default rules (obs/alerts.py) evaluated over
    the stream's window records — fired alerts with their transition
    spans, and whatever is still firing at end of stream."""
    from .alerts import evaluate_records, firing_spans

    if not windows:
        return
    res = [r for r in evaluate_records(windows) if r["fired"]]
    if not res:
        return
    firing = [r for r in res if r["firing"]]
    print(f"\nAlerts: {len(res)} fired "
          f"({len(firing)} still firing at end of stream)", file=out)
    for r in res:
        spans = [f"w{a}->w{b}" if b is not None
                 else f"w{a}->(still firing)"
                 for a, b in firing_spans(r["transitions"])]
        print(f"  {r['name']:<24} [{r['severity']}] "
              f"{', '.join(spans)}", file=out)


def _render_audit(audits: list[dict], out) -> None:
    if not audits:
        return
    flagged = [a for a in audits if a.get("flags")]
    sils = [a["silhouette"] for a in audits if a.get("silhouette")
            is not None]
    line = f"\nAudit: {len(audits)} windows"
    if sils:
        line += (f", silhouette {sils[0]:.3f} -> {sils[-1]:.3f}"
                 f" (min {min(sils):.3f})")
    last = audits[-1]
    if last.get("category_entropy") is not None:
        line += f", entropy {last['category_entropy']:.3f}"
    print(line, file=out)
    if flagged:
        print(f"  anomalies in {len(flagged)} windows:", file=out)
        for a in flagged:
            print(f"    window {a.get('window')}: "
                  f"{', '.join(a['flags'])}", file=out)
    else:
        print("  no anomalies flagged", file=out)


def _render_daemon(digest: dict, out) -> None:
    """Streaming-daemon + critical-path digest lines (traced streams
    only — obs/trace.py; untraced streams render unchanged)."""
    dd = daemon_digest(digest.get("decisions") or [],
                       digest.get("epoch_pins") or [])
    if dd is None:
        return
    line = (f"\nDaemon: {dd['decisions']} traced decisions, "
            f"{dd['epochs_published']} epochs published, "
            f"{dd['epochs_pinned']} pinned; event-to-decision "
            f"p50 {dd['event_to_decision_p50_seconds']:.4g}s / "
            f"p99 {dd['event_to_decision_p99_seconds']:.4g}s")
    if dd.get("publish_to_pin_p50_seconds") is not None:
        line += (f"; publish-to-pin p50 "
                 f"{dd['publish_to_pin_p50_seconds']:.4g}s")
    print(line, file=out)
    cp = critical_path_digest(digest.get("decisions") or [],
                              digest.get("windows") or [])
    if cp is None:
        return
    shares = " / ".join(f"{k} {v:.0%}"
                        for k, v in cp["stage_shares"].items()
                        if v >= 0.005)
    recon = ("reconciled" if cp["reconciled"] else
             f"RECONCILIATION BROKEN x{cp['reconcile_mismatches']}")
    print(f"Critical path: decision p99 {cp['total_p99_seconds']:.4g}s "
          f"= {shares} ({recon})", file=out)
    if cp["exemplars"]:
        ex = ", ".join(f"{e['trace']} {e['total_seconds']:.4g}s"
                       for e in cp["exemplars"][:4])
        print(f"  exemplars (full span trees kept): {ex}", file=out)


def summarize_events(events: list[dict], out=None, peak_flops=None,
                     peak_gbps=None) -> None:
    out = out or sys.stdout
    digest = collect(events)
    if digest["spans"]:
        print("Span tree (wall-clock, aggregated):", file=out)
        _render_span_tree(digest["spans"], out)

    counters = digest["counters"]
    if counters:
        print("\nCounters:", file=out)
        for name in sorted(counters):
            v = counters[name]
            print(f"  {name:<40} {v:g}", file=out)

    gauges = digest["gauges"]
    if gauges:
        print("\nGauges (last value):", file=out)
        for name in sorted(gauges):
            print(f"  {name:<40} {gauges[name]:g}", file=out)

    hists = digest["hists"]
    buckets = digest.get("hist_buckets", {})
    if hists or buckets:
        print("\nHistograms:", file=out)
        for name in sorted(hists):
            vs = hists[name]
            print(f"  {name:<34} n={len(vs):<5} p50={percentile(vs, 0.5):g} "
                  f"p95={percentile(vs, 0.95):g} max={max(vs):g}", file=out)
        # Bucketed (hist_bulk) entries: percentiles are bucket upper
        # bounds (~ marks the ladder resolution, one 10^(1/4) step).
        for name in sorted(buckets):
            agg = buckets[name]
            print(f"  {name:<34} n={agg['count']:<5} "
                  f"p50~{bucket_percentile(agg, 0.5):.4g} "
                  f"p95~{bucket_percentile(agg, 0.95):.4g} "
                  f"max={agg['max']:g}", file=out)

    _render_roofline(digest, out, peak_flops, peak_gbps)

    traces = digest["traces"]
    if traces:
        print("\nKMeans convergence traces:", file=out)
        # Display index is stream-wide; grouping stays per (run, call) so
        # appended runs never merge their traces.
        for call, key in enumerate(sorted(traces), start=1):
            steps = sorted(traces[key], key=lambda e: e["step"])
            first, last = steps[0], steps[-1]
            backend = first.get("backend", "?")
            k = first.get("k", "?")
            inertia = ""
            if first.get("inertia") is not None:
                inertia = (f", inertia {first['inertia']:.6g} -> "
                           f"{last['inertia']:.6g}")
            print(f"  call {call} [{first.get('kernel', '?')} backend="
                  f"{backend} k={k}]: {len(steps)} iterations"
                  f"{inertia}, final shift {last['shift']:.3g}", file=out)

    _render_audit(digest["audits"], out)
    _render_alerts(digest["windows"], out)
    _render_cells(digest.get("cells") or [], out)
    _render_checkpoint(digest, out)
    _render_daemon(digest, out)
    _render_serving(digest["windows"], out)
    _render_storage(digest["windows"], out)
    _render_durability(digest["windows"], out)
    _render_integrity(digest["windows"], out)

    windows = digest["windows"]
    if windows:
        n_events = sum(int(w.get("n_events", 0)) for w in windows)
        recl = [w for w in windows if w.get("recluster")]
        moved = sum(int(w.get("bytes_migrated", 0)) for w in windows)
        print(f"\nController windows: {len(windows)} ({n_events} events, "
              f"{len(recl)} reclusters, {moved} bytes migrated)", file=out)
        pacing = pacing_digest(windows)
        if pacing:
            line = f"End-to-end: {pacing['windows_per_sec']:.3f} windows/sec"
            if "plan_p50_seconds" in pacing:
                line += (f" (plan p50 "
                         f"{pacing['plan_p50_seconds'] * 1e3:.2f} ms/window, "
                         f"{pacing['plan_seconds_fraction']:.1%} "
                         f"of host time)")
            if "devices" in pacing:
                # Mesh runs: windows/sec must be readable against mesh
                # size and the per-iteration collective traffic it buys.
                line += (f" across {pacing['devices']} devices "
                         f"(~{pacing['collective_bytes_per_iter']} B/iter "
                         f"collectives)")
            print(line, file=out)


# -- export ------------------------------------------------------------------

# The exposition renderer lives in obs/prom.py now (ONE renderer shared
# with the daemon's live /metrics endpoint, obs/httpz.py); these aliases
# keep the long-standing import surface of this module working.
from .prom import meta_lines  # noqa: E402
from .prom import prom_name as _prom_name  # noqa: E402,F401
from .prom import prometheus_lines  # noqa: E402,F401


# -- tail --------------------------------------------------------------------


def _tail_line(e: dict) -> str:
    kind = e.get("kind", "?")
    if kind == "span":
        return f"span {e['name']} dur={e['dur']:.6f}s id={e['id']}" + (
            f" parent={e['parent']}" if e.get("parent") is not None else "")
    if kind in ("counter", "gauge", "hist"):
        return f"{kind} {e['name']} = {e['value']:g}"
    if kind == "hist_bulk":
        return (f"hist_bulk {e['name']} n={e.get('count')} "
                f"min={e.get('min', 0):g} max={e.get('max', 0):g}")
    if kind == "kmeans_iter":
        inertia = e.get("inertia")
        istr = "" if inertia is None else f" inertia={inertia:.6g}"
        return (f"kmeans_iter call={e.get('call')} step={e['step']}"
                f"{istr} shift={e['shift']:.3g}")
    if kind == "window":
        return (f"window {e.get('window')} events={e.get('n_events')} "
                f"recluster={e.get('recluster')} "
                f"moves={e.get('moves_applied')}")
    if kind == "lineage":
        return (f"lineage window={e.get('window')} cause={e.get('cause')} "
                f"files={e.get('files')} bytes={e.get('bytes')}")
    if kind == "decision_trace":
        return (f"decision {e.get('trace')} window={e.get('window')} "
                f"total={int(e.get('total_ns', 0)) / 1e9:.4g}s "
                f"epoch={e.get('epoch_id')}"
                + (" exemplar" if e.get("exemplar") else ""))
    if kind == "epoch_pin":
        p2p = e.get("publish_to_pin_ns")
        return (f"epoch_pin epoch={e.get('epoch_id')} "
                f"trace={e.get('trace')}"
                + (f" publish_to_pin={p2p / 1e9:.4g}s"
                   if p2p is not None else ""))
    if kind == "audit":
        sil = e.get("silhouette")
        sil = "" if sil is None else f" silhouette={sil:.3f}"
        flags = f" flags={','.join(e['flags'])}" if e.get("flags") else ""
        return f"audit window={e.get('window')}{sil}{flags}"
    if kind == "xla":
        if e.get("event") == "exec":
            return (f"xla exec {e.get('kernel')} "
                    f"seconds={e.get('seconds', 0):.4g}")
        return (f"xla compile {e.get('kernel')} "
                f"flops={e.get('flops', 0):.4g} "
                f"compile={e.get('compile_seconds', 0):.3g}s")
    return json.dumps(e)


# -- watch -------------------------------------------------------------------


def watch(path: str, *, interval: float = 1.0, poll: float | None = None,
          max_seconds: float | None = None, once: bool = False,
          out=None) -> int:
    """Live terminal view of a growing stream.

    Tails ``path`` through ``obs.sink.iter_events`` and redraws a compact
    dashboard — windows processed, re-clusters, migrated bytes, last audit
    verdict, top counters, event rate — every ``interval`` seconds while
    the producer (e.g. ``cdrs control --metrics``) appends.  ``poll``
    sets the file-poll cadence separately from the redraw ``interval``
    (default: same) — a sub-second poll against a live daemon keeps
    tail latency low without redrawing the terminal at that rate.
    ``once`` renders the current state a single time (no follow);
    ``max_seconds`` bounds a follow session (tests, CI).  Ctrl-C exits
    cleanly.
    """
    import time as _time

    from .sink import iter_events

    out = out or sys.stdout
    t0 = _time.monotonic()
    events: list[dict] = []
    rendered_at = -1
    #: Retained-event cap: the dashboard is a live view, not an archive —
    #: a multi-hour controller stream must not grow the re-aggregated
    #: list (and each redraw's cost) without bound.  Past the cap the
    #: oldest half is dropped; last-wins window/audit dedup means the
    #: digest of the trailing stream stays correct for everything the
    #: dashboard shows except all-time totals, which fall back to
    #: trailing-window totals.
    cap = 200_000
    if poll is None:
        poll = interval
    interactive = (not once) and getattr(out, "isatty", lambda: False)()

    def render():
        digest = collect(events)
        lines = [f"cdrs metrics watch — {path}  "
                 f"({len(events)} events, "
                 f"{_time.monotonic() - t0:.0f}s)"]
        windows = digest["windows"]
        if windows:
            recl = sum(1 for w in windows if w.get("recluster"))
            moved = sum(int(w.get("bytes_migrated", 0)) for w in windows)
            last = windows[-1]
            lines.append(
                f"windows: {len(windows)} (last #{last.get('window')}, "
                f"{recl} reclusters, {_fmt_bytes(moved)} migrated)")
        audits = digest["audits"]
        if audits:
            lines.append("audit:   " + _tail_line(audits[-1]))
        for name in sorted(digest["gauges"])[:6]:
            lines.append(f"gauge:   {name} = {digest['gauges'][name]:g}")
        flagged = [a for a in audits if a.get("flags")]
        if flagged:
            lines.append(f"flags:   {len(flagged)} windows flagged "
                         f"(last: {', '.join(flagged[-1]['flags'])})")
        if windows:
            # Streaming alert verdicts over the (deduplicated) trailing
            # windows — FIRING lines appear while a rule is hot and
            # clear to a resolved note once the stream heals.
            from .alerts import evaluate_records

            res = [r for r in evaluate_records(windows) if r["fired"]]
            for r in res:
                if r["firing"]:
                    lines.append(f"ALERT FIRING: {r['name']} "
                                 f"[{r['severity']}] since window "
                                 f"{r['since']}")
            resolved = [r for r in res if not r["firing"]]
            if resolved:
                lines.append("alerts resolved: " + ", ".join(
                    r["name"] for r in resolved))
        if interactive:
            print("\x1b[2J\x1b[H" + "\n".join(lines), file=out, flush=True)
        else:
            print("\n".join(lines) + "\n", file=out, flush=True)

    last_draw = -float("inf")

    def stop() -> bool:
        nonlocal rendered_at, last_draw
        now = _time.monotonic()
        # Redraw only on new data, at most once per ``interval`` — the
        # file may be polled much faster (--poll) than the terminal
        # should repaint.
        if len(events) != rendered_at and now - last_draw >= interval:
            render()
            rendered_at = len(events)
            last_draw = now
        return max_seconds is not None \
            and _time.monotonic() - t0 >= max_seconds

    try:
        for e in iter_events(path, follow=not once, poll=poll,
                             stop=stop):
            events.append(e)
            if len(events) > cap:
                del events[:cap // 2]
    except KeyboardInterrupt:
        pass
    except FileNotFoundError:
        print(f"error: no such stream {path}", file=sys.stderr)
        return 1
    render()
    return 0


# -- watch --url (live daemon endpoint) --------------------------------------


def base_url(spec: str) -> str:
    """Normalize ``HOST:PORT`` / ``http://host:port[/]`` into a scheme'd
    base URL with no trailing slash (the ``cdrs status`` / ``watch
    --url`` address argument)."""
    u = spec.strip().rstrip("/")
    if not u.startswith(("http://", "https://")):
        u = "http://" + u
    return u


def fetch_statusz(base: str, timeout: float = 5.0) -> dict:
    """One GET of a live daemon's ``/statusz`` (obs/httpz.py), parsed.
    Raises OSError/ValueError on unreachable or malformed endpoints —
    callers render the one-line error."""
    import urllib.request

    with urllib.request.urlopen(base + "/statusz",
                                timeout=timeout) as r:
        return json.loads(r.read().decode("utf-8"))


def statusz_lines(base: str, doc: dict) -> list[str]:
    """Human rendering of one /statusz document — shared by ``cdrs
    status`` and ``cdrs metrics watch --url``."""
    lines = [f"cdrs daemon @ {base}  (snapshot seq {doc.get('seq')}, "
             f"up {doc.get('uptime_seconds', 0):.0f}s)"]
    state = "ready" if doc.get("ready") else (
        "draining" if doc.get("draining") else "not ready")
    lines.append(f"state:    {state}")
    lines.append(
        f"epoch:    {doc.get('epoch_id')} "
        f"(window {doc.get('window')}, "
        f"{doc.get('epochs_published')} published)")
    lines.append(
        f"ingest:   {doc.get('events_ingested')} events, "
        f"{doc.get('windows_processed')} windows, backlog "
        f"{(doc.get('backlog') or {}).get('events', 0)} events / "
        f"{_fmt_bytes((doc.get('backlog') or {}).get('bytes', 0))}")
    dec = doc.get("decision") or {}
    if dec.get("count"):
        p50 = dec.get("p50_seconds")
        p99 = dec.get("p99_seconds")
        lines.append(
            f"decide:   n={dec['count']} p50="
            f"{'-' if p50 is None else f'{p50 * 1e3:.2f}ms'} p99="
            f"{'-' if p99 is None else f'{p99 * 1e3:.2f}ms'}")
    stages = doc.get("stages") or []
    if stages:
        top = sorted(stages, key=lambda s: -s.get("share", 0))[:4]
        lines.append("stages:   " + "  ".join(
            f"{s['stage']} {s.get('share', 0):.1%}" for s in top))
    lines.append(
        f"moves:    {doc.get('reclusters')} reclusters, "
        f"{_fmt_bytes(doc.get('bytes_migrated', 0))} migrated, "
        f"{doc.get('checkpoints_written')} checkpoints")
    for a in doc.get("alerts") or []:
        if a.get("firing"):
            lines.append(f"ALERT FIRING: {a['name']} [{a['severity']}] "
                         f"since window {a.get('since')} "
                         f"(streak {a.get('streak')})")
    return lines


def watch_url(url: str, *, interval: float = 1.0,
              max_seconds: float | None = None, once: bool = False,
              out=None) -> int:
    """``watch`` against a live daemon's /statusz endpoint instead of a
    sink file: no shared filesystem needed, and the view is the daemon's
    own atomic snapshot rather than a re-aggregated tail."""
    import time as _time

    out = out or sys.stdout
    base = base_url(url)
    t0 = _time.monotonic()
    interactive = (not once) and getattr(out, "isatty", lambda: False)()
    code = 0
    try:
        while True:
            try:
                lines = statusz_lines(base, fetch_statusz(base))
                code = 0
            except (OSError, ValueError) as e:
                lines = [f"cdrs metrics watch — {base} unreachable: "
                         f"{e}"]
                code = 1
            if interactive:
                print("\x1b[2J\x1b[H" + "\n".join(lines), file=out,
                      flush=True)
            else:
                print("\n".join(lines) + "\n", file=out, flush=True)
            if once:
                return code
            if max_seconds is not None \
                    and _time.monotonic() - t0 >= max_seconds:
                return code
            _time.sleep(interval)
    except KeyboardInterrupt:
        return code
    except BrokenPipeError:
        # The downstream pipe reader hung up (``| grep -q``, ``| head``):
        # end of session, not an error.  Point stdout at devnull so the
        # interpreter's exit flush does not raise the same error again.
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return code


# -- alerts ------------------------------------------------------------------


def _load_rules(spec: str | None):
    """Rules from --rules (inline JSON list or a file path); None = the
    built-in default set."""
    from .alerts import default_rules, rules_from_json

    if not spec:
        return default_rules()
    text = spec
    if not text.lstrip().startswith("["):
        with open(text, encoding="utf-8") as f:
            text = f.read()
    return rules_from_json(text)


def _print_transition(t: dict, out) -> None:
    w = t.get("window")
    where = f"window {w}" if w is not None else "stream"
    if t["state"] == "firing":
        v = f" value={t['value']:g}" if "value" in t else ""
        print(f"{where}: FIRING {t['alert']} [{t['severity']}]{v}",
              file=out)
    else:
        print(f"{where}: resolved {t['alert']}", file=out)


def alerts_cmd(path: str, *, rules=None, follow: bool = False,
               interval: float = 1.0, poll: float | None = None,
               max_seconds: float | None = None,
               fail_firing: bool = False, out=None) -> int:
    """Evaluate alert rules over a stream: batch (transition timeline +
    final verdicts) or live follow (transitions print as they land,
    staleness checked per poll).  ``poll`` overrides the file-poll
    cadence separately from ``interval`` (default: same) — paging on a
    live daemon wants sub-second detection latency.  ``--fail_firing``
    turns a still-firing end state into a nonzero exit — the CI/script
    gate."""
    import time as _time

    from .alerts import AlertEngine
    from .sink import iter_events, read_events

    out = out or sys.stdout
    eng = AlertEngine(rules)
    if follow:
        t0 = _time.monotonic()
        if poll is None:
            poll = interval

        def stop() -> bool:
            for t in eng.check_staleness():
                _print_transition(t, out)
            return max_seconds is not None \
                and _time.monotonic() - t0 >= max_seconds

        try:
            for e in iter_events(path, follow=True, poll=poll,
                                 stop=stop):
                for t in eng.observe(e):
                    _print_transition(t, out)
        except KeyboardInterrupt:
            pass
    else:
        try:
            events = read_events(path)
        except OSError as e:
            print(f"error: cannot read {path}: {e}", file=sys.stderr)
            return 1
        if not events:
            print(f"error: {path}: no telemetry events (missing, "
                  f"empty, or corrupt stream)", file=sys.stderr)
            return 1
        # Last-wins window dedup BEFORE evaluation: a crash/resume tail
        # repeats windows (sink contract), and the verdicts must match
        # what summarize/report/watch evaluate over the same file —
        # stale pre-crash records must not fire, and repeats must not
        # double-count streaks or burn-rate means.
        from .aggregate import dedup_windows

        for e in dedup_windows(events):
            for t in eng.observe(e):
                _print_transition(t, out)
        for t in eng.finish():
            _print_transition(t, out)
    res = eng.results()
    fired = [r for r in res if r["fired"]]
    firing = [r for r in fired if r["firing"]]
    print(f"alerts: {len(fired)} fired over {eng.windows_seen} windows, "
          f"{len(firing)} firing at end"
          + (f" ({', '.join(r['name'] for r in firing)})" if firing
             else ""), file=out)
    return 1 if fail_firing and firing else 0


# -- entry -------------------------------------------------------------------


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="cdrs metrics", description="inspect a telemetry JSONL stream")
    sub = parser.add_subparsers(dest="action", required=True)

    p = sub.add_parser("summarize", help="span tree, counters, p50/p95, "
                                         "roofline, audit digest, traces")
    p.add_argument("file")
    p.add_argument("--peak_flops", type=float, default=None,
                   help="chip peak FLOP/s for the roofline lines "
                        "(default: known TPU table via run metadata)")
    p.add_argument("--peak_gbps", type=float, default=None,
                   help="chip peak HBM GB/s for the roofline lines")

    p = sub.add_parser("tail", help="print the last N events")
    p.add_argument("file")
    p.add_argument("-n", type=int, default=20)

    p = sub.add_parser("export", help="export aggregates for scrapers")
    p.add_argument("file")
    p.add_argument("--format", choices=["prometheus"], default="prometheus")
    p.add_argument("--out", default=None,
                   help="write here (default stdout); point your "
                        "node_exporter textfile collector at it")

    p = sub.add_parser("report", help="self-contained static HTML report "
                                      "(span tree, sparklines, audit "
                                      "timeline, roofline table)")
    p.add_argument("file")
    p.add_argument("-o", "--out", default=None,
                   help="output path (default: <file>.html)")
    p.add_argument("--title", default=None)

    p = sub.add_parser("watch", help="live terminal view tailing a running "
                                     "producer's stream (or polling a "
                                     "daemon's --http endpoint via --url)")
    p.add_argument("file", nargs="?", default=None)
    p.add_argument("--url", default=None, metavar="HOST:PORT|URL",
                   help="poll a live daemon's /statusz endpoint "
                        "(cdrs daemon --http) instead of tailing a "
                        "sink file")
    p.add_argument("--interval", type=float, default=1.0)
    p.add_argument("--poll", type=float, default=None, metavar="SECONDS",
                   help="file-poll cadence, decoupled from the redraw "
                        "--interval (default: same) — sub-second polls "
                        "track a live daemon without repainting at "
                        "that rate")
    p.add_argument("--max_seconds", type=float, default=None,
                   help="stop after this long (default: until Ctrl-C)")
    p.add_argument("--once", action="store_true",
                   help="render the current state once and exit")

    p = sub.add_parser("alerts", help="evaluate AlertRules over the "
                                      "stream: thresholds, SRE burn-"
                                      "rate pairs, staleness — batch "
                                      "timeline or live --follow")
    p.add_argument("file")
    p.add_argument("--rules", default=None, metavar="JSON|FILE",
                   help="declarative rule list (obs/alerts.py schema); "
                        "default: the built-in ruleset")
    p.add_argument("--follow", action="store_true",
                   help="tail the stream live, printing transitions as "
                        "they land (staleness rules active)")
    p.add_argument("--interval", type=float, default=1.0)
    p.add_argument("--poll", type=float, default=None, metavar="SECONDS",
                   help="file-poll cadence override (default: "
                        "--interval) — sub-second detection latency "
                        "against a live daemon")
    p.add_argument("--max_seconds", type=float, default=None,
                   help="bound a follow session (tests, CI)")
    p.add_argument("--fail_firing", action="store_true",
                   help="exit nonzero when any alert is still firing "
                        "at the end")

    sub.add_parser("regress", add_help=False,
                   help="compare a bench run against the recorded "
                        "trajectory bands; nonzero exit on regression")

    # Delegate regress wholesale (its options would otherwise be eaten by
    # this parser — argparse.REMAINDER does not capture leading options).
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "regress":
        from ..benchmarks.regress import main as regress_main

        return regress_main(list(argv[1:]))

    args = parser.parse_args(argv)
    if args.action == "watch":
        if args.url:
            return watch_url(args.url, interval=args.interval,
                             max_seconds=args.max_seconds,
                             once=args.once)
        if not args.file:
            print("error: watch needs a stream FILE or --url",
                  file=sys.stderr)
            return 2
        return watch(args.file, interval=args.interval, poll=args.poll,
                     max_seconds=args.max_seconds, once=args.once)
    if args.action == "alerts":
        try:
            rules = _load_rules(args.rules)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"error: bad --rules: {e}", file=sys.stderr)
            return 2
        return alerts_cmd(args.file, rules=rules, follow=args.follow,
                          interval=args.interval, poll=args.poll,
                          max_seconds=args.max_seconds,
                          fail_firing=args.fail_firing)

    try:
        events = read_events(args.file)
    except OSError as e:
        print(f"error: cannot read {args.file}: {e}", file=sys.stderr)
        return 1
    if not events and args.action in ("summarize", "tail", "report"):
        # One clean line naming the path — a missing stream, an empty
        # file and an all-torn (corrupt) file all land here; none of
        # them should traceback or silently render nothing.
        print(f"error: {args.file}: no telemetry events (missing, "
              f"empty, or corrupt stream)", file=sys.stderr)
        return 1

    try:
        if args.action == "summarize":
            summarize_events(events, peak_flops=args.peak_flops,
                             peak_gbps=args.peak_gbps)
            return 0
        if args.action == "tail":
            if args.n > 0:  # [-0:] would be the whole stream
                for e in events[-args.n:]:
                    print(_tail_line(e))
            return 0
        if args.action == "report":
            from .report import render_html

            out_path = args.out or (args.file + ".html")
            html = render_html(events, title=args.title
                               or f"cdrs report — {args.file}")
            with open(out_path, "w", encoding="utf-8") as f:
                f.write(html)
            print(f"wrote {out_path}", file=sys.stderr)
            return 0
        # export — aggregate exposition plus the meta series every
        # Prometheus surface carries (start-time gauge for rate() over
        # resume-reset counters, build info; obs/prom.py).
        text = "\n".join(prometheus_lines(events) + meta_lines()) + "\n"
        if args.out:
            with open(args.out, "w", encoding="utf-8") as f:
                f.write(text)
        else:
            sys.stdout.write(text)
        return 0
    except BrokenPipeError:
        # `cdrs metrics ... | head` closing the pipe is a clean exit, not
        # a traceback.
        try:
            sys.stdout.close()
        except Exception:
            pass
        return 0
