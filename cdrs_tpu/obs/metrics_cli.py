"""``cdrs metrics`` — human and scraper consumption of telemetry JSONL.

Subcommands:

* ``summarize FILE`` — per-span wall-clock tree (aggregated over repeated
  spans), counters, gauges, histogram p50/p95, kmeans convergence traces,
  and a controller-window digest.
* ``tail FILE [-n N]`` — the last N events, one compact line each.
* ``export FILE --format prometheus [--out FILE]`` — Prometheus textfile
  exposition (node_exporter textfile-collector compatible): counters,
  gauges, and histogram summaries.

The reader is resilient by construction: unknown ``kind``s are ignored
(forward compatibility) and a torn final line from a killed writer is
skipped (sink contract, obs/sink.py).
"""

from __future__ import annotations

import argparse
import json
import re
import sys

from .sink import read_events

__all__ = ["main", "summarize_events", "prometheus_lines"]


def _percentile(values: list[float], q: float) -> float:
    """Nearest-rank percentile on a sorted copy (no numpy dependency)."""
    s = sorted(values)
    if not s:
        return float("nan")
    idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
    return s[idx]


# -- summarize ---------------------------------------------------------------


def _span_forest(events: list[dict]):
    """Aggregate span events by their name-path.

    Returns ``{path_tuple: {"count": int, "total": float}}`` where the path
    is the chain of span names from the root — repeated spans (e.g. one per
    window) aggregate into one node.  Span ids restart per process, so ids
    are scoped by the event's ``run`` stamp: appended streams from several
    runs aggregate instead of shadowing each other.
    """
    by_id = {(e.get("run"), e["id"]): e for e in events
             if e.get("kind") == "span"}
    agg: dict[tuple, dict] = {}
    for e in by_id.values():
        run = e.get("run")
        path = [e["name"]]
        parent = e.get("parent")
        depth = 0
        while parent is not None and depth < 100:
            pe = by_id.get((run, parent))
            if pe is None:
                break
            path.append(pe["name"])
            parent = pe.get("parent")
            depth += 1
        key = tuple(reversed(path))
        node = agg.setdefault(key, {"count": 0, "total": 0.0})
        node["count"] += 1
        node["total"] += float(e.get("dur", 0.0))
    return agg


def _dedup_windows(events: list[dict]) -> list[dict]:
    """Controller window records, last-wins per window index.

    The controller's sink contract (control/controller.py): after a crash
    the append-only tail may repeat the windows between the last snapshot
    and the kill — consumers take the last record per window index."""
    by_index: dict = {}
    for e in events:
        if e.get("kind") == "window":
            by_index[e.get("window")] = e
    return [by_index[w] for w in sorted(by_index, key=lambda x: (x is None,
                                                                 x))]


def _final_counters(events: list[dict]) -> dict[str, float]:
    """Final counter values, summed across runs sharing the stream.

    Each counter event carries its run's *cumulative* value; within one run
    the last event wins, and separate runs (which each restart at zero)
    add.  Caveat: a kill/resume pair counts a crashed run's partial tail in
    both runs' counters — the deduplicated window digest (not the counter
    sums) is the authoritative per-window accounting."""
    per_run: dict[tuple, float] = {}
    for e in events:
        if e.get("kind") == "counter":
            per_run[(e.get("run"), e["name"])] = e["value"]
    totals: dict[str, float] = {}
    for (_, name), v in per_run.items():
        totals[name] = totals.get(name, 0.0) + v
    return totals


def _render_span_tree(agg, out) -> None:
    paths = sorted(agg, key=lambda p: (len(p), -agg[p]["total"]))
    # Stable depth-first ordering: parents before children, siblings by
    # total descending.
    ordered: list[tuple] = []

    def add_children(prefix):
        kids = [p for p in paths if len(p) == len(prefix) + 1
                and p[:len(prefix)] == prefix]
        for p in sorted(kids, key=lambda p: -agg[p]["total"]):
            ordered.append(p)
            add_children(p)

    add_children(())
    # Orphans (parent span missing from the stream) still print, flat.
    for p in paths:
        if p not in ordered:
            ordered.append(p)
    for path in ordered:
        node = agg[path]
        indent = "  " * (len(path) - 1)
        calls = f" x{node['count']}" if node["count"] > 1 else ""
        print(f"  {indent}{path[-1]:<{max(1, 28 - len(indent))}} "
              f"{node['total']:>9.3f}s{calls}", file=out)


def summarize_events(events: list[dict], out=None) -> None:
    out = out or sys.stdout
    spans = [e for e in events if e.get("kind") == "span"]
    if spans:
        print("Span tree (wall-clock, aggregated):", file=out)
        _render_span_tree(_span_forest(events), out)

    counters = _final_counters(events)
    if counters:
        print("\nCounters:", file=out)
        for name in sorted(counters):
            v = counters[name]
            print(f"  {name:<40} {v:g}", file=out)

    gauges: dict[str, float] = {}
    for e in events:
        if e.get("kind") == "gauge":
            gauges[e["name"]] = e["value"]
    if gauges:
        print("\nGauges (last value):", file=out)
        for name in sorted(gauges):
            print(f"  {name:<40} {gauges[name]:g}", file=out)

    hists: dict[str, list[float]] = {}
    for e in events:
        if e.get("kind") == "hist":
            hists.setdefault(e["name"], []).append(float(e["value"]))
    if hists:
        print("\nHistograms:", file=out)
        for name in sorted(hists):
            vs = hists[name]
            print(f"  {name:<34} n={len(vs):<5} p50={_percentile(vs, 0.5):g} "
                  f"p95={_percentile(vs, 0.95):g} max={max(vs):g}", file=out)

    traces: dict[tuple, list[dict]] = {}
    for e in events:
        if e.get("kind") == "kmeans_iter":
            traces.setdefault((str(e.get("run")), int(e.get("call", 0))),
                              []).append(e)
    if traces:
        print("\nKMeans convergence traces:", file=out)
        # Display index is stream-wide; grouping stays per (run, call) so
        # appended runs never merge their traces.
        for call, key in enumerate(sorted(traces), start=1):
            steps = sorted(traces[key], key=lambda e: e["step"])
            first, last = steps[0], steps[-1]
            backend = first.get("backend", "?")
            k = first.get("k", "?")
            inertia = ""
            if first.get("inertia") is not None:
                inertia = (f", inertia {first['inertia']:.6g} -> "
                           f"{last['inertia']:.6g}")
            print(f"  call {call} [{first.get('kernel', '?')} backend="
                  f"{backend} k={k}]: {len(steps)} iterations"
                  f"{inertia}, final shift {last['shift']:.3g}", file=out)

    windows = _dedup_windows(events)
    if windows:
        n_events = sum(int(w.get("n_events", 0)) for w in windows)
        recl = [w for w in windows if w.get("recluster")]
        moved = sum(int(w.get("bytes_migrated", 0)) for w in windows)
        print(f"\nController windows: {len(windows)} ({n_events} events, "
              f"{len(recl)} reclusters, {moved} bytes migrated)", file=out)


# -- export ------------------------------------------------------------------


def _prom_name(name: str) -> str:
    return "cdrs_" + re.sub(r"[^a-zA-Z0-9_]", "_", name)


def prometheus_lines(events: list[dict]) -> list[str]:
    """Prometheus textfile exposition of the stream's final aggregates."""
    lines: list[str] = []
    counters = _final_counters(events)
    gauges: dict[str, float] = {}
    hists: dict[str, list[float]] = {}
    for e in events:
        kind = e.get("kind")
        if kind == "gauge":
            gauges[e["name"]] = e["value"]
        elif kind == "hist":
            hists.setdefault(e["name"], []).append(float(e["value"]))
        elif kind == "span":
            hists.setdefault(f"span.{e['name']}.seconds", []).append(
                float(e.get("dur", 0.0)))
    for name in sorted(counters):
        m = _prom_name(name)
        lines += [f"# TYPE {m} counter", f"{m} {counters[name]:g}"]
    for name in sorted(gauges):
        m = _prom_name(name)
        lines += [f"# TYPE {m} gauge", f"{m} {gauges[name]:g}"]
    for name in sorted(hists):
        vs = hists[name]
        m = _prom_name(name)
        lines += [
            f"# TYPE {m} summary",
            f'{m}{{quantile="0.5"}} {_percentile(vs, 0.5):g}',
            f'{m}{{quantile="0.95"}} {_percentile(vs, 0.95):g}',
            f"{m}_sum {sum(vs):g}",
            f"{m}_count {len(vs)}",
        ]
    return lines


# -- tail --------------------------------------------------------------------


def _tail_line(e: dict) -> str:
    kind = e.get("kind", "?")
    if kind == "span":
        return f"span {e['name']} dur={e['dur']:.6f}s id={e['id']}" + (
            f" parent={e['parent']}" if e.get("parent") is not None else "")
    if kind in ("counter", "gauge", "hist"):
        return f"{kind} {e['name']} = {e['value']:g}"
    if kind == "kmeans_iter":
        inertia = e.get("inertia")
        istr = "" if inertia is None else f" inertia={inertia:.6g}"
        return (f"kmeans_iter call={e.get('call')} step={e['step']}"
                f"{istr} shift={e['shift']:.3g}")
    if kind == "window":
        return (f"window {e.get('window')} events={e.get('n_events')} "
                f"recluster={e.get('recluster')} "
                f"moves={e.get('moves_applied')}")
    return json.dumps(e)


# -- entry -------------------------------------------------------------------


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="cdrs metrics", description="inspect a telemetry JSONL stream")
    sub = parser.add_subparsers(dest="action", required=True)

    p = sub.add_parser("summarize", help="span tree, counters, p50/p95, "
                                         "convergence traces")
    p.add_argument("file")

    p = sub.add_parser("tail", help="print the last N events")
    p.add_argument("file")
    p.add_argument("-n", type=int, default=20)

    p = sub.add_parser("export", help="export aggregates for scrapers")
    p.add_argument("file")
    p.add_argument("--format", choices=["prometheus"], default="prometheus")
    p.add_argument("--out", default=None,
                   help="write here (default stdout); point your "
                        "node_exporter textfile collector at it")

    args = parser.parse_args(argv)
    try:
        events = read_events(args.file)
    except OSError as e:
        print(f"error: cannot read {args.file}: {e}", file=sys.stderr)
        return 1

    try:
        if args.action == "summarize":
            if not events:
                print(f"{args.file}: no events", file=sys.stderr)
                return 1
            summarize_events(events)
            return 0
        if args.action == "tail":
            if args.n > 0:  # [-0:] would be the whole stream
                for e in events[-args.n:]:
                    print(_tail_line(e))
            return 0
        # export
        text = "\n".join(prometheus_lines(events)) + "\n"
        if args.out:
            with open(args.out, "w") as f:
                f.write(text)
        else:
            sys.stdout.write(text)
        return 0
    except BrokenPipeError:
        # `cdrs metrics ... | head` closing the pipe is a clean exit, not
        # a traceback.
        try:
            sys.stdout.close()
        except Exception:
            pass
        return 0
