"""Configuration system for the clustering-driven replication strategy framework.

The reference scatters its configuration across argparse flags and hard-coded module
constants (reference: src/main.py:23-62, src/generator.py:17-25,
src/access_simulator.py:42-47, 67-72).  Here every knob is promoted into typed
dataclasses with the reference's defaults, so any stage can be driven
programmatically or from the single CLI (cdrs_tpu/cli.py).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Mapping

# ---------------------------------------------------------------------------
# Canonical category/feature vocabulary
# ---------------------------------------------------------------------------

#: Category order is load-bearing: scoring iterates in this order and the
#: replication-factor tie-break must match the reference (src/scoring.py:99-107).
CATEGORIES: tuple[str, ...] = ("Hot", "Shared", "Moderate", "Archival")

#: The five clustering features (reference: src/main.py:23-29).
CLUSTERING_FEATURES: tuple[str, ...] = (
    "access_freq_norm",
    "age_norm",
    "write_ratio_norm",
    "locality_norm",
    "concurrency_norm",
)

#: Raw (pre-normalization) feature names in the same order.
RAW_FEATURES: tuple[str, ...] = (
    "access_freq",
    "age_seconds",
    "write_ratio",
    "locality",
    "concurrency",
)

#: Ground-truth categories planted by the generator (lowercase, reference:
#: src/generator.py:45) mapped to scoring categories.
PLANTED_TO_CATEGORY: Mapping[str, str] = {
    "hot": "Hot",
    "shared": "Shared",
    "moderate": "Moderate",
    "archival": "Archival",
}


# ---------------------------------------------------------------------------
# Scoring configuration (reference: src/main.py:23-62)
# ---------------------------------------------------------------------------

def _default_global_medians() -> dict[str, float]:
    # Reference placeholders (src/main.py:32-38), flagged there as "MUST be
    # replaced".  We keep them as the default for behavioural parity but the
    # pipeline can compute real medians from data (compute_from_data=True).
    return {f: 0.5 for f in CLUSTERING_FEATURES}


def _default_weights() -> dict[str, dict[str, float]]:
    # Reference: src/main.py:41-46.
    return {
        "Hot": {"access_freq_norm": 1.0, "age_norm": 0.8, "write_ratio_norm": 0.5,
                "locality_norm": 0.5, "concurrency_norm": 1.0},
        "Shared": {"access_freq_norm": 0.7, "age_norm": 0.2, "write_ratio_norm": 1.0,
                   "locality_norm": 0.2, "concurrency_norm": 0.5},
        "Moderate": {"access_freq_norm": 0.5, "age_norm": 0.5, "write_ratio_norm": 0.5,
                     "locality_norm": 0.5, "concurrency_norm": 0.5},
        "Archival": {"access_freq_norm": 0.1, "age_norm": 1.0, "write_ratio_norm": 0.1,
                     "locality_norm": 0.5, "concurrency_norm": 0.1},
    }


def _default_directions() -> dict[str, dict[str, int]]:
    # Reference: src/main.py:49-54.
    return {
        "Hot": {"access_freq_norm": +1, "age_norm": -1, "write_ratio_norm": +1,
                "locality_norm": +1, "concurrency_norm": +1},
        "Shared": {"access_freq_norm": +1, "age_norm": +1, "write_ratio_norm": +1,
                   "locality_norm": +1, "concurrency_norm": +1},
        "Moderate": {"access_freq_norm": 0, "age_norm": 0, "write_ratio_norm": 0,
                     "locality_norm": 0, "concurrency_norm": 0},
        "Archival": {"access_freq_norm": -1, "age_norm": +1, "write_ratio_norm": -1,
                     "locality_norm": -1, "concurrency_norm": -1},
    }


def _default_replication_factors() -> dict[str, int]:
    # Reference: src/main.py:57-62.  Archival's rf=4 makes it the winner of
    # all-zero-score ties (SURVEY.md §2.3).
    return {"Hot": 3, "Shared": 2, "Moderate": 1, "Archival": 4}


@dataclass
class ScoringConfig:
    """Weighted directional-deviation scoring rules (reference:
    src/scoring.py:57-109)."""

    features: tuple[str, ...] = CLUSTERING_FEATURES
    global_medians: dict[str, float] = field(default_factory=_default_global_medians)
    weights: dict[str, dict[str, float]] = field(default_factory=_default_weights)
    directions: dict[str, dict[str, int]] = field(default_factory=_default_directions)
    replication_factors: dict[str, int] = field(
        default_factory=_default_replication_factors)
    #: Moderate's "minimal deviation" band (reference: src/scoring.py:78 |delta| < 0.1).
    moderate_band: float = 0.1
    #: When True the pipeline replaces ``global_medians`` with medians computed
    #: from the dataset (fixing reference quirk SURVEY.md §6.1.5).
    compute_global_medians_from_data: bool = False
    #: Per-cluster median strategy for the jax backend: "sort" (exact),
    #: "hist" (O(n) fixed-bin histogram), "bisect" (scatter-free MXU rank
    #: bisection — the fast path on TPU at very large n), or "auto"
    #: (past ops/scoring_jax.HIST_MEDIAN_THRESHOLD rows: bisect on a real
    #: TPU backend, hist elsewhere).
    median_method: str = "auto"
    #: Histogram resolution for the "hist" strategy (error <= range/bins).
    median_bins: int = 2048

    categories: tuple[str, ...] = CATEGORIES

    def weight_matrix(self):
        """(n_categories, n_features) weights as a nested list (row per category)."""
        return [[self.weights[c][f] for f in self.features] for c in self.categories]

    def direction_matrix(self):
        return [[self.directions[c][f] for f in self.features] for c in self.categories]

    def rf_vector(self):
        return [self.replication_factors[c] for c in self.categories]


def validated_scoring_config() -> ScoringConfig:
    """Scoring tables validated against the workload the simulator produces.

    The reference's tables (src/main.py:41-54) are placeholders: with
    data-derived global medians nearly every cluster lands within Moderate's
    band and Moderate's ``(1 - |delta|)^2`` reward (~1 per in-band feature)
    dwarfs the directional ``delta^2`` terms (~0.01-0.2), so the decision
    collapses to Moderate + one Hot cluster (planted-category recovery ~0.55,
    read-locality gain over rf=1 ~0).  This config keeps the scoring
    *algorithm* byte-identical (ops/scoring_np.py) and re-derives the *data*:

    * directions follow the generator's actual rate profiles
      (src/access_simulator.py:42-47): Shared means many foreign clients
      (locality LOW, writes LOW), Archival means near-zero traffic with high
      locality (untouched files score locality 1.0,
      src/compute_features.py:68) — the reference's +1 locality for Shared
      and -1 for Archival point the wrong way for its own simulator.
    * age carries no planted signal (generator ages are category-independent,
      src/generator.py:41-42), so its weight is 0 everywhere.
    * Moderate's weights shrink to 0.15 and its band to 0.05 so directional
      evidence can outvote the in-band reward.

    Validated on 5 seeded 300-file workloads x k in {8, 12, 16, 24} (numpy
    backend, deterministic): planted-category recovery 0.79-0.85 mean
    (reference tables: 0.55) and read-locality gain over uniform rf=1 of
    +0.10 to +0.13 absolute at 1.14-1.16x the storage (reference tables: 0.0
    gain on 4/5 workloads).  tests/test_cluster.py pins these outcomes.
    """
    features = CLUSTERING_FEATURES
    weights = {
        "Hot": (1.0, 0.0, 0.5, 0.3, 1.0),
        "Shared": (1.0, 0.0, 0.5, 2.5, 0.5),
        "Moderate": (0.15, 0.15, 0.15, 0.15, 0.15),
        "Archival": (2.0, 0.0, 0.5, 1.5, 1.0),
    }
    directions = {
        "Hot": (+1, 0, +1, +1, +1),
        "Shared": (+1, 0, -1, -1, +1),
        "Moderate": (0, 0, 0, 0, 0),
        "Archival": (-1, 0, -1, +1, -1),
    }
    return ScoringConfig(
        weights={c: dict(zip(features, w)) for c, w in weights.items()},
        directions={c: dict(zip(features, d)) for c, d in directions.items()},
        moderate_band=0.05,
        compute_global_medians_from_data=True,
    )


# ---------------------------------------------------------------------------
# KMeans configuration (reference: src/kmeans_plusplus.py)
# ---------------------------------------------------------------------------

@dataclass
class KMeansConfig:
    """KMeans++ init + Lloyd loop knobs.

    The reference caps iterations at ``max(100, n/100)`` — a float that crashes
    ``range`` for n > 10,000 (reference: src/kmeans_plusplus.py:29-31, SURVEY.md
    §6.1.1).  We fix it to the integer ``max(100, n // 100)`` unless an explicit
    ``max_iter`` is given.
    """

    k: int = 4
    tol: float = 1e-4
    max_iter: int | None = None  # None -> max(100, n // 100)
    seed: int | None = 42        # reference: src/main.py:91 random_state=42
    #: Rows per mini-batch for incremental (Sculley) KMeans; None = full-batch
    #: Lloyd.  jax backend only (ops/kmeans_stream.py).
    batch_size: int | None = None
    #: Shuffled passes over the data in mini-batch mode.
    batch_epochs: int = 5
    #: Centroid init for the jax backend: "d2" (reference KMeans++ semantics),
    #: "kmeans||" (oversampling init whose cost does not scale with k —
    #: ops/kmeans_jax._kmeans_par_init_local, SURVEY.md §7.4 hard part), or
    #: "auto" (kmeans|| at k >= 256 where D²'s k sequential rounds dominate,
    #: d2 below — quality gate in data/init_quality_r5.json).  The numpy
    #: backend always runs the reference D² init; "auto" is valid there and
    #: resolves to it.
    init_method: str = "auto"
    #: Points dtype for the jax backend (None = keep the input's float dtype).
    #: "bfloat16" halves the HBM stream the Lloyd assignment is bound by;
    #: centroids/stats stay float32 (ops/kmeans_jax._stat_dtype).
    dtype: str | None = None

    def __post_init__(self):
        # Validate enum-ish fields at the config layer (same rationale as
        # scoring_config_from_dict): a typo'd dtype must not surface as a
        # np.dtype TypeError after clustering has started.
        if self.dtype not in (None, "float32", "bfloat16", "float16",
                              "float64"):
            raise ValueError(
                f"dtype must be one of float32/bfloat16/float16/float64 or "
                f"None; got {self.dtype!r}")
        if self.init_method not in ("auto", "d2", "kmeans||"):
            raise ValueError(
                f"init_method must be 'auto', 'd2' or 'kmeans||'; "
                f"got {self.init_method!r}")

    def resolve_max_iter(self, n: int) -> int:
        if self.max_iter is not None:
            return int(self.max_iter)
        from .utils.params import default_max_iter

        return default_max_iter(n)


# ---------------------------------------------------------------------------
# Workload configuration (reference: src/generator.py, src/access_simulator.py)
# ---------------------------------------------------------------------------

def _default_category_mix() -> dict[str, float]:
    # Reference: src/generator.py:45 weights [0.10, 0.20, 0.50, 0.20].
    return {"hot": 0.10, "shared": 0.20, "moderate": 0.50, "archival": 0.20}


def _default_rate_profiles() -> dict[str, dict[str, float]]:
    # Reference: src/access_simulator.py:42-47.
    return {
        "hot": {"read_rate": 0.8, "write_rate": 0.2, "locality_bias": 0.7},
        "shared": {"read_rate": 0.6, "write_rate": 0.02, "locality_bias": 0.3},
        "moderate": {"read_rate": 0.1, "write_rate": 0.01, "locality_bias": 0.5},
        "archival": {"read_rate": 0.005, "write_rate": 0.001, "locality_bias": 0.9},
    }


@dataclass
class GeneratorConfig:
    """Synthetic file-population generator knobs (reference: src/generator.py:17-25)."""

    n_files: int = 200
    base_dir: str = "/user/root/synth"
    min_size: int = 1024
    max_size: int = 1024 * 1024
    nodes: tuple[str, ...] = ("dn1", "dn2", "dn3")
    age_days_max: float = 365.0
    category_mix: dict[str, float] = field(default_factory=_default_category_mix)
    seed: int | None = None
    #: When True, also materialize random-content files (the reference writes
    #: os.urandom files into HDFS, src/generator.py:33-39).  The manifest alone
    #: is enough for the analytics pipeline.
    write_payloads: bool = False


@dataclass
class SimulatorConfig:
    """Poisson access-pattern simulator knobs (reference:
    src/access_simulator.py:16-76)."""

    duration_seconds: float = 300.0
    clients: tuple[str, ...] = ("dn1", "dn2", "dn3", "dn4")
    rate_profiles: dict[str, dict[str, float]] = field(
        default_factory=_default_rate_profiles)
    #: Per-file Gaussian jitter of the rates (reference: src/access_simulator.py:55-57):
    #: read_rate  ~ N(mu, max(1e-4, 0.2*mu)), write_rate ~ N(mu, max(1e-4, 0.5*mu)),
    #: locality_bias ~ N(mu, 0.2) clipped to [0, 1].
    read_rate_jitter: float = 0.2
    write_rate_jitter: float = 0.5
    locality_jitter_std: float = 0.2
    seed: int | None = None


# ---------------------------------------------------------------------------
# Pipeline configuration
# ---------------------------------------------------------------------------

@dataclass
class PipelineConfig:
    """End-to-end pipeline: generator -> simulator -> features -> kmeans -> scoring."""

    backend: str = "numpy"  # {"numpy", "jax"}
    generator: GeneratorConfig = field(default_factory=GeneratorConfig)
    simulator: SimulatorConfig = field(default_factory=SimulatorConfig)
    kmeans: KMeansConfig = field(default_factory=KMeansConfig)
    scoring: ScoringConfig = field(default_factory=ScoringConfig)
    #: Mesh shape for the jax backend, e.g. {"data": 8} or {"data": 4, "model": 2}.
    mesh_shape: dict[str, int] | None = None
    #: When True, apply the decided replication factors on the simulated
    #: cluster and report locality/load/storage vs uniform baselines
    #: (cdrs_tpu/cluster — the loop the reference never closes).
    evaluate: bool = False

    def replace(self, **kwargs) -> "PipelineConfig":
        return dataclasses.replace(self, **kwargs)


# ---------------------------------------------------------------------------
# Config-file loading (JSON)
# ---------------------------------------------------------------------------

def scoring_config_from_dict(d: Mapping) -> ScoringConfig:
    """Build a ScoringConfig from a plain dict (e.g. parsed JSON).

    Unknown keys are rejected — a typo'd weight table must not silently fall
    back to defaults.  The reference hardcodes all of this in module constants
    flagged "MUST be replaced" (src/main.py:20-62); here it is user data.
    """
    allowed = {f.name for f in dataclasses.fields(ScoringConfig)}
    unknown = set(d) - allowed
    if unknown:
        raise ValueError(f"unknown scoring config keys: {sorted(unknown)}")
    kwargs = dict(d)
    for key in ("features", "categories"):
        if key in kwargs:
            kwargs[key] = tuple(kwargs[key])
    cfg = ScoringConfig(**kwargs)
    # Validate enum-ish fields here rather than deep inside a backend kernel
    # (an invalid value like "histo" would otherwise only surface mid-run).
    if cfg.median_method not in ("auto", "sort", "hist", "bisect"):
        raise ValueError(
            f"median_method must be 'auto', 'sort', 'hist', or 'bisect'; "
            f"got {cfg.median_method!r}")
    if int(cfg.median_bins) < 2:
        raise ValueError(f"median_bins must be >= 2, got {cfg.median_bins}")
    # Validate cross-references early (a missing weight/direction entry would
    # otherwise surface as a KeyError deep inside the score kernel).
    for c in cfg.categories:
        for table, name in ((cfg.weights, "weights"),
                            (cfg.directions, "directions")):
            if c not in table:
                raise ValueError(f"{name} missing category {c!r}")
            missing = set(cfg.features) - set(table[c])
            if missing:
                raise ValueError(
                    f"{name}[{c!r}] missing features {sorted(missing)}")
        if c not in cfg.replication_factors:
            raise ValueError(f"replication_factors missing category {c!r}")
    missing = set(cfg.features) - set(cfg.global_medians)
    if missing:
        raise ValueError(f"global_medians missing features {sorted(missing)}")
    # rf >= 1 per category, offender named (models/replication.py): an
    # rf=0 typo must fail at parse time, not deep inside placement.
    from .models.replication import validate_replication_factors

    validate_replication_factors(cfg)
    return cfg


def load_scoring_config(path: str) -> ScoringConfig:
    """Load a ScoringConfig from a JSON file."""
    import json

    with open(path) as f:
        return scoring_config_from_dict(json.load(f))
