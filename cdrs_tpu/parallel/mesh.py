"""Device-mesh utilities — the framework's one communication layer.

The reference's "cluster" is a docker-compose file of Hadoop/Spark containers
communicating over TCP shuffles and HDFS RPC (reference: docker/docker-compose.yml:4-79,
SURVEY.md §2.5).  The TPU-native equivalent is a ``jax.sharding.Mesh`` over
chips with XLA collectives (``psum``/``pmax``/``all_gather``) riding ICI/DCN —
every distributed operation in this framework goes through a mesh built here.

Mesh axes:

* ``data`` — file/event rows are sharded along it (the reference's Spark
  row-partitioning axis).
* ``model`` — optional second axis sharding the centroid table for very large
  k (tensor parallelism of the (n, k) distance matrix).
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

__all__ = ["make_mesh", "mesh_from_shape", "pad_rows", "prefix_mask",
           "shard_map_compat", "collective_bytes_estimate",
           "validate_mesh_shape", "DATA_AXIS", "MODEL_AXIS"]

DATA_AXIS = "data"
MODEL_AXIS = "model"


def shard_map_compat(f, *, mesh, in_specs, out_specs,
                     check_vma: bool = False):
    """``jax.shard_map`` across jax versions.

    The top-level ``jax.shard_map`` (and its ``check_vma`` kwarg) only
    exists in newer jax releases; older ones ship it as
    ``jax.experimental.shard_map.shard_map`` with the kwarg named
    ``check_rep``.  Every shard_map in the framework goes through here so
    the supported-version window is one function wide.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map

    return shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     check_rep=check_vma)


def make_mesh(n_data: int = 1, n_model: int = 1, devices=None) -> Mesh:
    """Build a mesh from the first n_data*n_model devices.

    1D ``(data,)`` when n_model == 1 (the common case — keeps specs simple for
    purely data-parallel kernels), 2D ``(data, model)`` otherwise.
    """
    devices = list(devices if devices is not None else jax.devices())
    need = n_data * n_model
    if need > len(devices):
        raise ValueError(
            f"mesh {DATA_AXIS}={n_data}, {MODEL_AXIS}={n_model} needs "
            f"{need} devices, have {len(devices)} (on CPU, force virtual "
            f"devices with XLA_FLAGS=--xla_force_host_platform_device_count"
            f"={need})"
        )
    if n_model == 1:
        return Mesh(np.array(devices[:n_data]), (DATA_AXIS,))
    arr = np.array(devices[:need]).reshape(n_data, n_model)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def mesh_from_shape(mesh_shape: dict[str, int] | None, devices=None) -> Mesh:
    """Mesh from a ``{"data": N, "model": M}`` dict (missing axes default 1).

    ``mesh_shape=None`` means a single-device mesh — the uniform code path:
    collectives over a 1-element axis are identity ops and XLA elides them.
    Unknown axis names are an error (a typo'd ``{"dtaa": 8}`` must not
    silently build a 1x1 mesh), as are non-positive sizes — the validation
    gate for shapes arriving from CLI/scenario JSON.
    """
    shape = validate_mesh_shape(mesh_shape)
    return make_mesh(shape.get(DATA_AXIS, 1), shape.get(MODEL_AXIS, 1), devices)


def validate_mesh_shape(mesh_shape: dict[str, int] | None) -> dict[str, int]:
    """Normalize a ``{"data": N, "model": M}`` spec: reject unknown axis
    names (named in the message) and sizes < 1; values coerce to int."""
    shape = {k: v for k, v in (mesh_shape or {}).items()}
    unknown = set(shape) - {DATA_AXIS, MODEL_AXIS}
    if unknown:
        raise ValueError(
            f"unknown mesh axis {sorted(unknown)}: a mesh shape takes "
            f"{DATA_AXIS!r} (rows sharded over devices) and "
            f"{MODEL_AXIS!r} (centroid table sharded)")
    for k, v in shape.items():
        if int(v) < 1:
            raise ValueError(
                f"mesh axis {k!r} must be >= 1, got {v}")
        shape[k] = int(v)
    return shape


def collective_bytes_estimate(payload_bytes: int, n_devices: int) -> int:
    """Estimated bytes moved across the mesh by one all-reduce (``psum``)
    of a ``payload_bytes`` buffer — the ring-allreduce model: each of the
    N devices sends ``2·(N-1)/N · payload``, so the mesh total is
    ``2·(N-1) · payload``.  0 on a single device (XLA elides the op).
    Used by the controller/bench telemetry to read windows/sec against
    mesh size; an estimate of wire traffic, not a measurement.
    """
    n = int(n_devices)
    if n <= 1:
        return 0
    return int(2 * (n - 1) * int(payload_bytes))


def pad_rows(x: np.ndarray, multiple: int) -> tuple[np.ndarray, int]:
    """Pad axis 0 up to a multiple (for even sharding); returns (padded, n_valid).

    Padded rows carry weight 0 in every kernel (see kmeans_jax), so they never
    influence sums, counts, or sampling.
    """
    n = x.shape[0]
    rem = (-n) % multiple
    if rem == 0:
        return x, n
    pad_width = [(0, rem)] + [(0, 0)] * (x.ndim - 1)
    return np.pad(x, pad_width), n


def prefix_mask(x, n_valid: int, sharded: bool = True):
    """Shard-local validity mask (valid rows are a global prefix).

    Built in-program from the static count so no O(n) mask array crosses the
    host boundary.  For use inside ``shard_map`` bodies sharded over
    DATA_AXIS; ``sharded=False`` for the single-device bypass (no axis).
    """
    import jax.numpy as jnp
    from jax import lax

    n_loc = x.shape[0]
    row0 = lax.axis_index(DATA_AXIS) * n_loc if sharded else 0
    return ((row0 + jnp.arange(n_loc)) < n_valid).astype(x.dtype)
