"""Multi-host initialization — the DCN tier of the communication layer.

The reference scales across machines with YARN containers exchanging Spark
shuffles over TCP (reference: docker/docker-compose.yml:22-64, Makefile:45-60);
its "communication backend" is the JVM's (SURVEY.md §2.5).  The TPU-native
equivalent is ``jax.distributed``: one Python process per host, a coordinator
for rendezvous, and after initialization ``jax.devices()`` spans every chip of
every host — the meshes built by ``parallel.mesh`` then stretch across hosts
transparently and XLA routes collectives over ICI within a slice and DCN
between hosts.

This workload's cross-shard traffic is deliberately tiny — per-iteration
``psum`` of the (k, d) centroid statistics and (k, bins) median histograms,
never the points matrix — so the data axis can span DCN without the usual
bandwidth penalty: the ICI/DCN boundary matters for all-gathers of activations
in an LLM, not for kilobyte-scale stat reductions (scaling-book recipe: keep
the fat axis on ICI; our fat axis never leaves the chip).

Usage (one process per host)::

    from cdrs_tpu.parallel.distributed import init_distributed, global_mesh

    init_distributed()                 # env-driven on TPU pods (GKE/QR set
                                       # the coordinator + process env vars)
    mesh = global_mesh(n_model=2)      # data axis spans all hosts
    model = ReplicationPolicyModel(..., mesh_shape=mesh_axis_sizes(mesh))

On a single host everything is a no-op: ``global_mesh`` over the local
devices is exactly ``parallel.mesh.make_mesh``.
"""

from __future__ import annotations

import jax

from .mesh import DATA_AXIS, MODEL_AXIS, make_mesh

__all__ = ["init_distributed", "global_mesh", "mesh_axis_sizes"]

_initialized = False


# Markers of a multi-process launch jax.distributed can auto-configure from.
# Mirrors the detectors in jax's cluster registry (jax._src.clusters):
# explicit coordinator overrides, multislice (MEGASCALE_*), single-slice
# GKE/QR TPU pods (the TPU runtime publishes the worker roster), SLURM, and
# Open MPI / mpiexec launches.  Presence alone is not enough — a 1-chip VM
# also carries TPU_WORKER_HOSTNAMES and a 1-task SLURM job carries
# SLURM_JOB_ID — so the size markers are checked for world size > 1 (a
# single-process "cluster" stays on the no-op path per the contract below).
_COORDINATOR_ENV_VARS = (
    "JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS",
)
_WORLD_SIZE_ENV_VARS = (    # var -> process count (int, or comma-roster)
    "TPU_WORKER_HOSTNAMES",  # comma-separated host roster (TPU pod)
    # SLURM: the STEP-scoped count (set only under srun, once per task).
    # The allocation-scoped SLURM_NTASKS is deliberately not consulted — a
    # bare `python ...` inside an `#SBATCH -n 4` allocation is still ONE
    # process, and initialize() would hang waiting for 3 phantom peers.
    "SLURM_STEP_NUM_TASKS",
    "OMPI_COMM_WORLD_SIZE", "PMI_SIZE",          # Open MPI / mpiexec
)


def _env_multiprocess() -> bool:
    """True when the environment describes a >1-process launch."""
    import os

    if any(v in os.environ for v in _COORDINATOR_ENV_VARS):
        return True
    for v in _WORLD_SIZE_ENV_VARS:
        raw = os.environ.get(v)
        if raw is None:
            continue
        if "," in raw or not raw.strip().isdigit():
            if len([h for h in raw.split(",") if h.strip()]) > 1:
                return True
        elif int(raw) > 1:
            return True
    return False


def init_distributed(coordinator_address: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None,
                     force: bool = False) -> bool:
    """Idempotent ``jax.distributed.initialize`` wrapper.

    With no arguments, initializes when the environment describes a
    multi-process launch — an explicit coordinator
    (``JAX_COORDINATOR_ADDRESS``/``COORDINATOR_ADDRESS``, multislice
    ``MEGASCALE_*``) or a world size > 1 from the markers JAX's own
    cluster detectors key on (``TPU_WORKER_HOSTNAMES`` roster,
    ``SLURM_STEP_NUM_TASKS``, ``OMPI_COMM_WORLD_SIZE``/``PMI_SIZE``) — and defers
    the actual address/rank resolution to ``jax.distributed.initialize()``'s
    auto-detection.  Pass ``force=True`` to skip the environment gate and
    always call ``initialize()`` (e.g. a pod runtime that exposes only the
    TPU metadata server, none of the env markers).  Explicit arguments
    support manual bring-up (e.g. two CPU hosts over DCN).

    Returns True when a multi-process runtime is active after the call,
    False when running single-process (in which case nothing was
    initialized and local devices are used as-is — the single-host path
    must keep working without a coordinator).
    """
    global _initialized
    if _initialized:
        return jax.process_count() > 1
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = int(num_processes)
    if process_id is not None:
        kwargs["process_id"] = int(process_id)
    if not kwargs and not force:
        # Decide from the ENVIRONMENT only: any jax call here (even
        # jax.process_count()) would initialize the XLA backend, which
        # jax.distributed.initialize() then rejects outright.
        if not _env_multiprocess():
            return False   # plain single-process run; nothing to do
    jax.distributed.initialize(**kwargs)
    _initialized = True
    return jax.process_count() > 1


def global_mesh(n_data: int | None = None, n_model: int = 1):
    """Mesh over the GLOBAL device set (all hosts after init_distributed).

    ``n_data=None`` uses every device not consumed by the model axis.  The
    device order groups each host's chips contiguously (jax.devices() order),
    so a 2D mesh keeps the model axis intra-host (ICI) and lets the data
    axis cross hosts (DCN) — the right layout for this workload's traffic
    (see module docstring).
    """
    devices = jax.devices()
    if n_data is None:
        if len(devices) % n_model:
            raise ValueError(
                f"{len(devices)} devices not divisible by model axis "
                f"{n_model}")
        n_data = len(devices) // n_model
    return make_mesh(n_data=n_data, n_model=n_model, devices=devices)


def mesh_axis_sizes(mesh) -> dict[str, int]:
    """``{"data": N, "model": M}`` dict for APIs taking ``mesh_shape``."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    return {DATA_AXIS: sizes.get(DATA_AXIS, 1),
            MODEL_AXIS: sizes.get(MODEL_AXIS, 1)}
