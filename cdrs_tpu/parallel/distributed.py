"""Multi-host initialization — the DCN tier of the communication layer.

The reference scales across machines with YARN containers exchanging Spark
shuffles over TCP (reference: docker/docker-compose.yml:22-64, Makefile:45-60);
its "communication backend" is the JVM's (SURVEY.md §2.5).  The TPU-native
equivalent is ``jax.distributed``: one Python process per host, a coordinator
for rendezvous, and after initialization ``jax.devices()`` spans every chip of
every host — the meshes built by ``parallel.mesh`` then stretch across hosts
transparently and XLA routes collectives over ICI within a slice and DCN
between hosts.

This workload's cross-shard traffic is deliberately tiny — per-iteration
``psum`` of the (k, d) centroid statistics and (k, bins) median histograms,
never the points matrix — so the data axis can span DCN without the usual
bandwidth penalty: the ICI/DCN boundary matters for all-gathers of activations
in an LLM, not for kilobyte-scale stat reductions (scaling-book recipe: keep
the fat axis on ICI; our fat axis never leaves the chip).

Usage (one process per host)::

    from cdrs_tpu.parallel.distributed import init_distributed, global_mesh

    init_distributed()                 # env-driven on TPU pods (GKE/QR set
                                       # the coordinator + process env vars)
    mesh = global_mesh(n_model=2)      # data axis spans all hosts
    model = ReplicationPolicyModel(..., mesh_shape=mesh_axis_sizes(mesh))

On a single host everything is a no-op: ``global_mesh`` over the local
devices is exactly ``parallel.mesh.make_mesh``.
"""

from __future__ import annotations

import jax

from .mesh import DATA_AXIS, MODEL_AXIS, make_mesh

__all__ = ["init_distributed", "global_mesh", "mesh_axis_sizes"]

_initialized = False


def init_distributed(coordinator_address: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None) -> bool:
    """Idempotent ``jax.distributed.initialize`` wrapper.

    With no arguments, relies on the environment (TPU pod runtimes and GKE
    set ``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/... for you);
    explicit arguments support manual bring-up (e.g. two CPU hosts over
    DCN).  Returns True when a multi-process runtime is active after the
    call, False when running single-process (in which case nothing was
    initialized and local devices are used as-is — the single-host path
    must keep working without a coordinator).
    """
    global _initialized
    if _initialized:
        return jax.process_count() > 1
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = int(num_processes)
    if process_id is not None:
        kwargs["process_id"] = int(process_id)
    if not kwargs:
        # Decide from the ENVIRONMENT only: any jax call here (even
        # jax.process_count()) would initialize the XLA backend, which
        # jax.distributed.initialize() then rejects outright.
        import os

        env_driven = any(v in os.environ for v in (
            "JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
            "MEGASCALE_COORDINATOR_ADDRESS"))
        if not env_driven:
            return False   # plain single-process run; nothing to do
    jax.distributed.initialize(**kwargs)
    _initialized = True
    return jax.process_count() > 1


def global_mesh(n_data: int | None = None, n_model: int = 1):
    """Mesh over the GLOBAL device set (all hosts after init_distributed).

    ``n_data=None`` uses every device not consumed by the model axis.  The
    device order groups each host's chips contiguously (jax.devices() order),
    so a 2D mesh keeps the model axis intra-host (ICI) and lets the data
    axis cross hosts (DCN) — the right layout for this workload's traffic
    (see module docstring).
    """
    devices = jax.devices()
    if n_data is None:
        if len(devices) % n_model:
            raise ValueError(
                f"{len(devices)} devices not divisible by model axis "
                f"{n_model}")
        n_data = len(devices) // n_model
    return make_mesh(n_data=n_data, n_model=n_model, devices=devices)


def mesh_axis_sizes(mesh) -> dict[str, int]:
    """``{"data": N, "model": M}`` dict for APIs taking ``mesh_shape``."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    return {DATA_AXIS: sizes.get(DATA_AXIS, 1),
            MODEL_AXIS: sizes.get(MODEL_AXIS, 1)}
