"""Streaming feature extraction — incremental segment reductions over event batches.

The reference computes features in one Spark job over the complete log
(src/compute_features.py); the BASELINE config-5 scenario instead feeds 1B
events as a stream.  This module keeps per-file running counters on device and
folds in fixed-size event batches with the same segment kernels as the batch
backend (features/jax_backend.py):

* ``access_freq`` / ``writes`` / ``local_accesses`` — additive int32 segment
  sums (exact regardless of x64 mode; float32 counters would silently
  saturate at 2**24 events per file — reachable at the 1B-event target).
* ``concurrency`` (max events-per-second per file) — per-batch run-length
  counts over lexsorted (path, second) plus an exact cross-batch merge: the
  state carries each file's last-seen second and that second's running count,
  and a batch whose first second for a file equals the carried second absorbs
  the carried count before the max.  Requires the stream to be time-ordered
  per file across batches (the reference sorts its log globally,
  src/access_simulator.py:60).
* ``age_seconds`` / ``write_ratio`` / min-max norm — computed at finalize
  from the accumulated counters (exact formulas of SURVEY.md §2.2).

**Multi-chip**: ``mesh_shape={"data": N}`` shards each batch's events over the
mesh's data axis (time-contiguous shards — requires globally time-sorted
batches), psum-merging the per-shard counter deltas — the streaming analogue
of the sharded batch kernel (features/jax_backend.py).  Cross-shard split
seconds are corrected exactly via the ≤ 2N shard-edge seconds (all_gather +
psum), and carried counts are folded in per file at its first second of the
batch.  The single-device path is the same code over a 1-element mesh
(collectives become identity ops — parallel/mesh.py's uniform-path design).

``stream_features`` over any batch split of a log is bit-equal to the batch
backends — enforced by tests/test_streaming.py, including on the 8-device
CPU mesh.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, replace

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..io.events import EventLog, Manifest
from ..parallel.mesh import DATA_AXIS, make_mesh
from .jax_backend import _concurrency_local, _pad_events
from .numpy_backend import FeatureTable

__all__ = ["StreamFeatureState", "stream_init", "stream_update", "stream_finalize"]


@dataclass
class StreamFeatureState:
    """Per-file running counters (device arrays, replicated) + host scalars."""

    access_freq: jax.Array   # (n,) int32
    writes: jax.Array        # (n,) int32
    local_acc: jax.Array     # (n,) int32
    conc_max: jax.Array      # (n,) int32
    last_sec: jax.Array      # (n,) int32, -1 = never seen
    last_count: jax.Array    # (n,) int32 — running count of last_sec's bucket
    sec_base: float | None = None   # host: epoch floor of the first event seen
    observation_end: float | None = None  # host: max raw ts seen
    n_events: int = 0
    #: Padded batch row count — later batches pad UP to this (bucketing) so a
    #: variable-length tail reuses the full batches' compiled fold instead of
    #: triggering a per-size XLA recompile (VERDICT r2 weak #6).
    pad_events: int = 0


def stream_init(n_files: int) -> StreamFeatureState:
    z = jnp.zeros((n_files,), jnp.int32)
    return StreamFeatureState(
        access_freq=z, writes=z, local_acc=z, conc_max=z,
        last_sec=jnp.full((n_files,), -1, jnp.int32),
        last_count=z,
    )


@functools.lru_cache(maxsize=32)
def _build_update(e: int, n: int, ndata: int = 1):
    """Compile the sharded batch fold for one (batch rows, n files, mesh) point.

    The returned function takes the event shard columns plus the replicated
    state arrays and returns the updated state arrays.

    ``ndata == 1`` compiles the body as a plain jit with identity collectives
    and no shard-edge pass: wrapping a 1-device mesh in shard_map forces
    XLA's SPMD scatter lowering, measured ~7x slower per segment_sum on v5e
    (the whole fold: 4.9 s vs 0.18 s per 1M-event batch), and shard-edge
    seconds cannot exist without shards.
    """
    sharded = ndata > 1
    imax = jnp.int32(np.iinfo(np.int32).max)

    if sharded:
        def ps(x):
            return lax.psum(x, DATA_AXIS)

        def pmax_(x):
            return lax.pmax(x, DATA_AXIS)

        def pmin_(x):
            return lax.pmin(x, DATA_AXIS)
    else:
        ps = pmax_ = pmin_ = lambda x: x

    def local_fn(pid, sec, op, client, primary_node_id,
                 access_freq, writes, local_acc, conc_max, last_sec, last_count):
        valid = pid >= 0
        wi = valid.astype(jnp.int32)
        pid_c = jnp.where(valid, pid, 0).astype(jnp.int32)

        batch_access = ps(jax.ops.segment_sum(wi, pid_c, num_segments=n))
        access_freq = access_freq + batch_access
        writes = writes + ps(
            jax.ops.segment_sum(wi * (op == 1), pid_c, num_segments=n))
        is_local = (client == primary_node_id[pid_c]).astype(jnp.int32) * wi
        local_acc = local_acc + ps(
            jax.ops.segment_sum(is_local, pid_c, num_segments=n))
        present = batch_access > 0

        # --- concurrency ---
        sort_pid = jnp.where(valid, pid, n).astype(jnp.int32)
        conc = jnp.maximum(
            conc_max,
            pmax_(_concurrency_local(sort_pid, sec, wi, n)),
        )

        # Per-file first/last second of this batch (int-extreme defaults for
        # absent files; ``present`` gates every use).
        sec_hi = jnp.where(valid, sec, imax)
        sec_lo = jnp.where(valid, sec, -1)
        s_first = pmin_(
            jnp.minimum(jax.ops.segment_min(sec_hi, pid_c, num_segments=n), imax))
        s_last = pmax_(
            jnp.maximum(jax.ops.segment_max(sec_lo, pid_c, num_segments=n), -1))

        # Cross-batch carry: the carried (last_sec, last_count) continues into
        # this batch iff the file's first second here equals the carried one.
        carry = jnp.where(present & (last_sec == s_first), last_count, 0)

        # Exact totals at each file's first second (local counts of events in
        # that file's first-second bucket, psum-merged, plus the carry).
        l_first = jax.ops.segment_sum(
            wi * (sec == s_first[pid_c]), pid_c, num_segments=n)
        total_first = ps(l_first) + carry
        conc = jnp.maximum(conc, jnp.where(present, total_first, 0))

        if sharded:
            # Shard-edge seconds (time-contiguous shards ⇒ only these can
            # hold a (file, second) bucket split across shards): psum exact
            # counts, with the carry folded in where the edge second is a
            # file's first.  Single-shard batches have no edges — the block
            # would only re-derive counts the run-length pass already has.
            smin = jnp.min(sec_hi)
            smax = jnp.max(sec_lo)
            bounds = lax.all_gather(jnp.stack([smin, smax]),
                                    DATA_AXIS).reshape(-1)

            def edge_count(i, conc):
                b = bounds[i]
                cnt = ps(jax.ops.segment_sum(wi * (sec == b), pid_c,
                                             num_segments=n))
                cnt = cnt + jnp.where(s_first == b, carry, 0)
                return jnp.maximum(conc, jnp.where(present, cnt, 0))

            conc = lax.fori_loop(0, bounds.shape[0], edge_count, conc)

        # Trailing (second, running count) for the next batch.  The last
        # second's total is exact: either all its events sit on one shard
        # (local count psums right because other shards contribute 0 at that
        # second for that file... only when split across shards do multiple
        # shards contribute, and the psum of per-shard partial counts IS the
        # total), plus the carry when the batch has a single bucket.
        l_last = jax.ops.segment_sum(
            wi * (sec == s_last[pid_c]), pid_c, num_segments=n)
        total_last = ps(l_last) + jnp.where(s_last == s_first, carry, 0)
        new_last_sec = jnp.where(present, s_last, last_sec)
        new_last_count = jnp.where(present, total_last, last_count)

        return access_freq, writes, local_acc, conc, new_last_sec, new_last_count

    if not sharded:
        return jax.jit(local_fn)

    mesh = make_mesh(n_data=ndata)
    return jax.jit(jax.shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),
                  P(), P(), P(), P(), P(), P(), P()),
        out_specs=(P(), P(), P(), P(), P(), P()),
        check_vma=False,
    ))


def stream_update(state: StreamFeatureState, events: EventLog,
                  manifest: Manifest,
                  mesh_shape: dict[str, int] | None = None,
                  check_sorted: bool = True) -> StreamFeatureState:
    """Fold one event batch into the state.

    Batches must be time-ordered per file across calls; with a multi-device
    ``mesh_shape`` each batch must additionally be globally time-sorted (the
    shards must be time-contiguous — see module docstring; verified per batch
    unless ``check_sorted=False``).
    """
    e = len(events)
    if e == 0:
        return state
    n = len(manifest)
    ndata = int((mesh_shape or {}).get(DATA_AXIS, 1))
    if ndata > 1 and check_sorted and not bool(np.all(np.diff(events.ts) >= 0)):
        raise ValueError(
            "sharded stream_update requires each batch to be globally "
            "time-sorted (shards must be time-contiguous for exact "
            "concurrency); sort the stream or pass check_sorted=False")

    batch_max = float(events.ts.max())
    obs = batch_max if state.observation_end is None else max(
        state.observation_end, batch_max)

    sec_base = state.sec_base
    if sec_base is None:
        sec_base = float(np.floor(events.ts.min()))
    sec = (np.floor(events.ts) - sec_base).astype(np.int32)

    pid = np.asarray(events.path_id, dtype=np.int32)
    op = np.asarray(events.op)
    client = np.asarray(events.client_id, dtype=np.int32)
    # Bucket-pad: batches no larger than the biggest seen so far reuse its
    # compiled fold (padded rows are pid=-1, masked in-kernel).
    pid, sec, op, client = _pad_events(pid, sec, op, client, ndata,
                                       target=state.pad_events)

    fn = _build_update(len(pid), n, ndata)
    af, wr, la, cm, ls, lc = fn(
        jnp.asarray(pid), jnp.asarray(sec), jnp.asarray(op),
        jnp.asarray(client),
        jnp.asarray(manifest.primary_node_id, dtype=jnp.int32),
        state.access_freq, state.writes, state.local_acc,
        state.conc_max, state.last_sec, state.last_count,
    )
    return replace(
        state,
        access_freq=af, writes=wr, local_acc=la, conc_max=cm,
        last_sec=ls, last_count=lc,
        sec_base=sec_base, observation_end=obs,
        n_events=state.n_events + e,
        pad_events=max(state.pad_events, len(pid)),
    )


def stream_finalize(state: StreamFeatureState, manifest: Manifest,
                    observation_end: float | None = None) -> FeatureTable:
    """Assemble the five features + norms from the accumulated counters."""
    from .streaming_np import finalize_counters

    if observation_end is None:
        observation_end = state.observation_end
    return finalize_counters(state.access_freq, state.writes, state.local_acc,
                             state.conc_max, manifest, observation_end)
