"""Streaming feature extraction — incremental segment reductions over event batches.

The reference computes features in one Spark job over the complete log
(src/compute_features.py); the BASELINE config-5 scenario instead feeds 1B
events as a stream.  This module keeps per-file running counters on device and
folds in fixed-size event batches with the same segment kernels as the batch
backend (features/jax_backend.py):

* ``access_freq`` / ``writes`` / ``local_accesses`` — additive int32 segment
  sums (exact regardless of x64 mode; float32 counters would silently
  saturate at 2**24 events per file — reachable at the 1B-event target).
* ``concurrency`` (max events-per-second per file) — per-batch run-length
  counts over lexsorted (path, second) plus an exact cross-batch merge: the
  state carries each file's last-seen second and that second's running count,
  and a batch whose first second for a file equals the carried second absorbs
  the carried count before the max.  Requires the stream to be time-ordered
  per file across batches (the reference sorts its log globally,
  src/access_simulator.py:60).
* ``age_seconds`` / ``write_ratio`` / min-max norm — computed at finalize
  from the accumulated counters (exact formulas of SURVEY.md §2.2).

**Multi-chip**: ``mesh_shape={"data": N}`` shards each batch's events over the
mesh's data axis (time-contiguous shards — requires globally time-sorted
batches), psum-merging the per-shard counter deltas — the streaming analogue
of the sharded batch kernel (features/jax_backend.py).  Cross-shard split
seconds are corrected exactly via the ≤ 2N shard-edge seconds (all_gather +
psum), and carried counts are folded in per file at its first second of the
batch.  The single-device path is the same code over a 1-element mesh
(collectives become identity ops — parallel/mesh.py's uniform-path design).

``stream_features`` over any batch split of a log is bit-equal to the batch
backends — enforced by tests/test_streaming.py, including on the 8-device
CPU mesh.
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass, replace

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..io.events import EventLog, Manifest
from ..parallel.mesh import DATA_AXIS, make_mesh, shard_map_compat
from .jax_backend import _concurrency_local
from .numpy_backend import FeatureTable

__all__ = ["StreamFeatureState", "stream_init", "stream_update",
           "stream_finalize", "fold_stream", "save_stream_state",
           "load_stream_state"]


@dataclass
class StreamFeatureState:
    """Per-file running counters (device arrays, replicated) + host scalars."""

    access_freq: jax.Array   # (n,) int32
    writes: jax.Array        # (n,) int32
    local_acc: jax.Array     # (n,) int32
    conc_max: jax.Array      # (n,) int32
    last_sec: jax.Array      # (n,) int32, -1 = never seen
    last_count: jax.Array    # (n,) int32 — running count of last_sec's bucket
    sec_base: float | None = None   # host: epoch floor of the first event seen
    observation_end: float | None = None  # host: max raw ts seen
    n_events: int = 0
    #: Padded batch row count — later batches pad UP to this (bucketing) so a
    #: variable-length tail reuses the full batches' compiled fold instead of
    #: triggering a per-size XLA recompile (VERDICT r2 weak #6).
    pad_events: int = 0


def stream_init(n_files: int) -> StreamFeatureState:
    z = jnp.zeros((n_files,), jnp.int32)
    return StreamFeatureState(
        access_freq=z, writes=z, local_acc=z, conc_max=z,
        last_sec=jnp.full((n_files,), -1, jnp.int32),
        last_count=z,
    )


@functools.lru_cache(maxsize=64)
def _build_update(e: int, n: int, ndata: int = 1, wire: str = "cols"):
    """Compile the sharded batch fold for one (batch rows, n files, mesh) point.

    The returned function takes the event shard columns plus the replicated
    state arrays and returns the updated state arrays.  ``wire`` selects the
    event encoding: ``"cols"`` takes (pid i32, sec i32, flags u8); ``"packed"``
    takes (pid|flags<<24 i32, sec-delta u8, sec0 scalar) — see _PreppedBatch.

    ``ndata == 1`` compiles the body as a plain jit with identity collectives
    and no shard-edge pass: wrapping a 1-device mesh in shard_map forces
    XLA's SPMD scatter lowering, measured ~7x slower per segment_sum on v5e
    (the whole fold: 4.9 s vs 0.18 s per 1M-event batch), and shard-edge
    seconds cannot exist without shards.
    """
    sharded = ndata > 1
    imax = jnp.int32(np.iinfo(np.int32).max)

    if sharded:
        def ps(x):
            return lax.psum(x, DATA_AXIS)

        def pmax_(x):
            return lax.pmax(x, DATA_AXIS)

        def pmin_(x):
            return lax.pmin(x, DATA_AXIS)
    else:
        ps = pmax_ = pmin_ = lambda x: x

    def local_fn(pid, sec, flags,
                 access_freq, writes, local_acc, conc_max, last_sec, last_count):
        # ``flags`` packs op (bit 0) and is-local (bit 1, precomputed on
        # host against the manifest's primary nodes) into one byte — the
        # event batch is 9 B/row over the wire instead of 13 B plus an (n,)
        # primary-node column per call.  On a remote-tunnel backend the
        # host->device transfer is the fold's bottleneck (measured 8-24
        # MB/s vs 0.56 s of device compute per 4M-event batch).
        valid = pid >= 0
        wi = valid.astype(jnp.int32)
        pid_c = jnp.where(valid, pid, 0).astype(jnp.int32)

        batch_access = ps(jax.ops.segment_sum(wi, pid_c, num_segments=n))
        access_freq = access_freq + batch_access
        writes = writes + ps(jax.ops.segment_sum(
            (flags & 1).astype(jnp.int32) * wi, pid_c, num_segments=n))
        is_local = ((flags >> 1) & 1).astype(jnp.int32) * wi
        local_acc = local_acc + ps(
            jax.ops.segment_sum(is_local, pid_c, num_segments=n))
        present = batch_access > 0

        # --- concurrency ---
        sort_pid = jnp.where(valid, pid, n).astype(jnp.int32)
        conc = jnp.maximum(
            conc_max,
            pmax_(_concurrency_local(sort_pid, sec, wi, n)),
        )

        # Per-file first/last second of this batch (int-extreme defaults for
        # absent files; ``present`` gates every use).
        sec_hi = jnp.where(valid, sec, imax)
        sec_lo = jnp.where(valid, sec, -1)
        s_first = pmin_(
            jnp.minimum(jax.ops.segment_min(sec_hi, pid_c, num_segments=n), imax))
        s_last = pmax_(
            jnp.maximum(jax.ops.segment_max(sec_lo, pid_c, num_segments=n), -1))

        # Cross-batch carry: the carried (last_sec, last_count) continues into
        # this batch iff the file's first second here equals the carried one.
        carry = jnp.where(present & (last_sec == s_first), last_count, 0)

        # Exact totals at each file's first second (local counts of events in
        # that file's first-second bucket, psum-merged, plus the carry).
        l_first = jax.ops.segment_sum(
            wi * (sec == s_first[pid_c]), pid_c, num_segments=n)
        total_first = ps(l_first) + carry
        conc = jnp.maximum(conc, jnp.where(present, total_first, 0))

        if sharded:
            # Shard-edge seconds (time-contiguous shards ⇒ only these can
            # hold a (file, second) bucket split across shards): psum exact
            # counts, with the carry folded in where the edge second is a
            # file's first.  Single-shard batches have no edges — the block
            # would only re-derive counts the run-length pass already has.
            smin = jnp.min(sec_hi)
            smax = jnp.max(sec_lo)
            bounds = lax.all_gather(jnp.stack([smin, smax]),
                                    DATA_AXIS).reshape(-1)

            def edge_count(i, conc):
                b = bounds[i]
                cnt = ps(jax.ops.segment_sum(wi * (sec == b), pid_c,
                                             num_segments=n))
                cnt = cnt + jnp.where(s_first == b, carry, 0)
                return jnp.maximum(conc, jnp.where(present, cnt, 0))

            conc = lax.fori_loop(0, bounds.shape[0], edge_count, conc)

        # Trailing (second, running count) for the next batch.  The last
        # second's total is exact: either all its events sit on one shard
        # (local count psums right because other shards contribute 0 at that
        # second for that file... only when split across shards do multiple
        # shards contribute, and the psum of per-shard partial counts IS the
        # total), plus the carry when the batch has a single bucket.
        l_last = jax.ops.segment_sum(
            wi * (sec == s_last[pid_c]), pid_c, num_segments=n)
        total_last = ps(l_last) + jnp.where(s_last == s_first, carry, 0)
        new_last_sec = jnp.where(present, s_last, last_sec)
        new_last_count = jnp.where(present, total_last, last_count)

        return access_freq, writes, local_acc, conc, new_last_sec, new_last_count

    if not sharded:
        base = jax.jit(local_fn)
    else:
        mesh = make_mesh(n_data=ndata)
        base = jax.jit(shard_map_compat(
            local_fn,
            mesh=mesh,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),
                      P(), P(), P(), P(), P(), P()),
            out_specs=(P(), P(), P(), P(), P(), P()),
            check_vma=False,
        ))
    if wire == "cols":
        return base

    # wire == "packed": 5 B/event over the tunnel instead of 9.
    #   pidf int32 = pid (24 bits, 0xFFFFFF = invalid) | flags << 24
    #   dsec uint8 = per-event second deltas (the stream is time-sorted, so
    #                deltas are almost all 0/1); sec0 () int32 = first second.
    # The decode (mask/shift + int32 cumsum) runs on device where it is
    # effectively free; host->device bytes are what the tunnel charges for.
    # ``base`` is the jitted cols-wire program for this (e, n, ndata) point,
    # sharded or not — one wrapper serves both branches.
    def packed_fn(pidf, dsec, sec0, *state_arrs):
        pid = pidf & jnp.int32(0xFFFFFF)
        pid = jnp.where(pid == jnp.int32(0xFFFFFF), -1, pid)
        flags = (pidf >> 24).astype(jnp.uint8)
        sec = jnp.cumsum(dsec.astype(jnp.int32)) + sec0
        return base(pid, sec, flags, *state_arrs)

    return jax.jit(packed_fn)


#: pid values >= this cannot share an int32 with the flags byte — such
#: populations (>16.7M files) fall back to the "cols" wire format.
_PACK_PID_LIMIT = 0xFFFFFF


@dataclass
class _PreppedBatch:
    """Host-side half of one fold: padded, packed columns + carried meta.

    Produced by ``_prep_batch`` (pure numpy — safe to run on a prefetch
    thread), consumed by ``_fold_prepped`` (the only half that touches jax).

    Two wire formats (``wire``):
      * ``"packed"`` — ``pid`` holds pid|flags<<24 int32, ``sec`` holds
        uint8 second-deltas, ``sec0`` the first second: 5 B/event.
      * ``"cols"`` — ``pid`` int32, ``sec`` int32, ``flags`` uint8: the
        9 B/event fallback (unsorted batch, second gaps > 255, or
        populations too large to pack).
    """

    pid: np.ndarray     # (E,) int32 — pid, or pid|flags<<24 when packed
    sec: np.ndarray     # (E,) int32 seconds, or (E,) uint8 deltas when packed
    flags: np.ndarray | None   # (E,) uint8 (cols wire only)
    n_events: int       # raw (unpadded) rows
    batch_max: float    # max raw ts in the batch
    sec_base: float
    ndata: int
    wire: str = "cols"
    sec0: int = 0       # first second (packed wire only)


def _prep_batch(events: EventLog, manifest: Manifest, *,
                sec_base: float | None, pad_target: int, ndata: int = 1,
                check_sorted: bool = True) -> _PreppedBatch | None:
    """numpy-only batch preparation; returns None for an empty batch."""
    e = len(events)
    if e == 0:
        return None
    if ndata > 1 and check_sorted and not bool(np.all(np.diff(events.ts) >= 0)):
        raise ValueError(
            "sharded stream_update requires each batch to be globally "
            "time-sorted (shards must be time-contiguous for exact "
            "concurrency); sort the stream or pass check_sorted=False")

    if sec_base is None:
        sec_base = float(np.floor(events.ts.min()))
    sec = (np.floor(events.ts) - sec_base).astype(np.int32)

    pid = np.asarray(events.path_id, dtype=np.int32)
    valid = pid >= 0
    prim = np.asarray(manifest.primary_node_id, dtype=np.int32)
    is_local = (np.asarray(events.client_id, dtype=np.int32)
                == prim[np.where(valid, pid, 0)]) & valid
    flags = ((np.asarray(events.op).astype(np.uint8) & 1)
             | (is_local.astype(np.uint8) << 1))

    # Wire-format choice: pack to 5 B/event when pids fit 24 bits and the
    # batch's seconds are monotone with gaps <= 255 (true for any globally
    # time-sorted log with sub-4-minute silences); else plain columns.
    dsec = np.diff(sec)
    packable = (len(manifest) < _PACK_PID_LIMIT
                and int(pid.max(initial=0)) < _PACK_PID_LIMIT
                and (e == 1 or (dsec.min(initial=0) >= 0
                                and dsec.max(initial=0) <= 255)))

    # Bucket-pad: batches no larger than the biggest seen so far reuse its
    # compiled fold (padded rows are pid-invalid, masked in-kernel).
    want = max(e, int(pad_target))
    want += (-want) % ndata
    pad = want - e

    def padded(a, fill):
        return np.concatenate([a, np.full(pad, fill, a.dtype)]) if pad else a

    if packable:
        pidf = np.where(valid, pid, _PACK_PID_LIMIT).astype(np.int32) \
            | (flags.astype(np.int32) << 24)
        d8 = np.empty(e, np.uint8)
        d8[0] = 0
        d8[1:] = dsec
        return _PreppedBatch(pid=padded(pidf, _PACK_PID_LIMIT),
                             sec=padded(d8, 0), flags=None, n_events=e,
                             batch_max=float(events.ts.max()),
                             sec_base=sec_base, ndata=ndata,
                             wire="packed", sec0=int(sec[0]))

    return _PreppedBatch(pid=padded(pid, -1), sec=padded(sec, sec[-1]),
                         flags=padded(flags, 0), n_events=e,
                         batch_max=float(events.ts.max()), sec_base=sec_base,
                         ndata=ndata)


def _fold_prepped(state: StreamFeatureState,
                  pb: _PreppedBatch) -> StreamFeatureState:
    """Device-side half: dispatch one prepped batch into the state."""
    n = int(state.access_freq.shape[0])
    fn = _build_update(len(pb.pid), n, pb.ndata, pb.wire)
    # Both wires take (pid-ish, sec-ish, third): sec0 scalar when packed,
    # the flags column otherwise.
    third = np.int32(pb.sec0) if pb.wire == "packed" else pb.flags
    af, wr, la, cm, ls, lc = fn(
        jnp.asarray(pb.pid), jnp.asarray(pb.sec), jnp.asarray(third),
        state.access_freq, state.writes, state.local_acc,
        state.conc_max, state.last_sec, state.last_count,
    )
    obs = pb.batch_max if state.observation_end is None else max(
        state.observation_end, pb.batch_max)
    return replace(
        state,
        access_freq=af, writes=wr, local_acc=la, conc_max=cm,
        last_sec=ls, last_count=lc,
        sec_base=pb.sec_base, observation_end=obs,
        n_events=state.n_events + pb.n_events,
        pad_events=max(state.pad_events, len(pb.pid)),
    )


def stream_update(state: StreamFeatureState, events: EventLog,
                  manifest: Manifest,
                  mesh_shape: dict[str, int] | None = None,
                  check_sorted: bool = True) -> StreamFeatureState:
    """Fold one event batch into the state.

    Batches must be time-ordered per file across calls; with a multi-device
    ``mesh_shape`` each batch must additionally be globally time-sorted (the
    shards must be time-contiguous — see module docstring; verified per batch
    unless ``check_sorted=False``).
    """
    ndata = int((mesh_shape or {}).get(DATA_AXIS, 1))
    pb = _prep_batch(events, manifest, sec_base=state.sec_base,
                     pad_target=state.pad_events, ndata=ndata,
                     check_sorted=check_sorted)
    if pb is None:
        return state
    return _fold_prepped(state, pb)


#: Fields of StreamFeatureState snapshotted by save/load_stream_state.
_STATE_ARRAYS = ("access_freq", "writes", "local_acc", "conc_max",
                 "last_sec", "last_count")


def save_stream_state(path: str, state: StreamFeatureState,
                      log_offset: int | None = None,
                      log_bytes: int | None = None) -> None:
    """Atomic snapshot of the fold state (+ the log byte offset it covers).

    ``log_bytes`` (the log's size at snapshot time) lets resume detect a
    swapped/rewritten log; n_files is implicit in the array shapes and
    validated against the manifest on resume.
    """
    from ..utils.checkpoint import save_state

    save_state(path,
               {k: np.asarray(getattr(state, k)) for k in _STATE_ARRAYS},
               meta={"sec_base": state.sec_base,
                     "observation_end": state.observation_end,
                     "n_events": state.n_events,
                     "pad_events": state.pad_events,
                     "log_offset": log_offset,
                     "log_bytes": log_bytes})


def load_stream_state(path: str) -> tuple[StreamFeatureState, int | None,
                                          int | None]:
    """Returns (state, log_offset, log_bytes) saved by save_stream_state."""
    from ..utils.checkpoint import load_state

    arrays, meta = load_state(path)
    state = StreamFeatureState(
        **{k: jnp.asarray(arrays[k]) for k in _STATE_ARRAYS},
        sec_base=meta.get("sec_base"),
        observation_end=meta.get("observation_end"),
        n_events=int(meta.get("n_events", 0)),
        pad_events=int(meta.get("pad_events", 0)),
    )
    return state, meta.get("log_offset"), meta.get("log_bytes")


def fold_stream(source, manifest: Manifest, *,
                state: StreamFeatureState | None = None,
                batch_size: int = 4_000_000,
                mesh_shape: dict[str, int] | None = None,
                native: bool | None = None,
                check_sorted: bool = True,
                queue_depth: int = 2,
                checkpoint_path: str | None = None,
                checkpoint_every: int = 25,
                stats: dict | None = None) -> StreamFeatureState:
    """Fold a whole log with parse/prep PIPELINED against the device fold.

    A producer thread parses batches (the native chunk parser and the tunnel
    waits both release the GIL) and runs the numpy prep; the calling thread
    — the only one that touches jax — transfers and folds.  On a
    remote-tunnel backend this hides the entire parse+prep cost behind the
    host->device transfer, which is the fold loop's real bottleneck
    (measured: parse 1.6 s + prep vs transfer 2-7 s per 4M-event batch).

    ``source`` is a log path (streamed via ``EventLog.read_csv_batches``)
    or an iterable of EventLog batches.  ``stats``, when given, receives
    ``producer_seconds`` (parse+prep busy time) and ``fold_seconds``
    (transfer+fold busy time) for disclosure.

    ``checkpoint_path`` makes the hour-scale 1B-event fold crash-safe: every
    ``checkpoint_every`` folded batches the state is fetched and snapshotted
    (atomic npz) together with the log byte offset it covers, and a later
    call with the same path resumes the scan from that offset — the resumed
    result is bit-identical to an uninterrupted fold (the cross-batch
    concurrency carry lives in the state arrays).  Requires a path source;
    the snapshot cadence stops if the python fallback parser takes over
    (no byte offsets there).
    """
    import queue as _queue
    import threading
    import time as _time

    start_offset = 0
    if checkpoint_path is not None:
        if not isinstance(source, (str, bytes, os.PathLike)):
            raise ValueError("checkpoint_path requires a log-path source "
                             "(resume needs byte offsets)")
        if state is not None:
            raise ValueError("pass state via the checkpoint, not both")
        if os.path.exists(checkpoint_path):
            state, off, ck_bytes = load_stream_state(checkpoint_path)
            # A stale checkpoint from a different dataset must be a loud
            # error, not silently-wrong features: the state arrays must
            # match the manifest, and the log must still be the (possibly
            # grown) file the snapshot's offset indexes into.
            n_ck = int(state.access_freq.shape[0])
            if n_ck != len(manifest):
                raise ValueError(
                    f"checkpoint {checkpoint_path!r} covers {n_ck} files "
                    f"but the manifest has {len(manifest)} — stale "
                    "checkpoint? delete it to start over")
            size_now = os.path.getsize(source)
            if ck_bytes is not None and size_now < int(ck_bytes):
                raise ValueError(
                    f"log {source!r} is smaller ({size_now} B) than when "
                    f"the checkpoint was written ({ck_bytes} B) — the log "
                    "was swapped or truncated; delete the checkpoint to "
                    "start over")
            start_offset = int(off or 0)
    if state is None:
        state = stream_init(len(manifest))
    ndata = int((mesh_shape or {}).get(DATA_AXIS, 1))

    if isinstance(source, (str, bytes, os.PathLike)):
        batches = EventLog.read_csv_batches(source, manifest,
                                            batch_size=batch_size,
                                            native=native,
                                            start_offset=start_offset,
                                            with_offsets=True)
    else:
        batches = ((ev, None) for ev in source)

    q: _queue.Queue = _queue.Queue(maxsize=max(1, queue_depth))
    done = object()
    stop = threading.Event()   # consumer died early: unwind the producer
    meta = {"sec_base": state.sec_base, "pad_target": state.pad_events,
            "busy": 0.0, "parse": 0.0}

    def produce():
        try:
            it = iter(batches)
            while not stop.is_set():
                t0 = _time.perf_counter()
                try:
                    ev, off = next(it)
                except StopIteration:
                    break
                meta["parse"] += _time.perf_counter() - t0
                t0 = _time.perf_counter()
                pb = _prep_batch(ev, manifest, sec_base=meta["sec_base"],
                                 pad_target=meta["pad_target"], ndata=ndata,
                                 check_sorted=check_sorted)
                meta["busy"] += _time.perf_counter() - t0
                if pb is None:
                    continue
                meta["sec_base"] = pb.sec_base
                meta["pad_target"] = max(meta["pad_target"], len(pb.pid))
                q.put((pb, off))
        except BaseException as exc:   # surface in the consumer
            q.put(exc)
        else:
            q.put(done)

    t = threading.Thread(target=produce, name="cdrs-stream-prep", daemon=True)
    t.start()
    fold_busy = 0.0
    n_batches = 0
    since_ckpt = 0
    try:
        while True:
            item = q.get()
            if item is done:
                break
            if isinstance(item, BaseException):
                raise item
            pb, off = item
            t0 = _time.perf_counter()
            state = _fold_prepped(state, pb)
            fold_busy += _time.perf_counter() - t0
            n_batches += 1
            since_ckpt += 1
            if (checkpoint_path is not None and off is not None
                    and since_ckpt >= max(1, checkpoint_every)):
                save_stream_state(checkpoint_path, state, log_offset=int(off),
                                  log_bytes=os.path.getsize(source))
                since_ckpt = 0
    finally:
        # A consumer exception can leave the producer blocked in q.put with
        # the log generator (and its file handle) open: signal it to stop
        # and drain the queue until the thread exits so nothing leaks.
        stop.set()
        while t.is_alive():
            try:
                q.get_nowait()
            except _queue.Empty:
                pass
            t.join(timeout=0.05)
    if checkpoint_path is not None and os.path.exists(checkpoint_path):
        # The fold is complete: the checkpoint has served its purpose (a
        # stale one must not hijack a future fresh run over the same path).
        os.unlink(checkpoint_path)
    if stats is not None:
        stats["producer_seconds"] = meta["busy"] + meta["parse"]
        stats["parse_seconds"] = meta["parse"]
        stats["prep_seconds"] = meta["busy"]
        stats["fold_seconds"] = fold_busy
        stats["batches"] = n_batches
        stats["resumed_from_offset"] = start_offset
    return state


def stream_finalize(state: StreamFeatureState, manifest: Manifest,
                    observation_end: float | None = None) -> FeatureTable:
    """Assemble the five features + norms from the accumulated counters."""
    from .streaming_np import finalize_counters

    if observation_end is None:
        observation_end = state.observation_end
    return finalize_counters(state.access_freq, state.writes, state.local_acc,
                             state.conc_max, manifest, observation_end)
