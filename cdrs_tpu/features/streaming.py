"""Streaming feature extraction — incremental segment reductions over event batches.

The reference computes features in one Spark job over the complete log
(src/compute_features.py); the BASELINE config-5 scenario instead feeds 1B
events as a stream.  This module keeps per-file running counters on device and
folds in fixed-size event batches with the same segment kernels as the batch
backend (features/jax_backend.py):

* ``access_freq`` / ``writes`` / ``local_accesses`` — additive segment sums.
* ``concurrency`` (max events-per-second per file) — per-batch run-length
  counts over lexsorted (path, second) plus an exact cross-batch merge: the
  state carries each file's last-seen second and that second's partial count,
  so a second split across batch boundaries is re-joined before the max.
  Requires the stream to be time-ordered per file (the reference sorts its
  log globally, src/access_simulator.py:60).
* ``age_seconds`` / ``write_ratio`` / min-max norm — computed at finalize
  from the accumulated counters (exact formulas of SURVEY.md §2.2).

``stream_features`` over any batch split of a log is bit-equal to the batch
backends — enforced by tests/test_streaming.py.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, replace

import numpy as np

import jax
import jax.numpy as jnp

from ..io.events import EventLog, Manifest
from .numpy_backend import FeatureTable, minmax_normalize

__all__ = ["StreamFeatureState", "stream_init", "stream_update", "stream_finalize"]


@dataclass
class StreamFeatureState:
    """Per-file running counters (device arrays) + host scalars.

    Counters are int32: exact accumulation with no dependence on x64 mode
    (float32 counters would silently saturate at 2**24 events per file —
    reachable at the 1B-event target scale).
    """

    access_freq: jax.Array   # (n,) int32
    writes: jax.Array        # (n,) int32
    local_acc: jax.Array     # (n,) int32
    conc_max: jax.Array      # (n,) int32
    last_sec: jax.Array      # (n,) int32, -1 = never seen
    last_count: jax.Array    # (n,) int32
    sec_base: float | None = None   # host: epoch floor of the first event seen
    observation_end: float | None = None  # host: max raw ts seen
    n_events: int = 0


def stream_init(n_files: int) -> StreamFeatureState:
    z = jnp.zeros((n_files,), jnp.int32)
    return StreamFeatureState(
        access_freq=z, writes=z, local_acc=z, conc_max=z,
        last_sec=jnp.full((n_files,), -1, jnp.int32),
        last_count=z,
    )


@functools.lru_cache(maxsize=32)
def _build_update(e, n):
    @jax.jit
    def update(pid, sec, op, client, primary_node_id,
               access_freq, writes, local_acc, conc_max, last_sec, last_count):
        valid = pid >= 0
        w = valid.astype(jnp.int32)
        pid_c = jnp.where(valid, pid, 0).astype(jnp.int32)

        access_freq = access_freq + jax.ops.segment_sum(w, pid_c, num_segments=n)
        writes = writes + jax.ops.segment_sum(w * (op == 1), pid_c, num_segments=n)
        is_local = (client == primary_node_id[pid_c]).astype(jnp.int32) * w
        local_acc = local_acc + jax.ops.segment_sum(is_local, pid_c, num_segments=n)

        # --- concurrency with cross-batch merge ---
        sort_pid = jnp.where(valid, pid, n).astype(jnp.int32)
        order = jnp.lexsort((sec, sort_pid))
        s_pid = sort_pid[order]
        s_sec = sec[order]
        s_w = w[order]

        first_of_pid = jnp.concatenate([
            jnp.ones((1,), bool), s_pid[1:] != s_pid[:-1]])
        last_of_pid = jnp.concatenate([
            s_pid[1:] != s_pid[:-1], jnp.ones((1,), bool)])
        new_run = first_of_pid | jnp.concatenate([
            jnp.ones((1,), bool), s_sec[1:] != s_sec[:-1]])
        run_id = jnp.cumsum(new_run.astype(jnp.int32)) - 1
        run_count = jax.ops.segment_sum(s_w, run_id, num_segments=e)  # (e,) run-level

        s_pid_safe = jnp.where(s_pid < n, s_pid, 0)
        # Carry merge: a run that starts a file's presence in this batch and
        # continues the file's last-seen second absorbs that second's partial
        # count from the previous batch.
        carry = jnp.where(
            first_of_pid & (last_sec[s_pid_safe] == s_sec) & (s_pid < n),
            last_count[s_pid_safe],
            0,
        )
        # run-level effective counts, viewed at run-start events
        eff = run_count[run_id] + carry  # carry only nonzero at run starts
        eff_at_start = jnp.where(new_run & (s_pid < n), eff, 0)
        conc_max = jnp.maximum(
            conc_max,
            jax.ops.segment_max(eff_at_start, s_pid_safe, num_segments=n),
        )

        # Store each file's trailing (second, count) for the next batch.  The
        # trailing run's effective count includes the carry when the file has
        # a single run in this batch.  ``eff`` lives at run-start events;
        # propagate it to every event of the run via each run's start index.
        start_idx = jax.ops.segment_max(
            jnp.where(new_run, jnp.arange(e), 0), run_id, num_segments=e)
        eff_run = eff_at_start[start_idx[run_id]]

        sel = last_of_pid & (s_pid < n)
        tgt = jnp.where(sel, s_pid, n)  # n = drop
        last_sec = last_sec.at[tgt].set(s_sec, mode="drop")
        last_count = last_count.at[tgt].set(eff_run, mode="drop")
        return access_freq, writes, local_acc, conc_max, last_sec, last_count

    return update


def stream_update(state: StreamFeatureState, events: EventLog,
                  manifest: Manifest) -> StreamFeatureState:
    """Fold one event batch into the state (batch must be time-ordered)."""
    e = len(events)
    if e == 0:
        return state
    n = len(manifest)

    batch_max = float(events.ts.max())
    obs = batch_max if state.observation_end is None else max(
        state.observation_end, batch_max)

    sec_base = state.sec_base
    if sec_base is None:
        sec_base = float(np.floor(events.ts.min()))
    sec = (np.floor(events.ts) - sec_base).astype(np.int32)

    fn = _build_update(e, n)
    af, wr, la, cm, ls, lc = fn(
        jnp.asarray(events.path_id, dtype=jnp.int32),
        jnp.asarray(sec),
        jnp.asarray(events.op),
        jnp.asarray(events.client_id, dtype=jnp.int32),
        jnp.asarray(manifest.primary_node_id, dtype=jnp.int32),
        state.access_freq, state.writes, state.local_acc,
        state.conc_max, state.last_sec, state.last_count,
    )
    return replace(
        state,
        access_freq=af, writes=wr, local_acc=la, conc_max=cm,
        last_sec=ls, last_count=lc,
        sec_base=sec_base, observation_end=obs,
        n_events=state.n_events + e,
    )


def stream_finalize(state: StreamFeatureState, manifest: Manifest,
                    observation_end: float | None = None) -> FeatureTable:
    """Assemble the five features + norms from the accumulated counters."""
    import time

    n = len(manifest)
    if observation_end is None:
        observation_end = (
            state.observation_end if state.observation_end is not None else time.time()
        )

    access_freq = np.asarray(state.access_freq, dtype=np.float64)
    writes = np.asarray(state.writes, dtype=np.float64)
    local_acc = np.asarray(state.local_acc, dtype=np.float64)
    concurrency = np.asarray(state.conc_max, dtype=np.float64)
    reads = access_freq - writes

    locality = np.where(access_freq > 0,
                        local_acc / np.maximum(access_freq, 1.0), 1.0)
    age_seconds = observation_end - manifest.creation_ts
    mean_writes = float(writes.mean()) if n else 0.0
    if mean_writes == 0:
        mean_writes = 1.0  # reference: compute_features.py:64-65
    write_ratio = writes / mean_writes

    raw = np.stack([access_freq, age_seconds, write_ratio, locality, concurrency],
                   axis=1)
    norm = np.stack([minmax_normalize(raw[:, j]) for j in range(raw.shape[1])],
                    axis=1)
    return FeatureTable(paths=list(manifest.paths), raw=raw, norm=norm,
                        writes=writes, reads=reads)
