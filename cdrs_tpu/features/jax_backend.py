"""JAX feature-extraction backend — segment reductions replace Spark groupBys.

Computes the five per-file features of reference src/compute_features.py
(exact formulas in SURVEY.md §2.2) as one jit-compiled kernel over the
struct-of-arrays event log:

* ``access_freq``/``writes``/``reads`` — ``segment_sum`` keyed by path id
  (replaces the Spark groupBy shuffles, compute_features.py:31-34).
* ``locality`` — segment_sum of (client == primary_node) matches; 1.0 for
  never-accessed files (compute_features.py:37-42, 68).
* ``concurrency`` — max events-per-second per path (compute_features.py:44-46):
  lexsort events by (path, second), run-length count the equal-(path, second)
  runs with a cumsum over run boundaries, then ``segment_max`` the run counts
  by path.  Static shapes throughout — no ``np.unique`` dynamic sizing.
* ``age_seconds``/``write_ratio``/min-max ``*_norm`` — full-array reductions
  (compute_features.py:48-54, 62-66, 77-94), including the degenerate guards
  (mean writes 0 -> 1.0; constant column -> all-zero norm).

Counters accumulate as **int32 segment sums** (exact regardless of x64 mode —
float32 accumulators would silently lose counts past 2^24 events per file,
reachable at the 1B-event target) and are cast to float only for ratios and
normalization.

Events with paths missing from the manifest are masked out of every counter
but still counted toward ``observation_end`` (left-join semantics,
compute_features.py:48, 56-60) — the mask happens in-kernel so event arrays
never need host-side filtering.

**Multi-chip**: ``mesh_shape={"data": N}`` shards the event stream over the
mesh's data axis in time-contiguous blocks — the TPU equivalent of the
reference's Spark executors partitioning the log (compute_features.py:11,
SURVEY.md §2.5).  Each chip segment-sums its event shard into a replicated
(n,) stats table and a single cross-chip ``psum`` merges them.  Concurrency
needs one extra step: a (path, second) pair can straddle a shard boundary, and
because shards are time-contiguous only the ≤ 2N shard-edge seconds can be
split — those are ``all_gather``-ed and their counts psum-merged exactly
(see ``_features_local``).  The result is bit-equal to the single-device
kernel for any time-sorted log; enforced by tests/test_features_jax.py on the
8-device CPU mesh.

The numpy backend (features/numpy_backend.py) is the golden model; parity is
enforced by tests/test_features_jax.py.
"""

from __future__ import annotations

import functools
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..io.events import EventLog, Manifest
from ..parallel.mesh import DATA_AXIS, make_mesh, shard_map_compat
from .numpy_backend import FeatureTable

__all__ = ["compute_features_jax", "features_kernel"]


def _pad_events(pid, sec, op, client, multiple):
    """Pad event columns to an even shard split.  Padded rows are pid=-1
    (masked in-kernel) with the last real second so they never widen the
    boundary-second set; mesh.pad_rows would zero-pad, aliasing pid 0.
    (Bucket padding for the streaming path lives in streaming._prep_batch.)
    """
    pad = (-len(pid)) % multiple
    if pad:
        # Empty batch: any fill second works — pid=-1 masks every padded row.
        last_sec = sec[-1] if len(sec) else np.int32(0)
        pid = np.concatenate([pid, np.full(pad, -1, np.int32)])
        sec = np.concatenate([sec, np.full(pad, last_sec, np.int32)])
        op = np.concatenate([op, np.zeros(pad, op.dtype)])
        client = np.concatenate([client, np.zeros(pad, client.dtype)])
    return pid, sec, op, client


def _concurrency_local(pid, sec, wi, n):
    """Shard-local max events-per-second per path (int32, (n,)).

    Lexsort by (path, second), run-length count equal-(path, second) runs via
    a cumsum over run boundaries, segment_max the run counts by path.  Exact
    when the shard holds every event of each (path, second) pair it sees;
    partial counts at shard-edge seconds are corrected by the caller.
    """
    e = pid.shape[0]
    order = jnp.lexsort((sec, pid))
    s_pid = pid[order]
    s_sec = sec[order]
    s_w = wi[order]
    new_run = jnp.concatenate([
        jnp.ones((1,), jnp.int32),
        ((s_pid[1:] != s_pid[:-1]) | (s_sec[1:] != s_sec[:-1])).astype(jnp.int32),
    ])
    run_id = jnp.cumsum(new_run) - 1                     # (e,) run index
    run_counts = jax.ops.segment_sum(s_w, run_id, num_segments=e)
    per_event_count = run_counts[run_id] * s_w
    conc = jax.ops.segment_max(
        per_event_count, jnp.where(s_pid < n, s_pid, 0), num_segments=n
    )
    return jnp.maximum(conc, 0)  # int-min identity -> 0 for no-event files


def _features_local(pid, sec, op, client, primary_node_id, age_seconds, *,
                    n, sharded):
    """Feature kernel body; runs standalone or inside shard_map over DATA_AXIS.

    Event arrays are the (sharded) stream; ``primary_node_id``/``age_seconds``
    are replicated (n,) manifest columns.  Returns replicated
    (raw (n,5), norm (n,5), writes (n,), reads (n,)) in ``age_seconds.dtype``.
    """
    ftype = age_seconds.dtype
    valid = pid >= 0
    wi = valid.astype(jnp.int32)
    pid_c = jnp.where(valid, pid, 0).astype(jnp.int32)

    access_i = jax.ops.segment_sum(wi, pid_c, num_segments=n)
    writes_i = jax.ops.segment_sum(wi * (op == 1), pid_c, num_segments=n)
    is_local = (client == primary_node_id[pid_c]).astype(jnp.int32) * wi
    local_i = jax.ops.segment_sum(is_local, pid_c, num_segments=n)

    sort_pid = jnp.where(valid, pid, n).astype(jnp.int32)  # invalid sorts last
    conc_i = _concurrency_local(sort_pid, sec, wi, n)

    if sharded:
        access_i = lax.psum(access_i, DATA_AXIS)
        writes_i = lax.psum(writes_i, DATA_AXIS)
        local_i = lax.psum(local_i, DATA_AXIS)
        # Shard-local run counts are exact except at seconds split across a
        # shard edge.  Shards are time-contiguous, so only each shard's first
        # and last valid second can be split: gather those ≤ 2N boundary
        # seconds (identical on every shard) and psum their exact counts.
        # Partial local counts at boundary seconds are ≤ the exact psum'd
        # total, so keeping them in the pmax is harmless.
        conc_i = lax.pmax(conc_i, DATA_AXIS)
        big = jnp.int32(np.iinfo(np.int32).max)
        smin = jnp.min(jnp.where(valid, sec, big))
        smax = jnp.max(jnp.where(valid, sec, -1))
        bounds = lax.all_gather(jnp.stack([smin, smax]), DATA_AXIS).reshape(-1)

        def edge_count(i, conc):
            b = bounds[i]
            cnt = jax.ops.segment_sum(wi * (sec == b), pid_c, num_segments=n)
            return jnp.maximum(conc, lax.psum(cnt, DATA_AXIS))

        conc_i = lax.fori_loop(0, bounds.shape[0], edge_count, conc_i)

    access_freq = access_i.astype(ftype)
    writes = writes_i.astype(ftype)
    reads = access_freq - writes
    locality = jnp.where(
        access_i > 0, local_i.astype(ftype) / jnp.maximum(access_freq, 1.0), 1.0
    )
    concurrency = conc_i.astype(ftype)

    mean_writes = jnp.mean(writes)
    mean_writes = jnp.where(mean_writes == 0, 1.0, mean_writes)
    write_ratio = writes / mean_writes

    raw = jnp.stack(
        [access_freq, age_seconds, write_ratio, locality, concurrency], axis=1
    )
    lo = raw.min(axis=0)
    hi = raw.max(axis=0)
    norm = jnp.where(hi > lo, (raw - lo) / jnp.where(hi > lo, hi - lo, 1.0), 0.0)
    return raw, norm, writes, reads


@functools.partial(jax.jit, static_argnames=("n",))
def features_kernel(
    pid: jnp.ndarray,          # (e,) int32, -1 = not in manifest
    sec: jnp.ndarray,          # (e,) int32 second bucket, rebased to min=0
    op: jnp.ndarray,           # (e,) int8, 1 = WRITE
    client: jnp.ndarray,       # (e,) int32
    primary_node_id: jnp.ndarray,  # (n,) int32
    age_seconds: jnp.ndarray,  # (n,) observation_end - creation_ts
    n: int,
):
    """Single-device kernel: (raw (n,5), norm (n,5), writes (n,), reads (n,)).

    Timestamps never enter the kernel as raw epoch floats: the second buckets
    (``floor(ts)`` rebased to the window start) and ``age_seconds`` are
    pre-reduced on host in float64, because float32 — the accelerator default
    when x64 is off — has ~256 s resolution at epoch magnitude (~1.75e9),
    which would merge every event into one concurrency bucket.
    """
    return _features_local(pid, sec, op, client, primary_node_id, age_seconds,
                           n=n, sharded=False)


@functools.lru_cache(maxsize=32)
def _build_features_sharded(n: int, ndata: int):
    """Compile the event-sharded feature kernel for one (n, mesh) point."""
    mesh = make_mesh(n_data=ndata)

    def local_fn(pid, sec, op, client, primary_node_id, age_seconds):
        return _features_local(pid, sec, op, client, primary_node_id,
                               age_seconds, n=n, sharded=True)

    return jax.jit(shard_map_compat(
        local_fn,
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),
                  P(), P()),
        out_specs=(P(), P(), P(), P()),
        check_vma=False,
    ))


def compute_features_jax(
    manifest: Manifest,
    events: EventLog,
    observation_end: float | None = None,
    mesh_shape: dict[str, int] | None = None,
    check_sorted: bool = True,
    as_device: bool = False,
) -> FeatureTable:
    """Drop-in replacement for features/numpy_backend.compute_features.

    ``mesh_shape={"data": N}`` shards the event stream over N chips (see
    module docstring); it requires a time-sorted log — the reference sorts
    its log globally (src/access_simulator.py:60) and every producer in this
    framework emits sorted events.  ``check_sorted=False`` skips the O(e)
    host-side verification for very large trusted logs.

    ``as_device=True`` keeps the feature table's arrays on device (kernel
    dtype — f32 without x64), so a jax pipeline can hand ``table.norm``
    straight to the clustering kernel without a host round trip (at the
    100M x 128 target the host copy alone is ~51 GB — SURVEY.md §7.4).
    """
    n = len(manifest)

    if observation_end is None:
        observation_end = float(events.ts.max()) if len(events) else time.time()

    ndata = int((mesh_shape or {}).get(DATA_AXIS, 1))

    if len(events) == 0 or n == 0:
        # Degenerate log: all counters zero, locality 1.0 (compute_features.py:60,68).
        raw = np.zeros((n, 5), dtype=np.float64)
        raw[:, 1] = observation_end - manifest.creation_ts
        raw[:, 3] = 1.0
        if n:
            lo, hi = raw.min(axis=0), raw.max(axis=0)
            norm = np.where(hi > lo, (raw - lo) / np.where(hi > lo, hi - lo, 1.0), 0.0)
        else:
            norm = raw.copy()
        zeros = np.zeros(n, dtype=np.float64)
        if as_device:  # honor the device-residency contract on this path too
            return FeatureTable(
                paths=list(manifest.paths), raw=jnp.asarray(raw),
                norm=jnp.asarray(norm), writes=jnp.asarray(zeros),
                reads=jnp.asarray(zeros))
        return FeatureTable(paths=list(manifest.paths), raw=raw, norm=norm,
                            writes=zeros, reads=zeros.copy())

    # Host-side float64 time reductions (see features_kernel docstring).
    sec_f = np.floor(events.ts)
    sec = (sec_f - sec_f.min()).astype(np.int32)
    age = np.asarray(observation_end - manifest.creation_ts, dtype=np.float64)

    pid = np.asarray(events.path_id, dtype=np.int32)
    op = np.asarray(events.op)
    client = np.asarray(events.client_id, dtype=np.int32)

    def _run_kernel(kernel_name, fn, args, static_args, n_static_trailing):
        """Dispatch through the XLA cost capture (obs/xprof.py) when an
        instrument with xprof is active; the plain jit call otherwise."""
        from ..obs import current as _obs_current

        tel = _obs_current()
        if tel is not None and tel.xprof:
            from ..obs.jaxtools import aval_signature
            from ..obs.xprof import instrumented_call

            return instrumented_call(
                kernel_name, fn, args,
                signature=aval_signature(*args[:4], static=static_args),
                n_static_trailing=n_static_trailing)
        return fn(*args)

    if ndata > 1:
        if check_sorted and not bool(np.all(np.diff(events.ts) >= 0)):
            raise ValueError(
                "sharded feature extraction requires a time-sorted event log "
                "(shards must be time-contiguous for exact concurrency); "
                "sort the log or pass check_sorted=False at your own risk"
            )
        pid, sec, op, client = _pad_events(pid, sec, op, client, ndata)
        fn = _build_features_sharded(n, ndata)
        raw, norm, writes, reads = _run_kernel(
            "features_sharded", fn,
            (jnp.asarray(pid), jnp.asarray(sec), jnp.asarray(op),
             jnp.asarray(client),
             jnp.asarray(manifest.primary_node_id, dtype=jnp.int32),
             jnp.asarray(age)),
            (n, ndata), 0,
        )
    else:
        raw, norm, writes, reads = _run_kernel(
            "features_kernel", features_kernel,
            (jnp.asarray(pid), jnp.asarray(sec), jnp.asarray(op),
             jnp.asarray(client),
             jnp.asarray(manifest.primary_node_id, dtype=jnp.int32),
             jnp.asarray(age),
             n),
            (n,), 1,
        )
    if as_device:
        return FeatureTable(paths=list(manifest.paths), raw=raw, norm=norm,
                            writes=writes, reads=reads)
    return FeatureTable(
        paths=list(manifest.paths),
        raw=np.asarray(raw, dtype=np.float64),
        norm=np.asarray(norm, dtype=np.float64),
        writes=np.asarray(writes, dtype=np.float64),
        reads=np.asarray(reads, dtype=np.float64),
    )
