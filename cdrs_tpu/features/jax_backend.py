"""JAX feature-extraction backend — segment reductions replace Spark groupBys.

Computes the five per-file features of reference src/compute_features.py
(exact formulas in SURVEY.md §2.2) as one jit-compiled kernel over the
struct-of-arrays event log:

* ``access_freq``/``writes``/``reads`` — ``segment_sum`` keyed by path id
  (replaces the Spark groupBy shuffles, compute_features.py:31-34).
* ``locality`` — segment_sum of (client == primary_node) matches; 1.0 for
  never-accessed files (compute_features.py:37-42, 68).
* ``concurrency`` — max events-per-second per path (compute_features.py:44-46):
  lexsort events by (path, second), run-length count the equal-(path, second)
  runs with a cumsum over run boundaries, then ``segment_max`` the run counts
  by path.  Static shapes throughout — no ``np.unique`` dynamic sizing.
* ``age_seconds``/``write_ratio``/min-max ``*_norm`` — full-array reductions
  (compute_features.py:48-54, 62-66, 77-94), including the degenerate guards
  (mean writes 0 -> 1.0; constant column -> all-zero norm).

Events with paths missing from the manifest are masked out of every counter
but still counted toward ``observation_end`` (left-join semantics,
compute_features.py:48, 56-60) — the mask happens in-kernel so event arrays
never need host-side filtering.

The numpy backend (features/numpy_backend.py) is the golden model; parity is
enforced by tests/test_features_jax.py.
"""

from __future__ import annotations

import functools
import time

import numpy as np

import jax
import jax.numpy as jnp

from ..io.events import EventLog, Manifest
from .numpy_backend import FeatureTable

__all__ = ["compute_features_jax", "features_kernel"]


@functools.partial(jax.jit, static_argnames=("n",))
def features_kernel(
    pid: jnp.ndarray,          # (e,) int32, -1 = not in manifest
    sec: jnp.ndarray,          # (e,) int32 second bucket, rebased to min=0
    op: jnp.ndarray,           # (e,) int8, 1 = WRITE
    client: jnp.ndarray,       # (e,) int32
    primary_node_id: jnp.ndarray,  # (n,) int32
    age_seconds: jnp.ndarray,  # (n,) observation_end - creation_ts
    n: int,
):
    """Returns (raw (n,5), norm (n,5), writes (n,), reads (n,)).

    Timestamps never enter the kernel as raw epoch floats: the second buckets
    (``floor(ts)`` rebased to the window start) and ``age_seconds`` are
    pre-reduced on host in float64, because float32 — the accelerator default
    when x64 is off — has ~256 s resolution at epoch magnitude (~1.75e9),
    which would merge every event into one concurrency bucket.
    """
    ftype = age_seconds.dtype
    valid = pid >= 0
    w = valid.astype(ftype)
    pid_c = jnp.where(valid, pid, 0).astype(jnp.int32)

    access_freq = jax.ops.segment_sum(w, pid_c, num_segments=n)
    writes = jax.ops.segment_sum(w * (op == 1), pid_c, num_segments=n)
    reads = access_freq - writes

    is_local = (client == primary_node_id[pid_c]).astype(ftype) * w
    local_acc = jax.ops.segment_sum(is_local, pid_c, num_segments=n)
    locality = jnp.where(
        access_freq > 0, local_acc / jnp.maximum(access_freq, 1.0), 1.0
    )

    # Two-level concurrency: runs of equal (path, second) after a lexsort.
    e = pid.shape[0]
    sort_pid = jnp.where(valid, pid, n).astype(jnp.int32)  # invalid sorts last
    order = jnp.lexsort((sec, sort_pid))
    s_pid = sort_pid[order]
    s_sec = sec[order]
    s_w = w[order]
    new_run = jnp.concatenate([
        jnp.ones((1,), jnp.int32),
        ((s_pid[1:] != s_pid[:-1]) | (s_sec[1:] != s_sec[:-1])).astype(jnp.int32),
    ])
    run_id = jnp.cumsum(new_run) - 1                     # (e,) run index
    run_counts = jax.ops.segment_sum(s_w, run_id, num_segments=e)
    per_event_count = run_counts[run_id] * s_w
    conc = jax.ops.segment_max(
        per_event_count, jnp.where(s_pid < n, s_pid, 0), num_segments=n
    )
    concurrency = jnp.maximum(conc, 0.0)  # -inf identity -> 0 for no-event files

    mean_writes = jnp.mean(writes)
    mean_writes = jnp.where(mean_writes == 0, 1.0, mean_writes)
    write_ratio = writes / mean_writes

    raw = jnp.stack(
        [access_freq, age_seconds, write_ratio, locality, concurrency], axis=1
    )
    lo = raw.min(axis=0)
    hi = raw.max(axis=0)
    norm = jnp.where(hi > lo, (raw - lo) / jnp.where(hi > lo, hi - lo, 1.0), 0.0)
    return raw, norm, writes, reads


def compute_features_jax(
    manifest: Manifest,
    events: EventLog,
    observation_end: float | None = None,
) -> FeatureTable:
    """Drop-in replacement for features/numpy_backend.compute_features."""
    n = len(manifest)

    if observation_end is None:
        observation_end = float(events.ts.max()) if len(events) else time.time()

    if len(events) == 0:
        # Degenerate log: all counters zero, locality 1.0 (compute_features.py:60,68).
        raw = np.zeros((n, 5), dtype=np.float64)
        raw[:, 1] = observation_end - manifest.creation_ts
        raw[:, 3] = 1.0
        lo, hi = raw.min(axis=0), raw.max(axis=0)
        norm = np.where(hi > lo, (raw - lo) / np.where(hi > lo, hi - lo, 1.0), 0.0)
        zeros = np.zeros(n, dtype=np.float64)
        return FeatureTable(paths=list(manifest.paths), raw=raw, norm=norm,
                            writes=zeros, reads=zeros.copy())

    # Host-side float64 time reductions (see features_kernel docstring).
    sec_f = np.floor(events.ts)
    sec = (sec_f - sec_f.min()).astype(np.int32)
    age = np.asarray(observation_end - manifest.creation_ts, dtype=np.float64)

    raw, norm, writes, reads = features_kernel(
        jnp.asarray(events.path_id, dtype=jnp.int32),
        jnp.asarray(sec),
        jnp.asarray(events.op),
        jnp.asarray(events.client_id, dtype=jnp.int32),
        jnp.asarray(manifest.primary_node_id, dtype=jnp.int32),
        jnp.asarray(age),
        n,
    )
    return FeatureTable(
        paths=list(manifest.paths),
        raw=np.asarray(raw, dtype=np.float64),
        norm=np.asarray(norm, dtype=np.float64),
        writes=np.asarray(writes, dtype=np.float64),
        reads=np.asarray(reads, dtype=np.float64),
    )
