"""NumPy feature-extraction backend — replaces the reference's PySpark job.

Computes the five per-file features with the exact formulas of
reference src/compute_features.py (SURVEY.md §2.2):

* ``access_freq`` — events per path (compute_features.py:31-32)
* ``writes`` / ``reads`` — per-op counts (l.33-34)
* ``locality`` — local/total accesses, where local means the event's client
  equals the file's primary node; **1.0 for files with zero accesses**
  (l.37-42, 68)
* ``concurrency`` — max events-per-second bucket (``floor(ts)``) per path
  (l.44-46)
* ``age_seconds`` — observation_end − creation_ts, observation_end = max event
  ts over the whole log (fallback ``time.time()`` on an empty log) (l.48-54)
* ``write_ratio`` — writes / mean(writes over all files); mean forced to 1.0
  when 0.  NOT a read/write ratio (l.62-66, SURVEY.md §6.1.10).
* ``*_norm`` — global min-max per column, **0.0 for every row when
  max == min** (l.85-94)

Files present in the manifest but never accessed get zero counters and
locality 1.0 (``na.fill(0)`` + ``otherwise(1.0)``, l.60, 68).  Events whose
path is not in the manifest are dropped by the joins (l.56-59) but still count
toward ``observation_end`` (the max is taken on the raw access frame, l.48).

The Spark groupBy/join machinery becomes ``np.bincount`` segment reductions —
the same shape as the JAX backend's ``segment_sum`` (features/jax_backend.py),
which this module is the golden model for.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np

from ..config import CLUSTERING_FEATURES, RAW_FEATURES
from ..io.events import EventLog, Manifest

__all__ = ["FeatureTable", "compute_features", "minmax_normalize"]


@dataclass
class FeatureTable:
    """Raw + normalized per-file features, (n, 5) each, column order RAW_FEATURES."""

    paths: list[str]
    raw: np.ndarray          # (n, 5) float64
    norm: np.ndarray         # (n, 5) float64 in [0, 1]
    writes: np.ndarray       # (n,) kept for parity checks/debugging
    reads: np.ndarray

    raw_names: tuple[str, ...] = RAW_FEATURES
    norm_names: tuple[str, ...] = CLUSTERING_FEATURES

    def write_csv(self, path: str) -> None:
        """Emit the Spark job's CSV schema: path, 5 raw, 5 *_norm columns
        (reference: src/compute_features.py:70-75, 90-96)."""
        import csv

        # One bulk host fetch if the table is device-resident (as_device=True).
        raw = np.asarray(self.raw)
        norm = np.asarray(self.norm)
        header = ["path", *self.raw_names, *self.norm_names]
        with open(path, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(header)
            for i, p in enumerate(self.paths):
                w.writerow([p,
                            *(repr(float(v)) for v in raw[i]),
                            *(repr(float(v)) for v in norm[i])])


def minmax_normalize(col: np.ndarray) -> np.ndarray:
    """Global min-max; all-zeros when the column is constant
    (reference: src/compute_features.py:85-88)."""
    lo, hi = float(col.min()), float(col.max())
    if hi == lo:
        return np.zeros_like(col, dtype=np.float64)
    return (col - lo) / (hi - lo)


def compute_features(
    manifest: Manifest,
    events: EventLog,
    observation_end: float | None = None,
) -> FeatureTable:
    n = len(manifest)

    # observation_end from the raw log (reference: compute_features.py:48-51).
    if observation_end is None:
        observation_end = float(events.ts.max()) if len(events) else time.time()

    # Drop events not anchored to a manifest file (left-join semantics).
    keep = events.path_id >= 0
    pid = events.path_id[keep].astype(np.int64)
    ts = events.ts[keep]
    op = events.op[keep]
    client = events.client_id[keep]

    access_freq = np.bincount(pid, minlength=n).astype(np.float64)
    writes = np.bincount(pid, weights=(op == 1), minlength=n)
    reads = access_freq - writes

    is_local = (client == manifest.primary_node_id[pid]).astype(np.float64)
    local_accesses = np.bincount(pid, weights=is_local, minlength=n)
    with np.errstate(divide="ignore", invalid="ignore"):
        locality = np.where(access_freq > 0,
                            local_accesses / np.maximum(access_freq, 1), 1.0)

    # Two-level concurrency: count per (path, second) then max per path
    # (reference: compute_features.py:44-46).  Composite key over the observed
    # second range keeps bincount dense and small (range ~ duration).
    concurrency = np.zeros(n, dtype=np.float64)
    if len(ts):
        sec = np.floor(ts).astype(np.int64)
        sec -= sec.min()
        n_sec = int(sec.max()) + 1
        key = pid * n_sec + sec
        uniq, counts = np.unique(key, return_counts=True)
        np.maximum.at(concurrency, uniq // n_sec, counts.astype(np.float64))

    age_seconds = observation_end - manifest.creation_ts

    mean_writes = float(writes.mean()) if n else 0.0
    if mean_writes == 0:
        mean_writes = 1.0  # reference: compute_features.py:64-65
    write_ratio = writes / mean_writes

    raw = np.stack([access_freq, age_seconds, write_ratio, locality,
                    concurrency], axis=1)
    norm = np.stack([minmax_normalize(raw[:, j]) for j in range(raw.shape[1])], axis=1)
    return FeatureTable(paths=list(manifest.paths), raw=raw, norm=norm,
                        writes=writes, reads=reads)
