"""NumPy streaming feature fold — jax-free batch-incremental counters.

The golden-model counterpart of features/streaming.py: folds time-ordered
event batches into per-file running counters with plain ``np.bincount`` /
``np.unique`` segment reductions, including the exact cross-batch concurrency
merge (a (path, second) bucket split across batches counts once, with the
carried partial count absorbed at the file's first second of the next batch).

Exists so ``cdrs stream --backend numpy`` runs on a jax-free install (the
``tpu`` extra is optional — pyproject.toml) and as the parity reference for
the sharded device fold.  Semantics mirror reference src/compute_features.py
(SURVEY.md §2.2); batch-split invariance is enforced by tests/test_streaming.py.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..io.events import EventLog, Manifest
from .numpy_backend import FeatureTable, minmax_normalize

__all__ = ["NumpyStreamState", "stream_init_np", "stream_update_np",
           "stream_finalize_np", "finalize_counters"]


@dataclass
class NumpyStreamState:
    """Per-file running counters (int64) + host scalars."""

    access_freq: np.ndarray   # (n,) int64
    writes: np.ndarray        # (n,) int64
    local_acc: np.ndarray     # (n,) int64
    conc_max: np.ndarray      # (n,) int64
    last_sec: np.ndarray      # (n,) int64, -1 = never seen
    last_count: np.ndarray    # (n,) int64 — running count of last_sec's bucket
    sec_base: float | None = None
    observation_end: float | None = None
    n_events: int = 0


def stream_init_np(n_files: int) -> NumpyStreamState:
    z = lambda: np.zeros(n_files, dtype=np.int64)
    return NumpyStreamState(
        access_freq=z(), writes=z(), local_acc=z(), conc_max=z(),
        last_sec=np.full(n_files, -1, dtype=np.int64), last_count=z(),
    )


def stream_update_np(state: NumpyStreamState, events: EventLog,
                     manifest: Manifest) -> NumpyStreamState:
    """Fold one event batch (time-ordered per file across calls) in place."""
    e = len(events)
    if e == 0:
        return state
    n = len(manifest)

    batch_max = float(events.ts.max())
    state.observation_end = batch_max if state.observation_end is None else max(
        state.observation_end, batch_max)
    if state.sec_base is None:
        state.sec_base = float(np.floor(events.ts.min()))
    sec_all = (np.floor(events.ts) - state.sec_base).astype(np.int64)
    state.n_events += e

    keep = events.path_id >= 0
    pid = events.path_id[keep].astype(np.int64)
    sec = sec_all[keep]
    op = events.op[keep]
    client = events.client_id[keep]
    if len(pid) == 0:
        return state

    state.access_freq += np.bincount(pid, minlength=n)
    state.writes += np.bincount(pid[op == 1], minlength=n)
    is_local = client == manifest.primary_node_id[pid]
    state.local_acc += np.bincount(pid[is_local], minlength=n)

    # Per-(path, second) bucket counts via a dense composite key (second range
    # is bounded by the batch's time span).
    smin = sec.min()
    span = int(sec.max() - smin) + 1
    key = pid * span + (sec - smin)
    uniq, cnt = np.unique(key, return_counts=True)
    upid = uniq // span
    usec = uniq % span + smin
    cnt = cnt.astype(np.int64)

    # ``uniq`` is sorted by (path, second): the first occurrence per path is
    # its earliest bucket (where the cross-batch carry applies), the last its
    # latest (the next carry).
    pids_present, fidx = np.unique(upid, return_index=True)
    carry = state.last_sec[pids_present] == usec[fidx]
    cnt[fidx[carry]] += state.last_count[pids_present[carry]]

    np.maximum.at(state.conc_max, upid, cnt)

    lidx = len(upid) - 1 - np.unique(upid[::-1], return_index=True)[1]
    state.last_sec[pids_present] = usec[lidx]
    state.last_count[pids_present] = cnt[lidx]
    return state


def finalize_counters(access_freq, writes, local_acc, concurrency,
                      manifest: Manifest,
                      observation_end: float | None) -> FeatureTable:
    """Five features + norms from accumulated counters (any array-likes).

    Shared by the numpy and device stream folds; formulas per SURVEY.md §2.2
    (reference: src/compute_features.py:37-94).
    """
    import time

    n = len(manifest)
    if observation_end is None:
        observation_end = time.time()

    access_freq = np.asarray(access_freq, dtype=np.float64)
    writes = np.asarray(writes, dtype=np.float64)
    local_acc = np.asarray(local_acc, dtype=np.float64)
    concurrency = np.asarray(concurrency, dtype=np.float64)
    reads = access_freq - writes

    locality = np.where(access_freq > 0,
                        local_acc / np.maximum(access_freq, 1.0), 1.0)
    age_seconds = observation_end - manifest.creation_ts
    mean_writes = float(writes.mean()) if n else 0.0
    if mean_writes == 0:
        mean_writes = 1.0  # reference: compute_features.py:64-65
    write_ratio = writes / mean_writes

    raw = np.stack([access_freq, age_seconds, write_ratio, locality, concurrency],
                   axis=1)
    norm = np.stack([minmax_normalize(raw[:, j]) for j in range(raw.shape[1])],
                    axis=1)
    return FeatureTable(paths=list(manifest.paths), raw=raw, norm=norm,
                        writes=writes, reads=reads)


def stream_finalize_np(state: NumpyStreamState, manifest: Manifest,
                       observation_end: float | None = None) -> FeatureTable:
    if observation_end is None:
        observation_end = state.observation_end
    return finalize_counters(state.access_freq, state.writes, state.local_acc,
                             state.conc_max, manifest, observation_end)
