"""Feature-extraction backends (L2): numpy golden model + jax segment kernels."""


def get_jax_backend():
    try:
        from .jax_backend import compute_features_jax
    except ImportError as e:  # pragma: no cover
        raise NotImplementedError(
            "jax feature backend unavailable (is jax installed?)"
        ) from e
    return compute_features_jax
