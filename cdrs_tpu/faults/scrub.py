"""Budgeted background scrubbing: find silent corruption before reads do.

Replication only protects data that is actually intact — a replica that
rots in place (faults/state.py ``slot_corrupt``) still counts toward every
durability tier until something READS it.  Production systems close that
gap with a background scanner: HDFS pairs its block scanner with the
re-replication queue, and Ceph's RADOS layer runs periodic scrub /
deep-scrub over the same placement machinery this repo reproduces.  This
module is that scanner in the controller's vocabulary:

* **Round-robin cursor** — each window the scrubber verification-reads
  every reachable copy of the next files in file-index order, wrapping at
  the population end.  The cursor rides the npz checkpoint, so a
  kill/resume resumes the scan bit-identically mid-lap.
* **Budgeted** — verification reads are real traffic: each verified copy
  charges ``shard_bytes / holder throughput`` (straggler wire-time
  inflation, the repair scheduler's rule) against ``bytes_per_window``,
  itself capped by what is LEFT of the shared per-window churn budget
  after the window's repairs ran (repair heals known damage first; scrub
  spends the remainder looking for unknown damage; migrations get what
  survives both).  A window whose SHARED remainder undercuts
  ``bytes_per_window`` and halts the scan early reports ``starved`` —
  the auditor's ``scrub_starved`` flag (halting on the configured rate
  itself is normal pacing).
* **Detection -> quarantine -> repair** — a rotten copy found by the scan
  is quarantined on the spot (``ClusterState.quarantine`` drops it), so
  the very next repair sync sees the gap and re-replicates from a clean,
  verified source.
* **Read hints** — the serve router's detect-on-read path
  (serve/router.py) reports the corrupt copies it tripped over; those
  files jump the cursor queue next window (their OTHER copies are now
  suspect — rot clusters by disk and by batch).  The hint queue is
  checkpointed with the cursor.

Everything is deterministic in (cluster state, cursor, hints, budget):
no RNG, so kill/resume replays the same scan and the same detections.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

__all__ = ["ScrubConfig", "ScrubReport", "Scrubber"]


@dataclass(frozen=True)
class ScrubConfig:
    """Knobs of the background scrubber."""

    #: Verification-read budget per window (bytes at nominal throughput;
    #: straggler holders inflate the charge).  The scan rate: the whole
    #: population's stored bytes divided by this is the worst-case
    #: detection bound in windows (one full lap).
    bytes_per_window: int

    def __post_init__(self):
        if self.bytes_per_window <= 0:
            raise ValueError(
                f"scrub bytes_per_window must be > 0, got "
                f"{self.bytes_per_window}")


@dataclass
class ScrubReport:
    """What one window's scrub pass did."""

    #: Budget consumed (throughput-inflated verification reads).
    bytes_used: int = 0
    copies_verified: int = 0
    files_verified: int = 0
    #: Rotten copies found and quarantined this window.
    corrupt_found: int = 0
    #: Files verified from the read-detection hint queue (ahead of the
    #: cursor scan).
    hinted: int = 0
    #: The SHARED churn budget's remainder (after repairs) cut this
    #: window's allowance below the configured ``bytes_per_window`` and
    #: the scan halted early on it: the cadence — and therefore the
    #: detection-latency bound — is slipping behind the configured rate.
    #: Halting on ``bytes_per_window`` itself is normal pacing, not
    #: starvation.
    starved: bool = False
    #: Cursor position after the pass (next file the scan will touch).
    cursor: int = 0


class Scrubber:
    """Checkpointed scrub cursor + hint queue over one ClusterState."""

    def __init__(self, n_files: int, cfg: ScrubConfig):
        self.n_files = int(n_files)
        self.cfg = cfg
        self.cursor = 0
        #: Read-detection hints (sorted unique file ids), verified before
        #: the cursor scan next window.
        self.hints = np.zeros(0, dtype=np.int64)

    def add_hints(self, fids) -> None:
        fids = np.asarray(fids, dtype=np.int64)
        if fids.size:
            self.hints = np.union1d(self.hints, fids)

    def run_window(self, window: int, state, *,
                   shared_left: int | None = None) -> ScrubReport:
        """One window's verification pass; mutates ``state`` (quarantines
        what it finds) and the cursor/hint state.

        ``shared_left``: bytes remaining of the shared churn budget after
        repairs pre-charged it (None = unshared).  The effective allowance
        is ``min(bytes_per_window, shared_left)``; the first copy of the
        window is admitted past the configured ``bytes_per_window`` pacing
        (the largest-file-must-not-starve rule repair and migration use)
        but NEVER past ``shared_left`` — the shared remainder is a hard
        conservation bound, not a pacing hint: breaching it over-charges
        the window's churn budget (the ``budget_conserved`` violation the
        failure-space search banked).  A first copy too large for the
        remainder is deferred to a richer window and the pass reports
        ``starved``.
        """
        cap = int(self.cfg.bytes_per_window)
        #: Hard conservation bound: the first-copy override may exceed the
        #: scrubber's own rate, never the shared remainder.
        hard = None if shared_left is None else max(int(shared_left), 0)
        if hard is not None:
            cap = min(cap, hard)
        rep = ScrubReport()
        if cap <= 0:
            rep.starved = True
            rep.cursor = self.cursor
            return rep
        blocked_hard = False
        reach = state.node_reachable()
        thr = state.node_throughput

        def verify_file(fid: int) -> bool:
            """Verify every reachable copy of ``fid``; False = budget died
            before the file finished (partial verifications are re-done
            next window — the cursor does not advance past it)."""
            row = state.row(fid)
            corr = state.corrupt_row(fid)
            checked = 0
            for s in np.flatnonzero(row >= 0):
                node = int(row[s])
                if not reach[node]:
                    continue
                charge = int(np.ceil(int(state.shard_bytes[fid])
                                     / max(float(thr[node]), 1e-9)))
                if rep.bytes_used + charge > cap:
                    if rep.bytes_used > 0:
                        return False
                    if hard is not None and charge > hard:
                        # First copy, but even the full shared remainder
                        # cannot pay for it: conservation wins over the
                        # no-starve override.
                        nonlocal blocked_hard
                        blocked_hard = True
                        return False
                rep.bytes_used += charge
                rep.copies_verified += 1
                checked += 1
                if corr[s]:
                    state.quarantine(fid, node)
                    rep.corrupt_found += 1
            if checked:
                rep.files_verified += 1
            return True

        # Hints first: a read already proved these files carry rot.  The
        # queue is damage-proportional (files whose copies reads tripped
        # over), so the per-copy Python loop is fine here.
        halted = False
        consumed = 0
        for fid in self.hints:
            if not verify_file(int(fid)):
                halted = True
                break
            consumed += 1
            rep.hinted += 1
        self.hints = self.hints[consumed:]

        # Round-robin cursor scan with what remains of the allowance —
        # one full lap per window at most.  Vectorized (copy-level
        # cumsum + one searchsorted budget cut, the SoA repair-admission
        # pattern) so the clean scan costs O(population) numpy work, not
        # O(copies) Python iterations; only the rot actually found (a
        # damage-proportional handful) is quarantined in a loop.  Copy
        # admission reproduces the per-copy loop exactly: admit while
        # the running charge stays inside ``cap``, the lap's very first
        # copy is admitted regardless (largest-file-must-not-starve,
        # only when no hint bytes were spent), a partially-verified
        # boundary file is charged but not completed — the cursor holds
        # on it for next window.
        if not halted:
            n = self.n_files
            order = (self.cursor + np.arange(n)) % n     # lap order
            rm = state.rows(order)                       # (n, R)
            ok = (rm >= 0) & reach[np.clip(rm, 0, None)]
            rows, slots = np.nonzero(ok)                 # copy-level
            charge = np.ceil(
                state.shard_bytes[order[rows]]
                / np.maximum(thr[rm[rows, slots]], 1e-9)).astype(np.int64)
            csum = rep.bytes_used + np.cumsum(charge)
            kpre = int(np.searchsorted(csum, cap, side="right"))
            if kpre == 0 and rep.bytes_used == 0 and charge.size:
                if hard is None or int(charge[0]) <= hard:
                    kpre = 1
                else:
                    blocked_hard = True
            if kpre:
                rep.bytes_used = int(csum[kpre - 1])
                rep.copies_verified += kpre
                fids = order[rows[:kpre]]
                corr = state.corrupt_at(fids, slots[:kpre])
                nodes = rm[rows[:kpre], slots[:kpre]]
                for f, nd in zip(fids[corr].tolist(),
                                 nodes[corr].tolist()):
                    state.quarantine(int(f), int(nd))
                    rep.corrupt_found += 1
            # File completion: a lap file is done when its LAST copy is
            # inside the admitted prefix (zero-copy files complete for
            # free behind a completed neighbour, hold behind a partial
            # one — the loop's visit order).
            ends = np.cumsum(np.bincount(rows, minlength=n))
            n_done = int(np.searchsorted(ends, kpre, side="right"))
            counts = ends[:n_done]
            if n_done:
                rep.files_verified += int(
                    (np.diff(np.concatenate(([0], counts))) > 0).sum())
            self.cursor = (self.cursor + n_done) % n
            halted = kpre < charge.size
        # Starvation is about the SHARED budget, not the configured rate:
        # halting because bytes_per_window ran out is normal pacing.  A
        # first copy refused because the shared remainder cannot pay for
        # it is starvation too, whatever the configured rate says.
        rep.starved = (halted and cap < int(self.cfg.bytes_per_window)) \
            or blocked_hard
        rep.cursor = self.cursor
        return rep

    # -- checkpoint (rides the controller's utils/checkpoint npz) -----------
    def state_arrays(self) -> dict[str, np.ndarray]:
        return {
            "scrub_cursor": np.asarray([self.cursor], dtype=np.int64),
            "scrub_hints": self.hints.copy(),
        }

    def load_state_arrays(self, arrays: dict) -> None:
        # Pre-scrub checkpoints lack the arrays: start a fresh lap.
        cur = np.asarray(arrays.get("scrub_cursor", [0]), dtype=np.int64)
        self.cursor = int(cur[0]) % max(self.n_files, 1)
        self.hints = np.asarray(arrays.get("scrub_hints", ()),
                                dtype=np.int64).copy()
