"""Repair planning: re-replication moves under the shared churn budget.

The HDFS namenode's re-replication queue (Shvachko et al. MSST 2010) in the
controller's vocabulary: every window the scheduler re-derives the work
list from ``ClusterState`` (files below their effective target rf, plus
files at target whose reachable replicas all share one failure domain),
orders it **lost > at-risk > under-replicated > correlated**, tie-broken by
category rf descending then file index (the highest-durability categories
heal first), and admits replica copies against the SAME per-window
byte/file budget the migration scheduler uses: the controller runs repairs
first and hands the consumed budget to ``MigrationScheduler.schedule`` as a
reservation, so repair traffic and drift-migration traffic genuinely
compete for one churn allowance instead of stacking two.

**Structure-of-arrays control plane** (PR 8): the backlog is five parallel
numpy columns (file, attempts, copy-failure backoff, partition-stall
backoff) kept in file-index order, ``sync`` derives it from the or-ed
work-list masks plus one ``searchsorted`` merge for carried backoff state,
and ``schedule`` computes backoff deferrals, lost/stranded classification
and the partition-stall bumps as UNORDERED array operations — the legacy
(tier, -rf, file) admission order packs into one int64 key, and only the
budget-bounded head of the work list is ever materialized in that order
(``argpartition`` top-k with geometric refill; a full sort happens only
for unbudgeted runs).  Only the copies actually admitted against the
budget run file-at-a-time (target picking mutates placement state) — and
the moment the remaining byte budget cannot fit any remaining task's
cheapest possible copy, or the file cap fills, the entire tail of the
work list is classified in one vectorized pass.  Combined with
``ClusterState``'s incrementally cached counts, a window's repair-planning
cost scales with the damage (the files the affected failure domain holds,
plus the budgeted copies), not with cluster size.  Decisions are
bit-identical to the legacy object path, which survives as
``compat/reference_planners.ReferenceRepairScheduler`` for the equivalence
tests and ``benchmarks/plan_bench.py``.

Domain spread: targets come from ``ClusterState.pick_repair_target``, which
prefers failure domains the file does not yet occupy, and the
**correlated-risk rebalance** pass moves one replica of an
all-in-one-domain file into a fresh domain (copy charged to the budget, the
same-domain drop free) — the self-healing counterpart of the domain-aware
placement policy.

Partitions: a file whose only live replicas sit behind a network partition
has no reachable copy source.  Instead of burning budget on doomed copies,
the task is **deferred with exponential backoff** (``deferred_partition``)
— when the partition heals the file usually has its replicas back and
leaves the backlog on the next sync; what it cost in the meantime is
visibility, not churn.

Stragglers: a node degraded to ``m``x throughput moves bytes ``1/m`` as
fast, so a copy routed through it is charged ``size/m`` against the byte
budget — the window's wire-time is the budgeted resource.  The charge uses
the slowest of (best reachable source, target); the report carries both
the raw data bytes (``bytes_copied``) and the budget charge
(``bytes_used``).

Erasure coding (cdrs_tpu/storage): rebuilding one shard of an ``ec(k, m)``
stripe reads ``k`` surviving shards, so the budget charge is ``k x
shard_bytes`` (~ one full file) while only ``shard_bytes`` of new data is
written — the EC repair-amplification tradeoff HDFS-EC documents.  A
stripe below ``k`` live shards is unrecoverable (``deferred_no_source``),
and one with >= k live but < k reachable shards is partition-stranded
exactly like a wholly stranded replicate file.

Failure handling: a copy targeting a flaky node (ClusterState
``node_fail_prob``) fails with that probability — decided by a *stateless*
seeded roll keyed on (seed, window, file, attempt), so a killed/resumed
controller replays identical outcomes without carrying RNG state.  A
failed file backs off exponentially (``window + 2^attempts``, capped) and
its retry rotates to a different candidate node.  Failed copies still
consume byte budget — the traffic was spent on the wire.

Lost files (0 live replicas) cannot be repaired — there is no source to
copy from; they sit at the head of the queue and heal the moment a crashed
holder recovers (recovery makes them merely under-replicated).  The
scheduler reports them as ``deferred_no_source`` so the degraded-mode
accounting (controller + obs/audit.py ``durability_lost`` flag) sees them
every window.

Verified repair (the integrity contract, faults/scrub.py lineage): when
the cluster carries silent corruption, every admitted repair first
verification-reads the file's reachable copies and quarantines the rotten
ones (``ClusterState.verify_sources``) before any copy streams — repair
must never propagate rot.  The verification traffic is charged against
the byte budget (the wasted best-source-first reads), quarantined copies
count in ``corrupt_sources``, and a file whose every surviving source was
rot defers as ``no_source`` — it is truly gone unless a clean holder
recovers.  With no corruption anywhere the guard is one O(1) flag check
and the pass is bit-identical to the pre-integrity behaviour.
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass, field

import numpy as np

__all__ = ["RepairTask", "RepairReport", "RepairBacklog", "RepairScheduler"]

#: Backoff cap: a permanently failing target must not push the retry past
#: the horizon of any realistic run.
_MAX_BACKOFF = 64


@dataclass
class RepairTask:
    """One under-replicated file's pending repair — the scalar row view of
    a ``RepairBacklog`` (tests and small-scale callers; the planner holds
    columns, not objects)."""

    file_index: int
    attempts: int = 0
    #: First window the task is eligible again (exponential backoff after
    #: a failed copy — a flaky target).
    next_window: int = 0
    #: Partition-stall backoff, kept SEPARATE from the copy-failure
    #: backoff: it gates only the stranded-file rescan and is ignored the
    #: moment a reachable source returns (a healed partition must not
    #: leave the file waiting out a stale retry window).
    stalled: int = 0
    stall_until: int = 0


@dataclass
class RepairReport:
    """What one window's repair pass did (per-window observation)."""

    applied: list[tuple[int, int, int]] = field(default_factory=list)
    #: Byte budget consumed, INCLUDING failed copies (traffic was spent)
    #: and straggler inflation (a 0.25x node charges 4x the bytes).
    bytes_used: int = 0
    #: Raw data bytes successfully copied (no straggler inflation).
    bytes_copied: int = 0
    files_touched: int = 0
    failed: int = 0
    #: Correlated-risk files rebalanced into a fresh failure domain.
    rebalanced: int = 0
    #: The rebalanced files and their byte charge, split out of
    #: ``applied``/``bytes_used`` so the controller's decision
    #: provenance (lineage events, per-window ``causes``) can tag
    #: spread-rebalance traffic ``correlated_rebalance`` instead of
    #: ``repair`` — two different answers to "why did this file move".
    rebalanced_fids: list[int] = field(default_factory=list)
    rebalanced_bytes: int = 0
    deferred_budget: int = 0
    deferred_backoff: int = 0
    deferred_no_source: int = 0
    deferred_no_target: int = 0
    #: Files stranded behind a partition (live replicas, none reachable).
    deferred_partition: int = 0
    #: Rotten sources the verified-read check caught and quarantined
    #: before a copy could stream from them (integrity layer); their
    #: verification reads are inside ``bytes_used``.
    corrupt_sources: int = 0


def _fail_roll(seed: int, window: int, fid: int, attempt: int,
               copy: int = 0) -> float:
    """Deterministic uniform [0, 1) — stateless, so resume replays it.
    ``copy`` is the file's in-window copy index: a file missing several
    replicas draws an independent roll per copy."""
    key = np.asarray([seed, window, fid, attempt, copy], dtype=np.int64)
    return zlib.crc32(key.tobytes()) / 2.0 ** 32


class RepairBacklog:
    """Pending repairs as five parallel columns, sorted by file index.

    Dict-like reads (``fid in bl``, ``bl[fid]``, ``bl.get``, ``items()``)
    materialize ``RepairTask`` snapshots for tests/inspection; the
    scheduler itself only touches the columns.
    """

    __slots__ = ("fid", "attempts", "next_window", "stalled", "stall_until")

    def __init__(self, fid, attempts, next_window, stalled, stall_until):
        self.fid = np.asarray(fid, dtype=np.int64)
        self.attempts = np.asarray(attempts, dtype=np.int64)
        self.next_window = np.asarray(next_window, dtype=np.int64)
        self.stalled = np.asarray(stalled, dtype=np.int64)
        self.stall_until = np.asarray(stall_until, dtype=np.int64)

    @classmethod
    def empty(cls) -> "RepairBacklog":
        z = np.zeros(0, dtype=np.int64)
        return cls(z, z, z, z, z)

    def __len__(self) -> int:
        return int(self.fid.shape[0])

    def _pos(self, fid) -> int:
        i = int(np.searchsorted(self.fid, int(fid)))
        if i < len(self) and int(self.fid[i]) == int(fid):
            return i
        return -1

    def __contains__(self, fid) -> bool:
        return self._pos(fid) >= 0

    def __getitem__(self, fid) -> RepairTask:
        i = self._pos(fid)
        if i < 0:
            raise KeyError(fid)
        return self._task(i)

    def get(self, fid, default=None):
        i = self._pos(fid)
        return self._task(i) if i >= 0 else default

    def _task(self, i: int) -> RepairTask:
        return RepairTask(int(self.fid[i]), attempts=int(self.attempts[i]),
                          next_window=int(self.next_window[i]),
                          stalled=int(self.stalled[i]),
                          stall_until=int(self.stall_until[i]))

    def items(self):
        for i in range(len(self)):
            yield int(self.fid[i]), self._task(i)

    def take(self, idx) -> "RepairBacklog":
        return RepairBacklog(*(getattr(self, c)[idx]
                               for c in self.__slots__))


class RepairScheduler:
    """SoA backlog + the budgeted per-window repair pass."""

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self.backlog: RepairBacklog = RepairBacklog.empty()

    def sync(self, state, target_rf: np.ndarray) -> None:
        """Re-derive the backlog from the cluster's current gaps: newly
        damaged files enter, files healed by a recover/migration leave
        (and their attempt counters reset with them), files still damaged
        keep their backoff state.  Correlated-risk files (at target but
        all reachable replicas in one failure domain) enter too — the
        rebalance work list.  Also prunes excess replicas a recovered node
        or healed partition resurfaced (free).  The two work lists are
        unioned at the MASK level (one ``flatnonzero`` over the or-ed
        boolean masks is sorted-unique by construction — no ``union1d``
        sort of the concatenation), then one ``searchsorted`` merge
        carries the old backoff state over — no per-task objects."""
        state.trim_excess(target_rf)
        reach = state._reach_counts
        eff = state.effective_target(target_rf)
        corr_mask = state.correlated_mask(target_rf, reach=reach, eff=eff)
        work = np.flatnonzero((reach < eff) | corr_mask).astype(np.int64)
        old = self.backlog
        n = work.shape[0]
        cols = {c: np.zeros(n, dtype=np.int64)
                for c in ("attempts", "next_window", "stalled",
                          "stall_until")}
        if len(old):
            pos = np.searchsorted(old.fid, work)
            safe = np.minimum(pos, len(old) - 1)
            match = old.fid[safe] == work
            for c in cols:
                cols[c][match] = getattr(old, c)[safe[match]]
        self.backlog = RepairBacklog(work, cols["attempts"],
                                     cols["next_window"], cols["stalled"],
                                     cols["stall_until"])

    def _charge(self, state, fid: int, target: int) -> int:
        """Budget charge of creating one new shard of ``fid`` on
        ``target`` — ``ClusterState.copy_charge``: wire bytes over the
        best source's effective rate, where the hierarchy's per-edge
        byte-cost multipliers both inflate a WAN copy's charge and lose
        it the source election when an in-region copy exists (flat edge
        costs: bit-identical to the historical straggler arithmetic)."""
        return state.copy_charge(fid, target)

    def _tail_avail(self, state, fids: np.ndarray,
                    rebalance: np.ndarray, reach: np.ndarray) -> np.ndarray:
        """Candidate-target counts for a work-list tail, vectorized: a
        normal repair can target any reachable node not already holding
        the file; a rebalance copy (``new_domain_only``) only reachable
        nodes in domains the file does not occupy.  Mirrors
        ``ClusterState.pick_repair_target``'s candidate filter exactly —
        only the *emptiness* matters here (no target vs budget defer)."""
        node_reach = state.node_reachable()
        n_avail = int(node_reach.sum())
        avail = n_avail - reach[fids]
        if rebalance.any():
            per_dom = np.bincount(state.domain_index[node_reach],
                                  minlength=state.n_domains)
            rows = state.rows(fids[rebalance])
            assigned = rows >= 0
            dom = state.domain_index[np.clip(rows, 0, None)]
            occ = np.zeros(rows.shape[0], dtype=np.int64)
            for d in range(state.n_domains):
                occ += ((dom == d) & assigned).any(axis=1) * int(per_dom[d])
            avail[rebalance] = n_avail - occ
        return avail

    def schedule(self, window: int, state, target_rf: np.ndarray,
                 cat: np.ndarray, *, max_bytes: int | None = None,
                 max_files: int | None = None) -> RepairReport:
        """One window's repair pass; mutates ``state`` and the backlog.

        Budget semantics mirror MigrationScheduler: a copy is admitted
        while ``bytes_used + charge <= max_bytes`` except that a single
        copy larger than the whole budget is admitted as the window's
        first byte-moving operation (the largest file must not starve);
        ``max_bytes == 0`` is a true freeze.  ``max_files`` caps distinct
        files repaired this window.
        """
        rep = RepairReport()
        bl = self.backlog
        if not len(bl):
            return rep
        live = state._live_counts     # read-only here: no copy
        reach = state.reachable_counts()   # scratch: the loop bumps it
        eff = state.effective_target(target_rf)
        corr = state.correlated_mask(target_rf, reach=reach, eff=eff)
        rf_vec = np.asarray(target_rf, dtype=np.int64)
        #: Existence threshold per file (storage/): 1 for replicate,
        #: k for an EC(k, m) stripe — below it there is no repair source.
        need = state.min_live

        # Bulk deferrals, UNORDERED (deferral counts, stall bumps and the
        # healed set are order-independent — only the admitted prefix
        # needs priority order, and it is budget-bounded):
        # 1. copy-failure backoff still running;
        bf = bl.fid
        r_b, n_b = reach[bf], need[bf]
        backoff = bl.next_window > window
        # 2. stranded (reachable below the existence threshold): lost
        #    outright when even LIVE shards are short — otherwise the
        #    data is intact behind a partition and the stall backoff
        #    gates the rescan (never burning budget on doomed copies).
        stranded = ~backoff & (r_b < n_b)
        lost = stranded & (live[bf] < n_b)
        stall = stranded & ~lost
        stall_waiting = stall & (bl.stall_until > window)
        stall_bump = stall & ~stall_waiting
        rep.deferred_backoff = int(backoff.sum() + stall_waiting.sum())
        rep.deferred_no_source = int(lost.sum())
        rep.deferred_partition = int(stall_bump.sum())
        if stall_bump.any():
            pos = np.flatnonzero(stall_bump)
            bl.stalled[pos] += 1
            # min(2^s, 64) == 2^min(s, 6): stays in int64 for any s.
            bl.stall_until[pos] = window + (
                np.int64(1) << np.minimum(bl.stalled[pos], 6))

        # The actionable work list.  The legacy admission order is the
        # sort by (tier, -rf, file); actionable tasks are never tier 0
        # (that is exactly ``stranded``), and file index is unique, so
        # the whole key packs into ONE int64 — top-k selection via
        # ``argpartition`` then replaces the full lexsort: the admitted
        # prefix is budget/cap-bounded, so sorting all five million
        # damaged files to admit a few hundred is wasted wall-clock.
        act_pos = np.flatnonzero(~backoff & ~stranded)
        af = bf[act_pos]
        m = act_pos.shape[0]
        r_a = r_b[act_pos]
        tier = np.where(r_a == n_b[act_pos], 1,
                        np.where(r_a < eff[af], 2, 3))
        rf_a = rf_vec[af]
        rmax = int(rf_a.max()) if m else 0
        span = np.int64(rmax + 1)
        n_total = np.int64(reach.shape[0])
        # Guard arithmetic in Python ints: the overflow test must not
        # itself overflow (np.int64 would wrap for pathological rf).
        if m and 4 * int(span) * int(n_total) >= 2 ** 62:
            # Pathological rf magnitudes: fall back to the explicit
            # three-key sort rather than risk key overflow.
            key = None
            full_order = np.lexsort((af, -rf_a, tier))
        else:
            key = (tier * span + (rmax - rf_a)) * n_total + af
            full_order = None
        # Cheapest possible budget charge per task: the reconstruction
        # read bytes at nominal throughput (straggler/source gating only
        # inflates it) — its minimum over the unprocessed remainder tells
        # when the budget is dry for every remaining task.
        min_charge = state.shard_bytes[af] * np.maximum(
            state.ec_k[af].astype(np.int64), 1)

        #: Indices into the actionable arrays already handed to the
        #: admission loop (chunk membership), NOT yet necessarily
        #: processed — ``done`` counts actual processing.
        picked = np.zeros(m, dtype=bool)

        def next_chunk(k: int) -> np.ndarray | None:
            """The k highest-priority unpicked actionable tasks, in
            priority order — sequential chunks walk the exact legacy
            admission order because every unpicked key exceeds every
            picked one."""
            if full_order is not None:
                if picked.all():
                    return None
                picked[:] = True
                return full_order
            rest = np.flatnonzero(~picked)
            if rest.size == 0:
                return None
            if k < rest.size:
                part = rest[np.argpartition(key[rest], k - 1)[:k]]
            else:
                part = rest
            picked[part] = True
            return part[np.argsort(key[part])]

        # Unbudgeted runs process every actionable task — select once in
        # full; budgeted runs start small and refill geometrically (a
        # refill only happens when admitted work outran the chunk).
        if max_bytes is None and max_files is None:
            chunk_size = m
        else:
            chunk_size = min(m, max(2048, 2 * (max_files or 0)))

        touched = 0
        healed: list[int] = []
        done = 0
        stop = False
        chunk = next_chunk(chunk_size) if m else None
        while chunk is not None and not stop:
            rest_any = not picked.all()
            rest_min = (int(min_charge[~picked].min()) if rest_any
                        else None)
            c_charge = min_charge[chunk]
            sfx = np.minimum.accumulate(c_charge[::-1])[::-1]
            for j in range(chunk.shape[0]):
                if max_files is not None and touched >= max_files:
                    # File cap filled: the legacy loop defers every
                    # remaining actionable task without picking targets.
                    rep.deferred_budget += m - done
                    stop = True
                    break
                low = int(sfx[j])
                if rest_min is not None:
                    low = min(low, rest_min)
                if max_bytes is not None \
                        and (rep.bytes_used > 0 or max_bytes == 0) \
                        and rep.bytes_used + low > max_bytes:
                    # Byte budget exhausted for every remaining task (any
                    # real charge >= its reconstruction read bytes):
                    # classify the whole tail — this chunk's remainder
                    # plus everything never selected — in one vectorized
                    # pass: no-work tasks heal, target-less tasks defer
                    # as no_target, the rest as budget.
                    sel = np.concatenate([chunk[j:],
                                          np.flatnonzero(~picked)])
                    fs = af[sel]
                    rebal = (reach[fs] >= eff[fs]) & corr[fs]
                    needs = (reach[fs] < eff[fs]) | rebal
                    avail = self._tail_avail(state, fs, rebal, reach)
                    no_t = needs & (avail <= 0)
                    rep.deferred_no_target += int(no_t.sum())
                    rep.deferred_budget += int((needs & ~no_t).sum())
                    healed.extend(int(q) for q in act_pos[sel[~needs]])
                    stop = True
                    break
                p = int(act_pos[chunk[j]])
                f = int(af[chunk[j]])
                done += 1
                size = int(state.shard_bytes[f])
                attempts = int(bl.attempts[p])
                copy = 0
                rebalance = reach[f] >= eff[f] and bool(corr[f])
                spread_fixed = False
                task_touched = False
                if state.has_corruption:
                    # Verified read: quarantine rotten reachable copies of
                    # this file BEFORE streaming a repair from them — rot
                    # must never propagate.  The verification traffic is
                    # real (charged), and the quarantines drop replicas,
                    # so the scratch reach count re-reads the cache.
                    nq, vbytes = state.verify_sources(f)
                    if nq:
                        rep.corrupt_sources += nq
                        rep.bytes_used += vbytes
                        reach[f] = int(state._reach_counts[f])
                        rebalance = reach[f] >= eff[f] and bool(corr[f])
                        task_touched = True
                    if reach[f] < int(need[f]):
                        # Every surviving source was rot: the file has no
                        # clean reachable copy (or an EC stripe dropped
                        # below k clean shards) — nothing to repair FROM.
                        rep.deferred_no_source += 1
                        if task_touched:
                            touched += 1
                        continue
                while reach[f] < eff[f] or (rebalance and copy == 0):
                    target = state.pick_repair_target(
                        f, rotate=attempts + copy,
                        new_domain_only=rebalance)
                    if target < 0:
                        rep.deferred_no_target += 1
                        break
                    charge = self._charge(state, f, target)
                    if max_bytes is not None:
                        over = rep.bytes_used + charge > max_bytes
                        first = rep.bytes_used == 0 and max_bytes > 0
                        if over and not first:
                            rep.deferred_budget += 1
                            break
                    pf = float(state.node_fail_prob[target])
                    if pf > 0.0 and _fail_roll(self.seed, window, f,
                                               attempts, copy) < pf:
                        # Mid-window target failure: traffic spent, copy
                        # lost.
                        attempts += 1
                        bl.attempts[p] = attempts
                        bl.next_window[p] = window + min(2 ** attempts,
                                                         _MAX_BACKOFF)
                        rep.failed += 1
                        rep.bytes_used += charge
                        task_touched = True
                        break
                    state.add_replica(f, target)
                    rep.bytes_used += charge
                    rep.bytes_copied += size
                    rep.applied.append((f, int(target), size))
                    task_touched = True
                    if rebalance:
                        # The spread move: the new-domain copy landed,
                        # drop one replica from the crowded domain (free
                        # metadata delete) — net reachable count
                        # unchanged.
                        state.drop_crowded(f)
                        rep.rebalanced += 1
                        rep.rebalanced_fids.append(f)
                        rep.rebalanced_bytes += charge
                        spread_fixed = True
                        break
                    reach[f] += 1
                    copy += 1
                if task_touched:
                    touched += 1
                if reach[f] >= eff[f] and (not bool(corr[f])
                                           or spread_fixed):
                    healed.append(p)
            else:
                chunk_size *= 8
                chunk = next_chunk(chunk_size)
        if healed:
            keep = np.ones(len(bl), dtype=bool)
            keep[np.asarray(healed, dtype=np.int64)] = False
            self.backlog = bl.take(keep)
        rep.files_touched = touched
        return rep

    # -- checkpoint (rides the controller's utils/checkpoint npz) -----------
    def state_arrays(self) -> dict[str, np.ndarray]:
        """The backlog columns verbatim (already file-index-sorted — the
        legacy checkpoint order, with no re-sort and no per-task
        objects)."""
        bl = self.backlog
        return {
            "repair_file_index": bl.fid.copy(),
            "repair_attempts": bl.attempts.copy(),
            "repair_next_window": bl.next_window.copy(),
            "repair_stalled": bl.stalled.copy(),
            "repair_stall_until": bl.stall_until.copy(),
        }

    def load_state_arrays(self, arrays: dict) -> None:
        fid = np.asarray(arrays["repair_file_index"], dtype=np.int64)
        att = np.asarray(arrays["repair_attempts"], dtype=np.int64)
        nxt = np.asarray(arrays["repair_next_window"], dtype=np.int64)
        # Pre-partition checkpoints lack the stall arrays: default to "no
        # partition stall" rather than refusing to load.
        zero = np.zeros_like(fid)
        stl = np.asarray(arrays.get("repair_stalled", zero), dtype=np.int64)
        unt = np.asarray(arrays.get("repair_stall_until", zero),
                         dtype=np.int64)
        if not (fid.shape == att.shape == nxt.shape == stl.shape
                == unt.shape):
            raise ValueError(
                f"repair backlog arrays disagree on length: "
                f"{fid.shape} vs {att.shape} vs {nxt.shape} vs "
                f"{stl.shape} vs {unt.shape}")
        # Checkpoints are written file-index-sorted; re-canonicalize
        # defensively so a hand-edited snapshot cannot corrupt the
        # searchsorted membership lookups.
        order = np.argsort(fid, kind="stable")
        self.backlog = RepairBacklog(fid[order], att[order], nxt[order],
                                     stl[order], unt[order])
