"""Repair planning: re-replication moves under the shared churn budget.

The HDFS namenode's re-replication queue (Shvachko et al. MSST 2010) in the
controller's vocabulary: every window the scheduler re-derives the work
list from ``ClusterState`` (files below their effective target rf, plus
files at target whose reachable replicas all share one failure domain),
orders it **lost > at-risk > under-replicated > correlated**, tie-broken by
category rf descending then file index (the highest-durability categories
heal first), and admits replica copies against the SAME per-window
byte/file budget the migration scheduler uses: the controller runs repairs
first and hands the consumed budget to ``MigrationScheduler.schedule`` as a
reservation, so repair traffic and drift-migration traffic genuinely
compete for one churn allowance instead of stacking two.

Domain spread: targets come from ``ClusterState.pick_repair_target``, which
prefers failure domains the file does not yet occupy, and the
**correlated-risk rebalance** pass moves one replica of an
all-in-one-domain file into a fresh domain (copy charged to the budget, the
same-domain drop free) — the self-healing counterpart of the domain-aware
placement policy.

Partitions: a file whose only live replicas sit behind a network partition
has no reachable copy source.  Instead of burning budget on doomed copies,
the task is **deferred with exponential backoff** (``deferred_partition``)
— when the partition heals the file usually has its replicas back and
leaves the backlog on the next sync; what it cost in the meantime is
visibility, not churn.

Stragglers: a node degraded to ``m``x throughput moves bytes ``1/m`` as
fast, so a copy routed through it is charged ``size/m`` against the byte
budget — the window's wire-time is the budgeted resource.  The charge uses
the slowest of (best reachable source, target); the report carries both
the raw data bytes (``bytes_copied``) and the budget charge
(``bytes_used``).

Erasure coding (cdrs_tpu/storage): rebuilding one shard of an ``ec(k, m)``
stripe reads ``k`` surviving shards, so the budget charge is ``k x
shard_bytes`` (~ one full file) while only ``shard_bytes`` of new data is
written — the EC repair-amplification tradeoff HDFS-EC documents.  A
stripe below ``k`` live shards is unrecoverable (``deferred_no_source``),
and one with >= k live but < k reachable shards is partition-stranded
exactly like a wholly stranded replicate file.

Failure handling: a copy targeting a flaky node (ClusterState
``node_fail_prob``) fails with that probability — decided by a *stateless*
seeded roll keyed on (seed, window, file, attempt), so a killed/resumed
controller replays identical outcomes without carrying RNG state.  A
failed file backs off exponentially (``window + 2^attempts``, capped) and
its retry rotates to a different candidate node.  Failed copies still
consume byte budget — the traffic was spent on the wire.

Lost files (0 live replicas) cannot be repaired — there is no source to
copy from; they sit at the head of the queue and heal the moment a crashed
holder recovers (recovery makes them merely under-replicated).  The
scheduler reports them as ``deferred_no_source`` so the degraded-mode
accounting (controller + obs/audit.py ``durability_lost`` flag) sees them
every window.
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass, field

import numpy as np

__all__ = ["RepairTask", "RepairReport", "RepairScheduler"]

#: Backoff cap: a permanently failing target must not push the retry past
#: the horizon of any realistic run.
_MAX_BACKOFF = 64


@dataclass
class RepairTask:
    """One under-replicated file's pending repair."""

    file_index: int
    attempts: int = 0
    #: First window the task is eligible again (exponential backoff after
    #: a failed copy — a flaky target).
    next_window: int = 0
    #: Partition-stall backoff, kept SEPARATE from the copy-failure
    #: backoff: it gates only the stranded-file rescan and is ignored the
    #: moment a reachable source returns (a healed partition must not
    #: leave the file waiting out a stale retry window).
    stalled: int = 0
    stall_until: int = 0


@dataclass
class RepairReport:
    """What one window's repair pass did (per-window observation)."""

    applied: list[tuple[int, int, int]] = field(default_factory=list)
    #: Byte budget consumed, INCLUDING failed copies (traffic was spent)
    #: and straggler inflation (a 0.25x node charges 4x the bytes).
    bytes_used: int = 0
    #: Raw data bytes successfully copied (no straggler inflation).
    bytes_copied: int = 0
    files_touched: int = 0
    failed: int = 0
    #: Correlated-risk files rebalanced into a fresh failure domain.
    rebalanced: int = 0
    deferred_budget: int = 0
    deferred_backoff: int = 0
    deferred_no_source: int = 0
    deferred_no_target: int = 0
    #: Files stranded behind a partition (live replicas, none reachable).
    deferred_partition: int = 0


def _fail_roll(seed: int, window: int, fid: int, attempt: int,
               copy: int = 0) -> float:
    """Deterministic uniform [0, 1) — stateless, so resume replays it.
    ``copy`` is the file's in-window copy index: a file missing several
    replicas draws an independent roll per copy."""
    key = np.asarray([seed, window, fid, attempt, copy], dtype=np.int64)
    return zlib.crc32(key.tobytes()) / 2.0 ** 32


class RepairScheduler:
    """Backlog of RepairTasks + the budgeted per-window repair pass."""

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self.backlog: dict[int, RepairTask] = {}

    def sync(self, state, target_rf: np.ndarray) -> None:
        """Re-derive the backlog from the cluster's current gaps: newly
        damaged files enter, files healed by a recover/migration leave
        (and their attempt counters reset with them), files still damaged
        keep their backoff state.  Correlated-risk files (at target but
        all reachable replicas in one failure domain) enter too — the
        rebalance work list.  Also prunes excess replicas a recovered node
        or healed partition resurfaced (free)."""
        state.trim_excess(target_rf)
        fids, _reach, _eff = state.repair_needs(target_rf)
        corr = np.flatnonzero(state.correlated_mask(target_rf))
        work = np.union1d(fids, corr)
        self.backlog = {int(f): self.backlog.get(int(f), RepairTask(int(f)))
                        for f in work}

    def _charge(self, state, fid: int, target: int) -> int:
        """Budget charge of creating one new shard of ``fid`` on
        ``target``: the wire bytes (one full copy for a replicate file;
        ``k x shard_bytes`` reconstruction reads for an EC stripe —
        ``ClusterState.repair_read_bytes``) divided by the slowest
        throughput on the route — straggler wire-time inflation,
        deterministic.  A replicate copy streams from the single BEST
        reachable source; an EC rebuild must read k shards from k
        distinct holders, so it is gated by the slowest of the k FASTEST
        sources."""
        read_bytes = int(state.repair_read_bytes(fid))
        node_reach = state.node_reachable()
        row = state.replica_map[fid]
        srcs = [float(state.node_throughput[int(x)]) for x in row[row >= 0]
                if node_reach[int(x)]]
        k = int(state.ec_k[fid])
        if k > 1 and srcs:
            srcs.sort(reverse=True)
            src_m = srcs[min(k, len(srcs)) - 1]
        else:
            src_m = max(srcs, default=1.0)
        m = min(src_m, float(state.node_throughput[target]))
        return int(np.ceil(read_bytes / max(m, 1e-9)))

    def schedule(self, window: int, state, target_rf: np.ndarray,
                 cat: np.ndarray, *, max_bytes: int | None = None,
                 max_files: int | None = None) -> RepairReport:
        """One window's repair pass; mutates ``state`` and the backlog.

        Budget semantics mirror MigrationScheduler: a copy is admitted
        while ``bytes_used + charge <= max_bytes`` except that a single
        copy larger than the whole budget is admitted as the window's
        first byte-moving operation (the largest file must not starve);
        ``max_bytes == 0`` is a true freeze.  ``max_files`` caps distinct
        files repaired this window.
        """
        rep = RepairReport()
        if not self.backlog:
            return rep
        live = state.live_counts()
        reach = state.reachable_counts()
        eff = state.effective_target(target_rf)
        corr = state.correlated_mask(target_rf)
        cat = np.asarray(cat)
        rf_vec = np.asarray(target_rf, dtype=np.int64)
        #: Existence threshold per file (storage/): 1 for replicate,
        #: k for an EC(k, m) stripe — below it there is no repair source.
        need = state.min_live

        def prio(t: RepairTask):
            f = t.file_index
            if reach[f] < need[f]:
                tier = 0          # lost / wholly stranded
            elif reach[f] == need[f]:
                tier = 1          # at risk: one failure from loss
            elif reach[f] < eff[f]:
                tier = 2
            else:
                tier = 3          # correlated-risk rebalance: spread last
            return (tier, -int(rf_vec[f]), f)

        order = sorted(self.backlog.values(), key=prio)
        touched: set[int] = set()
        healed: list[int] = []
        for task in order:
            f = task.file_index
            if task.next_window > window:
                rep.deferred_backoff += 1
                continue
            if reach[f] < need[f]:
                if live[f] >= need[f]:
                    # Stranded behind a partition: the data is intact but
                    # unreachable (a replicate copy, or enough EC shards,
                    # exists on live-but-partitioned nodes) — back off
                    # instead of rescanning (and never burn budget on a
                    # doomed copy).  The moment the partition heals the
                    # file either leaves the backlog (replicas back above
                    # target) or repairs immediately: the stall backoff
                    # gates only this branch.
                    if task.stall_until > window:
                        rep.deferred_backoff += 1
                    else:
                        task.stalled += 1
                        task.stall_until = window + min(2 ** task.stalled,
                                                        _MAX_BACKOFF)
                        rep.deferred_partition += 1
                else:
                    rep.deferred_no_source += 1
                continue
            if max_files is not None and f not in touched \
                    and len(touched) >= max_files:
                rep.deferred_budget += 1
                continue
            # Raw data bytes WRITTEN per new shard (no reconstruction
            # amplification — that lives in the budget charge).
            size = int(state.shard_bytes[f])
            copy = 0
            rebalance = reach[f] >= eff[f] and bool(corr[f])
            spread_fixed = False
            while reach[f] < eff[f] or (rebalance and copy == 0):
                target = state.pick_repair_target(
                    f, rotate=task.attempts + copy,
                    new_domain_only=rebalance)
                if target < 0:
                    rep.deferred_no_target += 1
                    break
                charge = self._charge(state, f, target)
                if max_bytes is not None:
                    over = rep.bytes_used + charge > max_bytes
                    first = rep.bytes_used == 0 and max_bytes > 0
                    if over and not first:
                        rep.deferred_budget += 1
                        break
                p = float(state.node_fail_prob[target])
                if p > 0.0 and _fail_roll(self.seed, window, f,
                                          task.attempts, copy) < p:
                    # Mid-window target failure: traffic spent, copy lost.
                    task.attempts += 1
                    task.next_window = window + min(2 ** task.attempts,
                                                    _MAX_BACKOFF)
                    rep.failed += 1
                    rep.bytes_used += charge
                    touched.add(f)
                    break
                state.add_replica(f, target)
                rep.bytes_used += charge
                rep.bytes_copied += size
                rep.applied.append((f, int(target), size))
                touched.add(f)
                if rebalance:
                    # The spread move: the new-domain copy landed, drop one
                    # replica from the crowded domain (free metadata
                    # delete) — net reachable count unchanged.
                    state.drop_crowded(f)
                    rep.rebalanced += 1
                    spread_fixed = True
                    break
                reach[f] += 1
                copy += 1
            if reach[f] >= eff[f] and (not bool(corr[f]) or spread_fixed):
                healed.append(f)
        for f in healed:
            self.backlog.pop(f, None)
        rep.files_touched = len(touched)
        return rep

    # -- checkpoint (rides the controller's utils/checkpoint npz) -----------
    def state_arrays(self) -> dict[str, np.ndarray]:
        tasks = sorted(self.backlog.values(), key=lambda t: t.file_index)
        return {
            "repair_file_index": np.asarray(
                [t.file_index for t in tasks], dtype=np.int64),
            "repair_attempts": np.asarray(
                [t.attempts for t in tasks], dtype=np.int64),
            "repair_next_window": np.asarray(
                [t.next_window for t in tasks], dtype=np.int64),
            "repair_stalled": np.asarray(
                [t.stalled for t in tasks], dtype=np.int64),
            "repair_stall_until": np.asarray(
                [t.stall_until for t in tasks], dtype=np.int64),
        }

    def load_state_arrays(self, arrays: dict) -> None:
        fid = np.asarray(arrays["repair_file_index"], dtype=np.int64)
        att = np.asarray(arrays["repair_attempts"], dtype=np.int64)
        nxt = np.asarray(arrays["repair_next_window"], dtype=np.int64)
        # Pre-partition checkpoints lack the stall arrays: default to "no
        # partition stall" rather than refusing to load.
        zero = np.zeros_like(fid)
        stl = np.asarray(arrays.get("repair_stalled", zero), dtype=np.int64)
        unt = np.asarray(arrays.get("repair_stall_until", zero),
                         dtype=np.int64)
        if not (fid.shape == att.shape == nxt.shape == stl.shape
                == unt.shape):
            raise ValueError(
                f"repair backlog arrays disagree on length: "
                f"{fid.shape} vs {att.shape} vs {nxt.shape} vs "
                f"{stl.shape} vs {unt.shape}")
        self.backlog = {
            int(fid[i]): RepairTask(int(fid[i]), attempts=int(att[i]),
                                    next_window=int(nxt[i]),
                                    stalled=int(stl[i]),
                                    stall_until=int(unt[i]))
            for i in range(fid.shape[0])
        }
