"""Fault injection & self-healing: the durability axis the paper's
category -> replication-factor mapping was designed for.

Pieces:

* ``FaultSchedule`` (schedule.py) — seeded, deterministic node events
  (crash/recover/decommission/flaky, ``partition``/``heal`` node sets,
  ``degrade``/``restore`` stragglers) keyed to controller windows.
* ``ClusterState`` (state.py) — the mutable cluster: node liveness and
  reachability, the evolving replica map, vectorized durability tiers
  (under-replicated / at-risk / unreachable / lost, plus the
  correlated-risk failure-domain overlay), and the ``placement_view``
  bridge back into the immutable evaluation world.
* ``RepairScheduler`` (repair.py) — HDFS-style re-replication under the
  same per-window churn budget as drift migrations, with deterministic
  flaky-failure rolls + exponential backoff, partition-stall deferral,
  straggler-inflated budget charging, cross-domain spread rebalance, and
  verified-read source checks that refuse rotten copies.
* ``Scrubber`` (scrub.py) — budgeted background verification of the data
  itself: a checkpointed round-robin cursor (plus read-detection hints)
  finds silent corruption and quarantines it into the repair queue.

The online controller (control/controller.py) wires these into its window
loop when ``ControllerConfig.fault_schedule`` is set; ``cdrs chaos`` is
the CLI entry and ``benchmarks/chaos_bench.py`` /
``benchmarks/integrity_bench.py`` the durability/integrity baselines.
"""

from .repair import RepairReport, RepairScheduler, RepairTask
from .schedule import FaultEvent, FaultSchedule
from .scrub import ScrubConfig, ScrubReport, Scrubber
from .state import ClusterState

__all__ = [
    "ClusterState",
    "FaultEvent",
    "FaultSchedule",
    "RepairReport",
    "RepairScheduler",
    "RepairTask",
    "ScrubConfig",
    "ScrubReport",
    "Scrubber",
]
