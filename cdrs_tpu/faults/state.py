"""Mutable cluster state: node liveness + the evolving replica map.

``cluster/placement.py`` produces an *immutable* placement — correct for
the batch pipeline, useless once nodes can die.  ``ClusterState`` takes one
placement as the starting condition and becomes the source of truth the
fault schedule (faults/schedule.py), the repair scheduler
(faults/repair.py) and the controller's migrations all mutate:

* per-node status — up/down (crash/recover), decommissioned (permanent,
  replicas destroyed), partitioned (up but unreachable as a group —
  netsplit), a flaky fail-probability for repair targeting, and a
  straggler throughput multiplier (degrade/restore);
* the replica map — ``(n_files, n_nodes)`` int32 node ids, -1 = empty slot
  (width = node count: replicas are distinct-per-node, so no file can ever
  need more slots);
* durability accounting — vectorized under-replicated / at-risk (1
  reachable replica) / lost (0 live replicas) tiers against an *effective*
  target rf = min(target, reachable nodes), plus two correlated-failure
  views: **unreachable** (live replicas exist but every one is stranded
  behind a partition — reads fail, data survives) and **correlated risk**
  (>= 2 reachable replicas that all share ONE failure domain while a
  second domain is available — a single rack/switch failure away from
  unavailability, the gap HDFS rack-awareness and CRUSH failure-domain
  buckets exist to close).

Two masks tell the liveness story: ``live`` = the replica's node is up
(data intact — partitioned nodes count, their disks are fine), ``reachable``
= up AND not behind a partition (can serve reads, source or sink repair
copies).  Without partitions they coincide, and every pre-partition
behaviour is unchanged.

A third, SILENT axis is ``slot_corrupt``: a copy whose holder is up and
reachable but whose bytes have rotted (bit flips, latent sector errors —
the HDFS block-scanner / Ceph scrub threat model).  Undetected rot still
counts as live — that blindness is the point: the blind tiers can report
"0 lost" while the cluster serves garbage.  Detection (background scrub,
verified read, repair source check) calls ``quarantine``, which drops the
copy so the ordinary tiers and the repair planner heal the gap; the
``true_lost_mask``/``integrity`` accessors expose the ground truth the
blind report cannot see.

Everything is deterministic and the whole state round-trips through
``state_arrays``/``load_state_arrays`` so a controller checkpoint taken
mid-fault resumes bit-identically (pre-partition checkpoints load with the
new arrays defaulted).  ``placement_view`` renders the REACHABLE replicas
back into a ``PlacementResult`` so the existing replay
(cluster/evaluate.py) measures locality/balance under the outage — no
second evaluation path.
"""

from __future__ import annotations

import numpy as np

from ..cluster.placement import ClusterTopology, PlacementResult

__all__ = ["ClusterState"]


def _corrupt_roll(window: int, nid: int, fids: np.ndarray) -> np.ndarray:
    """Deterministic uniform [0, 1) per file for the seeded ``corrupt``
    fraction selection — stateless (splitmix64 over (window, node, file))
    so a resumed controller replaying the same fault event selects the
    same copies; numpy uint64 arithmetic wraps silently by design."""
    base = ((window + 1) * 0x9E3779B97F4A7C15
            + (nid + 1) * 0xC2B2AE3D27D4EB4F) & 0xFFFFFFFFFFFFFFFF
    z = np.asarray(fids, dtype=np.uint64) + np.uint64(base)
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    z = z ^ (z >> np.uint64(31))
    return (z >> np.uint64(11)).astype(np.float64) / float(1 << 53)


class ClusterState:
    """One controlled cluster's mutable placement + node status."""

    def __init__(self, placement: PlacementResult, size_bytes: np.ndarray):
        self.topology: ClusterTopology = placement.topology
        self.nodes: tuple[str, ...] = tuple(placement.topology.nodes)
        n_nodes = len(self.nodes)
        n = placement.replica_map.shape[0]
        self._node_idx = {nm: i for i, nm in enumerate(self.nodes)}
        self.domain_index = self.topology.domain_index()
        self.n_domains = self.topology.n_domains
        #: Top-hierarchy-level (region) ids per node, None for one-level
        #: topologies — gates every hierarchy-aware code path to zero
        #: cost on pre-hierarchy clusters.
        self._top_index = (self.topology.top_domain_index()
                           if getattr(self.topology, "n_levels", 0) > 0
                           else None)
        self._n_top = (self.topology.n_domains_at(self.topology.n_levels)
                       if self._top_index is not None else 0)
        self.sizes = np.asarray(size_bytes, dtype=np.int64)
        if self.sizes.shape != (n,):
            raise ValueError(
                f"size_bytes shape {self.sizes.shape} != ({n},)")

        rm = np.full((n, n_nodes), -1, dtype=np.int32)
        w = min(placement.replica_map.shape[1], n_nodes)
        rm[:, :w] = placement.replica_map[:, :w]
        self.replica_map = rm
        #: Storage-strategy arrays (cdrs_tpu/storage): a slot of file i
        #: holds ``shard_bytes[i]`` bytes, the file is LOST below
        #: ``min_live[i]`` live shards, and ``ec_k[i]`` > 0 marks an
        #: erasure-coded stripe whose repair reads k surviving shards.
        #: The defaults (min_live=1, shard_bytes=size, ec_k=0) are
        #: exactly the historical replicate semantics.
        self.min_live = np.ones(n, dtype=np.int32)
        self.shard_bytes = self.sizes.copy()
        self.ec_k = np.zeros(n, dtype=np.int32)
        #: Region-locality flag per file (storage/ ``locality: region``):
        #: True pins every copy/shard to the file's current top-level
        #: domain — repair targets stay in-region.  All-False (the
        #: default, and any non-hierarchical topology) is bit-identical
        #: to the pre-hierarchy behaviour.
        self.region_local = np.zeros(n, dtype=bool)
        #: (n_nodes, n_nodes) per-copy byte-cost multipliers from the
        #: hierarchy's edge costs; None = flat costs (no matrix, no
        #: lookups — the historical charge arithmetic).
        self._byte_cost = (self.topology.byte_cost_matrix()
                           if getattr(self.topology, "edge_bytes", ())
                           else None)
        #: Shard-count INTENT of the installed form: what repair should
        #: maintain for each file.  Updated when an rf change or a
        #: strategy re-encode APPLIES — a deferred conversion keeps the
        #: old intent, so repair never tops a file up toward a target
        #: whose re-encode would drop the copies.
        self.installed_shards = placement.rf.astype(np.int32).copy()
        #: Ground-truth SILENT corruption per replica slot (parallel to
        #: ``replica_map``): the copy exists and its holder serves it, but
        #: the bytes are rot.  An undetected corrupt copy still counts as
        #: live/reachable — that blindness is the threat model; detection
        #: (scrub, verified read, repair source check) quarantines the
        #: copy via ``quarantine``, which drops it so the ordinary
        #: durability tiers and the repair planner pick up the gap.
        self.slot_corrupt = np.zeros((n, n_nodes), dtype=bool)
        #: Incrementally maintained count of set ``slot_corrupt`` bits —
        #: the O(1) "is integrity machinery needed at all" guard.
        self._n_corrupt = 0
        self.node_up = np.ones(n_nodes, dtype=bool)
        self.node_decommissioned = np.zeros(n_nodes, dtype=bool)
        self.node_partitioned = np.zeros(n_nodes, dtype=bool)
        self.node_fail_prob = np.zeros(n_nodes, dtype=np.float64)
        #: Straggler throughput multiplier in (0, 1]; 1.0 = nominal.
        self.node_throughput = np.ones(n_nodes, dtype=np.float64)
        #: Bytes *assigned* per node (down replicas still occupy disk);
        #: the deterministic least-loaded repair-target preference.
        self.node_bytes = np.zeros(n_nodes, dtype=np.int64)
        self._recompute_node_bytes()
        #: Bumped on every mutation — cache-invalidation for evaluators.
        self.version = 0
        #: Incrementally maintained per-file counts (the control-plane
        #: scaling contract): ``live_counts``/``reachable_counts``/
        #: ``domain_spread`` are O(1) cache reads per window instead of
        #: O(files x nodes) mask reductions, and mutations refresh ONLY
        #: the touched rows — a fault event's cost scales with the files
        #: holding the affected node (its failure domain's blast radius),
        #: not with the cluster.
        self._refresh_all()

    # -- cached per-file counts ----------------------------------------------
    def _refresh_all(self) -> None:
        live = self.live_mask()
        reach = self.reachable_mask()
        self._live_counts = live.sum(axis=1).astype(np.int32)
        self._reach_counts = reach.sum(axis=1).astype(np.int32)
        slot_dom = self.domain_index[np.clip(self.replica_map, 0, None)]
        spread = np.zeros(self.replica_map.shape[0], dtype=np.int32)
        for d in range(self.n_domains):
            spread += ((slot_dom == d) & reach).any(axis=1)
        self._dom_spread = spread
        if self._top_index is not None:
            top = self._top_index[np.clip(self.replica_map, 0, None)]
            tspread = np.zeros(self.replica_map.shape[0], dtype=np.int32)
            for d in range(self._n_top):
                tspread += ((top == d) & reach).any(axis=1)
            self._top_spread = tspread

    def _refresh_files(self, fids: np.ndarray) -> None:
        """Recompute the cached counts for a row subset (the files a
        mutation touched) — O(|subset| x nodes), not O(files x nodes)."""
        fids = np.asarray(fids, dtype=np.int64)
        if fids.size == 0:
            return
        rows = self.rows(fids)
        safe = np.clip(rows, 0, None)
        assigned = rows >= 0
        self._live_counts[fids] = (assigned
                                   & self.node_up[safe]).sum(axis=1)
        rmask = assigned & self.node_reachable()[safe]
        self._reach_counts[fids] = rmask.sum(axis=1)
        dom = self.domain_index[safe]
        spread = np.zeros(fids.shape[0], dtype=np.int32)
        for d in range(self.n_domains):
            spread += ((dom == d) & rmask).any(axis=1)
        self._dom_spread[fids] = spread
        if self._top_index is not None:
            top = self._top_index[safe]
            tspread = np.zeros(fids.shape[0], dtype=np.int32)
            for d in range(self._n_top):
                tspread += ((top == d) & rmask).any(axis=1)
            self._top_spread[fids] = tspread

    def _recompute_node_bytes(self) -> None:
        self.node_bytes = np.zeros(len(self.nodes), dtype=np.int64)
        assigned = self.replica_map >= 0
        np.add.at(self.node_bytes, self.replica_map[assigned],
                  np.broadcast_to(self.shard_bytes[:, None],
                                  self.replica_map.shape)[assigned])

    # -- storage strategies --------------------------------------------------
    def set_strategy_arrays(self, min_live: np.ndarray,
                            shard_bytes: np.ndarray,
                            ec_k: np.ndarray) -> None:
        """Install per-file storage-strategy arrays (controller wiring,
        checkpoint load) and re-derive the per-node byte accounting."""
        n = self.replica_map.shape[0]
        for name, a in (("min_live", min_live),
                        ("shard_bytes", shard_bytes), ("ec_k", ec_k)):
            if np.asarray(a).shape != (n,):
                raise ValueError(
                    f"{name} shape {np.asarray(a).shape} != ({n},)")
        self.min_live = np.asarray(min_live, dtype=np.int32).copy()
        self.shard_bytes = np.asarray(shard_bytes, dtype=np.int64).copy()
        self.ec_k = np.asarray(ec_k, dtype=np.int32).copy()
        self._recompute_node_bytes()
        self.version += 1

    def set_file_strategy(self, fid: int, min_live: int, shard_bytes: int,
                          ec_k: int, region_local: bool = False) -> None:
        """Re-strategize ONE file (a migration moved it to a category
        with a different storage strategy): its assigned slots re-account
        at the new shard size."""
        old = int(self.shard_bytes[fid])
        new = int(shard_bytes)
        if new != old:
            row = self.row(fid)
            for node in row[row >= 0]:
                self.node_bytes[int(node)] += new - old
        self.min_live[fid] = int(min_live)
        self.shard_bytes[fid] = new
        self.ec_k[fid] = int(ec_k)
        self.region_local[fid] = bool(region_local)
        self.version += 1

    def apply_strategy_target(self, fid: int, min_live: int,
                              shard_bytes: int, ec_k: int,
                              target: int,
                              region_local: bool = False) -> int:
        """Move ``fid`` to a (possibly different) storage strategy and
        bring it toward ``target`` shards — the migration-apply entry
        point when a storage config is active.

        An unchanged strategy shape (same min_live/shard_bytes/ec_k —
        every replicate->replicate rf change) is exactly
        ``apply_rf_target``.  A SHAPE change (replicate <-> EC, or a
        different k) is a re-encode: it needs a readable source under
        the CURRENT strategy and enough reachable nodes to host a
        viable new form; otherwise the conversion is deferred — the
        file keeps its current strategy (conservative: durability
        accounting stays truthful to the bytes actually on disk) and
        the controller's per-window reconcile pass retries once the
        file is readable again.  A granted re-encode drops every old
        slot (the old form's replicas are deleted once the new shards
        land) and places the new shards domain-spread via
        ``pick_repair_target``.  Returns the shard-count delta."""
        same = (int(self.min_live[fid]) == int(min_live)
                and int(self.shard_bytes[fid]) == int(shard_bytes)
                and int(self.ec_k[fid]) == int(ec_k)
                and bool(self.region_local[fid]) == bool(region_local))
        if same:
            return self.apply_rf_target(fid, target)
        # Per-row reachability from the maintained cache: the full
        # (n_files, n_nodes) mask would make the controller's reconcile
        # loop quadratic while conversions stay deferred.
        reach = int(self._reach_counts[fid])
        if reach < int(self.min_live[fid]) \
                or self.n_available < int(min_live):
            return 0
        row = self.row(fid)
        before = int((row >= 0).sum())
        for node in [int(x) for x in row[row >= 0]]:
            self.drop_replica(fid, node)
        self.set_file_strategy(fid, min_live, shard_bytes, ec_k,
                               region_local)
        self.installed_shards[fid] = int(target)
        placed = 0
        goal = min(int(target), self.n_available)
        while placed < goal:
            node = self.pick_repair_target(fid)
            if node < 0:  # pragma: no cover - goal <= n_available
                break
            self.add_replica(fid, node)
            placed += 1
        return placed - before

    def strategy_mismatch(self, min_live: np.ndarray,
                          shard_bytes: np.ndarray,
                          ec_k: np.ndarray,
                          region_local: np.ndarray | None = None
                          ) -> np.ndarray:
        """File ids whose installed strategy differs from the wanted
        arrays — deferred conversions the controller retries per
        window (see ``apply_strategy_target``)."""
        mism = ((self.min_live != np.asarray(min_live, np.int32))
                | (self.shard_bytes != np.asarray(shard_bytes, np.int64))
                | (self.ec_k != np.asarray(ec_k, np.int32)))
        if region_local is not None:
            mism |= self.region_local != np.asarray(region_local, bool)
        return np.flatnonzero(mism)

    def repair_read_bytes(self, fid: int) -> int:
        """Bytes read over the wire to create ONE new shard of ``fid``:
        a replicate repair streams one full copy; an EC repair
        reconstructs from k surviving shards (k x shard_bytes — the EC
        repair-amplification tradeoff, HDFS-EC/Ceph semantics)."""
        return int(self.shard_bytes[fid]) * max(int(self.ec_k[fid]), 1)

    # -- data integrity (silent corruption) ----------------------------------
    @property
    def has_corruption(self) -> bool:
        """Any slot currently holds rot — the O(1) guard that keeps every
        integrity code path free when no corruption was ever injected."""
        return self._n_corrupt > 0

    def corrupt_replica(self, fid: int, node: int) -> bool:
        """Silently rot ``fid``'s copy on ``node`` (no-op when the slot is
        unassigned or already rotten).  Nothing else changes: the copy
        still counts as live/reachable until something VERIFIES it."""
        row = self.replica_map[fid]
        slots = np.flatnonzero(row == node)
        if slots.size == 0:
            return False
        s = int(slots[0])
        if self.slot_corrupt[fid, s]:
            return False
        self.slot_corrupt[fid, s] = True
        self._n_corrupt += 1
        self.version += 1
        return True

    def quarantine(self, fid: int, node: int) -> None:
        """DETECTED corruption: drop the copy (the bytes are garbage — a
        quarantined slot is an empty slot as far as durability and repair
        are concerned) and clear its rot bit.  The existing tiers and the
        repair planner pick the gap up with no special-casing."""
        self.drop_replica(fid, node)

    def verify_sources(self, fid: int) -> tuple[int, int]:
        """Verified-read source check for a repair of ``fid``: quarantine
        every corrupt REACHABLE copy (rot on down/partitioned holders
        stays latent — nothing can read it) so the repair never streams
        from a rotten source.  Returns ``(n_quarantined, charge_bytes)``
        where the charge is one verification read per rotten copy found
        (``shard_bytes`` over the holder's throughput — the traffic the
        sequential best-source-first read spent before failing the
        checksum); clean sources verify as part of the copy read itself.
        """
        if not self._n_corrupt:
            return 0, 0
        row = self.row(fid)
        corr = self.slot_corrupt[fid]
        reach = self.node_reachable()
        found = 0
        charge = 0
        for s in np.flatnonzero((row >= 0) & corr):
            node = int(row[s])
            if not reach[node]:
                continue
            charge += int(np.ceil(
                int(self.shard_bytes[fid])
                / max(float(self.node_throughput[node]), 1e-9)))
            self.quarantine(fid, node)
            found += 1
        return found, charge

    def corrupt_row(self, fid: int) -> np.ndarray:
        """(n_nodes,) bool rot mask of one file (scrub's hint loop; the
        lowmem backend reconstructs it from its sparse bitmask)."""
        return self.slot_corrupt[fid]

    def corrupt_at(self, fids: np.ndarray, slots: np.ndarray) -> np.ndarray:
        """Bool per (fid, slot) pair — the scrub lap's gather."""
        return self.slot_corrupt[np.asarray(fids), np.asarray(slots)]

    def corrupt_file_counts(self) -> np.ndarray:
        """(n,) int32: LIVE corrupt copies per file (ground truth).  Rot
        on a down-but-not-decommissioned holder is excluded while the
        node is down but the bit persists — the disk returns with the
        rot intact on recovery (only decommission destroys it)."""
        if not self._n_corrupt:
            return np.zeros(self.replica_map.shape[0], dtype=np.int32)
        live = self.live_mask() & self.slot_corrupt
        return live.sum(axis=1).astype(np.int32)

    def true_lost_mask(self) -> np.ndarray:
        """(n,) bool GROUND TRUTH loss: fewer than ``min_live`` live
        CLEAN copies — the file is gone (or will be, the moment the rot
        is detected) even if the blind ``lost`` tier still reports it
        alive.  Equals ``lost_mask`` when nothing is corrupt."""
        if not self._n_corrupt:
            return self.lost_mask()
        clean = self.live_mask() & ~self.slot_corrupt
        return clean.sum(axis=1).astype(np.int32) < self.min_live

    def integrity(self) -> dict:
        """Ground-truth integrity digest for the window record: corrupt
        copies still in place, files carrying any rot, and the true-loss
        count the blind durability tiers cannot see."""
        cf = self.corrupt_file_counts()
        return {
            "corrupt_copies": int(cf.sum()),
            "files_corrupt": int((cf > 0).sum()),
            "true_lost": int(self.true_lost_mask().sum()),
        }

    # -- row access (the seam the lowmem functional backend overrides) -------
    def row(self, fid: int) -> np.ndarray:
        """(n_nodes,) int32 slot row of one file.  Dense backends return
        a live VIEW (in-place writes hit the map); overlay backends
        return a resolved copy and route writes through the mutation
        primitives — which is why shared policy code only ever mutates
        through ``add_replica``/``drop_replica``."""
        return self.replica_map[fid]

    def rows(self, fids: np.ndarray) -> np.ndarray:
        """(k, n_nodes) int32 slot rows of a file subset (copy-or-view;
        read-only by contract)."""
        return self.replica_map[fids]

    def assigned_counts(self) -> np.ndarray:
        """(n,) int64 ASSIGNED slots per file (up or down — bytes on
        disk; the storage record's byte accounting).  Overlay backends
        compute it chunked instead of materializing the map."""
        return (self.replica_map >= 0).sum(axis=1).astype(np.int64)

    # -- hierarchy-aware copy pricing ----------------------------------------
    def copy_charge(self, fid: int, target: int) -> int:
        """Budget charge of creating one new shard of ``fid`` on
        ``target``: the wire bytes (one full copy for a replicate file;
        ``k x shard_bytes`` reconstruction reads for an EC stripe)
        divided by the best source's effective rate — the slowest of
        (source, target) throughput, divided by the hierarchy's per-edge
        byte-cost multiplier, so a WAN copy both costs its multiplier
        and loses the source election to an in-region copy when one
        exists.  An EC rebuild reads k shards, so it is gated by the
        k-th best effective source.  With flat edge costs this is
        bit-identical to the historical straggler arithmetic (min and
        the throughput sort commute)."""
        read_bytes = int(self.repair_read_bytes(fid))
        node_reach = self.node_reachable()
        row = self.row(fid)
        tgt = float(self.node_throughput[target])
        cost = self._byte_cost
        rates = []
        for x in row[row >= 0]:
            s = int(x)
            if not node_reach[s]:
                continue
            r = min(float(self.node_throughput[s]), tgt)
            if cost is not None:
                r /= float(cost[s, target])
            rates.append(r)
        k = int(self.ec_k[fid])
        if k > 1 and rates:
            rates.sort(reverse=True)
            rate = rates[min(k, len(rates)) - 1]
        else:
            rate = max(rates, default=min(1.0, tgt))
        return int(np.ceil(read_bytes / max(rate, 1e-9)))

    # -- elastic capacity ----------------------------------------------------
    def _grow_common(self, topology) -> int:
        """The representation-independent half of ``grow``: validate the
        strict-prefix contract, swap the topology + LUTs, extend every
        per-NODE array.  Returns the number of appended nodes.  Shared
        by the dense and overlay backends so a future per-node array
        cannot be extended in one and forgotten in the other."""
        old_n = len(self.nodes)
        if tuple(topology.nodes[:old_n]) != self.nodes \
                or len(topology.nodes) <= old_n:
            raise ValueError(
                f"grow needs the current node set as a strict prefix of "
                f"the new topology (have {self.nodes}, got "
                f"{tuple(topology.nodes)})")
        add = len(topology.nodes) - old_n
        self.topology = topology
        self.nodes = tuple(topology.nodes)
        self._node_idx = {nm: i for i, nm in enumerate(self.nodes)}
        self.domain_index = topology.domain_index()
        self.n_domains = topology.n_domains
        self._top_index = (topology.top_domain_index()
                           if getattr(topology, "n_levels", 0) > 0
                           else None)
        self._n_top = (topology.n_domains_at(topology.n_levels)
                       if self._top_index is not None else 0)
        self._byte_cost = (topology.byte_cost_matrix()
                           if getattr(topology, "edge_bytes", ())
                           else None)
        self.node_up = np.concatenate([self.node_up, np.ones(add, bool)])
        self.node_decommissioned = np.concatenate(
            [self.node_decommissioned, np.zeros(add, bool)])
        self.node_partitioned = np.concatenate(
            [self.node_partitioned, np.zeros(add, bool)])
        self.node_fail_prob = np.concatenate(
            [self.node_fail_prob, np.zeros(add)])
        self.node_throughput = np.concatenate(
            [self.node_throughput, np.ones(add)])
        self.node_bytes = np.concatenate(
            [self.node_bytes, np.zeros(add, dtype=np.int64)])
        self.version += 1
        return add

    def grow(self, topology) -> None:
        """Install a GROWN topology (the old one with nodes appended —
        the elastic scale-out): per-node arrays extend, the map gains
        empty columns, and every existing file's placement, counts and
        domain ids are untouched (appended nodes introduce only new
        domain names, or join existing ones whose ids are stable under
        first-appearance ordering)."""
        add = self._grow_common(topology)
        n = self.replica_map.shape[0]
        self.replica_map = np.concatenate(
            [self.replica_map, np.full((n, add), -1, dtype=np.int32)],
            axis=1)
        self.slot_corrupt = np.concatenate(
            [self.slot_corrupt, np.zeros((n, add), dtype=bool)], axis=1)

    def pin_rows(self, fids) -> None:
        """Snapshot hook before a base-moving change (functional epoch
        advance): dense backends already hold every row, so this is a
        no-op; functional backends pin the resolved rows so they stand
        as exceptions until the rebalance physically moves them."""

    def retarget_row(self, fid: int, new_row: np.ndarray) -> int:
        """Install a fully specified slot row for one file (the elastic
        rebalance move): byte accounting follows the node-set delta, rot
        bits follow their surviving nodes (a dropped node's copy — and
        its rot — is deleted).  Returns the bytes WRITTEN (one shard per
        newly holding node)."""
        new_row = np.asarray(new_row, dtype=np.int32)
        old_row = self.row(fid).copy()
        old_nodes = {int(x) for x in old_row[old_row >= 0]}
        new_nodes = {int(x) for x in new_row[new_row >= 0]}
        sb = int(self.shard_bytes[fid])
        for v in old_nodes - new_nodes:
            self.node_bytes[v] -= sb
        for v in new_nodes - old_nodes:
            self.node_bytes[v] += sb
        corr = self.slot_corrupt[fid]
        if corr.any():
            new_corr = np.zeros_like(corr)
            slot_of = {int(v): int(s) for s, v in enumerate(new_row)
                       if v >= 0}
            for s in np.flatnonzero(corr):
                v = int(old_row[s])
                if v in slot_of:
                    new_corr[slot_of[v]] = True
                else:
                    self._n_corrupt -= 1
            self.slot_corrupt[fid] = new_corr
        self.replica_map[fid] = new_row
        self._refresh_files(np.asarray([fid]))
        self.version += 1
        return sb * len(new_nodes - old_nodes)

    # -- node status ---------------------------------------------------------
    def _nid(self, node: str) -> int:
        try:
            return self._node_idx[node]
        except KeyError:
            raise ValueError(
                f"unknown node {node!r} (topology: {self.nodes})") from None

    #: Event kinds that change liveness/reachability (and therefore the
    #: cached counts of the files holding the node); flaky/degrade kinds
    #: touch neither the replica map nor the masks.
    _COUNT_KINDS = ("crash", "recover", "decommission", "partition", "heal")

    def apply_event(self, ev) -> None:
        """Apply one FaultEvent (faults/schedule.py); partition/heal groups
        (``dn2+dn3``) apply to every member atomically.  The cached counts
        refresh only for files holding an affected node — the blast
        radius, not the cluster."""
        affected: list[np.ndarray] = []
        for name in ev.node_list:
            i = self._nid(name)
            if ev.kind in self._COUNT_KINDS:
                affected.append(np.flatnonzero(
                    (self.replica_map == i).any(axis=1)))
            if ev.kind == "crash":
                self.node_up[i] = False
            elif ev.kind == "recover":
                if not self.node_decommissioned[i]:
                    self.node_up[i] = True
            elif ev.kind == "decommission":
                self.node_up[i] = False
                self.node_decommissioned[i] = True
                gone = self.replica_map == i
                self.node_bytes[i] = 0
                self.replica_map[gone] = -1
                self._n_corrupt -= int((gone & self.slot_corrupt).sum())
                self.slot_corrupt[gone] = False
            elif ev.kind == "partition":
                self.node_partitioned[i] = True
            elif ev.kind == "heal":
                self.node_partitioned[i] = False
            elif ev.kind == "flaky":
                self.node_fail_prob[i] = float(ev.fail_prob)
            elif ev.kind == "unflaky":
                self.node_fail_prob[i] = 0.0
            elif ev.kind == "degrade":
                self.node_throughput[i] = float(ev.factor)
            elif ev.kind == "restore":
                self.node_throughput[i] = 1.0
            elif ev.kind == "corrupt":
                if ev.file >= 0:
                    if ev.file >= self.replica_map.shape[0]:
                        # Fail fast with the spec, not an IndexError
                        # several windows into the run (node names are
                        # validated up front; file pins can only be
                        # checked against the population here).
                        raise ValueError(
                            f"corrupt event {ev.spec()!r} pins file "
                            f"{ev.file} but the population has "
                            f"{self.replica_map.shape[0]} files")
                    self.corrupt_replica(int(ev.file), i)
                else:
                    # Seeded fraction of the node's assigned copies —
                    # stateless selection, so resume replays it exactly.
                    holds = np.flatnonzero(
                        (self.replica_map == i).any(axis=1))
                    roll = _corrupt_roll(ev.window, i, holds)
                    for f in holds[roll < float(ev.fail_prob)]:
                        self.corrupt_replica(int(f), i)
            else:  # pragma: no cover - FaultEvent validates kinds
                raise ValueError(f"unknown fault kind {ev.kind!r}")
        if affected:
            self._refresh_files(np.unique(np.concatenate(affected)))
        self.version += 1

    def node_reachable(self) -> np.ndarray:
        """(n_nodes,) bool: up, not decommissioned, not partitioned."""
        return (self.node_up & ~self.node_decommissioned
                & ~self.node_partitioned)

    @property
    def n_available(self) -> int:
        """Nodes that can hold a live replica AND be reached right now."""
        return int(self.node_reachable().sum())

    @property
    def n_partitioned(self) -> int:
        return int(self.node_partitioned.sum())

    def domains_reachable(self) -> int:
        """Failure domains with at least one reachable node."""
        reach = self.node_reachable()
        return int(np.unique(self.domain_index[reach]).size)

    def domains_reachable_at(self, level: int) -> int:
        """Hierarchy domains at ``level`` with >= 1 reachable node."""
        reach = self.node_reachable()
        idx = self.topology.domain_index_at(level)
        return int(np.unique(idx[reach]).size)

    def spread_at(self, level: int, chunk: int = 1 << 20) -> np.ndarray:
        """(n,) int32 distinct hierarchy-level-``level`` domains holding
        a REACHABLE replica of each file (the base level's cached twin
        is ``domain_spread``).  Chunked through ``rows`` so overlay
        backends never materialize the full map."""
        idx = self.topology.domain_index_at(level)
        n_dom = self.topology.n_domains_at(level)
        n = self.min_live.shape[0]
        node_reach = self.node_reachable()
        out = np.zeros(n, dtype=np.int32)
        for lo in range(0, n, int(chunk)):
            hi = min(lo + int(chunk), n)
            rows = self.rows(np.arange(lo, hi, dtype=np.int64))
            safe = np.clip(rows, 0, None)
            rmask = (rows >= 0) & node_reach[safe]
            dom = idx[safe]
            spread = np.zeros(hi - lo, dtype=np.int32)
            for d in range(n_dom):
                spread += ((dom == d) & rmask).any(axis=1)
            out[lo:hi] = spread
        return out

    # -- replica accounting --------------------------------------------------
    def live_mask(self) -> np.ndarray:
        """(n, n_nodes) bool: slot holds a replica on an UP node (the data
        exists — partitioned holders count, their disks are fine)."""
        rm = self.replica_map
        return (rm >= 0) & self.node_up[np.clip(rm, 0, None)]

    def reachable_mask(self) -> np.ndarray:
        """(n, n_nodes) bool: slot holds a replica that can actually serve
        (up AND not behind a partition)."""
        rm = self.replica_map
        return (rm >= 0) & self.node_reachable()[np.clip(rm, 0, None)]

    def live_counts(self) -> np.ndarray:
        """(n,) int32 live replicas per file — a copy of the maintained
        cache (callers may scratch on it, the legacy repair loop did)."""
        return self._live_counts.copy()

    def reachable_counts(self) -> np.ndarray:
        """(n,) int32 reachable replicas per file (cached copy)."""
        return self._reach_counts.copy()

    def domain_spread(self) -> np.ndarray:
        """(n,) int32: distinct failure domains holding a REACHABLE replica
        of each file (cached copy)."""
        return self._dom_spread.copy()

    def effective_target(self, target_rf: np.ndarray) -> np.ndarray:
        return np.minimum(np.asarray(target_rf, dtype=np.int64),
                          self.n_available)

    def repair_needs(self, target_rf: np.ndarray):
        """(file ids, reachable counts, effective targets) of every file
        below its effective target — the repair planner's work list."""
        reach = self.reachable_counts()
        eff = self.effective_target(target_rf)
        fids = np.flatnonzero(reach < eff)
        return fids, reach, eff

    def correlated_mask(self, target_rf: np.ndarray, *,
                        reach: np.ndarray | None = None,
                        eff: np.ndarray | None = None) -> np.ndarray:
        """(n,) bool: files whose >= 2 reachable replicas ALL share one
        failure domain while a second domain is reachable and the target
        wants >= 2 — one rack/switch failure from unavailability.  An
        overlay, not a tier: a file can be under-replicated AND
        correlated.  ``reach``/``eff`` let per-window callers reuse
        already-derived arrays instead of re-deriving 10M-row copies.

        On a geo hierarchy the overlay extends UP the tree: a file
        rack-diverse but region-concentrated (every reachable copy in
        one top-level domain while a second region is reachable) is one
        region outage from unavailability and joins the rebalance work
        list — except region-LOCAL files, whose concentration is their
        locality contract, not a risk to fight."""
        n = self.min_live.shape[0]
        if reach is None:
            reach = self._reach_counts
        if eff is None:
            eff = self.effective_target(target_rf)
        out = np.zeros(n, dtype=bool)
        if self.n_domains >= 2 and self.domains_reachable() >= 2:
            out |= (reach >= 2) & (self._dom_spread == 1) & (eff >= 2)
        if self._top_index is not None and self._n_top >= 2 \
                and self.domains_reachable_at(
                    self.topology.n_levels) >= 2:
            out |= ((reach >= 2) & (self._top_spread == 1) & (eff >= 2)
                    & ~self.region_local)
        return out

    def durability(self, target_rf: np.ndarray, cat: np.ndarray,
                   categories) -> dict:
        """Vectorized durability tiers, total and per category.

        Tiers are disjoint: ``lost`` (0 live replicas — every holder is
        crashed/decommissioned), ``unreachable`` (live replicas exist but
        all are stranded behind a partition — reads fail, data survives),
        ``at_risk`` (exactly 1 reachable replica when the effective target
        wants more), ``under_replicated`` (>= 2 reachable but below
        target).  ``correlated_risk`` is an overlay count (see
        ``correlated_mask``).  ``cat`` uses -1 for not-yet-planned files,
        bucketed as "Unplanned".
        """
        live = self._live_counts      # read-only below: no copies
        reach = self._reach_counts
        eff = self.effective_target(target_rf)
        # Shard-generalized tiers (storage/strategy.py arithmetic): a
        # file needs ``min_live`` shards to exist at all (1 full copy,
        # or k of an EC(k, m) stripe).  With the replicate defaults
        # (min_live == 1) these are bit-for-bit the historical tiers.
        need = self.min_live
        lost = live < need
        unreachable = (reach < need) & ~lost
        at_risk = (reach == need) & (eff > need)
        under = (reach > need) & (reach < eff)

        names = list(categories) + ["Unplanned"]
        bucket = np.where(np.asarray(cat) >= 0, cat, len(categories))
        per: dict[str, dict] = {}
        for mask, key in ((under, "under_replicated"), (at_risk, "at_risk"),
                          (unreachable, "unreachable"), (lost, "lost")):
            counts = np.bincount(bucket[mask], minlength=len(names))
            for ci, c in enumerate(counts):
                if c:
                    per.setdefault(names[ci], {})[key] = int(c)
        out = {
            "nodes_up": self.n_available,
            "nodes_partitioned": self.n_partitioned,
            "domains_reachable": self.domains_reachable(),
            "under_replicated": int(under.sum()),
            "at_risk": int(at_risk.sum()),
            "unreachable": int(unreachable.sum()),
            "lost": int(lost.sum()),
            "correlated_risk": int(self.correlated_mask(
                target_rf, reach=reach, eff=eff).sum()),
            "per_category": per,
        }
        n_levels = getattr(self.topology, "n_levels", 0)
        if n_levels > 0:
            # Geo-hierarchical view: correlated risk COMPUTED PER LEVEL —
            # a file rack-diverse but region-concentrated is one region
            # outage from unavailability, which the base-level overlay
            # cannot see.  Region-LOCAL files are exempt at levels above
            # the base: their concentration is the locality contract,
            # not a risk the rebalancer should fight.  Only stamped for
            # hierarchical topologies: pre-hierarchy records stay
            # byte-identical.
            out["regions_reachable"] = self.domains_reachable_at(n_levels)
            per_level: dict[str, int] = {}
            for lvl in range(1, n_levels + 1):
                name = self.topology.level_names[lvl]
                if self.domains_reachable_at(lvl) < 2:
                    per_level[name] = 0
                    continue
                spread = (self._top_spread if lvl == n_levels
                          else self.spread_at(lvl))
                mask = ((reach >= 2) & (spread == 1) & (eff >= 2)
                        & ~self.region_local)
                per_level[name] = int(mask.sum())
            out["correlated_risk_levels"] = per_level
        return out

    def lost_mask(self) -> np.ndarray:
        """Files below their existence threshold — no live full copy, or
        fewer than k live shards of an EC stripe (data gone until a
        crashed holder recovers)."""
        return self.live_counts() < self.min_live

    def unreadable_mask(self) -> np.ndarray:
        """Files a read cannot be served for right now: fewer than
        ``min_live`` reachable shards (lost outright, or enough of the
        stripe stranded behind a partition)."""
        return self.reachable_counts() < self.min_live

    # -- mutation ------------------------------------------------------------
    def _file_domains(self, fid: int) -> set:
        """Domains already holding an ASSIGNED replica of ``fid`` (down
        holders count: their copy returns on recovery)."""
        row = self.row(fid)
        return {int(self.domain_index[x]) for x in row[row >= 0]}

    def pick_repair_target(self, fid: int, rotate: int = 0,
                           new_domain_only: bool = False) -> int:
        """Deterministic target for a new replica of ``fid``: a reachable
        node not already assigned a replica (up OR down — a down holder
        still owns the bytes and will return), preferring nodes in failure
        domains the file does not yet occupy (maximum domain spread; with
        a geo hierarchy, unoccupied TOP-level domains outrank unoccupied
        racks — heal the region spread first), least-loaded within a
        preference class.  ``rotate`` (the repair attempt count) steps
        through the candidate ring so a retry after a flaky failure tries
        a different node.  ``new_domain_only`` restricts candidates to
        unoccupied domains (the correlated-risk rebalance pass — a
        same-domain copy would not fix anything).  A region-local file
        (``region_local``) only ever targets nodes in a top-level domain
        it already occupies — its locality contract survives repair."""
        row = self.row(fid)
        holding = set(int(x) for x in row[row >= 0])
        have_domains = self._file_domains(fid)
        reach = self.node_reachable()
        n_levels = getattr(self.topology, "n_levels", 0)
        avail = [i for i in range(len(self.nodes))
                 if reach[i] and i not in holding]
        if n_levels > 0:
            top = self.topology.top_domain_index()
            have_top = {int(top[x]) for x in holding}
            if self.region_local[fid] and have_top:
                avail = [i for i in avail if int(top[i]) in have_top]
        if new_domain_only:
            avail = [i for i in avail
                     if int(self.domain_index[i]) not in have_domains]
        if not avail:
            return -1
        if n_levels > 0:
            # Count-balancing, not boolean preference: the chooser's
            # (region count, rack count, priority) key carried into the
            # mutation path, so an EC(k, m) re-encode placing k+m
            # shards one at a time still lands region counts within one
            # of each other — the same ceil(shards / regions) worst
            # case a whole-region loss is survivable under.
            top_cnt: dict[int, int] = {}
            base_cnt: dict[int, int] = {}
            for x in holding:
                t = int(top[x])
                b = int(self.domain_index[x])
                top_cnt[t] = top_cnt.get(t, 0) + 1
                base_cnt[b] = base_cnt.get(b, 0) + 1
            avail.sort(key=lambda i: (
                top_cnt.get(int(top[i]), 0),
                base_cnt.get(int(self.domain_index[i]), 0),
                int(self.node_bytes[i]), i))
        else:
            avail.sort(key=lambda i: (
                int(self.domain_index[i]) in have_domains,  # new doms first
                int(self.node_bytes[i]), i))
        return avail[int(rotate) % len(avail)]

    def add_replica(self, fid: int, node: int) -> None:
        row = self.replica_map[fid]
        free = np.flatnonzero(row < 0)
        if free.size == 0:  # pragma: no cover - width==n_nodes prevents this
            raise RuntimeError(f"file {fid} has no free replica slot")
        row[free[0]] = node
        # A freshly written copy is clean by construction (repair streams
        # from a verified source; migration writes new bytes).
        if self.slot_corrupt[fid, free[0]]:  # pragma: no cover - drops clear
            self.slot_corrupt[fid, free[0]] = False
            self._n_corrupt -= 1
        self.node_bytes[node] += self.shard_bytes[fid]
        self._refresh_files(np.asarray([fid]))
        self.version += 1

    def drop_replica(self, fid: int, node: int) -> None:
        row = self.replica_map[fid]
        slots = np.flatnonzero(row == node)
        if slots.size:
            row[slots[0]] = -1
            if self.slot_corrupt[fid, slots[0]]:
                self.slot_corrupt[fid, slots[0]] = False
                self._n_corrupt -= 1
            self.node_bytes[node] -= self.shard_bytes[fid]
            self._refresh_files(np.asarray([fid]))
            self.version += 1

    def _drop_order(self, fid: int, holders: list[int]) -> list[int]:
        """Holders sorted most-droppable first: crowded domains lose
        replicas before singleton domains (keep the spread the domain-aware
        placement bought; with a geo hierarchy, crowded REGIONS outrank
        crowded racks — a rebalance's fresh cross-region copy must never
        be the drop victim), most-loaded node within a domain class."""
        dom_count: dict[int, int] = {}
        for h in holders:
            d = int(self.domain_index[h])
            dom_count[d] = dom_count.get(d, 0) + 1
        if self._top_index is not None:
            top_count: dict[int, int] = {}
            for h in holders:
                t = int(self._top_index[h])
                top_count[t] = top_count.get(t, 0) + 1
            return sorted(holders, key=lambda i: (
                -top_count[int(self._top_index[i])],
                -dom_count[int(self.domain_index[i])],
                -int(self.node_bytes[i]), i))
        return sorted(holders, key=lambda i: (
            -dom_count[int(self.domain_index[i])],
            -int(self.node_bytes[i]), i))

    def drop_crowded(self, fid: int) -> int:
        """Drop one REACHABLE replica from the file's most-crowded domain
        (the free half of a spread rebalance).  Returns the node dropped,
        or -1 when the file has fewer than 2 reachable replicas."""
        row = self.row(fid)
        reach = self.node_reachable()
        holders = [int(x) for x in row[row >= 0] if reach[int(x)]]
        if len(holders) < 2:
            return -1
        victim = self._drop_order(fid, holders)[0]
        self.drop_replica(fid, victim)
        return victim

    def apply_rf_target(self, fid: int, rf_new: int,
                        record_intent: bool = True) -> int:
        """Bring ``fid`` toward ``rf_new`` reachable replicas (capped at
        the reachable node count): migrations call this when a planned rf
        change applies.  Adds go to the spread-preferred least-loaded
        eligible node; drops release down-but-assigned slots first (free
        metadata deletes), then reachable holders crowded-domain-first.
        Replicas stranded behind a partition are never dropped — they are
        the durability story until the partition heals.  Returns reachable
        delta."""
        if record_intent:
            self.installed_shards[fid] = int(rf_new)
        target = min(int(rf_new), self.n_available)
        live = int(self._reach_counts[fid])
        delta = 0
        if live < int(self.min_live[fid]):
            # No reachable source to copy/reconstruct from (a replicate
            # file with no reachable copy, or an EC stripe below k
            # reachable shards): a lost or stranded file cannot be
            # re-replicated by fiat.  The repair path heals it the
            # window a holder recovers or the partition heals.
            return 0
        while live < target:
            node = self.pick_repair_target(fid)
            if node < 0:
                break
            self.add_replica(fid, node)
            live += 1
            delta += 1
        if live > target:
            # Release dead-weight slots on DOWN nodes first (partitioned
            # nodes are up — their stranded copies are kept).
            row = self.row(fid)
            for node in [int(x) for x in row[row >= 0]
                         if not self.node_up[int(x)]]:
                self.drop_replica(fid, node)
        reach = self.node_reachable()
        while live > target:
            row = self.row(fid)
            holders = [int(x) for x in row[row >= 0] if reach[int(x)]]
            if not holders:  # pragma: no cover - live>target implies holders
                break
            self.drop_replica(fid, self._drop_order(fid, holders)[0])
            live -= 1
            delta -= 1
        return delta

    def trim_excess(self, target_rf: np.ndarray) -> int:
        """Drop reachable replicas beyond the effective target (a recovered
        node or healed partition can resurface replicas the repair path
        already re-created) — free metadata deletes, HDFS's excess-replica
        pruning, crowded-domain-first so the trim never collapses the
        spread.  Returns files trimmed."""
        eff = self.effective_target(target_rf)
        # flatnonzero evaluates eagerly, so reading the cache in place is
        # safe even though apply_rf_target refreshes it row by row below.
        over = np.flatnonzero(self._reach_counts > eff)
        for fid in over:
            # The trim's capped target is NOT a new intent — the file's
            # installed_shards must survive a transient excess.
            self.apply_rf_target(int(fid), int(eff[fid]),
                                 record_intent=False)
        return int(over.size)

    # -- rendering back into the immutable world -----------------------------
    def placement_view(self) -> PlacementResult:
        """The REACHABLE replicas as a PlacementResult (rows compacted so
        reachable node ids lead, -1 padding trails) for cluster/evaluate.py
        replay.  Files with zero reachable replicas get rf=0 — their reads
        are served by nobody and count as non-local."""
        reach = self.reachable_mask()
        masked = np.where(reach, self.replica_map, -1).astype(np.int32)
        order = np.argsort(~reach, axis=1, kind="stable")
        compact = np.take_along_axis(masked, order, axis=1)
        rf_live = reach.sum(axis=1).astype(np.int32)
        view = PlacementResult(replica_map=compact, rf=rf_live,
                               topology=self.topology)
        view.compute_storage(self.shard_bytes)
        return view

    # -- checkpoint ----------------------------------------------------------
    def state_arrays(self) -> dict[str, np.ndarray]:
        return {
            "fault_replica_map": self.replica_map.copy(),
            "fault_node_up": self.node_up.copy(),
            "fault_node_decommissioned": self.node_decommissioned.copy(),
            "fault_node_partitioned": self.node_partitioned.copy(),
            "fault_node_fail_prob": self.node_fail_prob.copy(),
            "fault_node_throughput": self.node_throughput.copy(),
            # Storage-strategy state (storage/): which files are EC
            # stripes right now and at what shard size — a mid-outage
            # resume must account durability/repair identically.
            "fault_min_live": self.min_live.copy(),
            "fault_shard_bytes": self.shard_bytes.copy(),
            "fault_ec_k": self.ec_k.copy(),
            "fault_region_local": self.region_local.copy(),
            "fault_installed_shards": self.installed_shards.copy(),
            # Latent-rot ground truth (integrity layer): a mid-outage
            # resume must keep serving/refusing exactly the same copies.
            "fault_slot_corrupt": self.slot_corrupt.copy(),
        }

    def load_state_arrays(self, arrays: dict) -> None:
        rm = np.asarray(arrays["fault_replica_map"], dtype=np.int32)
        if rm.shape != self.replica_map.shape:
            raise ValueError(
                f"checkpoint replica map shape {rm.shape} != "
                f"{self.replica_map.shape} — stale checkpoint?")
        n_nodes = len(self.nodes)
        self.replica_map = rm.copy()
        self.node_up = np.asarray(arrays["fault_node_up"],
                                  dtype=bool).copy()
        self.node_decommissioned = np.asarray(
            arrays["fault_node_decommissioned"], dtype=bool).copy()
        # Pre-partition checkpoints lack the two newer arrays: default to
        # "no partition, nominal throughput" rather than refusing to load.
        self.node_partitioned = np.asarray(
            arrays.get("fault_node_partitioned", np.zeros(n_nodes, bool)),
            dtype=bool).copy()
        self.node_fail_prob = np.asarray(arrays["fault_node_fail_prob"],
                                         dtype=np.float64).copy()
        self.node_throughput = np.asarray(
            arrays.get("fault_node_throughput", np.ones(n_nodes)),
            dtype=np.float64).copy()
        # Pre-storage checkpoints lack the strategy arrays: default to
        # the replicate semantics (min_live=1, shard=size, no EC).
        n = self.replica_map.shape[0]
        self.min_live = np.asarray(
            arrays.get("fault_min_live", np.ones(n, np.int32)),
            dtype=np.int32).copy()
        self.shard_bytes = np.asarray(
            arrays.get("fault_shard_bytes", self.sizes),
            dtype=np.int64).copy()
        self.ec_k = np.asarray(
            arrays.get("fault_ec_k", np.zeros(n, np.int32)),
            dtype=np.int32).copy()
        # Pre-hierarchy checkpoints lack the locality flags: no file was
        # ever pinned to a region.
        self.region_local = np.asarray(
            arrays.get("fault_region_local", np.zeros(n, bool)),
            dtype=bool).copy()
        # Pre-intent checkpoints: fall back to the assigned-slot count
        # (floored at min_live) — the closest observable to the intent.
        self.installed_shards = np.asarray(
            arrays.get("fault_installed_shards",
                       np.maximum((rm >= 0).sum(axis=1), self.min_live)),
            dtype=np.int32).copy()
        # Pre-integrity checkpoints lack the rot mask: default to clean.
        self.slot_corrupt = np.asarray(
            arrays.get("fault_slot_corrupt",
                       np.zeros(self.replica_map.shape, bool)),
            dtype=bool).copy()
        self._n_corrupt = int(self.slot_corrupt.sum())
        self._recompute_node_bytes()
        self._refresh_all()
        self.version += 1
