"""Mutable cluster state: node liveness + the evolving replica map.

``cluster/placement.py`` produces an *immutable* placement — correct for
the batch pipeline, useless once nodes can die.  ``ClusterState`` takes one
placement as the starting condition and becomes the source of truth the
fault schedule (faults/schedule.py), the repair scheduler
(faults/repair.py) and the controller's migrations all mutate:

* per-node status — up/down (crash/recover), decommissioned (permanent,
  replicas destroyed), and a flaky fail-probability for repair targeting;
* the replica map — ``(n_files, n_nodes)`` int32 node ids, -1 = empty slot
  (width = node count: replicas are distinct-per-node, so no file can ever
  need more slots);
* durability accounting — vectorized under-replicated / at-risk (1 live
  replica) / lost (0 live replicas) tiers against an *effective* target
  rf = min(target, up nodes) (a 3-replica target is unattainable with 2
  nodes up; HDFS likewise re-replicates only to live capacity).

Everything is deterministic and the whole state round-trips through
``state_arrays``/``load_state_arrays`` so a controller checkpoint taken
mid-fault resumes bit-identically.  ``placement_view`` renders the live
replicas back into a ``PlacementResult`` so the existing replay
(cluster/evaluate.py) measures locality/balance under the outage — no
second evaluation path.
"""

from __future__ import annotations

import numpy as np

from ..cluster.placement import ClusterTopology, PlacementResult

__all__ = ["ClusterState"]


class ClusterState:
    """One controlled cluster's mutable placement + node status."""

    def __init__(self, placement: PlacementResult, size_bytes: np.ndarray):
        self.topology: ClusterTopology = placement.topology
        self.nodes: tuple[str, ...] = tuple(placement.topology.nodes)
        n_nodes = len(self.nodes)
        n = placement.replica_map.shape[0]
        self._node_idx = {nm: i for i, nm in enumerate(self.nodes)}
        self.sizes = np.asarray(size_bytes, dtype=np.int64)
        if self.sizes.shape != (n,):
            raise ValueError(
                f"size_bytes shape {self.sizes.shape} != ({n},)")

        rm = np.full((n, n_nodes), -1, dtype=np.int32)
        w = min(placement.replica_map.shape[1], n_nodes)
        rm[:, :w] = placement.replica_map[:, :w]
        self.replica_map = rm
        self.node_up = np.ones(n_nodes, dtype=bool)
        self.node_decommissioned = np.zeros(n_nodes, dtype=bool)
        self.node_fail_prob = np.zeros(n_nodes, dtype=np.float64)
        #: Bytes *assigned* per node (down replicas still occupy disk);
        #: the deterministic least-loaded repair-target preference.
        self.node_bytes = np.zeros(n_nodes, dtype=np.int64)
        assigned = self.replica_map >= 0
        np.add.at(self.node_bytes, self.replica_map[assigned],
                  np.broadcast_to(self.sizes[:, None],
                                  self.replica_map.shape)[assigned])
        #: Bumped on every mutation — cache-invalidation for evaluators.
        self.version = 0

    # -- node status ---------------------------------------------------------
    def _nid(self, node: str) -> int:
        try:
            return self._node_idx[node]
        except KeyError:
            raise ValueError(
                f"unknown node {node!r} (topology: {self.nodes})") from None

    def apply_event(self, ev) -> None:
        """Apply one FaultEvent (faults/schedule.py)."""
        i = self._nid(ev.node)
        if ev.kind == "crash":
            self.node_up[i] = False
        elif ev.kind == "recover":
            if not self.node_decommissioned[i]:
                self.node_up[i] = True
        elif ev.kind == "decommission":
            self.node_up[i] = False
            self.node_decommissioned[i] = True
            gone = self.replica_map == i
            self.node_bytes[i] = 0
            self.replica_map[gone] = -1
        elif ev.kind == "flaky":
            self.node_fail_prob[i] = float(ev.fail_prob)
        elif ev.kind == "unflaky":
            self.node_fail_prob[i] = 0.0
        else:  # pragma: no cover - FaultEvent validates kinds
            raise ValueError(f"unknown fault kind {ev.kind!r}")
        self.version += 1

    @property
    def n_available(self) -> int:
        """Nodes that can hold a live replica right now."""
        return int((self.node_up & ~self.node_decommissioned).sum())

    # -- replica accounting --------------------------------------------------
    def live_mask(self) -> np.ndarray:
        """(n, n_nodes) bool: slot holds a replica on an UP node."""
        rm = self.replica_map
        return (rm >= 0) & self.node_up[np.clip(rm, 0, None)]

    def live_counts(self) -> np.ndarray:
        return self.live_mask().sum(axis=1).astype(np.int32)

    def effective_target(self, target_rf: np.ndarray) -> np.ndarray:
        return np.minimum(np.asarray(target_rf, dtype=np.int64),
                          self.n_available)

    def repair_needs(self, target_rf: np.ndarray):
        """(file ids, live counts, effective targets) of every file below
        its effective target — the repair planner's work list."""
        live = self.live_counts()
        eff = self.effective_target(target_rf)
        fids = np.flatnonzero(live < eff)
        return fids, live, eff

    def durability(self, target_rf: np.ndarray, cat: np.ndarray,
                   categories) -> dict:
        """Vectorized durability tiers, total and per category.

        Tiers are disjoint: ``lost`` (0 live replicas — unreadable until a
        crashed holder recovers), ``at_risk`` (exactly 1 live replica when
        the effective target wants more — one failure from loss),
        ``under_replicated`` (>= 2 live but below target).  ``cat`` uses
        -1 for not-yet-planned files, bucketed as "Unplanned".
        """
        live = self.live_counts()
        eff = self.effective_target(target_rf)
        lost = live == 0
        at_risk = (live == 1) & (eff >= 2)
        under = (live >= 2) & (live < eff)

        names = list(categories) + ["Unplanned"]
        bucket = np.where(np.asarray(cat) >= 0, cat, len(categories))
        per: dict[str, dict] = {}
        for mask, key in ((under, "under_replicated"), (at_risk, "at_risk"),
                          (lost, "lost")):
            counts = np.bincount(bucket[mask], minlength=len(names))
            for ci, c in enumerate(counts):
                if c:
                    per.setdefault(names[ci], {})[key] = int(c)
        return {
            "nodes_up": self.n_available,
            "under_replicated": int(under.sum()),
            "at_risk": int(at_risk.sum()),
            "lost": int(lost.sum()),
            "per_category": per,
        }

    def lost_mask(self) -> np.ndarray:
        return self.live_counts() == 0

    # -- mutation ------------------------------------------------------------
    def pick_repair_target(self, fid: int, rotate: int = 0) -> int:
        """Deterministic target for a new replica of ``fid``: an available
        node not already assigned a replica (up OR down — a down holder
        still owns the bytes and will return), least-loaded first.
        ``rotate`` (the repair attempt count) steps through the candidate
        ring so a retry after a flaky failure tries a different node."""
        row = self.replica_map[fid]
        holding = set(int(x) for x in row[row >= 0])
        avail = [i for i in range(len(self.nodes))
                 if self.node_up[i] and not self.node_decommissioned[i]
                 and i not in holding]
        if not avail:
            return -1
        avail.sort(key=lambda i: (int(self.node_bytes[i]), i))
        return avail[int(rotate) % len(avail)]

    def add_replica(self, fid: int, node: int) -> None:
        row = self.replica_map[fid]
        free = np.flatnonzero(row < 0)
        if free.size == 0:  # pragma: no cover - width==n_nodes prevents this
            raise RuntimeError(f"file {fid} has no free replica slot")
        row[free[0]] = node
        self.node_bytes[node] += self.sizes[fid]
        self.version += 1

    def drop_replica(self, fid: int, node: int) -> None:
        row = self.replica_map[fid]
        slots = np.flatnonzero(row == node)
        if slots.size:
            row[slots[0]] = -1
            self.node_bytes[node] -= self.sizes[fid]
            self.version += 1

    def apply_rf_target(self, fid: int, rf_new: int) -> int:
        """Bring ``fid`` toward ``rf_new`` live replicas (capped at the
        available node count): migrations call this when a planned rf
        change applies.  Adds go to the least-loaded eligible node; drops
        release down-but-assigned slots first (free metadata deletes),
        then the most-loaded live holders.  Returns live delta."""
        target = min(int(rf_new), self.n_available)
        live = int((self.live_mask()[fid]).sum())
        delta = 0
        if live == 0:
            # No live source to copy from: a lost file cannot be
            # re-replicated by fiat.  The repair path heals it to target
            # the window a crashed holder recovers.
            return 0
        while live < target:
            node = self.pick_repair_target(fid)
            if node < 0:
                break
            self.add_replica(fid, node)
            live += 1
            delta += 1
        if live > target:
            # Release dead-weight slots on DOWN nodes first.
            row = self.replica_map[fid]
            for node in [int(x) for x in row[row >= 0]
                         if not self.node_up[int(x)]]:
                self.drop_replica(fid, node)
        while live > target:
            row = self.replica_map[fid]
            holders = [int(x) for x in row[row >= 0]
                       if self.node_up[int(x)]]
            if not holders:  # pragma: no cover - live>target implies holders
                break
            holders.sort(key=lambda i: (-int(self.node_bytes[i]), i))
            self.drop_replica(fid, holders[0])
            live -= 1
            delta -= 1
        return delta

    def trim_excess(self, target_rf: np.ndarray) -> int:
        """Drop live replicas beyond the effective target (a recovered node
        can resurface replicas the repair path already re-created) — free
        metadata deletes, HDFS's excess-replica pruning.  Returns files
        trimmed."""
        live = self.live_counts()
        eff = self.effective_target(target_rf)
        over = np.flatnonzero(live > eff)
        for fid in over:
            self.apply_rf_target(int(fid), int(eff[fid]))
        return int(over.size)

    # -- rendering back into the immutable world -----------------------------
    def placement_view(self) -> PlacementResult:
        """The LIVE replicas as a PlacementResult (rows compacted so live
        node ids lead, -1 padding trails) for cluster/evaluate.py replay.
        Files with zero live replicas get rf=0 — their reads are served by
        nobody and count as non-local."""
        live = self.live_mask()
        masked = np.where(live, self.replica_map, -1).astype(np.int32)
        order = np.argsort(~live, axis=1, kind="stable")
        compact = np.take_along_axis(masked, order, axis=1)
        rf_live = live.sum(axis=1).astype(np.int32)
        storage = np.zeros(len(self.nodes), dtype=np.int64)
        sel = compact >= 0
        np.add.at(storage, compact[sel],
                  np.broadcast_to(self.sizes[:, None], compact.shape)[sel])
        return PlacementResult(replica_map=compact, rf=rf_live,
                               topology=self.topology,
                               storage_per_node=storage)

    # -- checkpoint ----------------------------------------------------------
    def state_arrays(self) -> dict[str, np.ndarray]:
        return {
            "fault_replica_map": self.replica_map.copy(),
            "fault_node_up": self.node_up.copy(),
            "fault_node_decommissioned": self.node_decommissioned.copy(),
            "fault_node_fail_prob": self.node_fail_prob.copy(),
        }

    def load_state_arrays(self, arrays: dict) -> None:
        rm = np.asarray(arrays["fault_replica_map"], dtype=np.int32)
        if rm.shape != self.replica_map.shape:
            raise ValueError(
                f"checkpoint replica map shape {rm.shape} != "
                f"{self.replica_map.shape} — stale checkpoint?")
        self.replica_map = rm.copy()
        self.node_up = np.asarray(arrays["fault_node_up"],
                                  dtype=bool).copy()
        self.node_decommissioned = np.asarray(
            arrays["fault_node_decommissioned"], dtype=bool).copy()
        self.node_fail_prob = np.asarray(arrays["fault_node_fail_prob"],
                                         dtype=np.float64).copy()
        self.node_bytes = np.zeros(len(self.nodes), dtype=np.int64)
        assigned = self.replica_map >= 0
        np.add.at(self.node_bytes, self.replica_map[assigned],
                  np.broadcast_to(self.sizes[:, None],
                                  self.replica_map.shape)[assigned])
        self.version += 1
