"""Seeded, deterministic fault schedules keyed to controller windows.

The paper's category -> replication-factor mapping (Hot=3, Shared=2,
Moderate=1, Archival=4) exists to survive datanode failures, yet nothing in
the batch pipeline or the online controller ever loses a node.  A
``FaultSchedule`` is the missing input: an ordered list of infrastructure
events — crash, recover, decommission, flaky, partition, degrade — each
pinned to a *window index* of the controller's time grid
(control/windows.py), so the same schedule replayed over the same log
produces the same failure trajectory, and a kill/resume of the controller
mid-fault is bit-identical by construction (the schedule is config, not
state; the *consequences* live in ``ClusterState`` and ride the
checkpoint).

Event kinds (HDFS namenode vocabulary, Shvachko et al. MSST 2010):

* ``crash``        — node down; its replicas become unavailable but are NOT
                     destroyed (the disk survives a process crash).
* ``recover``      — a crashed node returns with its replicas intact.
* ``decommission`` — node permanently removed; its replicas are destroyed.
* ``flaky``        — node stays up but repair copies targeting it fail with
                     the given probability (seeded, stateless rolls —
                     faults/repair.py), modelling a slow/half-broken node.
* ``unflaky``      — clears the flaky probability.
* ``partition``    — a node SET becomes unreachable as a group (switch
                     failure / netsplit): replicas behind it are intact but
                     cannot serve reads or source/sink repair copies.
                     Group syntax: ``dn2+dn3``.
* ``heal``         — the partition heals; the node set is reachable again.
* ``degrade``      — straggler: the node stays up but moves data at
                     ``factor``x its nominal throughput (repair copies
                     routed through it are charged ``size/factor`` of the
                     churn budget — the wire time is real).
* ``restore``      — clears the straggler multiplier back to 1.0.
* ``corrupt``      — SILENT data fault: replicas/shards hosted on the node
                     rot in place (bit flips, latent sector errors).  The
                     node stays up and keeps serving the rotten bytes —
                     nothing notices until a verified read (scrubber, read
                     path, or repair source check) touches the copy.
                     ``corrupt:dn2@3:0.25`` rots a seeded 25% fraction of
                     dn2's copies; ``corrupt:dn2#17@3`` rots exactly file
                     17's copy on dn2.  No span form: rot does not heal
                     itself.

Schedules come from three places: explicit specs (``crash:dn2@3``,
``crash:dn2@3-7`` = crash at 3 / recover at 8, ``flaky:dn1@2-6:0.5``,
``partition:dn2+dn3@4-6`` = partition at 4 / heal at 7,
``degrade:dn3@2-6:0.25``, ``corrupt:dn2@3:0.25``), JSON round-trip (the
``cdrs chaos --schedule`` contract), or the seeded ``random`` generator
(chaos smoke tests), which never downs the last remaining node.
"""

from __future__ import annotations

from dataclasses import dataclass, replace as _dc_replace

import numpy as np

__all__ = ["FaultEvent", "FaultSchedule"]

#: Within one window, events apply in this order (healing kinds before
#: breaking kinds so a same-window heal+break of two node sets is
#: order-independent by kind).
KINDS: tuple[str, ...] = ("recover", "heal", "unflaky", "restore",
                          "crash", "partition", "flaky", "degrade",
                          "decommission", "corrupt")
_KIND_ORDER = {k: i for i, k in enumerate(KINDS)}
#: Kinds whose span form (``@lo-hi``) expands to (start kind, end kind).
_SPAN_END = {"crash": "recover", "flaky": "unflaky",
             "partition": "heal", "degrade": "restore"}
#: Kinds accepting a ``level:name`` domain scope (``crash:region:eu@3-7``
#: downs EVERY node of region ``eu`` — the correlated whole-domain event
#: a geo hierarchy exists to survive).  Resolution needs the topology, so
#: scoped schedules expand through ``expand_domains`` before running.
_SCOPE_KINDS = ("crash", "recover", "decommission", "partition", "heal")


@dataclass(frozen=True)
class FaultEvent:
    """One infrastructure event at a window boundary."""

    window: int
    kind: str       # one of KINDS
    #: Topology node name; ``partition``/``heal`` accept a ``+``-joined
    #: group (``dn2+dn3``) — the set drops/returns atomically.
    node: str
    #: ``flaky``: probability a repair copy targeting the node fails.
    #: ``corrupt``: the seeded fraction of the node's copies that rot
    #: (ignored when ``file`` targets one copy explicitly).
    fail_prob: float = 0.0
    #: ``degrade`` only: throughput multiplier in (0, 1] — 0.25 = the node
    #: moves repair bytes at a quarter of nominal speed.
    factor: float = 1.0
    #: ``corrupt`` only: file index whose copy on ``node`` rots; -1 =
    #: seeded ``fail_prob`` fraction of the node's copies instead.
    file: int = -1

    def __post_init__(self):
        if self.kind not in _KIND_ORDER:
            raise ValueError(
                f"unknown fault kind {self.kind!r} (want one of {KINDS})")
        if self.window < 0:
            raise ValueError(f"fault window must be >= 0, got {self.window}")
        if not 0.0 <= self.fail_prob <= 1.0:
            raise ValueError(
                f"fail_prob must be in [0, 1], got {self.fail_prob}")
        if not 0.0 < self.factor <= 1.0:
            raise ValueError(
                f"degrade factor must be in (0, 1], got {self.factor}")
        if "+" in self.node and self.kind not in ("partition", "heal"):
            raise ValueError(
                f"node groups ('+') are only valid for partition/heal, "
                f"not {self.kind!r} ({self.node!r})")
        if ":" in self.node and self.kind not in _SCOPE_KINDS:
            raise ValueError(
                f"domain scopes ('level:name', e.g. 'region:eu') are "
                f"only valid for {'/'.join(_SCOPE_KINDS)}, not "
                f"{self.kind!r} ({self.node!r})")
        if self.file >= 0 and self.kind != "corrupt":
            raise ValueError(
                f"file targeting is only valid for corrupt, not "
                f"{self.kind!r}")

    @property
    def node_list(self) -> tuple[str, ...]:
        """The event's nodes (partition/heal groups split on ``+``)."""
        return tuple(self.node.split("+"))

    def spec(self) -> str:
        if self.kind == "corrupt":
            if self.file >= 0:
                return f"corrupt:{self.node}#{self.file}@{self.window}"
            return (f"corrupt:{self.node}@{self.window}"
                    f":{self.fail_prob:g}")
        s = f"{self.kind}:{self.node}@{self.window}"
        if self.kind == "flaky":
            s += f":{self.fail_prob:g}"
        elif self.kind == "degrade":
            s += f":{self.factor:g}"
        return s


class _EventsView(tuple):
    """The schedule's window-sorted event tuple, callable for edit flows.

    ``sched.events`` keeps its historical meaning (an immutable tuple
    attribute, iterable/indexable/comparable like any tuple), while
    ``sched.events()`` returns a fresh MUTABLE list of the same
    ``FaultEvent``s — the decomposition half of the edit contract whose
    recomposition half is ``FaultSchedule.from_events``.
    """

    def __call__(self) -> list:
        return list(self)


class FaultSchedule:
    """Immutable, window-sorted event list (see module docstring)."""

    def __init__(self, events=()):
        evs = tuple(sorted(events,
                           key=lambda e: (e.window, _KIND_ORDER[e.kind],
                                          e.node)))
        self.events: tuple[FaultEvent, ...] = _EventsView(evs)

    def __len__(self) -> int:
        return len(self.events)

    def __iter__(self):
        return iter(self.events)

    def for_window(self, w: int) -> tuple[FaultEvent, ...]:
        return tuple(e for e in self.events if e.window == int(w))

    @property
    def max_window(self) -> int:
        return max((e.window for e in self.events), default=-1)

    def nodes(self) -> tuple[str, ...]:
        return tuple(sorted({n for e in self.events for n in e.node_list}))

    def validate_nodes(self, topology_nodes) -> None:
        scoped = sorted(n for n in self.nodes() if ":" in n)
        if scoped:
            raise ValueError(
                f"fault schedule still carries unexpanded domain scopes "
                f"{scoped} — resolve them against the topology first "
                f"(FaultSchedule.expand_domains)")
        unknown = sorted(set(self.nodes()) - set(topology_nodes))
        if unknown:
            raise ValueError(
                f"fault schedule names nodes outside the topology "
                f"{tuple(topology_nodes)}: {unknown}")

    def expand_domains(self, topology) -> "FaultSchedule":
        """Resolve ``level:name`` domain scopes against a topology:
        ``crash:region:eu@3`` becomes one crash per node of region
        ``eu``; a scoped partition/heal keeps the resolved nodes as ONE
        atomic group (the whole region drops/returns together — the WAN
        partition).  Scope-free schedules return ``self`` unchanged.
        Unknown levels/domains raise naming the offending token
        (``ClusterTopology.nodes_in``)."""
        if not any(":" in n for e in self.events for n in e.node_list):
            return self
        events: list[FaultEvent] = []
        for e in self.events:
            if not any(":" in n for n in e.node_list):
                events.append(e)
                continue
            resolved: list[str] = []
            for token in e.node_list:
                if ":" not in token:
                    resolved.append(token)
                    continue
                level, dom = token.split(":", 1)
                try:
                    members = topology.nodes_in(level, dom)
                except (ValueError, AttributeError) as err:
                    raise ValueError(
                        f"fault event {e.spec()!r}: {err}") from None
                resolved.extend(members)
            kw = {"fail_prob": e.fail_prob, "factor": e.factor,
                  "file": e.file}
            if e.kind in ("partition", "heal"):
                events.append(FaultEvent(e.window, e.kind,
                                         "+".join(resolved), **kw))
            else:
                events.extend(FaultEvent(e.window, e.kind, n, **kw)
                              for n in resolved)
        return FaultSchedule(events)

    # -- event-level editing (mutate / splice / drop) ------------------------
    @classmethod
    def from_events(cls, events) -> "FaultSchedule":
        """Recompose a schedule from an edited event list — the inverse of
        ``events()``.  Accepts ``FaultEvent``s or ``to_json``-style dicts,
        so both ``from_events(s.events())`` and ``from_events(s.to_json())``
        are lossless identities (order is renormalized, duplicates kept)."""
        rows = list(events)
        if rows and isinstance(rows[0], dict):
            return cls.from_json(rows)
        return cls(rows)

    def drop(self, index: int) -> "FaultSchedule":
        """New schedule without ``events()[index]`` (negative indices OK)."""
        rows = self.events()
        del rows[index]
        return FaultSchedule(rows)

    def splice(self, event: FaultEvent) -> "FaultSchedule":
        """New schedule with ``event`` added (window order renormalized)."""
        return FaultSchedule((*self.events, event))

    def retime(self, index: int, window: int) -> "FaultSchedule":
        """New schedule with ``events()[index]`` moved to ``window``."""
        return self.mutate(index, window=int(window))

    def mutate(self, index: int, **changes) -> "FaultSchedule":
        """New schedule with ``events()[index]`` field-replaced (any
        ``FaultEvent`` field: window/kind/node/fail_prob/factor/file);
        validation reruns, so an edit that breaks an event invariant
        raises the same ``ValueError`` construction would."""
        rows = self.events()
        rows[index] = _dc_replace(rows[index], **changes)
        return FaultSchedule(rows)

    # -- constructors --------------------------------------------------------
    @classmethod
    def from_specs(cls, specs) -> "FaultSchedule":
        """Parse ``kind:node@window`` specs.

        ``crash:dn2@3-7`` expands to crash at 3 plus recover at 8 (the span
        is inclusive); partitions likewise (``partition:dn2+dn3@4-6`` =
        partition at 4, heal at 7).  ``flaky:dn1@2-6:0.5`` expands to
        flaky(p=0.5) at 2 plus unflaky at 7 (probability defaults to 0.5);
        ``degrade:dn3@2-6:0.25`` to degrade(factor=0.25) at 2 plus restore
        at 7 (factor defaults to 0.5).  ``corrupt:dn2@3:0.25`` silently
        rots a seeded 25% of dn2's copies at window 3 (fraction defaults
        to 0.1); ``corrupt:dn2#17@3`` rots exactly file 17's copy.
        """
        events: list[FaultEvent] = []
        for spec in specs:
            try:
                kind, rest = spec.split(":", 1)
                if kind in ("flaky", "degrade", "corrupt") \
                        and rest.count(":") == 1:
                    rest, prob_s = rest.rsplit(":", 1)
                    prob = float(prob_s)
                else:
                    prob = 0.1 if kind == "corrupt" else 0.5
                node, span = rest.split("@", 1)
                file_idx = -1
                if kind == "corrupt" and "#" in node:
                    node, fid_s = node.split("#", 1)
                    file_idx = int(fid_s)
                    if file_idx < 0:
                        # A negative pin would silently fall through to
                        # fraction mode (FaultEvent treats -1 as "no
                        # pin") — reject it as a bad spec instead.
                        raise ValueError
                if "-" in span:
                    lo, hi = (int(s) for s in span.split("-", 1))
                else:
                    lo = hi = int(span)
            except ValueError:
                raise ValueError(
                    f"bad fault spec {spec!r} (want kind:node@window, e.g. "
                    f"'crash:dn2@3', 'crash:dn2@3-7', 'flaky:dn1@2-6:0.5', "
                    f"'partition:dn2+dn3@4-6', 'degrade:dn3@2-6:0.25', "
                    f"'corrupt:dn2@3:0.25', 'corrupt:dn2#17@3')"
                ) from None
            kw = {}
            if kind == "flaky":
                kw["fail_prob"] = prob
            elif kind == "degrade":
                kw["factor"] = prob
            elif kind == "corrupt":
                kw["fail_prob"] = prob
                kw["file"] = file_idx
            if "-" in span:
                if hi < lo:
                    raise ValueError(
                        f"bad fault span in {spec!r}: {hi} < {lo}")
                if kind not in _SPAN_END:
                    raise ValueError(
                        f"spans are only valid for "
                        f"{'/'.join(_SPAN_END)}, not {kind!r} ({spec!r})")
                events += [FaultEvent(lo, kind, node, **kw),
                           FaultEvent(hi + 1, _SPAN_END[kind], node)]
            else:
                events.append(FaultEvent(lo, kind, node, **kw))
        return cls(events)

    @classmethod
    def cascade(cls, nodes, start: int, spacing: int = 1,
                recover_after: int | None = None) -> "FaultSchedule":
        """Cascading failure template: ``nodes[i]`` crashes at window
        ``start + i * spacing`` — the correlated rolling outage (power
        strip, bad kernel rollout) that a single-crash scenario never
        exercises: each crash lands while the repair backlog from the
        previous one is still draining, so the churn budget is contested
        the whole way down.  ``recover_after`` windows later each node
        returns (None = the cascade is permanent — but never pass ALL
        nodes then, or the cluster ends empty)."""
        nodes = tuple(nodes)
        if not nodes:
            raise ValueError("cascade needs at least one node")
        if spacing < 1:
            raise ValueError(f"spacing must be >= 1, got {spacing}")
        events = []
        for i, n in enumerate(nodes):
            w = int(start) + i * int(spacing)
            events.append(FaultEvent(w, "crash", n))
            if recover_after is not None:
                if recover_after < 1:
                    raise ValueError(
                        f"recover_after must be >= 1, got {recover_after}")
                events.append(FaultEvent(w + int(recover_after),
                                         "recover", n))
        return cls(events)

    @classmethod
    def rolling_decommission(cls, nodes, start: int,
                             spacing: int = 2) -> "FaultSchedule":
        """Rolling-decommission template: ``nodes[i]`` is PERMANENTLY
        removed (replicas destroyed) at window ``start + i * spacing`` —
        the planned fleet-drain scenario: data must be re-replicated off
        each node before the next one goes, entirely out of the shared
        churn budget, with zero loss as the pass/fail line.  The caller
        must leave enough surviving nodes for the target replication
        factors."""
        nodes = tuple(nodes)
        if not nodes:
            raise ValueError("rolling_decommission needs at least one node")
        if spacing < 1:
            raise ValueError(f"spacing must be >= 1, got {spacing}")
        return cls([FaultEvent(int(start) + i * int(spacing),
                               "decommission", n)
                    for i, n in enumerate(nodes)])

    @classmethod
    def random(cls, nodes, n_windows: int, seed: int = 0,
               crash_rate: float = 0.08, recover_windows=(2, 5),
               flaky_rate: float = 0.04,
               flaky_prob: float = 0.5,
               degrade_rate: float = 0.0,
               degrade_factor: float = 0.25,
               corrupt_rate: float = 0.0,
               corrupt_frac: float = 0.05) -> "FaultSchedule":
        """Seeded random schedule for chaos smoke runs.

        Per window each UP node crashes with ``crash_rate`` (recovering a
        uniform ``recover_windows`` span later) and each up node turns
        flaky for one window with ``flaky_rate`` or — when
        ``degrade_rate`` > 0 — into a one-window straggler with
        ``degrade_rate``, or — when ``corrupt_rate`` > 0 — silently rots
        a seeded ``corrupt_frac`` fraction of its copies with
        ``corrupt_rate``.  The generator never downs the last remaining
        up node, so the workload always has at least one replica target.
        Deterministic in (nodes, n_windows, seed); ``degrade_rate=0`` and
        ``corrupt_rate=0`` (the defaults) draw no extra rolls, so
        pre-existing (nodes, n_windows, seed) schedules are unchanged.
        """
        rng = np.random.default_rng(seed)
        nodes = tuple(nodes)
        up = {n: True for n in nodes}
        pending_recover: dict[str, int] = {}
        events: list[FaultEvent] = []
        for w in range(int(n_windows)):
            for n, rw in list(pending_recover.items()):
                if rw == w:
                    events.append(FaultEvent(w, "recover", n))
                    up[n] = True
                    del pending_recover[n]
            for n in nodes:  # fixed iteration order: determinism
                if not up[n]:
                    continue
                if rng.random() < crash_rate and sum(up.values()) > 1:
                    span = int(rng.integers(recover_windows[0],
                                            recover_windows[1] + 1))
                    events.append(FaultEvent(w, "crash", n))
                    up[n] = False
                    pending_recover[n] = w + span
                elif rng.random() < flaky_rate:
                    events += [FaultEvent(w, "flaky", n,
                                          fail_prob=flaky_prob),
                               FaultEvent(w + 1, "unflaky", n)]
                elif degrade_rate and rng.random() < degrade_rate:
                    events += [FaultEvent(w, "degrade", n,
                                          factor=degrade_factor),
                               FaultEvent(w + 1, "restore", n)]
                elif corrupt_rate and rng.random() < corrupt_rate:
                    events.append(FaultEvent(w, "corrupt", n,
                                             fail_prob=corrupt_frac))
        # Flush recoveries scheduled past the horizon: a node crashed near
        # the end must still heal if the replayed log runs longer than
        # ``n_windows``.
        for n, rw in sorted(pending_recover.items()):
            events.append(FaultEvent(rw, "recover", n))
        return cls(events)

    # -- serialization (the ``cdrs chaos --schedule`` JSON contract) --------
    def to_json(self) -> list[dict]:
        return [{"window": e.window, "kind": e.kind, "node": e.node,
                 **({"fail_prob": e.fail_prob}
                    if e.kind in ("flaky", "corrupt") else {}),
                 **({"factor": e.factor} if e.kind == "degrade" else {}),
                 **({"file": e.file}
                    if e.kind == "corrupt" and e.file >= 0 else {})}
                for e in self.events]

    @classmethod
    def from_json(cls, rows) -> "FaultSchedule":
        return cls([FaultEvent(int(r["window"]), r["kind"], r["node"],
                               fail_prob=float(r.get("fail_prob", 0.0)),
                               factor=float(r.get("factor", 1.0)),
                               file=int(r.get("file", -1)))
                    for r in rows])

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"FaultSchedule({[e.spec() for e in self.events]})"
