"""Seeded, deterministic fault schedules keyed to controller windows.

The paper's category -> replication-factor mapping (Hot=3, Shared=2,
Moderate=1, Archival=4) exists to survive datanode failures, yet nothing in
the batch pipeline or the online controller ever loses a node.  A
``FaultSchedule`` is the missing input: an ordered list of infrastructure
events — crash, recover, decommission, flaky — each pinned to a *window
index* of the controller's time grid (control/windows.py), so the same
schedule replayed over the same log produces the same failure trajectory,
and a kill/resume of the controller mid-fault is bit-identical by
construction (the schedule is config, not state; the *consequences* live in
``ClusterState`` and ride the checkpoint).

Event kinds (HDFS namenode vocabulary, Shvachko et al. MSST 2010):

* ``crash``        — node down; its replicas become unavailable but are NOT
                     destroyed (the disk survives a process crash).
* ``recover``      — a crashed node returns with its replicas intact.
* ``decommission`` — node permanently removed; its replicas are destroyed.
* ``flaky``        — node stays up but repair copies targeting it fail with
                     the given probability (seeded, stateless rolls —
                     faults/repair.py), modelling a slow/half-broken node.
* ``unflaky``      — clears the flaky probability.

Schedules come from three places: explicit specs (``crash:dn2@3``,
``crash:dn2@3-7`` = crash at 3 / recover at 8, ``flaky:dn1@2-6:0.5``),
JSON round-trip (the ``cdrs chaos --schedule`` contract), or the seeded
``random`` generator (chaos smoke tests), which never downs the last
remaining node.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

__all__ = ["FaultEvent", "FaultSchedule"]

#: Within one window, events apply in this order (recover before crash so a
#: same-window recover+crash of two nodes is order-independent by kind).
KINDS: tuple[str, ...] = ("recover", "unflaky", "crash", "flaky",
                          "decommission")
_KIND_ORDER = {k: i for i, k in enumerate(KINDS)}


@dataclass(frozen=True)
class FaultEvent:
    """One infrastructure event at a window boundary."""

    window: int
    kind: str       # one of KINDS
    node: str       # topology node name
    #: ``flaky`` only: probability a repair copy targeting the node fails.
    fail_prob: float = 0.0

    def __post_init__(self):
        if self.kind not in _KIND_ORDER:
            raise ValueError(
                f"unknown fault kind {self.kind!r} (want one of {KINDS})")
        if self.window < 0:
            raise ValueError(f"fault window must be >= 0, got {self.window}")
        if not 0.0 <= self.fail_prob <= 1.0:
            raise ValueError(
                f"fail_prob must be in [0, 1], got {self.fail_prob}")

    def spec(self) -> str:
        s = f"{self.kind}:{self.node}@{self.window}"
        if self.kind == "flaky":
            s += f":{self.fail_prob:g}"
        return s


class FaultSchedule:
    """Immutable, window-sorted event list (see module docstring)."""

    def __init__(self, events=()):
        evs = tuple(sorted(events,
                           key=lambda e: (e.window, _KIND_ORDER[e.kind],
                                          e.node)))
        self.events: tuple[FaultEvent, ...] = evs

    def __len__(self) -> int:
        return len(self.events)

    def __iter__(self):
        return iter(self.events)

    def for_window(self, w: int) -> tuple[FaultEvent, ...]:
        return tuple(e for e in self.events if e.window == int(w))

    @property
    def max_window(self) -> int:
        return max((e.window for e in self.events), default=-1)

    def nodes(self) -> tuple[str, ...]:
        return tuple(sorted({e.node for e in self.events}))

    def validate_nodes(self, topology_nodes) -> None:
        unknown = sorted(set(self.nodes()) - set(topology_nodes))
        if unknown:
            raise ValueError(
                f"fault schedule names nodes outside the topology "
                f"{tuple(topology_nodes)}: {unknown}")

    # -- constructors --------------------------------------------------------
    @classmethod
    def from_specs(cls, specs) -> "FaultSchedule":
        """Parse ``kind:node@window`` specs.

        ``crash:dn2@3-7`` expands to crash at 3 plus recover at 8 (the span
        is inclusive).  ``flaky:dn1@2-6:0.5`` expands to flaky(p=0.5) at 2
        plus unflaky at 7; the probability defaults to 0.5.
        """
        events: list[FaultEvent] = []
        for spec in specs:
            try:
                kind, rest = spec.split(":", 1)
                if kind == "flaky" and rest.count(":") == 1:
                    rest, prob_s = rest.rsplit(":", 1)
                    prob = float(prob_s)
                else:
                    prob = 0.5
                node, span = rest.split("@", 1)
                if "-" in span:
                    lo, hi = (int(s) for s in span.split("-", 1))
                else:
                    lo = hi = int(span)
            except ValueError:
                raise ValueError(
                    f"bad fault spec {spec!r} (want kind:node@window, e.g. "
                    f"'crash:dn2@3', 'crash:dn2@3-7', 'flaky:dn1@2-6:0.5')"
                ) from None
            if "-" in span:
                if hi < lo:
                    raise ValueError(
                        f"bad fault span in {spec!r}: {hi} < {lo}")
                if kind == "crash":
                    events += [FaultEvent(lo, "crash", node),
                               FaultEvent(hi + 1, "recover", node)]
                elif kind == "flaky":
                    events += [FaultEvent(lo, "flaky", node, fail_prob=prob),
                               FaultEvent(hi + 1, "unflaky", node)]
                else:
                    raise ValueError(
                        f"spans are only valid for crash/flaky, not "
                        f"{kind!r} ({spec!r})")
            elif kind == "flaky":
                events.append(FaultEvent(lo, kind, node, fail_prob=prob))
            else:
                events.append(FaultEvent(lo, kind, node))
        return cls(events)

    @classmethod
    def random(cls, nodes, n_windows: int, seed: int = 0,
               crash_rate: float = 0.08, recover_windows=(2, 5),
               flaky_rate: float = 0.04,
               flaky_prob: float = 0.5) -> "FaultSchedule":
        """Seeded random schedule for chaos smoke runs.

        Per window each UP node crashes with ``crash_rate`` (recovering a
        uniform ``recover_windows`` span later) and each up node turns
        flaky for one window with ``flaky_rate``.  The generator never
        downs the last remaining up node, so the workload always has at
        least one replica target.  Deterministic in (nodes, n_windows,
        seed).
        """
        rng = np.random.default_rng(seed)
        nodes = tuple(nodes)
        up = {n: True for n in nodes}
        pending_recover: dict[str, int] = {}
        events: list[FaultEvent] = []
        for w in range(int(n_windows)):
            for n, rw in list(pending_recover.items()):
                if rw == w:
                    events.append(FaultEvent(w, "recover", n))
                    up[n] = True
                    del pending_recover[n]
            for n in nodes:  # fixed iteration order: determinism
                if not up[n]:
                    continue
                if rng.random() < crash_rate and sum(up.values()) > 1:
                    span = int(rng.integers(recover_windows[0],
                                            recover_windows[1] + 1))
                    events.append(FaultEvent(w, "crash", n))
                    up[n] = False
                    pending_recover[n] = w + span
                elif rng.random() < flaky_rate:
                    events += [FaultEvent(w, "flaky", n,
                                          fail_prob=flaky_prob),
                               FaultEvent(w + 1, "unflaky", n)]
        # Flush recoveries scheduled past the horizon: a node crashed near
        # the end must still heal if the replayed log runs longer than
        # ``n_windows``.
        for n, rw in sorted(pending_recover.items()):
            events.append(FaultEvent(rw, "recover", n))
        return cls(events)

    # -- serialization (the ``cdrs chaos --schedule`` JSON contract) --------
    def to_json(self) -> list[dict]:
        return [{"window": e.window, "kind": e.kind, "node": e.node,
                 **({"fail_prob": e.fail_prob} if e.kind == "flaky"
                    else {})}
                for e in self.events]

    @classmethod
    def from_json(cls, rows) -> "FaultSchedule":
        return cls([FaultEvent(int(r["window"]), r["kind"], r["node"],
                               fail_prob=float(r.get("fail_prob", 0.0)))
                    for r in rows])

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"FaultSchedule({[e.spec() for e in self.events]})"
