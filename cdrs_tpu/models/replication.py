"""ReplicationPolicyModel — the flagship end-to-end pipeline.

Mirrors the reference's decision layer (src/main.py:66-144): features CSV (or
in-memory FeatureTable) -> KMeans++ clustering -> per-cluster median scoring ->
category per cluster -> ``final_categories.csv`` with centroid-string IDs
(``CENTROID_<v1>_<v2>_...``, main.py:131-136) — plus the per-file assignment
table the reference only keeps in memory (main.py:92).

Backend selection (``--backend {numpy,jax}``) happens here; both backends share
this orchestration and the IO contracts.
"""

from __future__ import annotations

import csv
from dataclasses import dataclass

import numpy as np

from ..config import CATEGORIES, KMeansConfig, ScoringConfig

__all__ = ["ClusterDecision", "ReplicationPolicyModel", "centroid_id",
           "validate_replication_factors"]


def validate_replication_factors(scoring_cfg: ScoringConfig) -> None:
    """Reject nonsensical replication factors at config time.

    An ``rf < 1`` category would sail through scoring and only explode
    deep in placement (``place_replicas`` clamps silently; the migration
    planner would schedule byte-free "drops" forever).  Raise here, at
    the decision layer's front door, with the offending CATEGORY named —
    the same posture the storage layer applies to EC shapes (``ec(k, m)``
    needs k >= 1, m >= 0; storage/strategy.StorageConfig names the
    category too).  Called by ``ReplicationPolicyModel`` and by
    ``config.scoring_config_from_dict``, so both programmatic and
    JSON-config entry points fail fast."""
    for c in scoring_cfg.categories:
        rf = scoring_cfg.replication_factors.get(c)
        if rf is not None and int(rf) < 1:
            raise ValueError(
                f"replication factor for category {c!r} must be >= 1, "
                f"got {rf} (0 replicas means the file does not exist; "
                f"use an ec/tier strategy for cheap cold storage "
                f"instead)")


def centroid_id(centroid: np.ndarray) -> str:
    """String centroid ID, 4-decimal per component (reference: src/main.py:131-136)."""
    return "CENTROID_" + "_".join(f"{float(v):.4f}" for v in centroid)


@dataclass
class ClusterDecision:
    """Output of one pipeline run."""

    centroids: np.ndarray         # (k, d)
    labels: np.ndarray            # (n,) cluster index per file
    category_idx: np.ndarray      # (k,) index into CATEGORIES
    scores: np.ndarray            # (k, n_categories)
    cluster_medians: np.ndarray   # (k, d)
    feature_names: tuple[str, ...]

    @property
    def categories(self) -> list[str]:
        return [CATEGORIES[int(i)] for i in self.category_idx]

    def replication_factor_per_file(self, cfg: ScoringConfig) -> np.ndarray:
        rf = np.asarray(cfg.rf_vector())
        return rf[self.category_idx[self.labels]]

    def write_csv(self, path: str) -> None:
        """``final_categories.csv``: centroid_id, category, then the feature
        columns (reference: src/main.py:139-142)."""
        with open(path, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["centroid_id", "category", *self.feature_names])
            for j in range(self.centroids.shape[0]):
                w.writerow([
                    centroid_id(self.centroids[j]),
                    CATEGORIES[int(self.category_idx[j])],
                    *[repr(float(v)) for v in self.centroids[j]],
                ])

    def write_assignments_csv(self, path: str, paths: list[str]) -> None:
        """Per-file table: path, cluster, category — the reference computes
        this (main.py:92) but never writes it; we do."""
        with open(path, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["path", "cluster", "category"])
            for i, p in enumerate(paths):
                c = int(self.labels[i])
                w.writerow([p, c, CATEGORIES[int(self.category_idx[c])]])


class ReplicationPolicyModel:
    """KMeans++ clustering + directional-deviation scoring, backend-switchable."""

    def __init__(
        self,
        kmeans_cfg: KMeansConfig | None = None,
        scoring_cfg: ScoringConfig | None = None,
        backend: str = "numpy",
        mesh_shape: dict[str, int] | None = None,
    ):
        self.kmeans_cfg = kmeans_cfg or KMeansConfig()
        self.scoring_cfg = scoring_cfg or ScoringConfig()
        validate_replication_factors(self.scoring_cfg)
        if backend not in ("numpy", "jax"):
            raise ValueError(f"unknown backend {backend!r}; expected 'numpy' or 'jax'")
        self.backend = backend
        self.mesh_shape = mesh_shape

    # -- clustering -------------------------------------------------------
    def cluster(self, X: np.ndarray, init_centroids: np.ndarray | None = None):
        cfg = self.kmeans_cfg
        n = X.shape[0]
        if n < cfg.k:
            raise ValueError(
                f"{n} samples found, but K={cfg.k} requested; cannot cluster"
            )  # reference guard: src/main.py:84-86
        if self.backend == "numpy":
            if cfg.batch_size is not None:
                raise ValueError(
                    "mini-batch KMeans (batch_size) requires the jax backend")
            if cfg.init_method not in ("auto", "d2"):
                # "auto" is the config default and the numpy backend has
                # exactly one init — the reference D² — so it resolves there.
                raise ValueError(
                    f"init_method {cfg.init_method!r} requires the jax backend")
            if cfg.dtype is not None:
                raise ValueError(
                    f"dtype {cfg.dtype!r} requires the jax backend")
            from ..ops.kmeans_np import kmeans

            return kmeans(
                np.asarray(X), cfg.k, number_of_files=n, tol=cfg.tol,
                random_state=cfg.seed, max_iter=cfg.max_iter,
                init_centroids=init_centroids,
            )
        if cfg.batch_size is not None:
            return self._cluster_minibatch(X, init_centroids)
        if cfg.dtype == "float64":
            import jax
            if not jax.config.jax_enable_x64:
                raise ValueError(
                    "dtype='float64' needs JAX_ENABLE_X64=1; without it jax "
                    "silently computes in float32")
        from ..ops.kmeans_jax import kmeans_jax

        centroids, labels = kmeans_jax(
            X, cfg.k, tol=cfg.tol, seed=cfg.seed,
            max_iter=cfg.resolve_max_iter(n),
            init_centroids=init_centroids,
            mesh_shape=self.mesh_shape,
            init_method=cfg.init_method,
            dtype=cfg.dtype,
        )
        return np.asarray(centroids), np.asarray(labels)

    def _cluster_minibatch(self, X: np.ndarray, init_centroids=None):
        """Incremental (Sculley) KMeans over shuffled row batches.

        The BASELINE config-5 capability reached through the same model API:
        ``batch_epochs`` seeded-shuffled passes of ``batch_size`` rows through
        ops/kmeans_stream.MiniBatchKMeans, then a chunked assignment pass.
        Bounded device memory — only one batch is resident per step.
        """
        import jax.numpy as jnp

        from ..ops.kmeans_stream import MiniBatchKMeans, MiniBatchState

        cfg = self.kmeans_cfg
        n = X.shape[0]
        bs = int(cfg.batch_size)
        if bs < 1:
            raise ValueError(f"batch_size must be >= 1, got {bs}")
        if cfg.dtype not in (None, "float32"):
            # Mini-batch state keeps f32 centroids over small resident
            # batches; a low-precision points stream buys nothing there.
            raise ValueError(
                f"dtype {cfg.dtype!r} is a full-batch Lloyd knob; mini-batch "
                f"KMeans (batch_size) always runs float32")
        if bs < cfg.k and init_centroids is None:
            # The first batch seeds the D2 init; fewer valid rows than
            # centroids would silently produce duplicate centroids (the
            # full-batch path raises the same class of error) — ADVICE r2.
            # Warm starts (init_centroids given) never run the init, and
            # small batches are valid updates there.
            raise ValueError(
                f"batch_size={bs} must be >= k={cfg.k} (the first mini-batch "
                f"seeds the centroid init; pass init_centroids to warm-start "
                f"with smaller batches)")
        mb = MiniBatchKMeans(k=cfg.k, seed=cfg.seed, mesh_shape=self.mesh_shape)
        if init_centroids is not None:
            mb.state = MiniBatchState(
                centroids=jnp.asarray(np.asarray(init_centroids, np.float32)),
                counts=jnp.zeros((cfg.k,), np.int32),
            )
        import jax

        is_dev = isinstance(X, jax.Array)
        rng = np.random.default_rng(cfg.seed)
        for _ in range(max(1, int(cfg.batch_epochs))):
            order = rng.permutation(n)
            for lo in range(0, n, bs):
                idx = order[lo:lo + bs]
                # Device inputs batch via on-device gather — no host round trip.
                mb.partial_fit(X[idx] if is_dev
                               else np.asarray(X[idx], np.float32))
        labels = np.empty(n, dtype=np.int32)
        for lo in range(0, n, bs):
            labels[lo:lo + bs] = mb.predict(X[lo:lo + bs])
        return mb.centroids, labels

    # -- scoring ----------------------------------------------------------
    def score(self, X, labels) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        if self.backend == "numpy":
            from ..ops.scoring_np import classify

            return classify(np.asarray(X), labels, self.kmeans_cfg.k,
                            self.scoring_cfg)
        from ..ops.scoring_jax import classify_jax

        # The model's mesh shards the median stage too (VERDICT r2 #5): at
        # the scales that need a mesh, X only exists sharded.
        winner, scores, medians = classify_jax(
            X, labels, self.kmeans_cfg.k, self.scoring_cfg,
            mesh_shape=self.mesh_shape)
        return np.asarray(winner), np.asarray(scores), np.asarray(medians)

    # -- end to end -------------------------------------------------------
    def run(
        self,
        X,
        feature_names: tuple[str, ...] | None = None,
        init_centroids: np.ndarray | None = None,
    ) -> ClusterDecision:
        """``X`` may be a host ndarray or a device array (jax backend):
        device inputs flow through clustering + scoring without a host
        round trip — only the k-sized decision tables and the final labels
        come back to host."""
        centroids, labels = self.cluster(X, init_centroids=init_centroids)
        winner, scores, medians = self.score(X, labels)
        return ClusterDecision(
            centroids=np.asarray(centroids),
            labels=np.asarray(labels),
            category_idx=np.asarray(winner),
            scores=np.asarray(scores),
            cluster_medians=np.asarray(medians),
            feature_names=tuple(feature_names or self.scoring_cfg.features),
        )
