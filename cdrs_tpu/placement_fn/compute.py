"""CRUSH-style functional placement: recompute, don't store.

The materialized chooser (cluster/placement.place_replicas) draws one
``(n_files, n_nodes)`` rng priority matrix per placement — correct, but a
function of the WHOLE population: row i cannot be recomputed without
generating rows 0..i-1, so every consumer (router, repair, durability,
checkpoints) must drag the materialized map around.  Ceph's CRUSH (Weil
et al., PAPERS.md) shows the alternative this module implements: the
priority of node j for file f is a **pure stateless hash** of
``(seed, file id, node name)`` — CRUSH's straw2 draw — so any subset of
rows recomputes vectorized in O(subset) with NO per-file state, any
process computes the same placement, and — because node salts are keyed
by node *identity*, not index — a topology change moves only the files
whose computed slots actually involve the changed nodes (the epoch-diff
contract, placement_fn/epoch.py; a mod-N scheme would remap everyone).

The structural policy is exactly the repo's rack-aware chooser: replica 0
on the file's primary node; with failure domains, replica 1 on the
best-priority node OUTSIDE the primary's domain and replica 2 on that
same remote domain's second-best node (HDFS rack-aware: off-rack, then
same remote rack); every further replica on distinct nodes in ascending
priority order.  On a flat topology the domain machinery vanishes and the
chooser degenerates bit-for-bit to the plain distinct-node priority
policy (property-tested against an independent argsort reference in
tests/test_placement_fn.py).

Only the PRIORITY SOURCE differs from the legacy chooser (hash vs rng
matrix), which is why the legacy rng path cannot be recomputed
functionally and stays the default; ``place_replicas(method="hash")``
materializes THIS chooser's output (one implementation, two surfaces —
the equivalence oracle of the functional mode).

Performance shape (the >= 50M placements/s CPU target of
benchmarks/placement_bench.py, hit on one core):

* priorities live in a **transposed (n_nodes, m) uint32 block** — each
  node's vector is contiguous, so the 4-op finishing mix streams at
  memory bandwidth instead of striding a row-major layout;
* each value packs the node id into its LOW 6 bits under a 26-bit
  priority, so taking a slot is one ``np.minimum.reduce`` over the node
  axis — the winner's identity rides the minimum, no argmin pass, and
  within a row values are all distinct (the node bits), so selection is
  tie-free and deterministic by construction;
* files process in L2-sized chunks (``chunk``, default 128k) with the
  priority block reused across chunks — the difference between 13M and
  20M files/s on one core.

The 6 node bits cap a topology at 63 nodes (node id 63 is reserved so
the all-ones sentinel can never collide with a live candidate); wider
clusters belong to the hierarchical-topology ROADMAP item.
"""

from __future__ import annotations

import hashlib

import numpy as np

__all__ = ["node_salts", "file_keys", "hash_priorities",
           "compute_placement", "explain_placement", "primary_on_topology",
           "hierarchical_fill", "clip_shards_for_locality",
           "PRIO_MAX", "NODE_MASK", "MAX_NODES"]

_SPLITMIX_GAMMA = np.uint64(0x9E3779B97F4A7C15)
_MIX_1 = np.uint64(0xBF58476D1CE4E5B9)
_MIX_2 = np.uint64(0x94D049BB133111EB)
_C1 = np.uint32(0xCC9E2D51)   # murmur3 mixing constants
_C2 = np.uint32(0x1B873593)
_M32 = np.uint32(0x85EBCA6B)
#: Low-bits node-id channel of a packed priority.
NODE_MASK = np.uint32(0x3F)
_PRIO_BITS_MASK = np.uint32(0xFFFFFFC0)
#: Sentinel "already taken / masked" priority: all-ones.  Node id 63 is
#: reserved (MAX_NODES = 63), so no live candidate can equal it.
PRIO_MAX = np.uint32(0xFFFFFFFF)
MAX_NODES = 63


def _splitmix64(z: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer over uint64 (wrapping by design)."""
    z = z + _SPLITMIX_GAMMA
    z = (z ^ (z >> np.uint64(30))) * _MIX_1
    z = (z ^ (z >> np.uint64(27))) * _MIX_2
    return z ^ (z >> np.uint64(31))


def node_salts(nodes, seed: int = 0) -> np.ndarray:
    """(n_nodes,) uint32 per-node salt keyed by node IDENTITY.

    blake2b of the node *name* (not its index), mixed with the seed: two
    topologies sharing a node name give it the same salt, so priorities —
    and therefore placements — of files that never touch the changed
    nodes are identical across epochs (the CRUSH stability property the
    epoch diff relies on).  Process- and platform-stable by construction
    (Python's salted ``hash`` is neither).
    """
    if len(nodes) > MAX_NODES:
        raise ValueError(
            f"functional placement supports up to {MAX_NODES} nodes "
            f"(6-bit packed node ids), got {len(nodes)}")
    seed_key = _splitmix64(np.asarray([np.uint64(seed & 0xFFFFFFFFFFFFFFFF)],
                                      dtype=np.uint64))[0]
    out = np.empty(len(nodes), dtype=np.uint64)
    for i, name in enumerate(nodes):
        h = hashlib.blake2b(str(name).encode(), digest_size=8).digest()
        out[i] = np.uint64(int.from_bytes(h, "little"))
    mixed = _splitmix64(out ^ seed_key)
    return (mixed ^ (mixed >> np.uint64(32))).astype(np.uint32)


def file_keys(file_ids: np.ndarray, seed: int = 0) -> np.ndarray:
    """(m,) uint32 well-mixed per-file keys (murmur3-style double round).

    File ids hash through their low 32 bits — populations are capped at
    4B files per controller, far past the 100M the bench drives.
    """
    x = np.asarray(file_ids).astype(np.uint32)
    x = x ^ np.uint32((seed * 2654435761) & 0xFFFFFFFF)
    x = x * _C1
    x = x ^ (x >> np.uint32(16))
    x = x * _C2
    return x ^ (x >> np.uint32(16))


def hash_priorities(keys: np.ndarray, salts: np.ndarray,
                    out: np.ndarray | None = None) -> np.ndarray:
    """(n_nodes, m) uint32 PACKED priorities, transposed layout.

    Each value is ``(hash26 << 6) | node_id`` — lower is better, the
    minimum over the node axis carries its winner's identity, and values
    within a file's column are all distinct (the node bits), so
    comparisons can never tie.  4 contiguous vector ops per node row —
    the throughput-critical inner loop of the whole functional engine.
    """
    m = keys.shape[0]
    n = salts.shape[0]
    if out is None:
        out = np.empty((n, m), dtype=np.uint32)
    for j in range(n):
        row = out[j]
        np.bitwise_xor(keys, salts[j], out=row)
        np.multiply(row, _M32, out=row)
        np.bitwise_and(row, _PRIO_BITS_MASK, out=row)
        np.bitwise_or(row, np.uint32(j), out=row)
    return out


def primary_on_topology(node_vocab, primary_node_id: np.ndarray,
                        topology) -> np.ndarray:
    """Remap manifest primary ids onto a topology via a per-NAME LUT.

    The shared resolution (historically inlined in ``place_replicas``):
    O(vocabulary), not O(files); names absent from the topology spread
    over it via a stable crc32 hash (Python's salted str hash would break
    run-to-run determinism).
    """
    import zlib

    n_nodes = len(topology.nodes)
    node_by_name = {nm: i for i, nm in enumerate(topology.nodes)}
    lut = np.asarray([
        node_by_name.get(nm, zlib.crc32(str(nm).encode()) % n_nodes)
        for nm in node_vocab
    ], dtype=np.int32)
    return lut[np.asarray(primary_node_id)]


_U64_MAX = np.uint64(0xFFFFFFFFFFFFFFFF)


def clip_shards_for_locality(n_shards: np.ndarray, primary: np.ndarray,
                             topology,
                             local_mask: np.ndarray | None) -> np.ndarray:
    """Effective shard counts under a region-locality mask: a file pinned
    to its primary's top-level domain can hold at most that domain's node
    count (the region-local analogue of the global distinct-node cap).
    Returns ``n_shards`` untouched when no mask applies — shared by the
    choosers and by callers that need the cap without placing."""
    if local_mask is None or topology.n_levels == 0:
        return n_shards
    local = np.asarray(local_mask, dtype=bool)
    if not local.any():
        return n_shards
    top = topology.top_domain_index()
    region_size = np.bincount(top,
                              minlength=topology.n_domains_at(
                                  topology.n_levels))
    cap = region_size[top[np.asarray(primary, dtype=np.int64)]]
    return np.where(local, np.minimum(n_shards, cap),
                    n_shards).astype(np.int32)


def hierarchical_fill(w: np.ndarray, out: np.ndarray, prim: np.ndarray,
                      max_rf: int, topology) -> None:
    """Greedy highest-level-first fill of one chunk's slots 1..max_rf-1.

    ``w`` is the (n_nodes, m) PACKED priority block (mutated: chosen and
    excluded candidates are masked to PRIO_MAX); slot 0 (the primary)
    must already be written to ``out`` and masked in ``w``.  Each
    subsequent slot takes the node minimizing the lexicographic key
    ``(replicas already in its TOP-level domain, replicas already in its
    base domain, packed priority)`` — CRUSH's descend-and-spread shape:
    top-level (region) counts differ by at most one across the row, so a
    whole-region loss can only take ``ceil(rf / n_regions)`` copies of
    anything, and within a region copies spread racks first.
    Region-local files (EC stripes pinned to the primary's region) have
    their off-region candidates pre-masked by the caller: the same key
    then spreads racks within the region (the only region with copies).
    Deterministic and tie-free (the packed node-id bits),
    nested in rf (slot c depends only on slots < c), and subset-safe
    (per-file state only) — the same contracts the flat chooser makes.

    Used by BOTH choosers: ``compute_placement`` feeds it hash-packed
    priorities, the legacy rng chooser feeds it rng-packed ones — one
    structural policy, two priority sources.
    """
    n_nodes, m = w.shape
    cols = np.arange(m)
    dom_base = topology.domain_index()
    dom_top = topology.top_domain_index()
    n_base = topology.n_domains
    n_top = topology.n_domains_at(topology.n_levels)
    base_rows = [np.flatnonzero(dom_base == d) for d in range(n_base)]
    #: Each base domain's top-level domain (nesting is validated).
    top_of_base = np.asarray([int(dom_top[rows[0]])
                              for rows in base_rows], dtype=np.int64)
    base_cnt = np.zeros((n_base, m), dtype=np.uint16)
    top_cnt = np.zeros((n_top, m), dtype=np.uint16)
    base_cnt[dom_base[prim], cols] = 1
    top_cnt[dom_top[prim], cols] = 1
    dvb = np.empty((n_base, m), dtype=np.uint32)
    comp = np.empty((n_base, m), dtype=np.uint64)
    span = np.uint64(n_nodes + 2)
    for c in range(1, max_rf):
        for d, rows in enumerate(base_rows):
            np.copyto(dvb[d], w[rows[0]])
            for r in rows[1:]:
                np.minimum(dvb[d], w[r], out=dvb[d])
        # Composite key: (top count * span + base count) in the high 32
        # bits, the packed priority (node id in the low 6) below — the
        # min over base domains picks the least-covered region, then the
        # least-covered rack, then the best node, and its identity rides
        # the minimum.  Exhausted domains are forced to the ceiling.
        np.multiply(top_cnt[top_of_base].astype(np.uint64), span,
                    out=comp)
        comp += base_cnt
        comp <<= np.uint64(32)
        comp |= dvb
        comp[dvb == PRIO_MAX] = _U64_MAX
        best = comp.min(axis=0)
        valid = best != _U64_MAX
        sel = (best.astype(np.uint32) & NODE_MASK).astype(np.int32)
        # Exhausted rows (rf past the candidate pool — only reachable
        # when a locality clip or mixed rf leaves the slot unused) must
        # not index with the sentinel id.
        np.copyto(sel, np.int32(0), where=~valid)
        out[:, c] = np.where(valid, sel, -1)
        w[sel, cols] = np.where(valid, PRIO_MAX, w[sel, cols])
        np.add.at(base_cnt, (dom_base[sel], cols),
                  valid.astype(np.uint16))
        np.add.at(top_cnt, (dom_top[sel], cols),
                  valid.astype(np.uint16))


def compute_placement(
    file_ids: np.ndarray,
    n_shards: np.ndarray,
    primary: np.ndarray,
    topology,
    seed: int = 0,
    *,
    salts: np.ndarray | None = None,
    out_width: int | None = None,
    chunk: int = 1 << 17,
    local_mask: np.ndarray | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Functional placement of an arbitrary file-id subset.

    Returns ``(slots, rf)``: ``slots`` is (m, width) int32 node ids with
    -1 padding past each row's effective rf, ``rf`` is (m,) int32 =
    ``clip(n_shards, 1, n_nodes)`` (the placement cap — distinct nodes
    per replica, HDFS behaviour).  ``primary`` must already be resolved
    onto ``topology`` (:func:`primary_on_topology`).

    Row i depends ONLY on ``(seed, file_ids[i], n_shards[i],
    primary[i], topology)`` — computing a subset yields exactly the
    matching rows of the full-population computation, and the slot
    sequence is NESTED in rf: ``slots(rf=4)[:3] == slots(rf=3)`` for the
    same file (growing a file's rf only appends nodes; shrinking only
    drops the tail) — the property the functional ClusterState's
    exception accounting leans on.
    """
    fids = np.asarray(file_ids)
    m_total = fids.shape[0]
    n_nodes = len(topology)
    if salts is None:
        salts = node_salts(topology.nodes, seed)
    rf = np.asarray(n_shards)
    if rf.dtype != np.int32:
        rf = rf.astype(np.int32)
    rf = np.clip(rf, 1, n_nodes)
    if rf.shape == ():  # scalar broadcast
        rf = np.full(m_total, int(rf), dtype=np.int32)
    hier = getattr(topology, "n_levels", 0) > 0
    if hier and m_total:
        rf = clip_shards_for_locality(rf, primary, topology, local_mask)
    max_rf = int(rf.max()) if m_total else 1
    width = max_rf if out_width is None else int(out_width)
    # np.empty, not np.full: every cell in [:, :max_rf] is written below
    # (selection + rf padding), and the extra out_width columns get one
    # explicit fill per chunk — at 10M+ files the avoided 2D -1 fill is
    # a measurable slice of the whole computation.
    slots = np.empty((m_total, width), dtype=np.int32)
    if m_total == 0:
        return slots, rf

    primary_all = np.asarray(primary, dtype=np.int32)
    dom = topology.domain_index()
    n_domains = topology.n_domains
    # All-singleton domains (the flat topology) degenerate exactly to
    # the generic ascending-priority fill: "best node of a remote
    # singleton domain" IS the best non-primary node, and a singleton
    # remote domain has no second member — so skip the domain machinery
    # wholesale (bit-identical, property-tested).
    multi_domain = (not hier and 1 < n_domains < n_nodes
                    and max_rf >= 2)
    dom_top = topology.top_domain_index() if hier else None
    local_all = None
    if hier and local_mask is not None:
        local_all = np.asarray(local_mask, dtype=bool)
    uniform_rf = bool((rf == max_rf).all())
    chunk = max(int(chunk), 1)
    buf = min(chunk, m_total)
    work = np.empty((n_nodes, buf), dtype=np.uint32)
    dmin = dom_rows = None
    if multi_domain:
        # Per-domain row groups: the domain rules become grouped
        # minimums over contiguous node rows instead of masked copies of
        # the whole priority block (the masked np.where construction
        # costs more than every reduction combined at 10M+ files).
        dom_rows = [np.flatnonzero(dom == d) for d in range(n_domains)]
        dmin = np.empty((n_domains, buf), dtype=np.uint32)

    def _grouped_min(w, m):
        """dmin[d] = min over domain d's rows of ``w`` (value carries the
        winning node's packed id) — pairwise row mins, no copies."""
        dv = dmin[:, :m]
        for d, rows in enumerate(dom_rows):
            np.copyto(dv[d], w[rows[0]])
            for r in rows[1:]:
                np.minimum(dv[d], w[r], out=dv[d])
        return dv

    all_cols = np.arange(buf)

    for lo in range(0, m_total, chunk):
        hi = min(lo + chunk, m_total)
        m = hi - lo
        w = work[:, :m]
        hash_priorities(file_keys(fids[lo:hi], seed), salts, out=w)
        prim = primary_all[lo:hi]
        cols = all_cols[:m]
        out = slots[lo:hi]

        out[:, 0] = prim
        w[prim, cols] = PRIO_MAX

        if hier:
            # Geo-hierarchical policy: region-local files lose every
            # off-region candidate up front, then the greedy
            # highest-level-first fill places the remaining slots (one
            # policy for both choosers — hierarchical_fill).
            if local_all is not None:
                lc = local_all[lo:hi]
                if lc.any():
                    offr = dom_top[:, None] != dom_top[prim][None, :]
                    w[offr & lc[None, :]] = PRIO_MAX
            if max_rf >= 2:
                hierarchical_fill(w, out, prim, max_rf, topology)
            start_col = max_rf
        else:
            start_col = 1
        if multi_domain:
            # Replica 1: best-priority node OUTSIDE the primary's
            # domain; replica 2: that same remote domain's second-best
            # (HDFS rack-aware).  Guarded per file — a file whose every
            # other node shares the primary's domain (or whose remote
            # domain has one member) falls through to the generic fill.
            # Each step is a grouped per-domain minimum (identical
            # values to the masked construction — min is associative).
            dp = dom[prim]
            dv = _grouped_min(w, m)
            best = dv.copy()
            best[dp, cols] = PRIO_MAX        # exclude the primary's domain
            mn1 = np.minimum.reduce(best, axis=0)
            has1 = mn1 != PRIO_MAX
            sel1 = (mn1 & NODE_MASK).astype(np.int32)
            if not has1.all():
                gen = (np.minimum.reduce(w, axis=0)
                       & NODE_MASK).astype(np.int32)
                sel1 = np.where(has1, sel1, gen)
            out[:, 1] = sel1
            w[sel1, cols] = PRIO_MAX
            start_col = 2
            if max_rf >= 3:
                # Second-best of sel1's domain: regroup after masking
                # sel1, then gather each file's own remote-domain row.
                dv = _grouped_min(w, m)
                mn2 = dv[dom[sel1], cols]
                # A file without a remote domain (has1 false) must not
                # take a same-domain second copy here.
                if not has1.all():
                    mn2 = np.where(has1, mn2, PRIO_MAX)
                has2 = mn2 != PRIO_MAX
                sel2 = (mn2 & NODE_MASK).astype(np.int32)
                if not has2.all():
                    gen = (np.minimum.reduce(w, axis=0)
                           & NODE_MASK).astype(np.int32)
                    sel2 = np.where(has2, sel2, gen)
                out[:, 2] = sel2
                w[sel2, cols] = PRIO_MAX
                start_col = 3

        for c in range(start_col, max_rf):
            mn = np.minimum.reduce(w, axis=0)
            mn &= NODE_MASK
            s = mn.astype(np.int32)
            out[:, c] = s
            if c + 1 < max_rf:      # the last slot needs no re-masking
                w[s, cols] = PRIO_MAX

        if not uniform_rf:
            # Pad past each row's rf while the chunk is cache-hot —
            # masked per-column stores, NOT a 2D boolean fancy-index
            # (which costs more than the whole selection at scale).
            rfc = rf[lo:hi]
            for c in range(1, max_rf):
                np.copyto(out[:, c], np.int32(-1), where=rfc <= c)
        if width > max_rf:
            out[:, max_rf:] = -1

    return slots, rf


def explain_placement(file_id: int, n_shards: int, primary: int,
                      topology, seed: int = 0, *,
                      local: bool = False) -> dict:
    """Per-slot decision trace of ONE file's computed placement.

    The provenance hook behind ``cdrs explain file``: re-derives the
    chooser's slot sequence scalar-by-scalar — every candidate's packed
    hash priority, the domain-count keys the hierarchical greedy
    compares, which rule picked each slot — and then ASSERTS the
    narrated slots equal the matching :func:`compute_placement` row, so
    the narration can never drift from the decision (decision-faithful
    by construction; a mismatch raises instead of explaining fiction).

    Returns ``{"file", "seed", "rf", "local", "slots": [...]}`` where
    each slot entry carries ``slot``/``node``/``node_name``/``rule`` and
    a ``candidates`` list of per-node dicts (``priority`` is the 26-bit
    hash channel; hierarchical slots add the ``(top_count, base_count)``
    key components; masked candidates say why).  ``primary`` must be
    resolved onto ``topology`` (:func:`primary_on_topology`), exactly as
    the vector path requires.
    """
    fid = int(file_id)
    n_nodes = len(topology)
    prim = int(primary)
    salts = node_salts(topology.nodes, seed)
    w = hash_priorities(file_keys(np.asarray([fid]), seed),
                        salts).reshape(n_nodes).copy()
    hier = topology.n_levels > 0
    rf_arr = np.clip(np.asarray([int(n_shards)], dtype=np.int32),
                     1, n_nodes)
    local_mask = np.asarray([bool(local)]) if hier else None
    if hier:
        rf_arr = clip_shards_for_locality(
            rf_arr, np.asarray([prim], dtype=np.int64), topology,
            local_mask)
    rf = int(rf_arr[0])
    dom = topology.domain_index()
    dom_top = topology.top_domain_index() if hier else None
    names = list(topology.nodes)
    base_names = list(topology.domains) if topology.domains else names
    masked_why = {}  # node -> reason it cannot be a candidate anymore

    def cand_rows(extra=None):
        rows = []
        for j in range(n_nodes):
            row = {"node": j, "name": str(names[j]),
                   "domain": str(base_names[j])}
            if w[j] == PRIO_MAX:
                row["masked"] = masked_why.get(j, "taken")
            else:
                row["priority"] = int(w[j] >> np.uint32(6))
            if extra is not None:
                row.update(extra(j))
            rows.append(row)
        return rows

    slots: list[dict] = [{
        "slot": 0, "node": prim, "node_name": str(names[prim]),
        "rule": "primary",
    }]
    w[prim] = PRIO_MAX
    masked_why[prim] = "primary (slot 0)"

    if hier:
        # Geo-hierarchical policy — the scalar mirror of
        # ``hierarchical_fill``: per slot, the candidate minimizing
        # (copies in its TOP-level domain, copies in its base domain,
        # packed priority); region-local files lose every off-region
        # candidate up front.
        if local:
            for j in range(n_nodes):
                if dom_top[j] != dom_top[prim] and w[j] != PRIO_MAX:
                    w[j] = PRIO_MAX
                    masked_why[j] = "off-region (locality pin)"
        base_cnt = np.zeros(topology.n_domains, dtype=np.int64)
        top_cnt = np.zeros(topology.n_domains_at(topology.n_levels),
                           dtype=np.int64)
        base_cnt[dom[prim]] += 1
        top_cnt[dom_top[prim]] += 1
        for c in range(1, rf):
            def key_of(j):
                return {"top_count": int(top_cnt[dom_top[j]]),
                        "base_count": int(base_cnt[dom[j]])}
            cands = cand_rows(key_of)
            live = [j for j in range(n_nodes) if w[j] != PRIO_MAX]
            if not live:
                slots.append({"slot": c, "node": -1, "node_name": None,
                              "rule": "exhausted", "candidates": cands})
                continue
            # Tie-free: the packed node-id bits make priorities distinct.
            sel = min(live, key=lambda j: (int(top_cnt[dom_top[j]]),
                                           int(base_cnt[dom[j]]),
                                           int(w[j])))
            slots.append({"slot": c, "node": int(sel),
                          "node_name": str(names[sel]),
                          "rule": "hierarchical_fill "
                                  "(least-covered region, then rack, "
                                  "then best priority)",
                          "key": {"top_count": int(top_cnt[dom_top[sel]]),
                                  "base_count": int(base_cnt[dom[sel]]),
                                  "priority": int(w[sel]
                                                  >> np.uint32(6))},
                          "candidates": cands})
            w[sel] = PRIO_MAX
            masked_why[sel] = f"taken (slot {c})"
            base_cnt[dom[sel]] += 1
            top_cnt[dom_top[sel]] += 1
    else:
        n_domains = topology.n_domains
        multi_domain = 1 < n_domains < n_nodes and rf >= 2
        start_col = 1
        if multi_domain:
            # HDFS rack-aware: replica 1 = best-priority node OUTSIDE
            # the primary's domain (fallback: best anywhere), replica 2
            # = that remote domain's second-best (fallback likewise).
            dp = int(dom[prim])
            cands = cand_rows()
            remote = [j for j in range(n_nodes)
                      if w[j] != PRIO_MAX and dom[j] != dp]
            if remote:
                sel1 = min(remote, key=lambda j: int(w[j]))
                rule1 = "best node of a remote domain (off-rack)"
                has1 = True
            else:
                live = [j for j in range(n_nodes) if w[j] != PRIO_MAX]
                sel1 = min(live, key=lambda j: int(w[j]))
                rule1 = "best remaining node (no remote domain)"
                has1 = False
            slots.append({"slot": 1, "node": int(sel1),
                          "node_name": str(names[sel1]), "rule": rule1,
                          "candidates": cands})
            w[sel1] = PRIO_MAX
            masked_why[sel1] = "taken (slot 1)"
            start_col = 2
            if rf >= 3:
                cands = cand_rows()
                second = [j for j in range(n_nodes)
                          if w[j] != PRIO_MAX and has1
                          and dom[j] == dom[sel1]]
                if second:
                    sel2 = min(second, key=lambda j: int(w[j]))
                    rule2 = "second-best node of the remote domain"
                else:
                    live = [j for j in range(n_nodes)
                            if w[j] != PRIO_MAX]
                    sel2 = min(live, key=lambda j: int(w[j]))
                    rule2 = ("best remaining node (remote domain has "
                             "no second member)")
                slots.append({"slot": 2, "node": int(sel2),
                              "node_name": str(names[sel2]),
                              "rule": rule2, "candidates": cands})
                w[sel2] = PRIO_MAX
                masked_why[sel2] = "taken (slot 2)"
                start_col = 3
        for c in range(start_col, rf):
            cands = cand_rows()
            live = [j for j in range(n_nodes) if w[j] != PRIO_MAX]
            sel = min(live, key=lambda j: int(w[j]))
            slots.append({"slot": c, "node": int(sel),
                          "node_name": str(names[sel]),
                          "rule": "ascending hash priority",
                          "candidates": cands})
            w[sel] = PRIO_MAX
            masked_why[sel] = f"taken (slot {c})"

    # The faithfulness guard: the narration above must reproduce the
    # vector chooser exactly or the explanation is fiction.
    truth, truth_rf = compute_placement(
        np.asarray([fid], dtype=np.int64),
        np.asarray([int(n_shards)], dtype=np.int32),
        np.asarray([prim], dtype=np.int64), topology, seed,
        local_mask=local_mask)
    told = [s["node"] for s in slots]
    want = [int(x) for x in truth[0, :int(truth_rf[0])]]
    if told != want or rf != int(truth_rf[0]):
        raise RuntimeError(
            f"explain_placement narration diverged from "
            f"compute_placement for file {fid}: narrated {told}, "
            f"computed {want} — report this; the trace above is not "
            f"trustworthy")
    return {"file": fid, "seed": int(seed), "rf": rf,
            "local": bool(local), "slots": slots}
