"""Functional ClusterState backend: exceptions over a computed base.

``faults.ClusterState`` is the mutable source of truth of the fault path;
its checkpoint is the ``(n_files, n_nodes)`` replica map plus a parallel
corruption mask — the npz term that scales with file count (ROADMAP
item 3).  This backend keeps the WHOLE mutation machinery (and therefore
every repair/durability/serving decision) bit-identical while changing
what a checkpoint *is*: placement state is serialized as

* the functional base — ``(seed, epoch)`` plus the per-file shard intent,
  re-derivable from vectors the controller checkpoint already carries;
* per-file **exceptions** — the rows whose current placement differs from
  the computed base (repair retargets onto live nodes, quarantine drops,
  deferred strategy conversions, decommission wipes);
* sparse corruption ``(file, slot)`` pairs and sparse strategy overrides.

In memory the dense map stays resident as a CACHE of computed-base +
exceptions (the mutation primitives, blast-radius refreshes and
vectorized durability tiers all index it; shrinking the resident cache
is the noted follow-up) — the O(exceptions) wins land on the checkpoint,
the epoch-diff planner and the serve router, which is where the
materialized representation actually bottlenecked.

Two representation modes share this class: ``sparse_checkpoint=True`` is
the functional mode; ``False`` keeps the dense npz contract and serves
as the **materialized equivalence oracle** (the PR-8 compat pattern —
same chooser, same retarget policy, dense serialization), so a
functional run resumed mid-fault must reproduce the oracle's records
bit-for-bit.

The one behavioural difference from the legacy ``ClusterState`` policy
(shared by BOTH modes of the hash family, which is what keeps them
decision-identical) is the **base-form retarget**: an rf change applied
to a file whose row is in base form on a fully reachable node set moves
along the computed slot order — the nested-in-rf property of
``compute_placement`` means growth appends computed nodes and shrink
drops the computed tail, so steady-state migrations never create
exceptions.  Any fault in the way (unreachable target or holder, prior
exception) falls back to the legacy stateful path, and the file becomes
an exception until topology health lets a later retarget reconverge it.
"""

from __future__ import annotations

import numpy as np

from ..faults.state import ClusterState
from .compute import compute_placement, node_salts

__all__ = ["FunctionalClusterState", "OverlayClusterState"]


class FunctionalClusterState(ClusterState):
    """ClusterState whose placement state round-trips as exceptions."""

    def __init__(self, placement, size_bytes, *, primary: np.ndarray,
                 seed: int = 0, epoch: int = 0,
                 sparse_checkpoint: bool = True):
        super().__init__(placement, size_bytes)
        self._fn_primary = np.asarray(primary, dtype=np.int32)
        if self._fn_primary.shape[0] != self.replica_map.shape[0]:
            raise ValueError(
                f"primary shape {self._fn_primary.shape} != "
                f"({self.replica_map.shape[0]},)")
        self._fn_seed = int(seed)
        self._fn_epoch = int(epoch)
        self._fn_sparse = bool(sparse_checkpoint)
        self._fn_salts = node_salts(self.topology.nodes, self._fn_seed)
        #: Files whose row MAY deviate from base since the last verify
        #: (every mutated fid lands here) — ``exception_fids`` classifies
        #: them into ``_fn_exceptions`` and clears the set, so the
        #: stamp/checkpoint cost is O(mutations since last verify) plus a
        #: cached read of the standing exceptions, not O(files) and not
        #: O(standing exceptions) per window.
        self._fn_touched: set[int] = set()
        #: VERIFIED standing exceptions (row != computed base).
        self._fn_exceptions: set[int] = set()
        #: Sorted-array cache of ``_fn_exceptions``; invalidated when the
        #: classification changes.
        self._fn_exc_array: np.ndarray | None = None

    # -- base placement ------------------------------------------------------
    def _fn_base_rows(self, fids: np.ndarray) -> np.ndarray:
        """(k, n_nodes) computed-base rows (padded to map width) for a
        file subset — the pure recompute every consumer shares.  The
        base is a function of (seed, epoch topology, installed shards,
        primary, region-locality flag): region-local files compute with
        their off-region candidates masked."""
        fids = np.asarray(fids, dtype=np.int64)
        local = None
        if getattr(self.topology, "n_levels", 0) > 0 \
                and self.region_local.any():
            local = self.region_local[fids]
        slots, _ = compute_placement(
            fids, self.installed_shards[fids], self._fn_primary[fids],
            self.topology, self._fn_seed, salts=self._fn_salts,
            out_width=len(self.nodes), local_mask=local)
        return slots

    def exception_fids(self, verify_chunk: int = 1 << 18) -> np.ndarray:
        """Sorted int64 fids whose row differs from the computed base —
        EXACT.  Only fids mutated since the last call are re-verified
        against a fresh base recompute (a row repaired back into base
        form stops being an exception); the standing set is returned
        from a cache, so a mass fault's exceptions are classified once,
        not re-hashed every window.  Callers must treat the returned
        array as read-only."""
        if self._fn_touched:
            cand = np.fromiter(self._fn_touched, dtype=np.int64,
                               count=len(self._fn_touched))
            cand.sort()
            self._fn_exceptions.difference_update(self._fn_touched)
            self._fn_touched.clear()
            for lo in range(0, cand.size, verify_chunk):
                part = cand[lo:lo + verify_chunk]
                base = self._fn_base_rows(part)
                diff = (self.replica_map[part] != base).any(axis=1)
                self._fn_exceptions.update(
                    int(f) for f in part[diff])
            self._fn_exc_array = None
        if self._fn_exc_array is None:
            arr = np.fromiter(self._fn_exceptions, dtype=np.int64,
                              count=len(self._fn_exceptions))
            arr.sort()
            self._fn_exc_array = arr
        return self._fn_exc_array

    # -- mutation tracking ---------------------------------------------------
    def add_replica(self, fid: int, node: int) -> None:
        self._fn_touched.add(int(fid))
        super().add_replica(fid, node)

    def drop_replica(self, fid: int, node: int) -> None:
        self._fn_touched.add(int(fid))
        super().drop_replica(fid, node)

    def apply_event(self, ev) -> None:
        if ev.kind == "decommission":
            # Decommission wipes rows in bulk (no drop_replica calls).
            for name in ev.node_list:
                i = self._nid(name)
                self._fn_touched.update(
                    int(f) for f in np.flatnonzero(
                        (self.replica_map == i).any(axis=1)))
        super().apply_event(ev)

    def grow(self, topology) -> None:
        """Elastic scale-out: the appended nodes join the functional
        base (fresh salts, epoch bump).  The caller must ``pin_rows``
        the epoch diff's moved set FIRST — every other file's computed
        row is unchanged (salts are name-keyed)."""
        super().grow(topology)
        self._fn_salts = node_salts(self.topology.nodes, self._fn_seed)
        self._fn_epoch += 1

    def pin_rows(self, fids) -> None:
        """Dense backend: rows are already materialized; just mark them
        for exception re-verification against the (about to move)
        base."""
        self._fn_touched.update(int(f) for f in np.asarray(fids))

    def retarget_row(self, fid: int, new_row: np.ndarray) -> int:
        self._fn_touched.add(int(fid))
        return super().retarget_row(fid, new_row)

    # -- base-form retarget --------------------------------------------------
    def apply_rf_target(self, fid: int, rf_new: int,
                        record_intent: bool = True) -> int:
        if record_intent:
            # An intent change moves the file's BASE even when the row
            # itself does not move (e.g. a shrink whose surplus copy sits
            # on a down node the legacy policy refuses to drop) — the
            # exception verifier must re-check it either way.
            self._fn_touched.add(int(fid))
            if self._fn_can_retarget(fid, rf_new):
                return self._fn_retarget(fid, rf_new)
        return super().apply_rf_target(fid, rf_new, record_intent)

    def _fn_can_retarget(self, fid: int, rf_new: int) -> bool:
        """Fast path only when it cannot change semantics vs a healthy
        cluster: current row in base form, every holder AND every would-be
        computed target reachable (a fault anywhere defers to the legacy
        stateful policy and its partial-placement semantics)."""
        row = self.row(fid)
        cur = int(self.installed_shards[fid])
        base = self._fn_order(fid, max(cur, int(rf_new)))
        n_cur = int((row >= 0).sum())
        if n_cur != min(max(cur, 1), len(self.nodes)) \
                or not np.array_equal(row[:n_cur], base[:n_cur]):
            return False
        reach = self.node_reachable()
        target = min(max(int(rf_new), 1), len(self.nodes))
        need = base[:max(n_cur, target)]
        return bool(reach[need].all())

    def _fn_order(self, fid: int, shards: int) -> np.ndarray:
        """(min(shards, n_nodes),) computed slot order of one file."""
        local = None
        if getattr(self.topology, "n_levels", 0) > 0 \
                and self.region_local[fid]:
            local = np.asarray([True])
        slots, _ = compute_placement(
            np.asarray([fid], dtype=np.int64), np.asarray([shards]),
            self._fn_primary[fid:fid + 1], self.topology, self._fn_seed,
            salts=self._fn_salts, local_mask=local)
        row = slots[0]
        return row[row >= 0]

    def _fn_retarget(self, fid: int, rf_new: int) -> int:
        """Move ``fid`` along its computed slot order (nested in rf:
        growth appends computed nodes, shrink drops the computed tail) —
        the add/drop primitives keep bytes, corruption bits and cached
        counts consistent, and the row stays in base form."""
        cur = int((self.row(fid) >= 0).sum())
        self.installed_shards[fid] = int(rf_new)
        target = min(max(int(rf_new), 1), len(self.nodes))
        if target == cur:
            return 0
        order = self._fn_order(fid, max(cur, target))
        delta = 0
        for node in order[cur:target]:
            self.add_replica(fid, int(node))
            delta += 1
        for node in order[target:cur][::-1]:
            self.drop_replica(fid, int(node))
            delta -= 1
        return delta

    # -- checkpoint ----------------------------------------------------------
    def state_arrays(self, rf_hint: np.ndarray | None = None
                     ) -> dict[str, np.ndarray]:
        """Sparse placement snapshot (functional mode); the dense parent
        contract when ``sparse_checkpoint=False`` (the oracle).

        ``rf_hint`` (the controller's ``current_rf``) anchors the
        shard-intent reconstruction: intents are stored only where they
        deviate from ``clip(current_rf, 1, ...)`` — never-applied files
        and every plain rf migration reconstruct for free; deferred
        conversions and capped-topology corners ride the sparse override.
        Without a hint the intent vector is stored densely (correct, just
        not O(exceptions) — direct library use outside the controller).
        """
        if not self._fn_sparse:
            return super().state_arrays()
        exc = self.exception_fids()
        arrays: dict[str, np.ndarray] = {
            "fault_fn_sparse": np.asarray([1], dtype=np.int8),
            "fault_fn_seed": np.asarray([self._fn_seed], dtype=np.int64),
            "fault_fn_epoch": np.asarray([self._fn_epoch], dtype=np.int64),
            "fault_fn_exc_fids": exc,
            "fault_fn_exc_rows": self.replica_map[exc].copy(),
            "fault_node_up": self.node_up.copy(),
            "fault_node_decommissioned": self.node_decommissioned.copy(),
            "fault_node_partitioned": self.node_partitioned.copy(),
            "fault_node_fail_prob": self.node_fail_prob.copy(),
            "fault_node_throughput": self.node_throughput.copy(),
        }
        # Latent rot as sparse (file, slot) pairs.
        if self._n_corrupt:
            cf, cs = np.nonzero(self.slot_corrupt)
            arrays["fault_fn_corrupt_fid"] = cf.astype(np.int64)
            arrays["fault_fn_corrupt_slot"] = cs.astype(np.int32)
        # Shard intent: sparse vs the current_rf reconstruction, or dense
        # without a hint.
        if rf_hint is not None:
            default = np.clip(np.asarray(rf_hint, dtype=np.int64),
                              1, None).astype(np.int32)
            dev = np.flatnonzero(self.installed_shards != default)
            arrays["fault_fn_intent_fids"] = dev.astype(np.int64)
            arrays["fault_fn_intent_vals"] = \
                self.installed_shards[dev].copy()
        else:
            arrays["fault_fn_intent_dense"] = self.installed_shards.copy()
        # Storage-strategy state: sparse vs the replicate construction
        # defaults (min_live=1, shard_bytes=size, ec_k=0) — empty for
        # replicate-only runs, O(converted files) otherwise.
        dev = np.flatnonzero((self.min_live != 1)
                             | (self.shard_bytes != self.sizes)
                             | (self.ec_k != 0)
                             | self.region_local)
        arrays["fault_fn_strat_fids"] = dev.astype(np.int64)
        arrays["fault_fn_strat_min_live"] = self.min_live[dev].copy()
        arrays["fault_fn_strat_shard_bytes"] = self.shard_bytes[dev].copy()
        arrays["fault_fn_strat_ec_k"] = self.ec_k[dev].copy()
        arrays["fault_fn_strat_local"] = self.region_local[dev].copy()
        return arrays

    def load_state_arrays(self, arrays: dict) -> None:
        if "fault_fn_sparse" not in arrays:
            # A dense snapshot (the oracle's, or a hand-built one): the
            # parent contract loads it; exception tracking restarts from
            # a full-row verify of nothing (rows may deviate from base —
            # mark everything deviating by one vectorized sweep).
            super().load_state_arrays(arrays)
            self._fn_touched = set()
            self._fn_exceptions = set()
            self._fn_exc_array = None
            self._fn_mark_deviations()
            return
        n = self.replica_map.shape[0]
        n_nodes = len(self.nodes)
        if int(arrays["fault_fn_seed"][0]) != self._fn_seed:
            raise ValueError(
                f"checkpoint placement seed "
                f"{int(arrays['fault_fn_seed'][0])} != {self._fn_seed} — "
                f"stale checkpoint? delete it to start over")
        self._fn_epoch = int(arrays["fault_fn_epoch"][0])
        # Shard intent first: the base recompute depends on it.
        if "fault_fn_intent_dense" in arrays:
            self.installed_shards = np.asarray(
                arrays["fault_fn_intent_dense"], dtype=np.int32).copy()
        else:
            if "current_rf" not in arrays:
                raise ValueError(
                    "sparse functional checkpoint needs the controller's "
                    "current_rf for intent reconstruction")
            self.installed_shards = np.clip(
                np.asarray(arrays["current_rf"], dtype=np.int64), 1,
                None).astype(np.int32)
            fids = np.asarray(arrays["fault_fn_intent_fids"],
                              dtype=np.int64)
            self.installed_shards[fids] = np.asarray(
                arrays["fault_fn_intent_vals"], dtype=np.int32)
        # Strategy state from the replicate defaults + sparse overrides.
        self.min_live = np.ones(n, dtype=np.int32)
        self.shard_bytes = self.sizes.copy()
        self.ec_k = np.zeros(n, dtype=np.int32)
        self.region_local = np.zeros(n, dtype=bool)
        sf = np.asarray(arrays.get("fault_fn_strat_fids",
                                   np.zeros(0, np.int64)), dtype=np.int64)
        if sf.size:
            self.min_live[sf] = np.asarray(
                arrays["fault_fn_strat_min_live"], dtype=np.int32)
            self.shard_bytes[sf] = np.asarray(
                arrays["fault_fn_strat_shard_bytes"], dtype=np.int64)
            self.ec_k[sf] = np.asarray(
                arrays["fault_fn_strat_ec_k"], dtype=np.int32)
            if "fault_fn_strat_local" in arrays:
                self.region_local[sf] = np.asarray(
                    arrays["fault_fn_strat_local"], dtype=bool)
        # Recompute the base, then lay the exceptions over it.
        self.replica_map = np.full((n, n_nodes), -1, dtype=np.int32)
        chunk = 1 << 20
        for lo in range(0, n, chunk):
            hi = min(lo + chunk, n)
            self.replica_map[lo:hi] = self._fn_base_rows(
                np.arange(lo, hi, dtype=np.int64))
        exc = np.asarray(arrays["fault_fn_exc_fids"], dtype=np.int64)
        self.replica_map[exc] = np.asarray(arrays["fault_fn_exc_rows"],
                                           dtype=np.int32)
        # The snapshot's exceptions were verified at save time and the
        # base recompute is deterministic — restore them as the standing
        # set, nothing pending.
        self._fn_touched = set()
        self._fn_exceptions = set(int(f) for f in exc)
        self._fn_exc_array = None
        # Corruption + node status.
        self.slot_corrupt = np.zeros((n, n_nodes), dtype=bool)
        if "fault_fn_corrupt_fid" in arrays:
            self.slot_corrupt[
                np.asarray(arrays["fault_fn_corrupt_fid"], dtype=np.int64),
                np.asarray(arrays["fault_fn_corrupt_slot"],
                           dtype=np.int64)] = True
        self._n_corrupt = int(self.slot_corrupt.sum())
        self.node_up = np.asarray(arrays["fault_node_up"],
                                  dtype=bool).copy()
        self.node_decommissioned = np.asarray(
            arrays["fault_node_decommissioned"], dtype=bool).copy()
        self.node_partitioned = np.asarray(
            arrays["fault_node_partitioned"], dtype=bool).copy()
        self.node_fail_prob = np.asarray(
            arrays["fault_node_fail_prob"], dtype=np.float64).copy()
        self.node_throughput = np.asarray(
            arrays["fault_node_throughput"], dtype=np.float64).copy()
        self._recompute_node_bytes()
        self._refresh_all()
        self.version += 1

    def _fn_mark_deviations(self, chunk: int = 1 << 20) -> None:
        """Seed the standing-exception set with every row deviating from
        base (one vectorized sweep) — dense-snapshot loads only."""
        n = self.replica_map.shape[0]
        for lo in range(0, n, chunk):
            hi = min(lo + chunk, n)
            base = self._fn_base_rows(np.arange(lo, hi, dtype=np.int64))
            dev = np.flatnonzero((self.replica_map[lo:hi] != base)
                                 .any(axis=1))
            self._fn_exceptions.update(int(lo + f) for f in dev)


class OverlayClusterState(FunctionalClusterState):
    """Functional ClusterState with NO resident dense map (ROADMAP item
    3's leftover): the ``(n_files, n_nodes)`` replica map and corruption
    mask — the two arrays that dominated functional-mode RSS once
    checkpoints, router and planner stopped needing them (PR 13) — are
    replaced by the sparse overlay itself.

    * a row is **computed** on demand (``_fn_base_rows``) and overlaid
      by ``_ov`` — a dict of exactly the rows that deviate from base, so
      the overlay IS the standing exception set (an entry is written
      only when the mutated row differs from its recomputed base, and
      removed the moment a repair reconverges it);
    * corruption is a ``fid -> slot bitmask`` dict (n_nodes <= 63 — one
      int per rotten file);
    * the per-file count caches (live/reachable/domain-spread — O(n)
      int32, the durability plane) stay maintained exactly as before;
    * liveness events recompute their blast radius by a chunked base
      scan — the explicit CRUSH trade: O(population) hashing per
      node-status event instead of O(population x nodes) resident
      bytes every second of every run.

    Decision-identical to the dense family by construction: every
    mutation primitive reproduces the dense semantics on the resolved
    row (slot positions included), and the class is exercised against
    the ``materialized_hash`` oracle by the same controller-equivalence
    tests.  Checkpoints are the sparse snapshot (always —
    ``sparse_checkpoint=False`` makes no sense without a dense map).
    ``replica_map``/``slot_corrupt``/masks materialize on access for
    tests and the evaluate replay; hot paths never touch them.
    """

    def __init__(self, placement, size_bytes, *, primary: np.ndarray,
                 seed: int = 0, epoch: int = 0,
                 sparse_checkpoint: bool = True):
        if not sparse_checkpoint:
            raise ValueError(
                "OverlayClusterState has no dense map to snapshot — use "
                "FunctionalClusterState for the dense oracle")
        # Deliberately NOT calling the dense __init__ chain: replicate
        # the scalar/per-node/per-file (but never per-file-x-node) setup.
        topology = placement.topology
        self.topology = topology
        self.nodes = tuple(topology.nodes)
        n_nodes = len(self.nodes)
        rf = np.asarray(placement.rf, dtype=np.int32)
        n = rf.shape[0]
        self._node_idx = {nm: i for i, nm in enumerate(self.nodes)}
        self.domain_index = topology.domain_index()
        self.n_domains = topology.n_domains
        self._top_index = (topology.top_domain_index()
                           if getattr(topology, "n_levels", 0) > 0
                           else None)
        self._n_top = (topology.n_domains_at(topology.n_levels)
                       if self._top_index is not None else 0)
        self.sizes = np.asarray(size_bytes, dtype=np.int64)
        if self.sizes.shape != (n,):
            raise ValueError(
                f"size_bytes shape {self.sizes.shape} != ({n},)")
        self.min_live = np.ones(n, dtype=np.int32)
        self.shard_bytes = self.sizes.copy()
        self.ec_k = np.zeros(n, dtype=np.int32)
        self.region_local = np.zeros(n, dtype=bool)
        self._byte_cost = (topology.byte_cost_matrix()
                           if getattr(topology, "edge_bytes", ())
                           else None)
        self.installed_shards = rf.copy()
        self._n_corrupt = 0
        self._corrupt_bits: dict[int, int] = {}
        self.node_up = np.ones(n_nodes, dtype=bool)
        self.node_decommissioned = np.zeros(n_nodes, dtype=bool)
        self.node_partitioned = np.zeros(n_nodes, dtype=bool)
        self.node_fail_prob = np.zeros(n_nodes, dtype=np.float64)
        self.node_throughput = np.ones(n_nodes, dtype=np.float64)
        self._fn_primary = np.asarray(primary, dtype=np.int32)
        if self._fn_primary.shape[0] != n:
            raise ValueError(
                f"primary shape {self._fn_primary.shape} != ({n},)")
        self._fn_seed = int(seed)
        self._fn_epoch = int(epoch)
        self._fn_sparse = True
        self._fn_salts = node_salts(self.topology.nodes, self._fn_seed)
        self._fn_touched: set[int] = set()   # compat no-op (see parent)
        self._fn_exceptions: set[int] = set()
        self._fn_exc_array = None
        #: The overlay: fid -> (n_nodes,) int32 row, stored IFF != base.
        self._ov: dict[int, np.ndarray] = {}
        rm = placement.replica_map
        if rm is not None and rm.size:
            # A hand-built placement may deviate from base: seed the
            # overlay with exactly the deviating rows (base-form input —
            # place_replicas(method='hash') — seeds nothing).
            chunk = 1 << 20
            for lo in range(0, n, chunk):
                hi = min(lo + chunk, n)
                base = self._fn_base_rows(np.arange(lo, hi,
                                                    dtype=np.int64))
                width = min(rm.shape[1], n_nodes)
                given = np.full((hi - lo, n_nodes), -1, dtype=np.int32)
                given[:, :width] = rm[lo:hi, :width]
                for f in np.flatnonzero((given != base).any(axis=1)):
                    self._ov[int(lo + f)] = given[f].copy()
        self.node_bytes = np.zeros(n_nodes, dtype=np.int64)
        self._recompute_node_bytes()
        self.version = 0
        self._refresh_all()

    @classmethod
    def from_base(cls, topology, size_bytes, *, n_shards: np.ndarray,
                  primary: np.ndarray, seed: int = 0,
                  epoch: int = 0) -> "OverlayClusterState":
        """Construct directly in base form — no placement materialized
        anywhere, which is what makes a 100M-file fault-mode controller
        constructible without the transient (n, rf) map."""
        import types

        shim = types.SimpleNamespace(
            topology=topology,
            # The placement cap (distinct nodes per shard), exactly as
            # place_replicas would have applied it.
            rf=np.clip(np.asarray(n_shards), 1,
                       len(topology.nodes)).astype(np.int32),
            replica_map=None)
        return cls(shim, size_bytes, primary=primary, seed=seed,
                   epoch=epoch)

    # -- row resolution ------------------------------------------------------
    def row(self, fid: int) -> np.ndarray:
        """Resolved (n_nodes,) row — overlay entry or computed base.
        Read-only by contract (mutations go through the primitives)."""
        r = self._ov.get(int(fid))
        if r is not None:
            return r
        return self._fn_base_rows(np.asarray([fid], dtype=np.int64))[0]

    def rows(self, fids: np.ndarray) -> np.ndarray:
        fids = np.asarray(fids, dtype=np.int64)
        out = self._fn_base_rows(fids)
        if self._ov:
            ov = self._ov
            for i, f in enumerate(fids.tolist()):
                r = ov.get(f)
                if r is not None:
                    out[i] = r
        return out

    def _set_row(self, fid: int, row: np.ndarray) -> None:
        base = self._fn_base_rows(np.asarray([fid], dtype=np.int64))[0]
        if np.array_equal(row, base):
            self._ov.pop(int(fid), None)
        else:
            self._ov[int(fid)] = np.asarray(row, dtype=np.int32)

    def assigned_counts(self, chunk: int = 1 << 20) -> np.ndarray:
        """Chunked through ``rows`` — never materializes the map."""
        n = self.min_live.shape[0]
        out = np.empty(n, dtype=np.int64)
        for lo in range(0, n, int(chunk)):
            hi = min(lo + int(chunk), n)
            rows = self.rows(np.arange(lo, hi, dtype=np.int64))
            out[lo:hi] = (rows >= 0).sum(axis=1)
        return out

    #: Materialized compat views (tests / evaluate replay only).
    @property
    def replica_map(self) -> np.ndarray:
        n = self.min_live.shape[0]
        return self.rows(np.arange(n, dtype=np.int64))

    @property
    def slot_corrupt(self) -> np.ndarray:
        n = self.min_live.shape[0]
        out = np.zeros((n, len(self.nodes)), dtype=bool)
        for f, bits in self._corrupt_bits.items():
            for s in range(len(self.nodes)):
                if bits >> s & 1:
                    out[f, s] = True
        return out

    def live_mask(self) -> np.ndarray:
        rm = self.replica_map
        return (rm >= 0) & self.node_up[np.clip(rm, 0, None)]

    def reachable_mask(self) -> np.ndarray:
        rm = self.replica_map
        return (rm >= 0) & self.node_reachable()[np.clip(rm, 0, None)]

    # -- cached counts -------------------------------------------------------
    def _refresh_all(self, chunk: int = 1 << 20) -> None:
        """Chunked rebuild through ``rows`` (the per-file refresh is
        inherited — it already resolves through the overlay)."""
        n = self.min_live.shape[0]
        self._live_counts = np.zeros(n, dtype=np.int32)
        self._reach_counts = np.zeros(n, dtype=np.int32)
        self._dom_spread = np.zeros(n, dtype=np.int32)
        if self._top_index is not None:
            self._top_spread = np.zeros(n, dtype=np.int32)
        for lo in range(0, n, int(chunk)):
            hi = min(lo + int(chunk), n)
            self._refresh_files(np.arange(lo, hi, dtype=np.int64))

    def _recompute_node_bytes(self, chunk: int = 1 << 20) -> None:
        n = self.min_live.shape[0]
        self.node_bytes = np.zeros(len(self.nodes), dtype=np.int64)
        for lo in range(0, n, int(chunk)):
            hi = min(lo + int(chunk), n)
            rows = self.rows(np.arange(lo, hi, dtype=np.int64))
            sel = rows >= 0
            np.add.at(self.node_bytes, rows[sel],
                      np.broadcast_to(self.shard_bytes[lo:hi, None],
                                      rows.shape)[sel])

    # -- holders scan (the per-event recompute trade) ------------------------
    def _holders(self, node: int, chunk: int = 1 << 20) -> np.ndarray:
        """Sorted fids whose RESOLVED row assigns ``node`` — chunked
        base scan patched by the overlay."""
        n = self.min_live.shape[0]
        parts: list[np.ndarray] = []
        for lo in range(0, n, int(chunk)):
            hi = min(lo + int(chunk), n)
            fids = np.arange(lo, hi, dtype=np.int64)
            base = self._fn_base_rows(fids)
            parts.append(fids[(base == node).any(axis=1)])
        holders = set(np.concatenate(parts).tolist()) if parts else set()
        for f, r in self._ov.items():
            if (r == node).any():
                holders.add(f)
            else:
                holders.discard(f)
        return np.asarray(sorted(holders), dtype=np.int64)

    # -- mutation primitives -------------------------------------------------
    def add_replica(self, fid: int, node: int) -> None:
        row = self.row(fid).copy()
        free = np.flatnonzero(row < 0)
        if free.size == 0:  # pragma: no cover - width==n_nodes prevents
            raise RuntimeError(f"file {fid} has no free replica slot")
        s = int(free[0])
        row[s] = node
        self._clear_corrupt_bit(fid, s)
        self.node_bytes[node] += self.shard_bytes[fid]
        self._set_row(fid, row)
        self._refresh_files(np.asarray([fid]))
        self.version += 1

    def drop_replica(self, fid: int, node: int) -> None:
        row = self.row(fid).copy()
        slots = np.flatnonzero(row == node)
        if slots.size:
            s = int(slots[0])
            row[s] = -1
            self._clear_corrupt_bit(fid, s)
            self.node_bytes[node] -= self.shard_bytes[fid]
            self._set_row(fid, row)
            self._refresh_files(np.asarray([fid]))
            self.version += 1

    # -- corruption (sparse bitmasks) ----------------------------------------
    def _clear_corrupt_bit(self, fid: int, slot: int) -> None:
        bits = self._corrupt_bits.get(int(fid))
        if bits is not None and bits >> slot & 1:
            bits &= ~(1 << slot)
            self._n_corrupt -= 1
            if bits:
                self._corrupt_bits[int(fid)] = bits
            else:
                del self._corrupt_bits[int(fid)]
            self.version += 1

    def corrupt_replica(self, fid: int, node: int) -> bool:
        row = self.row(fid)
        slots = np.flatnonzero(row == node)
        if slots.size == 0:
            return False
        s = int(slots[0])
        bits = self._corrupt_bits.get(int(fid), 0)
        if bits >> s & 1:
            return False
        self._corrupt_bits[int(fid)] = bits | (1 << s)
        self._n_corrupt += 1
        self.version += 1
        return True

    def corrupt_row(self, fid: int) -> np.ndarray:
        """(n_nodes,) bool rot mask of one file (scrub hint loop)."""
        out = np.zeros(len(self.nodes), dtype=bool)
        bits = self._corrupt_bits.get(int(fid), 0)
        s = 0
        while bits:
            if bits & 1:
                out[s] = True
            bits >>= 1
            s += 1
        return out

    def corrupt_at(self, fids: np.ndarray, slots: np.ndarray) -> np.ndarray:
        """Bool per (fid, slot) pair — the scrub lap's gather, O(pairs)
        dict lookups but only when rot exists at all."""
        if not self._n_corrupt:
            return np.zeros(np.asarray(fids).shape[0], dtype=bool)
        cb = self._corrupt_bits
        return np.fromiter(
            (bool(cb.get(int(f), 0) >> int(s) & 1)
             for f, s in zip(np.asarray(fids), np.asarray(slots))),
            dtype=bool, count=np.asarray(fids).shape[0])

    def verify_sources(self, fid: int) -> tuple[int, int]:
        if not self._n_corrupt:
            return 0, 0
        bits = self._corrupt_bits.get(int(fid), 0)
        if not bits:
            return 0, 0
        row = self.row(fid)
        reach = self.node_reachable()
        found = 0
        charge = 0
        for s in range(len(self.nodes)):
            if not (bits >> s & 1) or row[s] < 0:
                continue
            node = int(row[s])
            if not reach[node]:
                continue
            charge += int(np.ceil(
                int(self.shard_bytes[fid])
                / max(float(self.node_throughput[node]), 1e-9)))
            self.quarantine(fid, node)
            found += 1
        return found, charge

    def corrupt_file_counts(self) -> np.ndarray:
        n = self.min_live.shape[0]
        out = np.zeros(n, dtype=np.int32)
        if not self._n_corrupt:
            return out
        for f, bits in self._corrupt_bits.items():
            row = self.row(f)
            c = 0
            for s in range(len(self.nodes)):
                if bits >> s & 1 and row[s] >= 0 \
                        and self.node_up[int(row[s])]:
                    c += 1
            out[f] = c
        return out

    def true_lost_mask(self) -> np.ndarray:
        if not self._n_corrupt:
            return self.lost_mask()
        clean = self._live_counts - self.corrupt_file_counts()
        return clean < self.min_live

    # -- events --------------------------------------------------------------
    def apply_event(self, ev) -> None:
        affected: list[np.ndarray] = []
        for name in ev.node_list:
            i = self._nid(name)
            if ev.kind in self._COUNT_KINDS:
                affected.append(self._holders(i))
            if ev.kind == "crash":
                self.node_up[i] = False
            elif ev.kind == "recover":
                if not self.node_decommissioned[i]:
                    self.node_up[i] = True
            elif ev.kind == "decommission":
                self.node_up[i] = False
                self.node_decommissioned[i] = True
                gone = affected[-1]
                self.node_bytes[i] = 0
                for f in gone.tolist():
                    row = self.row(int(f)).copy()
                    for s in np.flatnonzero(row == i):
                        row[int(s)] = -1
                        self._clear_corrupt_bit(int(f), int(s))
                    self._set_row(int(f), row)
            elif ev.kind == "partition":
                self.node_partitioned[i] = True
            elif ev.kind == "heal":
                self.node_partitioned[i] = False
            elif ev.kind == "flaky":
                self.node_fail_prob[i] = float(ev.fail_prob)
            elif ev.kind == "unflaky":
                self.node_fail_prob[i] = 0.0
            elif ev.kind == "degrade":
                self.node_throughput[i] = float(ev.factor)
            elif ev.kind == "restore":
                self.node_throughput[i] = 1.0
            elif ev.kind == "corrupt":
                if ev.file >= 0:
                    if ev.file >= self.min_live.shape[0]:
                        raise ValueError(
                            f"corrupt event {ev.spec()!r} pins file "
                            f"{ev.file} but the population has "
                            f"{self.min_live.shape[0]} files")
                    self.corrupt_replica(int(ev.file), i)
                else:
                    from ..faults.state import _corrupt_roll

                    holds = self._holders(i)
                    roll = _corrupt_roll(ev.window, i, holds)
                    for f in holds[roll < float(ev.fail_prob)]:
                        self.corrupt_replica(int(f), i)
            else:  # pragma: no cover - FaultEvent validates kinds
                raise ValueError(f"unknown fault kind {ev.kind!r}")
        if affected:
            self._refresh_files(np.unique(np.concatenate(affected)))
        self.version += 1

    # -- intent changes pin the row (the implicit-base contract) -------------
    # A row absent from the overlay IS its computed base — which moves
    # the moment installed_shards (or the strategy/locality vectors, or
    # the epoch) changes.  Every intent-changing path therefore pins the
    # currently-resolved row first; the mutation primitives' _set_row
    # drops the pin again the moment the row physically reaches the new
    # base (so steady-state retargets still serialize to zero
    # exceptions, exactly like the dense twin).
    def apply_rf_target(self, fid: int, rf_new: int,
                        record_intent: bool = True) -> int:
        if record_intent:
            if self._fn_can_retarget(fid, rf_new):
                return self._fn_retarget(fid, rf_new)
            pinned = self.row(fid).copy()
            self.installed_shards[fid] = int(rf_new)
            self._ov[int(fid)] = pinned
            delta = ClusterState.apply_rf_target(self, fid, rf_new,
                                                 record_intent=False)
            self._set_row(fid, self.row(fid).copy())
            return delta
        return ClusterState.apply_rf_target(self, fid, rf_new,
                                            record_intent=False)

    def _fn_retarget(self, fid: int, rf_new: int) -> int:
        cur_row = self.row(fid).copy()
        cur = int((cur_row >= 0).sum())
        self.installed_shards[fid] = int(rf_new)
        self._ov[int(fid)] = cur_row          # pin under the new base
        target = min(max(int(rf_new), 1), len(self.nodes))
        if target == cur:
            self._set_row(fid, self.row(fid).copy())
            return 0
        order = self._fn_order(fid, max(cur, target))
        delta = 0
        for node in order[cur:target]:
            self.add_replica(fid, int(node))
            delta += 1
        for node in order[target:cur][::-1]:
            self.drop_replica(fid, int(node))
            delta -= 1
        return delta

    def apply_strategy_target(self, fid: int, min_live: int,
                              shard_bytes: int, ec_k: int,
                              target: int,
                              region_local: bool = False) -> int:
        same = (int(self.min_live[fid]) == int(min_live)
                and int(self.shard_bytes[fid]) == int(shard_bytes)
                and int(self.ec_k[fid]) == int(ec_k)
                and bool(self.region_local[fid]) == bool(region_local))
        if not same:
            # The re-encode changes the base (strategy vectors feed it):
            # pin the resolved row so the drops/adds below mutate real
            # state, not a phantom recompute.
            self._ov.setdefault(int(fid), self.row(fid).copy())
        delta = ClusterState.apply_strategy_target(
            self, fid, min_live, shard_bytes, ec_k, target, region_local)
        if not same:
            self._set_row(fid, self.row(fid).copy())
        return delta

    def pin_rows(self, fids) -> None:
        """Pin resolved rows before a base-moving change (epoch
        advance): afterwards they stand as exceptions until the
        rebalance physically reconverges them."""
        fids = np.asarray(fids, dtype=np.int64)
        if fids.size == 0:
            return
        rows = self.rows(fids)
        for i, f in enumerate(fids.tolist()):
            self._ov[int(f)] = rows[i].copy()

    def retarget_row(self, fid: int, new_row: np.ndarray) -> int:
        new_row = np.asarray(new_row, dtype=np.int32)
        old_row = self.row(fid).copy()
        old_nodes = {int(x) for x in old_row[old_row >= 0]}
        new_nodes = {int(x) for x in new_row[new_row >= 0]}
        sb = int(self.shard_bytes[fid])
        for v in old_nodes - new_nodes:
            self.node_bytes[v] -= sb
        for v in new_nodes - old_nodes:
            self.node_bytes[v] += sb
        bits = self._corrupt_bits.get(int(fid), 0)
        if bits:
            slot_of = {int(v): int(s) for s, v in enumerate(new_row)
                       if v >= 0}
            new_bits = 0
            for s in range(len(self.nodes)):
                if bits >> s & 1:
                    v = int(old_row[s])
                    if v in slot_of:
                        new_bits |= 1 << slot_of[v]
                    else:
                        self._n_corrupt -= 1
            if new_bits:
                self._corrupt_bits[int(fid)] = new_bits
            else:
                del self._corrupt_bits[int(fid)]
        self._set_row(fid, new_row)
        self._refresh_files(np.asarray([fid]))
        self.version += 1
        return sb * len(new_nodes - old_nodes)

    def grow(self, topology) -> None:
        """Scale-out without a dense map: per-node arrays extend
        (``_grow_common`` — shared with the dense backend), every
        PINNED overlay row widens, salts refresh, epoch bumps."""
        add = self._grow_common(topology)
        pad = np.full(add, -1, dtype=np.int32)
        for f in list(self._ov):
            self._ov[f] = np.concatenate([self._ov[f], pad])
        self._fn_salts = node_salts(self.topology.nodes, self._fn_seed)
        self._fn_epoch += 1

    # -- serve resolution ----------------------------------------------------
    def read_rows(self, uniq: np.ndarray):
        """(rows, slot_ok, slot_corrupt|None) for a unique-pid subset —
        the serve layer's O(unique pids) view (serve/view.read_view)."""
        rows = self.rows(uniq)
        ok = (rows >= 0) & self.node_reachable()[np.clip(rows, 0, None)]
        corrupt = None
        if self._n_corrupt:
            corrupt = np.zeros(rows.shape, dtype=bool)
            cb = self._corrupt_bits
            for i, f in enumerate(np.asarray(uniq).tolist()):
                bits = cb.get(int(f), 0)
                s = 0
                while bits:
                    if bits & 1:
                        corrupt[i, s] = True
                    bits >>= 1
                    s += 1
        return rows, ok, corrupt

    # -- exceptions / checkpoint ---------------------------------------------
    def exception_fids(self, verify_chunk: int = 1 << 18) -> np.ndarray:
        """The overlay keys — maintained exactly (rows are stored iff
        they deviate from base), so no re-verification pass exists."""
        return np.asarray(sorted(self._ov), dtype=np.int64)

    def state_arrays(self, rf_hint: np.ndarray | None = None
                     ) -> dict[str, np.ndarray]:
        exc = self.exception_fids()
        arrays: dict[str, np.ndarray] = {
            "fault_fn_sparse": np.asarray([1], dtype=np.int8),
            "fault_fn_seed": np.asarray([self._fn_seed], dtype=np.int64),
            "fault_fn_epoch": np.asarray([self._fn_epoch],
                                         dtype=np.int64),
            "fault_fn_exc_fids": exc,
            "fault_fn_exc_rows": (
                np.stack([self._ov[int(f)] for f in exc])
                if exc.size else np.zeros((0, len(self.nodes)),
                                          dtype=np.int32)),
            "fault_node_up": self.node_up.copy(),
            "fault_node_decommissioned": self.node_decommissioned.copy(),
            "fault_node_partitioned": self.node_partitioned.copy(),
            "fault_node_fail_prob": self.node_fail_prob.copy(),
            "fault_node_throughput": self.node_throughput.copy(),
        }
        if self._n_corrupt:
            cf, cs = [], []
            for f in sorted(self._corrupt_bits):
                bits = self._corrupt_bits[f]
                for s in range(len(self.nodes)):
                    if bits >> s & 1:
                        cf.append(f)
                        cs.append(s)
            arrays["fault_fn_corrupt_fid"] = np.asarray(cf,
                                                        dtype=np.int64)
            arrays["fault_fn_corrupt_slot"] = np.asarray(cs,
                                                         dtype=np.int32)
        if rf_hint is not None:
            default = np.clip(np.asarray(rf_hint, dtype=np.int64),
                              1, None).astype(np.int32)
            dev = np.flatnonzero(self.installed_shards != default)
            arrays["fault_fn_intent_fids"] = dev.astype(np.int64)
            arrays["fault_fn_intent_vals"] = \
                self.installed_shards[dev].copy()
        else:
            arrays["fault_fn_intent_dense"] = self.installed_shards.copy()
        dev = np.flatnonzero((self.min_live != 1)
                             | (self.shard_bytes != self.sizes)
                             | (self.ec_k != 0)
                             | self.region_local)
        arrays["fault_fn_strat_fids"] = dev.astype(np.int64)
        arrays["fault_fn_strat_min_live"] = self.min_live[dev].copy()
        arrays["fault_fn_strat_shard_bytes"] = \
            self.shard_bytes[dev].copy()
        arrays["fault_fn_strat_ec_k"] = self.ec_k[dev].copy()
        arrays["fault_fn_strat_local"] = self.region_local[dev].copy()
        return arrays

    def load_state_arrays(self, arrays: dict) -> None:
        if "fault_fn_sparse" not in arrays:
            raise ValueError(
                "OverlayClusterState resumes from sparse functional "
                "snapshots only (this one is dense — a materialized-"
                "mode checkpoint; stale checkpoint? delete it to start "
                "over)")
        n = self.min_live.shape[0]
        if int(arrays["fault_fn_seed"][0]) != self._fn_seed:
            raise ValueError(
                f"checkpoint placement seed "
                f"{int(arrays['fault_fn_seed'][0])} != {self._fn_seed} "
                f"— stale checkpoint? delete it to start over")
        self._fn_epoch = int(arrays["fault_fn_epoch"][0])
        if "fault_fn_intent_dense" in arrays:
            self.installed_shards = np.asarray(
                arrays["fault_fn_intent_dense"], dtype=np.int32).copy()
        else:
            if "current_rf" not in arrays:
                raise ValueError(
                    "sparse functional checkpoint needs the "
                    "controller's current_rf for intent reconstruction")
            self.installed_shards = np.clip(
                np.asarray(arrays["current_rf"], dtype=np.int64), 1,
                None).astype(np.int32)
            fids = np.asarray(arrays["fault_fn_intent_fids"],
                              dtype=np.int64)
            self.installed_shards[fids] = np.asarray(
                arrays["fault_fn_intent_vals"], dtype=np.int32)
        self.min_live = np.ones(n, dtype=np.int32)
        self.shard_bytes = self.sizes.copy()
        self.ec_k = np.zeros(n, dtype=np.int32)
        self.region_local = np.zeros(n, dtype=bool)
        sf = np.asarray(arrays.get("fault_fn_strat_fids",
                                   np.zeros(0, np.int64)),
                        dtype=np.int64)
        if sf.size:
            self.min_live[sf] = np.asarray(
                arrays["fault_fn_strat_min_live"], dtype=np.int32)
            self.shard_bytes[sf] = np.asarray(
                arrays["fault_fn_strat_shard_bytes"], dtype=np.int64)
            self.ec_k[sf] = np.asarray(
                arrays["fault_fn_strat_ec_k"], dtype=np.int32)
            if "fault_fn_strat_local" in arrays:
                self.region_local[sf] = np.asarray(
                    arrays["fault_fn_strat_local"], dtype=bool)
        exc = np.asarray(arrays["fault_fn_exc_fids"], dtype=np.int64)
        rows = np.asarray(arrays["fault_fn_exc_rows"], dtype=np.int32)
        self._ov = {int(f): rows[i].copy()
                    for i, f in enumerate(exc.tolist())}
        self._corrupt_bits = {}
        self._n_corrupt = 0
        if "fault_fn_corrupt_fid" in arrays:
            for f, s in zip(
                    np.asarray(arrays["fault_fn_corrupt_fid"]).tolist(),
                    np.asarray(arrays["fault_fn_corrupt_slot"]).tolist()):
                self._corrupt_bits[int(f)] = \
                    self._corrupt_bits.get(int(f), 0) | (1 << int(s))
                self._n_corrupt += 1
        self.node_up = np.asarray(arrays["fault_node_up"],
                                  dtype=bool).copy()
        self.node_decommissioned = np.asarray(
            arrays["fault_node_decommissioned"], dtype=bool).copy()
        self.node_partitioned = np.asarray(
            arrays["fault_node_partitioned"], dtype=bool).copy()
        self.node_fail_prob = np.asarray(
            arrays["fault_node_fail_prob"], dtype=np.float64).copy()
        self.node_throughput = np.asarray(
            arrays["fault_node_throughput"], dtype=np.float64).copy()
        self._recompute_node_bytes()
        self._refresh_all()
        self.version += 1
