"""Functional ClusterState backend: exceptions over a computed base.

``faults.ClusterState`` is the mutable source of truth of the fault path;
its checkpoint is the ``(n_files, n_nodes)`` replica map plus a parallel
corruption mask — the npz term that scales with file count (ROADMAP
item 3).  This backend keeps the WHOLE mutation machinery (and therefore
every repair/durability/serving decision) bit-identical while changing
what a checkpoint *is*: placement state is serialized as

* the functional base — ``(seed, epoch)`` plus the per-file shard intent,
  re-derivable from vectors the controller checkpoint already carries;
* per-file **exceptions** — the rows whose current placement differs from
  the computed base (repair retargets onto live nodes, quarantine drops,
  deferred strategy conversions, decommission wipes);
* sparse corruption ``(file, slot)`` pairs and sparse strategy overrides.

In memory the dense map stays resident as a CACHE of computed-base +
exceptions (the mutation primitives, blast-radius refreshes and
vectorized durability tiers all index it; shrinking the resident cache
is the noted follow-up) — the O(exceptions) wins land on the checkpoint,
the epoch-diff planner and the serve router, which is where the
materialized representation actually bottlenecked.

Two representation modes share this class: ``sparse_checkpoint=True`` is
the functional mode; ``False`` keeps the dense npz contract and serves
as the **materialized equivalence oracle** (the PR-8 compat pattern —
same chooser, same retarget policy, dense serialization), so a
functional run resumed mid-fault must reproduce the oracle's records
bit-for-bit.

The one behavioural difference from the legacy ``ClusterState`` policy
(shared by BOTH modes of the hash family, which is what keeps them
decision-identical) is the **base-form retarget**: an rf change applied
to a file whose row is in base form on a fully reachable node set moves
along the computed slot order — the nested-in-rf property of
``compute_placement`` means growth appends computed nodes and shrink
drops the computed tail, so steady-state migrations never create
exceptions.  Any fault in the way (unreachable target or holder, prior
exception) falls back to the legacy stateful path, and the file becomes
an exception until topology health lets a later retarget reconverge it.
"""

from __future__ import annotations

import numpy as np

from ..faults.state import ClusterState
from .compute import compute_placement, node_salts

__all__ = ["FunctionalClusterState"]


class FunctionalClusterState(ClusterState):
    """ClusterState whose placement state round-trips as exceptions."""

    def __init__(self, placement, size_bytes, *, primary: np.ndarray,
                 seed: int = 0, epoch: int = 0,
                 sparse_checkpoint: bool = True):
        super().__init__(placement, size_bytes)
        self._fn_primary = np.asarray(primary, dtype=np.int32)
        if self._fn_primary.shape[0] != self.replica_map.shape[0]:
            raise ValueError(
                f"primary shape {self._fn_primary.shape} != "
                f"({self.replica_map.shape[0]},)")
        self._fn_seed = int(seed)
        self._fn_epoch = int(epoch)
        self._fn_sparse = bool(sparse_checkpoint)
        self._fn_salts = node_salts(self.topology.nodes, self._fn_seed)
        #: Files whose row MAY deviate from base since the last verify
        #: (every mutated fid lands here) — ``exception_fids`` classifies
        #: them into ``_fn_exceptions`` and clears the set, so the
        #: stamp/checkpoint cost is O(mutations since last verify) plus a
        #: cached read of the standing exceptions, not O(files) and not
        #: O(standing exceptions) per window.
        self._fn_touched: set[int] = set()
        #: VERIFIED standing exceptions (row != computed base).
        self._fn_exceptions: set[int] = set()
        #: Sorted-array cache of ``_fn_exceptions``; invalidated when the
        #: classification changes.
        self._fn_exc_array: np.ndarray | None = None

    # -- base placement ------------------------------------------------------
    def _fn_base_rows(self, fids: np.ndarray) -> np.ndarray:
        """(k, n_nodes) computed-base rows (padded to map width) for a
        file subset — the pure recompute every consumer shares."""
        fids = np.asarray(fids, dtype=np.int64)
        slots, _ = compute_placement(
            fids, self.installed_shards[fids], self._fn_primary[fids],
            self.topology, self._fn_seed, salts=self._fn_salts,
            out_width=len(self.nodes))
        return slots

    def exception_fids(self, verify_chunk: int = 1 << 18) -> np.ndarray:
        """Sorted int64 fids whose row differs from the computed base —
        EXACT.  Only fids mutated since the last call are re-verified
        against a fresh base recompute (a row repaired back into base
        form stops being an exception); the standing set is returned
        from a cache, so a mass fault's exceptions are classified once,
        not re-hashed every window.  Callers must treat the returned
        array as read-only."""
        if self._fn_touched:
            cand = np.fromiter(self._fn_touched, dtype=np.int64,
                               count=len(self._fn_touched))
            cand.sort()
            self._fn_exceptions.difference_update(self._fn_touched)
            self._fn_touched.clear()
            for lo in range(0, cand.size, verify_chunk):
                part = cand[lo:lo + verify_chunk]
                base = self._fn_base_rows(part)
                diff = (self.replica_map[part] != base).any(axis=1)
                self._fn_exceptions.update(
                    int(f) for f in part[diff])
            self._fn_exc_array = None
        if self._fn_exc_array is None:
            arr = np.fromiter(self._fn_exceptions, dtype=np.int64,
                              count=len(self._fn_exceptions))
            arr.sort()
            self._fn_exc_array = arr
        return self._fn_exc_array

    # -- mutation tracking ---------------------------------------------------
    def add_replica(self, fid: int, node: int) -> None:
        self._fn_touched.add(int(fid))
        super().add_replica(fid, node)

    def drop_replica(self, fid: int, node: int) -> None:
        self._fn_touched.add(int(fid))
        super().drop_replica(fid, node)

    def apply_event(self, ev) -> None:
        if ev.kind == "decommission":
            # Decommission wipes rows in bulk (no drop_replica calls).
            for name in ev.node_list:
                i = self._nid(name)
                self._fn_touched.update(
                    int(f) for f in np.flatnonzero(
                        (self.replica_map == i).any(axis=1)))
        super().apply_event(ev)

    # -- base-form retarget --------------------------------------------------
    def apply_rf_target(self, fid: int, rf_new: int,
                        record_intent: bool = True) -> int:
        if record_intent:
            # An intent change moves the file's BASE even when the row
            # itself does not move (e.g. a shrink whose surplus copy sits
            # on a down node the legacy policy refuses to drop) — the
            # exception verifier must re-check it either way.
            self._fn_touched.add(int(fid))
            if self._fn_can_retarget(fid, rf_new):
                return self._fn_retarget(fid, rf_new)
        return super().apply_rf_target(fid, rf_new, record_intent)

    def _fn_can_retarget(self, fid: int, rf_new: int) -> bool:
        """Fast path only when it cannot change semantics vs a healthy
        cluster: current row in base form, every holder AND every would-be
        computed target reachable (a fault anywhere defers to the legacy
        stateful policy and its partial-placement semantics)."""
        row = self.replica_map[fid]
        cur = int(self.installed_shards[fid])
        base = self._fn_order(fid, max(cur, int(rf_new)))
        n_cur = int((row >= 0).sum())
        if n_cur != min(max(cur, 1), len(self.nodes)) \
                or not np.array_equal(row[:n_cur], base[:n_cur]):
            return False
        reach = self.node_reachable()
        target = min(max(int(rf_new), 1), len(self.nodes))
        need = base[:max(n_cur, target)]
        return bool(reach[need].all())

    def _fn_order(self, fid: int, shards: int) -> np.ndarray:
        """(min(shards, n_nodes),) computed slot order of one file."""
        slots, _ = compute_placement(
            np.asarray([fid], dtype=np.int64), np.asarray([shards]),
            self._fn_primary[fid:fid + 1], self.topology, self._fn_seed,
            salts=self._fn_salts)
        row = slots[0]
        return row[row >= 0]

    def _fn_retarget(self, fid: int, rf_new: int) -> int:
        """Move ``fid`` along its computed slot order (nested in rf:
        growth appends computed nodes, shrink drops the computed tail) —
        the add/drop primitives keep bytes, corruption bits and cached
        counts consistent, and the row stays in base form."""
        cur = int((self.replica_map[fid] >= 0).sum())
        self.installed_shards[fid] = int(rf_new)
        target = min(max(int(rf_new), 1), len(self.nodes))
        if target == cur:
            return 0
        order = self._fn_order(fid, max(cur, target))
        delta = 0
        for node in order[cur:target]:
            self.add_replica(fid, int(node))
            delta += 1
        for node in order[target:cur][::-1]:
            self.drop_replica(fid, int(node))
            delta -= 1
        return delta

    # -- checkpoint ----------------------------------------------------------
    def state_arrays(self, rf_hint: np.ndarray | None = None
                     ) -> dict[str, np.ndarray]:
        """Sparse placement snapshot (functional mode); the dense parent
        contract when ``sparse_checkpoint=False`` (the oracle).

        ``rf_hint`` (the controller's ``current_rf``) anchors the
        shard-intent reconstruction: intents are stored only where they
        deviate from ``clip(current_rf, 1, ...)`` — never-applied files
        and every plain rf migration reconstruct for free; deferred
        conversions and capped-topology corners ride the sparse override.
        Without a hint the intent vector is stored densely (correct, just
        not O(exceptions) — direct library use outside the controller).
        """
        if not self._fn_sparse:
            return super().state_arrays()
        exc = self.exception_fids()
        arrays: dict[str, np.ndarray] = {
            "fault_fn_sparse": np.asarray([1], dtype=np.int8),
            "fault_fn_seed": np.asarray([self._fn_seed], dtype=np.int64),
            "fault_fn_epoch": np.asarray([self._fn_epoch], dtype=np.int64),
            "fault_fn_exc_fids": exc,
            "fault_fn_exc_rows": self.replica_map[exc].copy(),
            "fault_node_up": self.node_up.copy(),
            "fault_node_decommissioned": self.node_decommissioned.copy(),
            "fault_node_partitioned": self.node_partitioned.copy(),
            "fault_node_fail_prob": self.node_fail_prob.copy(),
            "fault_node_throughput": self.node_throughput.copy(),
        }
        # Latent rot as sparse (file, slot) pairs.
        if self._n_corrupt:
            cf, cs = np.nonzero(self.slot_corrupt)
            arrays["fault_fn_corrupt_fid"] = cf.astype(np.int64)
            arrays["fault_fn_corrupt_slot"] = cs.astype(np.int32)
        # Shard intent: sparse vs the current_rf reconstruction, or dense
        # without a hint.
        if rf_hint is not None:
            default = np.clip(np.asarray(rf_hint, dtype=np.int64),
                              1, None).astype(np.int32)
            dev = np.flatnonzero(self.installed_shards != default)
            arrays["fault_fn_intent_fids"] = dev.astype(np.int64)
            arrays["fault_fn_intent_vals"] = \
                self.installed_shards[dev].copy()
        else:
            arrays["fault_fn_intent_dense"] = self.installed_shards.copy()
        # Storage-strategy state: sparse vs the replicate construction
        # defaults (min_live=1, shard_bytes=size, ec_k=0) — empty for
        # replicate-only runs, O(converted files) otherwise.
        dev = np.flatnonzero((self.min_live != 1)
                             | (self.shard_bytes != self.sizes)
                             | (self.ec_k != 0))
        arrays["fault_fn_strat_fids"] = dev.astype(np.int64)
        arrays["fault_fn_strat_min_live"] = self.min_live[dev].copy()
        arrays["fault_fn_strat_shard_bytes"] = self.shard_bytes[dev].copy()
        arrays["fault_fn_strat_ec_k"] = self.ec_k[dev].copy()
        return arrays

    def load_state_arrays(self, arrays: dict) -> None:
        if "fault_fn_sparse" not in arrays:
            # A dense snapshot (the oracle's, or a hand-built one): the
            # parent contract loads it; exception tracking restarts from
            # a full-row verify of nothing (rows may deviate from base —
            # mark everything deviating by one vectorized sweep).
            super().load_state_arrays(arrays)
            self._fn_touched = set()
            self._fn_exceptions = set()
            self._fn_exc_array = None
            self._fn_mark_deviations()
            return
        n = self.replica_map.shape[0]
        n_nodes = len(self.nodes)
        if int(arrays["fault_fn_seed"][0]) != self._fn_seed:
            raise ValueError(
                f"checkpoint placement seed "
                f"{int(arrays['fault_fn_seed'][0])} != {self._fn_seed} — "
                f"stale checkpoint? delete it to start over")
        self._fn_epoch = int(arrays["fault_fn_epoch"][0])
        # Shard intent first: the base recompute depends on it.
        if "fault_fn_intent_dense" in arrays:
            self.installed_shards = np.asarray(
                arrays["fault_fn_intent_dense"], dtype=np.int32).copy()
        else:
            if "current_rf" not in arrays:
                raise ValueError(
                    "sparse functional checkpoint needs the controller's "
                    "current_rf for intent reconstruction")
            self.installed_shards = np.clip(
                np.asarray(arrays["current_rf"], dtype=np.int64), 1,
                None).astype(np.int32)
            fids = np.asarray(arrays["fault_fn_intent_fids"],
                              dtype=np.int64)
            self.installed_shards[fids] = np.asarray(
                arrays["fault_fn_intent_vals"], dtype=np.int32)
        # Strategy state from the replicate defaults + sparse overrides.
        self.min_live = np.ones(n, dtype=np.int32)
        self.shard_bytes = self.sizes.copy()
        self.ec_k = np.zeros(n, dtype=np.int32)
        sf = np.asarray(arrays.get("fault_fn_strat_fids",
                                   np.zeros(0, np.int64)), dtype=np.int64)
        if sf.size:
            self.min_live[sf] = np.asarray(
                arrays["fault_fn_strat_min_live"], dtype=np.int32)
            self.shard_bytes[sf] = np.asarray(
                arrays["fault_fn_strat_shard_bytes"], dtype=np.int64)
            self.ec_k[sf] = np.asarray(
                arrays["fault_fn_strat_ec_k"], dtype=np.int32)
        # Recompute the base, then lay the exceptions over it.
        self.replica_map = np.full((n, n_nodes), -1, dtype=np.int32)
        chunk = 1 << 20
        for lo in range(0, n, chunk):
            hi = min(lo + chunk, n)
            self.replica_map[lo:hi] = self._fn_base_rows(
                np.arange(lo, hi, dtype=np.int64))
        exc = np.asarray(arrays["fault_fn_exc_fids"], dtype=np.int64)
        self.replica_map[exc] = np.asarray(arrays["fault_fn_exc_rows"],
                                           dtype=np.int32)
        # The snapshot's exceptions were verified at save time and the
        # base recompute is deterministic — restore them as the standing
        # set, nothing pending.
        self._fn_touched = set()
        self._fn_exceptions = set(int(f) for f in exc)
        self._fn_exc_array = None
        # Corruption + node status.
        self.slot_corrupt = np.zeros((n, n_nodes), dtype=bool)
        if "fault_fn_corrupt_fid" in arrays:
            self.slot_corrupt[
                np.asarray(arrays["fault_fn_corrupt_fid"], dtype=np.int64),
                np.asarray(arrays["fault_fn_corrupt_slot"],
                           dtype=np.int64)] = True
        self._n_corrupt = int(self.slot_corrupt.sum())
        self.node_up = np.asarray(arrays["fault_node_up"],
                                  dtype=bool).copy()
        self.node_decommissioned = np.asarray(
            arrays["fault_node_decommissioned"], dtype=bool).copy()
        self.node_partitioned = np.asarray(
            arrays["fault_node_partitioned"], dtype=bool).copy()
        self.node_fail_prob = np.asarray(
            arrays["fault_node_fail_prob"], dtype=np.float64).copy()
        self.node_throughput = np.asarray(
            arrays["fault_node_throughput"], dtype=np.float64).copy()
        self._recompute_node_bytes()
        self._refresh_all()
        self.version += 1

    def _fn_mark_deviations(self, chunk: int = 1 << 20) -> None:
        """Seed the standing-exception set with every row deviating from
        base (one vectorized sweep) — dense-snapshot loads only."""
        n = self.replica_map.shape[0]
        for lo in range(0, n, chunk):
            hi = min(lo + chunk, n)
            base = self._fn_base_rows(np.arange(lo, hi, dtype=np.int64))
            dev = np.flatnonzero((self.replica_map[lo:hi] != base)
                                 .any(axis=1))
            self._fn_exceptions.update(int(lo + f) for f in dev)
