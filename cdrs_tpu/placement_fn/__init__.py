"""Functional placement (CRUSH-style): recompute replica/stripe maps
instead of storing them.

* :mod:`.compute` — the stateless hash chooser: any subset of files
  re-places vectorized with NO per-file state, reproducing the
  rack-aware policy's structural guarantees.
* :mod:`.epoch` — cluster-map epochs; a topology change plans its
  migrations by hashing twice and comparing.
* :mod:`.state` — the ClusterState backend whose checkpoints store only
  per-file exceptions over the computed base.

See docs/ARCHITECTURE.md "Functional placement" for the hash scheme,
the exception-overlay semantics, the epoch-diff contract and the
equivalence fine print.
"""

from .compute import (
    compute_placement,
    explain_placement,
    file_keys,
    hash_priorities,
    node_salts,
    primary_on_topology,
)
from .compute import clip_shards_for_locality, hierarchical_fill
from .epoch import Epoch, EpochDiff, EpochMap, addition_moved
from .state import FunctionalClusterState, OverlayClusterState

__all__ = [
    "Epoch",
    "EpochDiff",
    "EpochMap",
    "FunctionalClusterState",
    "OverlayClusterState",
    "addition_moved",
    "clip_shards_for_locality",
    "compute_placement",
    "explain_placement",
    "file_keys",
    "hash_priorities",
    "hierarchical_fill",
    "node_salts",
    "primary_on_topology",
]
