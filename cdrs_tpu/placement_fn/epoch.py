"""Cluster-map epochs: a topology change as a hash-twice diff.

With the materialized chooser, "what moves when the topology changes" is
answered by building the new ``(n_files, max_rf)`` map and diffing it
against the stored one — O(n_files x nodes) rng + a full argsort + two
resident maps.  With the functional chooser the answer is *computed*:
place every file under the old epoch and the new epoch (two vectorized
hash passes, chunked so the working set stays cache-sized) and compare —
the CRUSH posture where a cluster-map revision is data, not a rebuild.

Because node salts are keyed by node identity (compute.node_salts), an
unchanged topology hashes to an unchanged placement: ``diff`` between
equal epochs is ZERO moves by construction (tested), and a pure node
REMOVAL prunes to the files whose computed slots held a removed node —
nobody else's priorities changed, so nobody else can move (the legacy
chooser cannot make this argument: its priority matrix is indexed by
node position, so removing one node re-rolls everyone).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .compute import compute_placement, node_salts, primary_on_topology

__all__ = ["Epoch", "EpochDiff", "EpochMap", "addition_moved"]


def addition_moved(topo_old, topo_new, n_shards: np.ndarray,
                   primary: np.ndarray, seed: int = 0, *,
                   chunk: int = 1 << 20,
                   local_mask: np.ndarray | None = None) -> np.ndarray:
    """File ids whose computed placement changes when nodes are APPENDED.

    The additive twin of ``EpochMap.diff``'s removal prune (the elastic
    scale-out path): when the new topology is the old one plus appended
    nodes (surviving names, domains, hierarchy levels and ORDER all
    preserved), a file's placement changes iff its NEW computed slots
    touch an added node — existing nodes' salts and tie-break order are
    untouched, so the added nodes' priorities merely splice into each
    file's otherwise-identical candidate sequence (and an rf re-capped
    upward by the growth necessarily drafts an added node).  One hash
    pass over the new topology, candidacy IS the moved set.  ``primary``
    must already be resolved onto the (shared) node-id space.
    """
    old_n = len(topo_old.nodes)
    prefix_ok = (
        tuple(topo_new.nodes[:old_n]) == tuple(topo_old.nodes)
        and len(topo_new.nodes) > old_n
        and tuple(topo_new.domains[:old_n] if topo_new.domains else ())
        == tuple(topo_old.domains)
        and len(topo_old.levels) == len(topo_new.levels)
        and all(a[0] == b[0] and tuple(b[1][:old_n]) == tuple(a[1])
                for a, b in zip(topo_old.levels, topo_new.levels)))
    if not prefix_ok:
        raise ValueError(
            "addition_moved needs the new topology to be the old one "
            "with nodes APPENDED (names, domains, levels and order of "
            "survivors preserved) — anything else is a general epoch "
            "diff (EpochMap.diff)")
    n = int(np.asarray(n_shards).shape[0])
    shards = np.asarray(n_shards)
    prim = np.asarray(primary)
    salts = node_salts(topo_new.nodes, seed)
    moved_parts: list[np.ndarray] = []
    for lo in range(0, n, int(chunk)):
        hi = min(lo + int(chunk), n)
        fids = np.arange(lo, hi, dtype=np.int64)
        slots, _ = compute_placement(
            fids, shards[lo:hi], prim[lo:hi], topo_new, seed,
            salts=salts,
            local_mask=None if local_mask is None else local_mask[lo:hi])
        hit = (slots >= old_n).any(axis=1)
        if hit.any():
            moved_parts.append(fids[hit])
    if not moved_parts:
        return np.zeros(0, dtype=np.int64)
    return np.concatenate(moved_parts)


@dataclass(frozen=True)
class Epoch:
    """One immutable cluster-map revision."""

    epoch_id: int
    topology: object            # cluster.placement.ClusterTopology


@dataclass
class EpochDiff:
    """Files whose computed placement moved between two epochs."""

    moved: np.ndarray           # (k,) int64 file ids that must migrate
    old_slots: np.ndarray       # (k, w) int32 placement under the old epoch
    new_slots: np.ndarray       # (k, w) int32 placement under the new epoch
    n_checked: int              # files the diff actually resolved
    pruned: bool                # True when the removal fast path applied

    def __len__(self) -> int:
        return int(self.moved.shape[0])


def _node_bitmask(slots: np.ndarray, gids: np.ndarray) -> np.ndarray:
    """(m,) uint64 bitmask of each row's node SET in a global id space.

    Placement identity across epochs is set-identity (a migration moves
    bytes between nodes; slot order is a local detail), and a <= 64-node
    global vocabulary packs the comparison into one integer per file."""
    out = np.zeros(slots.shape[0], dtype=np.uint64)
    for c in range(slots.shape[1]):
        col = slots[:, c]
        assigned = col >= 0
        out[assigned] |= np.uint64(1) << gids[col[assigned]].astype(np.uint64)
    return out


class EpochMap:
    """The cluster's topology history + the vectorized epoch diff.

    ``vocab`` is the node-name vocabulary the manifest's
    ``primary_node_id`` indexes (``manifest.nodes``); every epoch
    re-resolves primaries onto its own topology through the shared
    per-name LUT, so a removed primary re-homes deterministically
    (stable crc spread — compute.primary_on_topology).
    """

    def __init__(self, vocab, topology, seed: int = 0):
        self.vocab = tuple(vocab)
        self.seed = int(seed)
        self.epochs: list[Epoch] = [Epoch(0, topology)]

    @property
    def current(self) -> Epoch:
        return self.epochs[-1]

    def advance(self, topology) -> Epoch:
        """Install a new cluster-map revision; returns the new epoch."""
        ep = Epoch(len(self.epochs), topology)
        self.epochs.append(ep)
        return ep

    def topology(self, epoch_id: int):
        return self.epochs[epoch_id].topology

    def placement(self, epoch_id: int, file_ids: np.ndarray,
                  n_shards: np.ndarray, primary_node_id: np.ndarray,
                  out_width: int | None = None):
        """Computed slots of ``file_ids`` under one epoch (subset-safe)."""
        topo = self.topology(epoch_id)
        prim = primary_on_topology(self.vocab,
                                   np.asarray(primary_node_id), topo)
        return compute_placement(file_ids, n_shards, prim, topo,
                                 self.seed, out_width=out_width)

    # -- the diff ------------------------------------------------------------
    def diff(self, old_id: int, new_id: int, n_shards: np.ndarray,
             primary_node_id: np.ndarray, *, chunk: int = 1 << 20,
             prune: bool = True) -> EpochDiff:
        """Migration plan between two epochs: hash twice, compare.

        ``n_shards``/``primary_node_id`` are full-population vectors (the
        strategy state the controller already owns).  Chunked so the
        per-chunk priority blocks stay cache-resident at any population
        size.  ``prune=True`` engages the removal fast path when the new
        node set is a subset of the old one: only old holders of removed
        nodes are re-placed (plus files whose rf the shrink re-caps).
        """
        n = int(np.asarray(n_shards).shape[0])
        topo_old, topo_new = self.topology(old_id), self.topology(new_id)
        names_old, names_new = set(topo_old.nodes), set(topo_new.nodes)
        if old_id == new_id or (
                tuple(topo_old.nodes) == tuple(topo_new.nodes)
                and tuple(topo_old.domains) == tuple(topo_new.domains)
                and tuple(topo_old.levels) == tuple(topo_new.levels)):
            w = 0
            empty = np.zeros((0, w), dtype=np.int32)
            return EpochDiff(np.zeros(0, dtype=np.int64), empty, empty,
                             n_checked=0, pruned=True)

        # Global node-id space spanning both epochs (order: old, then new
        # additions) for the set-identity bitmasks.
        union = list(topo_old.nodes) + [x for x in topo_new.nodes
                                        if x not in names_old]
        if len(union) > 64:
            raise ValueError(
                f"epoch diff supports up to 64 distinct nodes across the "
                f"two epochs, got {len(union)}")
        gid_old = np.asarray([union.index(x) for x in topo_old.nodes],
                             dtype=np.int64)
        gid_new = np.asarray([union.index(x) for x in topo_new.nodes],
                             dtype=np.int64)

        # Pure removal = surviving nodes keep their names AND domains (a
        # node that changed racks re-rolls its priorities' meaning for
        # the domain rules, so the pruning argument no longer holds).
        dom_old = dict(zip(topo_old.nodes,
                           topo_old.domains or topo_old.nodes))
        dom_new = dict(zip(topo_new.nodes,
                           topo_new.domains or topo_new.nodes))
        # The survivors must also keep their RELATIVE ORDER: packed
        # priorities break the (astronomically rare) 26-bit tie by node
        # index, and a removal shifts indices monotonically — any other
        # reorder could flip a tie and move a non-holder.
        survivors_in_old_order = [x for x in topo_old.nodes
                                  if x in names_new]
        lvl_old = {n: tuple(d[i] for _, d in topo_old.levels)
                   for i, n in enumerate(topo_old.nodes)}
        lvl_new = {n: tuple(d[i] for _, d in topo_new.levels)
                   for i, n in enumerate(topo_new.nodes)}
        removal_only = (names_new <= names_old
                        and survivors_in_old_order == list(topo_new.nodes)
                        and all(dom_new[nd] == dom_old[nd]
                                for nd in topo_new.nodes)
                        and len(topo_old.levels) == len(topo_new.levels)
                        and all(lvl_new[nd] == lvl_old[nd]
                                for nd in topo_new.nodes))
        n_removed = len(names_old - names_new)
        use_prune = bool(prune and removal_only and n_removed)

        shards = np.asarray(n_shards)
        prim = np.asarray(primary_node_id)
        width = int(min(int(shards.max()) if n else 1,
                        max(len(topo_old), len(topo_new))))
        moved_parts: list[np.ndarray] = []
        old_parts: list[np.ndarray] = []
        new_parts: list[np.ndarray] = []
        n_checked = 0
        salts_old = node_salts(topo_old.nodes, self.seed)
        salts_new = node_salts(topo_new.nodes, self.seed)
        prim_lut_old = primary_on_topology(self.vocab,
                                           np.arange(len(self.vocab)),
                                           topo_old)
        prim_lut_new = primary_on_topology(self.vocab,
                                           np.arange(len(self.vocab)),
                                           topo_new)
        recap = len(topo_new) < len(topo_old)  # rf caps can shrink
        removed_old_idx = np.asarray(
            [list(topo_old.nodes).index(x) for x in names_old - names_new],
            dtype=np.int32)
        for lo in range(0, n, int(chunk)):
            hi = min(lo + int(chunk), n)
            fids = np.arange(lo, hi, dtype=np.int64)
            sh = shards[lo:hi]
            old_slots, _ = compute_placement(
                fids, sh, prim_lut_old[prim[lo:hi]], topo_old, self.seed,
                salts=salts_old, out_width=width)
            if use_prune:
                # A candidate is a file whose computed slots hold a
                # removed node, or whose rf the shrunken node count
                # re-caps — and for a pure removal every candidate MUST
                # move (its old set contains a node the new epoch cannot
                # place, or strictly more slots than the new cap allows)
                # while nobody else CAN (survivors' priorities and their
                # tie-break order are untouched), so candidacy IS the
                # moved set: no bitmask compare at all.
                cand = np.zeros(hi - lo, dtype=bool)
                for ri in removed_old_idx:
                    cand |= (old_slots == ri).any(axis=1)
                if recap:
                    cand |= sh > len(topo_new)
                idx = np.flatnonzero(cand)
                n_checked += int(idx.size)
                if idx.size == 0:
                    continue
                fids_c = fids[idx]
                new_slots, _ = compute_placement(
                    fids_c, sh[idx], prim_lut_new[prim[fids_c]], topo_new,
                    self.seed, salts=salts_new, out_width=width)
                moved_parts.append(fids_c)
                old_parts.append(old_slots[idx])
                new_parts.append(new_slots)
            else:
                bm_old = _node_bitmask(old_slots, gid_old)
                new_slots, _ = compute_placement(
                    fids, sh, prim_lut_new[prim[lo:hi]], topo_new,
                    self.seed, salts=salts_new, out_width=width)
                bm_new = _node_bitmask(new_slots, gid_new)
                moved_loc = np.flatnonzero(bm_old != bm_new)
                n_checked += int(hi - lo)
                if moved_loc.size:
                    moved_parts.append(fids[moved_loc])
                    old_parts.append(old_slots[moved_loc])
                    new_parts.append(new_slots[moved_loc])

        if moved_parts:
            moved = np.concatenate(moved_parts)
            old_s = np.concatenate(old_parts)
            new_s = np.concatenate(new_parts)
        else:
            moved = np.zeros(0, dtype=np.int64)
            old_s = np.zeros((0, width), dtype=np.int32)
            new_s = np.zeros((0, width), dtype=np.int32)
        return EpochDiff(moved, old_s, new_s, n_checked=n_checked,
                         pruned=use_prune)
