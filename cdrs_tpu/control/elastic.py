"""Elastic capacity: node add/drain driven by serve-layer telemetry.

The serve layer already measures the two signals an autoscaler needs —
per-window SLO burn (error-budget consumption) and peak node utilization
(serve/router.py) — and the functional placement engine already answers
the hard rebalance question ("who moves when capacity changes?") as an
epoch diff (placement_fn/epoch.py).  ``ElasticPolicy`` closes the loop:

* **scale-out** — after ``hot_windows`` consecutive windows whose burn
  or utilization crosses the hot thresholds, the standby ``pool``
  activates: the topology GROWS (appended nodes, hierarchy domains
  declared per pool entry), and the files that must move are exactly
  the addition-pruned epoch diff (``placement_fn.addition_moved`` — the
  hash-twice moved set, nobody else's computed row changes).  The moved
  set drains as a **budgeted rebalance queue**: each window, after
  repairs pre-charge the shared churn budget, queued files retarget to
  their new computed rows while the remaining byte allowance lasts — so
  flash-crowd rebalancing competes for the SAME per-window churn
  allowance as repair and drift-migration traffic instead of stacking a
  second budget.
* **drain** — once the crowd passes (``cool_windows`` consecutive cool
  windows, rebalance queue empty), the added nodes decommission on a
  rolling schedule (``drain_spacing`` windows apart — exactly the
  ``rolling_decommission`` fleet-drain shape), and the ordinary repair
  machinery re-replicates their data back onto the baseline fleet under
  the same budget.  Capacity returns to baseline; zero loss is the
  invariant, not a hope.

Scale-out requires a hash placement mode (``functional`` /
``materialized_hash``): only the stateless chooser can answer the moved
set without materializing two full maps.  Every decision is a pure
function of the window records and the policy, so kill/resume replays
identically (the counters, active set, queue and drain schedule ride
the controller checkpoint).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

__all__ = ["ElasticPolicy"]


@dataclass(frozen=True)
class ElasticPolicy:
    """Autoscaling knobs + the standby pool (see module docstring).

    ``pool`` entries are ``{"name": node, "domains": [base domain,
    level-1 domain, ...]}`` — one domain per hierarchy level, finest
    first, naming where the standby node racks when it activates.  For
    a flat topology (no ``domains``) entries may be plain name strings.
    Activation order is pool order; a scale-out activates the whole
    remaining pool (the "capacity doubles" posture) unless
    ``add_count`` limits it.  The pool is ONE-SHOT per run: drained
    nodes are decommissioned, never re-activated — a later crowd with
    the pool consumed stamps ``pool_exhausted`` on the elastic record
    instead of silently doing nothing.
    """

    pool: tuple = ()
    #: A window is HOT when its SLO burn exceeds ``burn_hot`` OR its
    #: peak node utilization exceeds ``util_hot``.
    burn_hot: float = 1.0
    util_hot: float = 0.95
    #: Consecutive hot windows before scale-out fires.
    hot_windows: int = 2
    #: A window is COOL when burn stays under ``burn_hot`` AND peak
    #: utilization under ``util_cool``.
    util_cool: float = 0.4
    #: Consecutive cool windows (queue drained) before the drain
    #: schedule is laid down.
    cool_windows: int = 3
    #: Windows between successive drain decommissions
    #: (``rolling_decommission`` spacing).
    drain_spacing: int = 2
    #: Nodes activated per scale-out; 0 = the whole remaining pool.
    add_count: int = 0

    def __post_init__(self):
        norm = []
        for e in self.pool:
            if isinstance(e, str):
                e = {"name": e, "domains": []}
            if not isinstance(e, dict) or "name" not in e:
                raise ValueError(
                    f"elastic pool entry {e!r} must be a node name or a "
                    f"{{'name': ..., 'domains': [...]}} dict")
            unknown = set(e) - {"name", "domains"}
            if unknown:
                raise ValueError(
                    f"elastic pool entry {e['name']!r}: unknown keys "
                    f"{sorted(unknown)}")
            norm.append({"name": str(e["name"]),
                         "domains": tuple(str(d)
                                          for d in e.get("domains", ()))})
        names = [e["name"] for e in norm]
        dupes = sorted({n for n in names if names.count(n) > 1})
        if dupes:
            raise ValueError(f"duplicate elastic pool nodes: {dupes}")
        object.__setattr__(self, "pool", tuple(norm))
        if not self.pool:
            raise ValueError("elastic policy needs a non-empty pool")
        for label, v in (("burn_hot", self.burn_hot),
                         ("util_hot", self.util_hot),
                         ("util_cool", self.util_cool)):
            if v <= 0:
                raise ValueError(f"elastic {label} must be > 0, got {v}")
        if self.hot_windows < 1 or self.cool_windows < 1:
            raise ValueError(
                "elastic hot_windows/cool_windows must be >= 1")
        if self.drain_spacing < 1:
            raise ValueError(
                f"elastic drain_spacing must be >= 1, got "
                f"{self.drain_spacing}")
        if self.add_count < 0:
            raise ValueError(
                f"elastic add_count must be >= 0, got {self.add_count}")

    # -- spec round trip ----------------------------------------------------
    @classmethod
    def from_dict(cls, d: dict) -> "ElasticPolicy":
        allowed = {"pool", "burn_hot", "util_hot", "hot_windows",
                   "util_cool", "cool_windows", "drain_spacing",
                   "add_count"}
        unknown = sorted(set(d) - allowed)
        if unknown:
            raise ValueError(f"unknown elastic policy keys: {unknown}")
        kw = dict(d)
        if "pool" in kw:
            kw["pool"] = tuple(kw["pool"])
        return cls(**kw)

    def to_dict(self) -> dict:
        return {
            "pool": [{"name": e["name"],
                      "domains": list(e["domains"])}
                     for e in self.pool],
            "burn_hot": self.burn_hot, "util_hot": self.util_hot,
            "hot_windows": self.hot_windows,
            "util_cool": self.util_cool,
            "cool_windows": self.cool_windows,
            "drain_spacing": self.drain_spacing,
            "add_count": self.add_count,
        }

    # -- topology growth ----------------------------------------------------
    def validate_against(self, topology) -> None:
        """Fail fast at controller construction: every pool entry must
        declare one domain per hierarchy level of the topology it will
        join (or none, for a flat topology), and must not collide with
        an existing node name."""
        want = (0 if not topology.domains
                else getattr(topology, "n_levels", 0) + 1)
        for e in self.pool:
            if e["name"] in topology.nodes:
                raise ValueError(
                    f"elastic pool node {e['name']!r} already exists in "
                    f"the topology")
            if len(e["domains"]) != want:
                raise ValueError(
                    f"elastic pool node {e['name']!r} declares "
                    f"{len(e['domains'])} domains for a topology with "
                    f"{want} hierarchy levels "
                    f"({tuple(topology.level_names) if want else '(flat)'}"
                    f") — one per level, finest first")

    def grown_topology(self, base, names):
        """``base`` with the named pool nodes APPENDED (activation
        order), each racked into the domains its pool entry declares —
        the strict-prefix growth ``ClusterState.grow`` and
        ``addition_moved`` require."""
        from ..cluster.placement import ClusterTopology

        chosen = [e for e in self.pool if e["name"] in set(names)]
        nodes = tuple(base.nodes) + tuple(e["name"] for e in chosen)
        domains = tuple(base.domains)
        if domains:
            domains = domains + tuple(e["domains"][0] for e in chosen)
        levels = tuple(
            (nm, tuple(doms) + tuple(e["domains"][i + 1]
                                     for e in chosen))
            for i, (nm, doms) in enumerate(base.levels))
        return ClusterTopology(
            nodes=nodes, domains=domains, levels=levels,
            edge_bytes=base.edge_bytes, edge_latency=base.edge_latency,
            domain_level_name=base.domain_level_name)

    def next_activation(self, active) -> tuple[str, ...]:
        """Pool names the next scale-out activates (pool order, minus
        the already-active set, capped by ``add_count``)."""
        remaining = [e["name"] for e in self.pool
                     if e["name"] not in set(active)]
        if self.add_count:
            remaining = remaining[:self.add_count]
        return tuple(remaining)


@dataclass
class _ElasticRuntime:
    """Mutable controller-side autoscaler state (rides the checkpoint)."""

    policy: ElasticPolicy
    hot: int = 0
    cool: int = 0
    active: tuple = ()
    #: Files still awaiting their post-growth rebalance (epoch-diff
    #: moved set, drained under the shared churn budget).
    queue: np.ndarray = field(
        default_factory=lambda: np.zeros(0, dtype=np.int64))
    moved_total: int = 0
    #: Pending rolling-drain decommissions: [(window, node), ...].
    drains: list = field(default_factory=list)
    scaled: bool = False
    #: Previous window's (slo_burn, utilization_max) — the decision
    #: inputs (a scale decision at window w reads window w-1's serving).
    last_burn: float | None = None
    last_util: float | None = None
