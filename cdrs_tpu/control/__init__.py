"""Online replication control: windowed drift detection -> incremental
re-cluster -> bounded-churn migration (see control/controller.py)."""

from .controller import ControllerConfig, ControllerResult, \
    ReplicationController
from .drift import DriftReport, detect_drift
from .elastic import ElasticPolicy
from .migrate import MigrationScheduler, PlanMove, plan_diff
from .windows import iter_windows

__all__ = [
    "ControllerConfig", "ControllerResult", "ReplicationController",
    "DriftReport", "detect_drift",
    "ElasticPolicy",
    "MigrationScheduler", "PlanMove", "plan_diff",
    "iter_windows",
]
