"""Online replication controller: the batch pipeline as a control loop.

The batch pipeline (pipeline.py) decides replication factors exactly once
over a static log; access patterns shift, so the dynamic-replication
literature (CDRM-style popularity-driven replica adjustment) treats the
decision as a *continuous* loop.  This module wires the primitives the repo
already owns into that loop.  Per time window (control/windows.py):

1. **fold** — window events fold into the carried streaming feature state
   (features/streaming_np for the numpy backend, features/streaming for the
   jax backend; exact cross-window concurrency carry).  An optional
   per-window exponential ``decay`` (numpy backend) re-weights the counters
   toward recent traffic so a mid-stream workload shift is visible through
   the cumulative history.
2. **drift** — the cheap detector (control/drift.py) scores the feature
   snapshot against the last ACCEPTED model; below ``drift_threshold``
   nothing else runs.
3. **re-cluster** — on drift, a warm-started re-cluster (``init_centroids``
   = accepted centroids, ``warm_max_iter`` Lloyd iterations — with the jax
   backend and ``kmeans.batch_size`` set this is the incremental mini-batch
   path, ops/kmeans_stream.py) or, past ``full_recluster_drift``, a full
   re-cluster with a fresh init.  Scoring reuses ReplicationPolicyModel.
4. **diff + schedule** — the new plan is diffed against the currently
   APPLIED plan (control/migrate.plan_diff; priority = scoring margin of
   the new category over the applied one) and handed to the bounded-churn
   MigrationScheduler (byte/file budget per window, hysteresis).
5. **apply + evaluate** — scheduled moves mutate the applied plan; the
   simulated cluster (cluster/placement.py + cluster/evaluate.py) replays
   the window's events against placements before and after the moves, so
   the controller's benefit is measured, not assumed.

Every window emits one structured record (events folded, drift score,
re-cluster trigger/mode, plan delta, bytes migrated, locality/balance
before/after, per-stage wall clock, plan hash) to an in-memory list and an
optional JSONL sink.  The whole controller state — feature carry, accepted
model, applied plan, scheduler backlog — snapshots through the
utils/checkpoint atomic-npz contract: kill/resume reproduces the
uninterrupted run's plan sequence bit-identically (enforced by
tests/test_control.py).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import time
from dataclasses import dataclass, field

import numpy as np

from ..config import CATEGORIES, KMeansConfig, ScoringConfig
from ..io.events import EventLog, Manifest
from ..models.replication import ReplicationPolicyModel
from .drift import detect_drift, detect_drift_jax
from .migrate import MigrationScheduler, plan_diff
from .windows import iter_windows

__all__ = ["ControllerConfig", "ControllerResult", "ReplicationController",
           "MOVE_CAUSES", "LINEAGE_ID_CAP"]

#: Decision-provenance cause vocabulary: why an admitted move happened.
#: Codes 1..3 ride the per-file ``_move_cause`` vector (migration moves
#: keep their cause across backlog windows and checkpoints); the rest
#: are tagged at their emission site (repair pass, elastic machinery).
#: Code 0 = unknown (a backlog resumed from a pre-provenance snapshot).
MOVE_CAUSES = {0: "unknown", 1: "drift", 2: "hotspot", 3: "conversion"}

#: Per-lineage-event file-id cap: counts/bytes stay EXACT past it, only
#: the id listing truncates (stamped ``truncated``) — a 100M-file epoch
#: change must not write a 100M-integer JSON line.
LINEAGE_ID_CAP = 4096


@dataclass
class ControllerConfig:
    """Knobs of the online control loop (see module docstring for the loop)."""

    window_seconds: float = 60.0
    #: Drift score (control/drift.py: max of centroid-shift RMS and category
    #: population TV-distance) at/above which a re-cluster runs.
    drift_threshold: float = 0.05
    #: Drift at/above which the re-cluster abandons the warm start (fresh
    #: init, full iteration budget) — the model is assumed stale.
    full_recluster_drift: float = 0.30
    #: Lloyd budget of a warm-started re-cluster.
    warm_max_iter: int = 25
    #: Per-window churn budget (None = unbounded).
    max_bytes_per_window: int | None = None
    max_files_per_window: int | None = None
    #: Windows a migrated file stays frozen after a move (anti-flap).
    hysteresis_windows: int = 1
    #: Per-window exponential decay of the feature counters (1.0 = exact
    #: cumulative fold, the batch pipeline's semantics).  < 1.0 re-weights
    #: toward recent windows (numpy backend only) so shifts surface faster.
    decay: float = 1.0
    #: rf applied to files before the first accepted plan.
    default_rf: int = 1
    backend: str = "numpy"
    kmeans: KMeansConfig = field(default_factory=lambda: KMeansConfig(k=8))
    scoring: ScoringConfig = field(default_factory=ScoringConfig)
    #: Device mesh for the per-window device computation (jax backend):
    #: ``{"data": N}`` shards the cluster step, scoring medians, streaming
    #: feature fold AND the drift detector's one-Lloyd-step data-parallel
    #: over files (one psum of (k, d+1) sufficient statistics per
    #: iteration; the (n, k) distance matrix and the feature table never
    #: gather to one device).  A RUNTIME choice, not checkpoint state: a
    #: checkpoint written at ``data=1`` resumes at ``data=8`` and vice
    #: versa with identical decisions (drift scalars agree to fp
    #: tolerance — float psum association).  ``None`` = the historical
    #: single-device path, kept as the equivalence oracle.
    mesh_shape: dict[str, int] | None = None
    #: Replay window events against the simulated cluster before/after the
    #: window's moves (cluster/evaluate.py).
    evaluate: bool = True
    #: Fault feed (faults/schedule.FaultSchedule): node crash/recover/
    #: decommission/flaky/partition/degrade events keyed to window
    #: indices.  When set the controller maintains a mutable ClusterState,
    #: accounts durability tiers per window, and runs the repair planner
    #: against the SAME byte/file churn budget as drift migrations
    #: (repairs first).
    fault_schedule: object | None = None
    #: Seed of the deterministic flaky-target failure rolls
    #: (faults/repair.py) — stateless, so kill/resume replays them.
    repair_seed: int = 0
    #: Failure-domain topology (cluster/placement.ClusterTopology) for the
    #: fault path: maps nodes to racks/zones so placement and repair
    #: spread replicas across domains.  None = flat (every manifest node
    #: its own domain).  Node set must equal the manifest's.
    topology: object | None = None
    #: Read-path serving (serve/router.ServeConfig): when set, every
    #: window's reads route through the vectorized replica-selection
    #: router against the live placement (reachability + straggler
    #: factors when a fault schedule is also set), adding latency
    #: p50/p95/p99, SLO burn, utilization and hotspot fields to the
    #: window records — and, with ``recluster_on_hotspot``, feeding the
    #: hotspot detector back into the re-cluster trigger as a drift
    #: signal (a flash crowd re-clusters the window it lands, without
    #: waiting for the cumulative feature fold).
    serve: object | None = None
    #: Storage strategies (storage/strategy.StorageConfig): when set,
    #: each category resolves to ``replicate(rf)`` or ``ec(k, m)`` on a
    #: storage tier instead of the scoring rf table.  Shard counts drive
    #: placement/migration targets, the faults layer accounts stripe
    #: durability (lost below k live shards) and charges EC
    #: reconstruction reads against the churn budget, the serve router
    #: adds tier/degraded-read latency, and every window record carries
    #: a ``storage`` byte/cost digest.  A config with only ``replicate``
    #: strategies reproduces the historical behaviour bit-for-bit.
    storage: object | None = None
    #: Background scrubber (faults/scrub.ScrubConfig): when set (fault
    #: mode only), every window verification-reads the next slice of the
    #: population round-robin under ``bytes_per_window`` — capped by what
    #: remains of the SHARED churn budget after repairs — quarantining
    #: the silent corruption it finds into the repair queue.  The scrub
    #: cursor and read-detection hint queue ride the npz checkpoint.
    scrub: object | None = None
    #: Elastic capacity (control/elastic.ElasticPolicy): when set, the
    #: controller watches the serve layer's per-window SLO burn and
    #: utilization; sustained heat activates the standby pool (topology
    #: grows, the addition-pruned epoch diff becomes a budgeted
    #: rebalance queue) and sustained cool rolls the added nodes back
    #: out via rolling decommission.  Requires ``serve`` (the telemetry
    #: source) and a hash placement mode (the epoch diff); implies the
    #: fault machinery (an empty schedule is synthesized when none is
    #: given).
    elastic: object | None = None
    #: Placement representation (placement_fn/, ROADMAP item 3):
    #: ``"materialized"`` (default) is the historical rng chooser + dense
    #: replica-map state — byte-identical to every pre-placement-mode
    #: run.  ``"functional"`` switches the base placement to the
    #: stateless hash chooser (``place_replicas(method="hash")`` /
    #: ``placement_fn.compute_placement``): the fault path runs a
    #: ``FunctionalClusterState`` whose checkpoints store only per-file
    #: EXCEPTIONS over the computed base (npz size stops scaling with
    #: file count), and serve-mode reads resolve their replica rows on
    #: the fly (O(unique pids) router memory).  ``"materialized_hash"``
    #: is the equivalence ORACLE: the same hash chooser and retarget
    #: policy over the dense representation and dense checkpoints — a
    #: functional run must be decision-identical to it (the PR-8 compat
    #: pattern; enforced by tests/test_placement_fn.py on 3 seeds).
    placement_mode: str = "materialized"
    #: Double-buffered windows: dispatch window t+1's (already jit'd)
    #: cluster step before window t's host-side planning runs, so JAX's
    #: async dispatch keeps the device busy while the host diffs plans,
    #: admits migrations and runs repairs.  Decision/record-identical to
    #: the serial order (the phases touch disjoint state; enforced by
    #: tests): only wall-clock moves.  Overlap is suspended around
    #: checkpoints — a snapshot must not contain the next window's fold —
    #: so ``checkpoint_every=1`` degenerates to the serial schedule.
    #: Meaningful on the jax backend; accepted (as a no-op pipeline) on
    #: numpy.
    overlap_windows: bool = False

    def __post_init__(self):
        if self.window_seconds <= 0:
            raise ValueError(
                f"window_seconds must be > 0, got {self.window_seconds}")
        if not 0.0 < self.decay <= 1.0:
            raise ValueError(f"decay must be in (0, 1], got {self.decay}")
        if self.backend not in ("numpy", "jax"):
            raise ValueError(f"unknown backend {self.backend!r}")
        if self.decay < 1.0 and self.backend != "numpy":
            raise ValueError(
                "decay < 1.0 requires backend='numpy' (the jax fold's "
                "cross-batch concurrency carry has no decayed analogue)")
        if self.drift_threshold < 0 or self.full_recluster_drift < 0:
            raise ValueError("drift thresholds must be >= 0")
        if self.mesh_shape is not None:
            # Backend check BEFORE the axis validation: validate_mesh_shape
            # lives in parallel/ which imports jax, and a numpy-backend
            # config must fail with the real reason, not an ImportError.
            if self.backend != "jax":
                raise ValueError(
                    "mesh_shape requires backend='jax' (the numpy backend "
                    "is the single-host oracle; drop mesh_shape or switch "
                    "backends)")
            from ..parallel.mesh import validate_mesh_shape

            self.mesh_shape = validate_mesh_shape(self.mesh_shape)
        if self.scrub is not None and self.fault_schedule is None:
            raise ValueError(
                "scrub requires a fault_schedule (the scrubber verifies "
                "the mutable ClusterState the fault path maintains)")
        if self.placement_mode not in ("materialized", "functional",
                                       "materialized_hash"):
            raise ValueError(
                f"unknown placement_mode {self.placement_mode!r} (want "
                f"'materialized', 'functional' or 'materialized_hash')")
        if self.elastic is not None:
            if self.serve is None:
                raise ValueError(
                    "elastic requires serve (the SLO-burn/utilization "
                    "telemetry that drives the scale decisions)")
            if self.placement_mode == "materialized":
                raise ValueError(
                    "elastic scale-out requires a hash placement mode "
                    "('functional' or 'materialized_hash') — the "
                    "rebalance plan is the addition-pruned epoch diff, "
                    "which only the stateless chooser can answer")


@dataclass
class ControllerResult:
    """Final controller state + the per-window record stream."""

    records: list[dict]
    rf: np.ndarray             # (n,) applied replication factor per file
    category_idx: np.ndarray   # (n,) applied category index, -1 = unplanned
    manifest: Manifest
    #: Per-save checkpoint observations ({window, bytes, seconds}) — the
    #: artifact behind the O(exceptions)-checkpoint claim (placement_fn).
    checkpoints: list = field(default_factory=list)

    def plan_entries(self):
        """The applied plan as cluster/plan.PlanEntry rows (exportable)."""
        from ..cluster.plan import PlanEntry

        return [PlanEntry(p, CATEGORIES[int(c)] if c >= 0 else "Unplanned",
                          int(r))
                for p, c, r in zip(self.manifest.paths, self.category_idx,
                                   self.rf)]

    def summary(self) -> dict:
        recl = [r for r in self.records if r["recluster"]]
        out = {
            "windows": len(self.records),
            "events": int(sum(r["n_events"] for r in self.records)),
            "reclusters": len(recl),
            "full_reclusters": sum(1 for r in recl
                                   if r["recluster_mode"] == "full"),
            "moves_applied": int(sum(r["moves_applied"]
                                     for r in self.records)),
            "bytes_migrated": int(sum(r["bytes_migrated"]
                                      for r in self.records)),
            # From the APPLIED plan, not the records: a resume run that
            # processed zero new windows still reports the real plan.
            "final_plan_hash": _plan_hash(self.rf, self.category_idx),
        }
        # End-to-end pacing: windows per second of host wall-clock, and
        # the planning slice of it (the SoA control-plane observable;
        # plan_bench tracks the same two numbers at scale, and `cdrs
        # metrics summarize` digests the same records via pacing_digest).
        from ..obs.aggregate import pacing_digest

        pacing = pacing_digest(self.records)
        if pacing:
            out["windows_per_sec"] = round(pacing["windows_per_sec"], 3)
            if "plan_seconds_fraction" in pacing:
                out["plan_seconds_fraction"] = round(
                    pacing["plan_seconds_fraction"], 4)
        dur = [r for r in self.records if r.get("durability")]
        if dur:
            last = dur[-1]["durability"]
            out["durability"] = {
                "fault_events": sum(len(r.get("fault_events") or ())
                                    for r in self.records),
                "files_lost_max": max(r["durability"]["lost"]
                                      for r in dur),
                "unreachable_max": max(r["durability"].get("unreachable", 0)
                                       for r in dur),
                "correlated_risk_max": max(
                    r["durability"].get("correlated_risk", 0) for r in dur),
                "lost_final": last["lost"],
                "at_risk_final": last["at_risk"],
                "under_replicated_final": last["under_replicated"],
                "unreachable_final": last.get("unreachable", 0),
                "correlated_risk_final": last.get("correlated_risk", 0),
                "nodes_up_final": last["nodes_up"],
                "repair_moves_total": int(sum(r.get("repair_moves", 0)
                                              for r in self.records)),
                "repair_bytes_total": int(sum(r.get("repair_bytes", 0)
                                              for r in self.records)),
                "repair_failed_total": int(sum(r.get("repair_failed", 0)
                                               for r in self.records)),
                "repair_rebalanced_total": int(sum(
                    r.get("repair_rebalanced", 0) for r in self.records)),
                "partition_stalled_repairs": int(sum(
                    r.get("repair_deferred_partition", 0)
                    for r in self.records)),
                "unavailable_reads": int(sum(
                    r.get("unavailable_reads", 0) for r in self.records)),
            }
            # Length-normalized: raw unavailable counts from runs of
            # different window counts are not comparable (older records
            # lack n_reads; fall back to the event count).
            n_reads = sum(int(r.get("n_reads", 0)) for r in self.records)
            denom = n_reads or out["events"]
            out["durability"]["unavailable_read_fraction"] = (
                out["durability"]["unavailable_reads"] / denom if denom
                else 0.0)
        from ..obs.aggregate import (
            integrity_digest,
            serve_digest,
            storage_digest,
        )

        if self.checkpoints:
            last = self.checkpoints[-1]
            out["checkpoint"] = {
                "saves": len(self.checkpoints),
                "bytes_last": int(last["bytes"]),
                "save_seconds_last": float(last["seconds"]),
            }
        serve = serve_digest(self.records)
        if serve is not None:
            out["serve"] = serve
        storage = storage_digest(self.records)
        if storage is not None:
            out["storage"] = storage
        integrity = integrity_digest(self.records)
        if integrity is not None:
            out["integrity"] = integrity
        return out


def _plan_hash(rf: np.ndarray, cat: np.ndarray) -> str:
    h = hashlib.blake2b(digest_size=8)
    h.update(np.ascontiguousarray(rf, dtype=np.int32).tobytes())
    h.update(np.ascontiguousarray(cat, dtype=np.int32).tobytes())
    return h.hexdigest()


class ReplicationController:
    """Drives the window loop; one instance = one controlled population."""

    #: Cumulative feature-counter fields shared with the streaming backends.
    _NP_STATE = ("access_freq", "writes", "local_acc", "conc_max",
                 "last_sec", "last_count")

    def __init__(self, manifest: Manifest, cfg: ControllerConfig):
        n = len(manifest)
        if n < cfg.kmeans.k:
            raise ValueError(
                f"{n} files < k={cfg.kmeans.k}; cannot control this "
                f"population")
        self.manifest = manifest
        self.cfg = cfg
        self._sizes = np.asarray(manifest.size_bytes, dtype=np.int64)

        if cfg.backend == "numpy":
            from ..features.streaming_np import stream_init_np

            self._state = stream_init_np(n)
        else:
            from ..features.streaming import stream_init

            self._state = stream_init(n)
        # Decayed counters (numpy decay < 1 only): float64 views of the same
        # five counters, re-weighted per window.
        self._dec = None
        if cfg.decay < 1.0:
            self._dec = {k: np.zeros(n) for k in
                         ("access_freq", "writes", "local_acc", "conc_max")}
            self._dec_obs_end: float | None = None
        self._events_total = 0

        self._model_full = self._make_model(warm=False)
        self._model_warm = self._make_model(warm=True)

        self._accepted_centroids: np.ndarray | None = None
        self._accepted_category_idx: np.ndarray | None = None
        self._accepted_fractions: np.ndarray | None = None
        #: Per-file category of the last MATERIALIZED decision —
        #: ``_accept_plan`` reuses this gather instead of recomputing it.
        self._accepted_file_cat: np.ndarray | None = None
        #: Most recent accepted decision not yet materialized to host
        #: arrays — with ``overlap_windows`` the jax result stays a lazy
        #: device future until the first host consumer (drift, checkpoint)
        #: blocks on it, which is what lets window t+1's cluster step run
        #: under window t's planning.
        self._pending_accept = None

        #: Storage-strategy vectors (storage/): None = historical rf
        #: semantics.  Resolved here so a bad strategy (EC k < 1, unknown
        #: tier, typo'd category) fails at construction, not mid-run.
        self._storage = None
        if cfg.storage is not None:
            self._storage = cfg.storage.vectors(
                CATEGORIES, cfg.scoring.replication_factors)
            # Replicate rf caps at the node count (the placement cap); an
            # EC stripe CANNOT — fewer than k+m distinct nodes means the
            # stripe never reaches full strength and below k it cannot
            # reconstruct, so the storage record would report bytes no
            # real cluster could hold.
            n_nodes = (len(cfg.topology.nodes) if cfg.topology is not None
                       else len(manifest.nodes))
            sv = self._storage
            for i, c in enumerate(sv.categories):
                if sv.ec_k[i] > 0 and int(sv.n_shards[i]) > n_nodes:
                    raise ValueError(
                        f"storage strategy for category {c!r} needs "
                        f"{int(sv.n_shards[i])} distinct nodes for its "
                        f"EC stripe but the topology has {n_nodes}")

        self.current_rf = np.full(n, int(cfg.default_rf), dtype=np.int32)
        self.current_cat = np.full(n, -1, dtype=np.int32)
        #: Decision provenance: cause code (MOVE_CAUSES) of each file's
        #: pending submitted move — written at plan submission, read at
        #: admission, checkpointed so a resumed backlog keeps its story.
        self._move_cause = np.zeros(n, dtype=np.int8)
        #: One window's lineage batches [(cause, file_ids, bytes)] —
        #: built in phase B, emitted by ``_instrument_window`` as
        #: ``lineage`` events and digested into the record's ``causes``.
        self._lineage: list[tuple[str, np.ndarray, int]] = []
        #: Category whose strategy is actually INSTALLED per file.  A
        #: deferred conversion (apply_strategy_target refused while the
        #: file was unreadable) keeps the OLD encoding on disk, so byte
        #: billing and read penalties follow this vector while the
        #: target follows current_cat; they re-converge when the
        #: reconcile pass lands the re-encode.
        self._installed_cat = self.current_cat.copy()
        self.scheduler = MigrationScheduler(
            n, max_bytes_per_window=cfg.max_bytes_per_window,
            max_files_per_window=cfg.max_files_per_window,
            hysteresis_windows=cfg.hysteresis_windows)
        self._placement_key: bytes | None = None
        self._placement = None
        #: Placement representation (placement_fn/): "materialized" is
        #: the historical rng chooser; the hash family ("functional",
        #: "materialized_hash") shares the stateless chooser so replica
        #: rows can be recomputed for any file subset.
        self._hash_placement = cfg.placement_mode != "materialized"
        self._placement_method = "hash" if self._hash_placement else "rng"
        #: Fault-tolerance state (faults/): when a schedule is set, or
        #: when elasticity needs the mutable cluster (drains decommission
        #: through it; growth extends it).
        self._cluster_state = None
        self._repairs = None
        #: The RESOLVED schedule (region scopes expanded against the
        #: topology) — the one phase B consumes.
        self._fault_schedule = None
        self._elastic = None
        if cfg.fault_schedule is not None or cfg.elastic is not None:
            from ..cluster import ClusterTopology, place_replicas
            from ..faults import ClusterState, FaultSchedule, \
                RepairScheduler

            topology = cfg.topology or ClusterTopology(
                nodes=tuple(manifest.nodes))
            if set(topology.nodes) != set(manifest.nodes):
                raise ValueError(
                    f"topology nodes {tuple(topology.nodes)} != manifest "
                    f"nodes {tuple(manifest.nodes)} — the failure-domain "
                    f"topology must cover exactly the manifest's node set")
            schedule = (cfg.fault_schedule if cfg.fault_schedule
                        is not None else FaultSchedule(()))
            # Region-scoped events (crash:region:eu) resolve against the
            # hierarchy here — unknown levels/domains fail at
            # construction naming the offending token.
            schedule = schedule.expand_domains(topology)
            schedule.validate_nodes(topology.nodes)
            self._fault_schedule = schedule
            if cfg.elastic is not None:
                from .elastic import _ElasticRuntime

                cfg.elastic.validate_against(topology)
                self._elastic = _ElasticRuntime(cfg.elastic)
            if cfg.placement_mode == "functional":
                # Lowmem functional backend: NO dense map is ever
                # materialized — construction is pure base form
                # (placement_fn.OverlayClusterState), and the resident
                # placement state is the exception overlay itself.
                from ..placement_fn import (
                    OverlayClusterState,
                    primary_on_topology,
                )

                self._cluster_state = OverlayClusterState.from_base(
                    topology, self._sizes,
                    n_shards=self.current_rf,
                    primary=primary_on_topology(
                        manifest.nodes, manifest.primary_node_id,
                        topology),
                    seed=0)
            elif self._hash_placement:
                from ..placement_fn import (
                    FunctionalClusterState,
                    primary_on_topology,
                )

                placement = place_replicas(manifest, self.current_rf,
                                           topology, seed=0,
                                           method=self._placement_method)
                self._cluster_state = FunctionalClusterState(
                    placement, self._sizes,
                    primary=primary_on_topology(
                        manifest.nodes, manifest.primary_node_id,
                        topology),
                    seed=0, sparse_checkpoint=False)
            else:
                placement = place_replicas(manifest, self.current_rf,
                                           topology, seed=0,
                                           method=self._placement_method)
                self._cluster_state = ClusterState(placement, self._sizes)
            self._repairs = RepairScheduler(seed=cfg.repair_seed)
        #: Integrity layer: the background scrubber (faults/scrub.py) and
        #: the static "does this run care about integrity at all" flag —
        #: per-window integrity records are emitted when corruption can
        #: happen (a corrupt fault is scheduled) or is looked for (scrub
        #: on), so pre-integrity runs keep byte-identical records.
        self._scrub = None
        if cfg.scrub is not None:
            from ..faults import Scrubber

            self._scrub = Scrubber(n, cfg.scrub)
        self._integrity_on = self._cluster_state is not None and (
            self._scrub is not None
            or any(ev.kind == "corrupt" for ev in self._fault_schedule))
        #: Serving layer (serve/): router + hotspot detector, only when a
        #: ServeConfig is set.  The router is stateless per window; the
        #: hotspot EWMA is the ONLY serve state and rides the checkpoint.
        self._router = None
        self._hotspot = None
        self._serve_topology = None
        self._last_latency_ms: np.ndarray | None = None
        if cfg.serve is not None:
            from ..serve import HotspotDetector, ReadRouter

            if self._cluster_state is not None:
                self._serve_topology = self._cluster_state.topology
            else:
                from ..cluster import ClusterTopology

                self._serve_topology = cfg.topology or ClusterTopology(
                    nodes=tuple(manifest.nodes))
            self._router = ReadRouter(len(self._serve_topology.nodes),
                                      cfg.serve)
            self._edge_ms = self._edge_latency_ms(self._serve_topology)
            self._hotspot = HotspotDetector(
                n, alpha=cfg.serve.hotspot_alpha,
                spike_factor=cfg.serve.hotspot_spike_factor,
                min_reads=cfg.serve.hotspot_min_reads,
                top_k=cfg.serve.hotspot_top_k)
        #: Lazy primary LUT of the functional static-serve resolver.
        self._fn_static_primary = None
        #: Mesh telemetry template (mesh runs only): device count and the
        #: per-Lloyd-iteration collective-traffic estimate — one psum of
        #: the f32 (k, d+1) sufficient statistics over the data axis —
        #: stamped on every window record so `cdrs metrics summarize` can
        #: read windows/sec against mesh size.  Pre-mesh runs carry no
        #: ``mesh`` key: their records stay byte-identical.
        self._mesh_rec = None
        if cfg.mesh_shape is not None:
            from ..parallel.mesh import collective_bytes_estimate

            ndev = 1
            for v in cfg.mesh_shape.values():
                ndev *= int(v)
            d_feat = len(cfg.scoring.features)
            payload = int(cfg.kmeans.k) * (d_feat + 1) * 4
            self._mesh_rec = {
                "devices": ndev,
                "collective_bytes_per_iter": collective_bytes_estimate(
                    payload, int(cfg.mesh_shape.get("data", 1))),
            }
        #: One warning per controller when the jax kernel path degrades to
        #: the numpy fallback (fault-tolerance part 4).
        self._kernel_fallback_warned = False
        #: Lazy numpy fallback models, built at the first kernel failure.
        self._fallback_models: dict[bool, ReplicationPolicyModel] = {}
        #: Lazy decision-quality auditor (obs/audit.py); created at the
        #: first audited window so telemetry-off runs never import it.
        self._auditor = None
        #: Per-save {window, bytes, seconds} observations (save_state
        #: additionally emits checkpoint.* gauges when telemetry is on).
        self.checkpoint_log: list[dict] = []
        self.window_index = 0
        #: Events folded from the FINAL processed window — lets a resume
        #: over a grown (append-only) log fold that window's late tail
        #: instead of silently dropping it.
        self._last_window_events = 0
        self._t0: float | None = None
        #: Degraded-mode levers the streaming daemon's brownout ladder
        #: (daemon/brownout.py) pulls: a subset of its rung names.  The
        #: controller only honours the two that change ITS work —
        #: ``defer_scrub`` (skip the window's verification pass; known
        #: damage still heals) and ``shed_reads`` (via ``serve_shed``).
        #: Always empty outside a brownout-enabled daemon, so batch
        #: records stay byte-identical.
        self.degraded_modes: frozenset = frozenset()
        #: Serve-path load shedding, ``(fraction, seed)`` or None: a
        #: seeded per-window draw drops that fraction of the window's
        #: reads BEFORE routing — an explicit shed, not a timeout.
        self.serve_shed: tuple | None = None

    def _make_model(self, warm: bool,
                    backend: str | None = None) -> ReplicationPolicyModel:
        """The full or warm-start policy model (warm = the bounded
        ``warm_max_iter`` Lloyd budget).  ``backend`` overrides the
        configured one — the degraded numpy fallback's only difference."""
        cfg = self.cfg
        km = cfg.kmeans if not warm else dataclasses.replace(
            cfg.kmeans, max_iter=cfg.warm_max_iter)
        backend = backend or cfg.backend
        return ReplicationPolicyModel(
            kmeans_cfg=km, scoring_cfg=cfg.scoring, backend=backend,
            mesh_shape=cfg.mesh_shape if backend == cfg.backend else None)

    # -- feature fold ------------------------------------------------------
    def _fold_window(self, events: EventLog, new_window: bool = True) -> None:
        """Fold events into the carried state.  ``new_window=False`` folds a
        late-arriving tail of the ALREADY-processed final window (resume
        over a grown log): same fold, but the decayed accumulators are not
        re-decayed — the tail belongs to the window whose decay already
        applied."""
        if self.cfg.backend == "jax":
            from ..features.streaming import stream_update

            self._state = stream_update(self._state, events, self.manifest,
                                        mesh_shape=self.cfg.mesh_shape)
            self._events_total = self._state.n_events
            return
        from ..features.streaming_np import stream_init_np, stream_update_np

        if self._dec is None:
            self._state = stream_update_np(self._state, events, self.manifest)
            self._events_total = self._state.n_events
            return
        # Decayed mode: each window folds into a FRESH state (the exact
        # streaming fold over the window's events), then merges into the
        # decayed accumulators.  A (file, second) concurrency bucket split
        # exactly across a window boundary counts per window — an accepted
        # approximation of the recency re-weighting mode.
        ws = stream_update_np(stream_init_np(len(self.manifest)), events,
                              self.manifest)
        g = self.cfg.decay if new_window else 1.0
        for k in ("access_freq", "writes", "local_acc"):
            self._dec[k] *= g
            self._dec[k] += getattr(ws, k)
        np.maximum(self._dec["conc_max"] * g, ws.conc_max,
                   out=self._dec["conc_max"])
        if ws.observation_end is not None:
            self._dec_obs_end = ws.observation_end if self._dec_obs_end \
                is None else max(self._dec_obs_end, ws.observation_end)
        self._events_total += ws.n_events

    def _feature_snapshot(self) -> np.ndarray:
        """(n, 5) normalized feature matrix from the carried state."""
        if self._dec is not None:
            from ..features.streaming_np import finalize_counters

            table = finalize_counters(
                self._dec["access_freq"], self._dec["writes"],
                self._dec["local_acc"], self._dec["conc_max"],
                self.manifest, self._dec_obs_end)
        elif self.cfg.backend == "jax":
            from ..features.streaming import stream_finalize

            table = stream_finalize(self._state, self.manifest)
        else:
            from ..features.streaming_np import stream_finalize_np

            table = stream_finalize_np(self._state, self.manifest)
        # float32 on the jax backend: a float64 matrix (or warm-start
        # centroids) would be truncated by jax anyway, with a per-call
        # UserWarning; the numpy backend keeps the pipeline's float64.
        dtype = np.float64 if self.cfg.backend == "numpy" else np.float32
        return np.asarray(table.norm, dtype=dtype)

    # -- one window --------------------------------------------------------
    def process_window(self, w: int, events: EventLog) -> dict:
        """Serial per-window step: phase A (fold, drift, cluster-step
        dispatch) immediately followed by phase B (host planning).  The
        overlap run loop interleaves the same two phases across
        consecutive windows instead — identical decisions either way (the
        phases touch disjoint controller state)."""
        return self._window_phase_b(self._window_phase_a(w, events))

    def _window_phase_a(self, w: int, events: EventLog) -> dict:
        """Fold + drift + (maybe) dispatch the window's cluster step.

        Returns the window context the planning phase consumes.  On the
        jax backend the re-cluster result is an ASYNC device future: the
        only state mutated here is the feature carry, the hotspot EWMA and
        the pending-accept slot — nothing phase B of the PREVIOUS window
        reads, which is what makes the overlap schedule legal.
        """
        cfg = self.cfg
        seconds: dict[str, float] = {}
        t_start = time.perf_counter()
        rec: dict = {"window": int(w), "n_events": int(len(events))}
        if self._mesh_rec is not None:
            rec["mesh"] = dict(self._mesh_rec)

        t0 = time.perf_counter()
        if len(events):
            self._fold_window(events)
        elif self._dec is not None:
            g = cfg.decay
            for k in self._dec:
                self._dec[k] *= g
        seconds["fold"] = time.perf_counter() - t0
        rec["events_total"] = int(self._events_total)

        # Serving: extract the window's reads once (hotspot detection now,
        # routing after the window's repairs/migrations apply) and score
        # them against the EWMA baseline — the flash-crowd signal the
        # cumulative feature fold dilutes away.
        read_pid = read_ts = read_client = None
        hotspot = None
        if self._router is not None and len(events):
            t0 = time.perf_counter()
            from ..cluster.evaluate import _client_to_topology

            keep = events.path_id >= 0
            is_read = np.asarray(events.op)[keep] == 0
            read_pid = events.path_id[keep][is_read]
            read_ts = events.ts[keep][is_read]
            read_client = _client_to_topology(
                events, self._serve_topology)[keep][is_read]
            counts = np.bincount(read_pid, minlength=len(self.manifest))
            hotspot = self._hotspot.observe(counts)
            rec["n_reads"] = int(read_pid.shape[0])
            rec["hotspot_score"] = round(hotspot.score, 6)
            rec["hotspot_files"] = list(hotspot.files)
            seconds["hotspot"] = time.perf_counter() - t0

        X = None
        drift = None
        t0 = time.perf_counter()
        # Materialize the previously accepted decision (if any) — the
        # pipeline's one synchronization point: blocking here is blocking
        # on the PREVIOUS window's cluster step, after planning already
        # overlapped it.
        self._ensure_accepted()
        if self._accepted_centroids is not None and len(events):
            X = self._feature_snapshot()
            if self._mesh_rec is not None:
                # Mesh runs score drift on device, data-parallel over
                # files (control/drift.detect_drift_jax) — the host
                # oracle below stays the mesh-less path's detector.
                drift = detect_drift_jax(
                    X, self._accepted_centroids,
                    self._accepted_category_idx,
                    self._accepted_fractions, len(CATEGORIES),
                    mesh_shape=cfg.mesh_shape)
            else:
                drift = detect_drift(X, self._accepted_centroids,
                                     self._accepted_category_idx,
                                     self._accepted_fractions,
                                     len(CATEGORIES))
        seconds["drift"] = time.perf_counter() - t0
        rec["drift"] = None if drift is None else drift.score
        rec["centroid_shift"] = None if drift is None else drift.centroid_shift
        rec["population_delta"] = None if drift is None \
            else drift.population_delta

        cold = self._accepted_centroids is None and self._events_total > 0
        drift_fire = (drift is not None
                      and drift.score >= cfg.drift_threshold)
        # Hotspot feedback: a fired detector triggers a re-cluster exactly
        # like drift crossing its threshold.  Drift keeps naming priority
        # in the trigger label — a window where both fire is a drift
        # window that also happens to be hot.
        hot_fire = (hotspot is not None and hotspot.fired
                    and cfg.serve.recluster_on_hotspot
                    and self._accepted_centroids is not None)
        trigger = cold or drift_fire or hot_fire
        rec["recluster"] = bool(trigger)
        rec["recluster_trigger"] = ("cold" if cold
                                    else "drift" if drift_fire
                                    else "hotspot" if hot_fire else None)
        rec["recluster_mode"] = None
        rec["plan_moves_pending"] = None
        decision = None
        t0 = time.perf_counter()
        if trigger:
            warm = (not cold
                    and drift.score < cfg.full_recluster_drift)
            rec["recluster_mode"] = "warm" if warm else "full"
            if X is None:
                X = self._feature_snapshot()
            init = self._accepted_centroids if warm else None
            model = self._model_warm if warm else self._model_full
            try:
                decision = model.run(X, init_centroids=init)
            except Exception as e:
                if cfg.backend != "jax":
                    raise
                decision = self._degraded_recluster(warm, X, init, e)
                rec["degraded_kernel"] = True
            # Accept the MODEL now (next window's drift reference) but
            # leave materialization lazy; the plan diff runs in phase B
            # against the then-current applied plan.
            self._pending_accept = decision
        seconds["recluster"] = time.perf_counter() - t0

        seconds["host_a"] = time.perf_counter() - t_start
        return {"w": int(w), "events": events, "rec": rec,
                "seconds": seconds, "X": X, "decision": decision,
                "read_pid": read_pid, "read_ts": read_ts,
                "read_client": read_client}

    def _window_phase_b(self, ctx: dict) -> dict:
        """Host-side planning + accounting for a dispatched window: plan
        diff/submit, fault events, repairs, budgeted migration admission,
        durability/storage/serving records, evaluation, telemetry.  Under
        ``overlap_windows`` this runs while the device executes the NEXT
        window's cluster step."""
        cfg = self.cfg
        w = ctx["w"]
        events: EventLog = ctx["events"]
        rec: dict = ctx["rec"]
        seconds: dict = ctx["seconds"]
        X = ctx["X"]
        read_pid, read_ts, read_client = (ctx["read_pid"], ctx["read_ts"],
                                          ctx["read_client"])
        t_b = time.perf_counter()
        plan_seconds = 0.0
        self._lineage = []

        if ctx["decision"] is not None:
            t0 = time.perf_counter()
            if self._pending_accept is ctx["decision"]:
                # Serial schedule: the decision is still pending, so
                # materialize the model now — the window's own audit must
                # score against the newly accepted centroids exactly as
                # the pre-split accept did.  Under overlap the next
                # window's phase A already materialized it, so the audit
                # sees the same model either way.
                self._ensure_accepted()
            self._accept_plan(ctx["decision"],
                              trigger=rec.get("recluster_trigger"))
            rec["plan_moves_pending"] = len(self.scheduler.backlog)
            dt = time.perf_counter() - t0
            seconds["recluster"] += dt
            plan_seconds += dt

        if self._cluster_state is not None:
            t0 = time.perf_counter()
            fault_events = list(
                self._fault_schedule.for_window(w))
            if self._elastic is not None:
                # Scale decision first (reads LAST window's serving
                # telemetry; may grow the topology and seed the
                # rebalance queue), then any due rolling-drain
                # decommissions join this window's fault events.
                fault_events += self._elastic_step(w, rec)
            for ev in fault_events:
                self._cluster_state.apply_event(ev)
            rec["fault_events"] = [ev.spec() for ev in fault_events]
            rec["nodes_up"] = self._cluster_state.n_available
            seconds["faults"] = time.perf_counter() - t0

        # Pre-mutation placement snapshot for the before/after replay (the
        # fault path's placement is the mutable ClusterState, so "before"
        # must be rendered now, not re-derived from an rf vector later).
        view_before = None
        ver_before = -1
        want_eval = cfg.evaluate and len(events) > 0
        if self._cluster_state is not None and want_eval:
            view_before = self._cluster_state.placement_view()
            ver_before = self._cluster_state.version
        rf_before = self.current_rf.copy() if cfg.evaluate else None

        # Repairs run FIRST and pre-charge the churn budget: re-replication
        # traffic outranks drift migrations for the same per-window
        # byte/file allowance (faults/repair.py module docstring).
        bytes_reserved = files_reserved = 0
        if self._cluster_state is not None:
            t0 = time.perf_counter()
            repair_rf = self.current_rf
            if self._storage is not None:
                converted, deferred = self._reconcile_strategies()
                rec["storage_conversions_retried"] = converted
                if len(deferred):
                    # A deferred conversion keeps its installed encoding,
                    # so repair maintains THAT form's intent
                    # (installed_shards): topping up toward the unapplied
                    # target's shard count would write full-size copies
                    # the re-encode drops the moment it lands — budget
                    # burned on doomed copies.
                    cs = self._cluster_state
                    repair_rf = self.current_rf.copy()
                    repair_rf[deferred] = np.maximum(
                        cs.installed_shards[deferred],
                        cs.min_live[deferred])
            self._repairs.sync(self._cluster_state, repair_rf)
            rr = self._repairs.schedule(
                w, self._cluster_state, repair_rf, self.current_cat,
                max_bytes=cfg.max_bytes_per_window,
                max_files=cfg.max_files_per_window)
            seconds["repair"] = time.perf_counter() - t0
            plan_seconds += seconds["repair"]
            rec["repair_moves"] = len(rr.applied)
            rec["repair_bytes"] = int(rr.bytes_used)
            rec["repair_bytes_copied"] = int(rr.bytes_copied)
            rec["repair_failed"] = rr.failed
            rec["repair_rebalanced"] = rr.rebalanced
            rec["repair_backlog"] = len(self._repairs.backlog)
            rec["repair_deferred_budget"] = rr.deferred_budget
            rec["repair_deferred_backoff"] = rr.deferred_backoff
            rec["repair_deferred_no_source"] = rr.deferred_no_source
            rec["repair_deferred_no_target"] = rr.deferred_no_target
            rec["repair_deferred_partition"] = rr.deferred_partition
            if rr.corrupt_sources:
                rec["repair_corrupt_sources"] = rr.corrupt_sources
            bytes_reserved = rr.bytes_used
            files_reserved = rr.files_touched
            # Provenance: repair copies vs correlated-risk spread
            # rebalances are two different answers to "why did this
            # file move" — split the pass's lineage accordingly (failed
            # copies' traffic stays attributed to repair: it was spent
            # healing).
            if rr.applied or rr.failed:
                rb = set(rr.rebalanced_fids)
                rep_fids = np.asarray(
                    sorted({f for f, _, _ in rr.applied} - rb),
                    dtype=np.int64)
                if rep_fids.size or rr.failed:
                    self._lineage.append(
                        ("repair", rep_fids,
                         int(rr.bytes_used - rr.rebalanced_bytes)))
                if rb:
                    self._lineage.append(
                        ("correlated_rebalance",
                         np.asarray(sorted(rb), dtype=np.int64),
                         int(rr.rebalanced_bytes)))

        # Elastic rebalance drains the epoch-diff moved set on what
        # remains of the shared churn budget after repairs (repairs
        # outrank rebalance; rebalance outranks scrub and migrations —
        # capacity the crowd needs beats hunting latent rot).
        if self._elastic is not None and self._elastic.queue.size:
            t0 = time.perf_counter()
            rb_bytes, rb_files = self._elastic_rebalance(bytes_reserved)
            seconds["rebalance"] = time.perf_counter() - t0
            plan_seconds += seconds["rebalance"]
            rec["elastic"]["rebalanced"] = rb_files
            rec["elastic"]["rebalance_bytes"] = rb_bytes
            rec["elastic"]["queue"] = int(self._elastic.queue.size)
            bytes_reserved += rb_bytes
            files_reserved += rb_files

        # Background scrub runs AFTER repairs (healing known damage
        # outranks hunting unknown damage) on what remains of the shared
        # churn budget, capped by its own bytes_per_window rate; its
        # quarantines surface in the NEXT window's repair sync.
        if self._scrub is not None:
            if "defer_scrub" in self.degraded_modes:
                # Brownout rung: the verification pass is optional work
                # — skip it wholesale (cursor and hints hold, so the
                # lap resumes exactly where it paused once the ladder
                # releases).  Deferral is not starvation: the budget
                # was never offered.
                rec["scrub"] = {
                    "bytes": 0, "copies_verified": 0,
                    "files_verified": 0, "corrupt_found": 0,
                    "hinted": 0, "starved": False,
                    "cursor": int(self._scrub.cursor),
                    "deferred": True,
                }
            else:
                t0 = time.perf_counter()
                left = None
                if cfg.max_bytes_per_window is not None:
                    left = max(int(cfg.max_bytes_per_window)
                               - bytes_reserved, 0)
                sr = self._scrub.run_window(w, self._cluster_state,
                                            shared_left=left)
                seconds["scrub"] = time.perf_counter() - t0
                plan_seconds += seconds["scrub"]
                rec["scrub"] = {
                    "bytes": int(sr.bytes_used),
                    "copies_verified": sr.copies_verified,
                    "files_verified": sr.files_verified,
                    "corrupt_found": sr.corrupt_found,
                    "hinted": sr.hinted,
                    "starved": bool(sr.starved),
                    "cursor": int(sr.cursor),
                }
                bytes_reserved += sr.bytes_used

        t0 = time.perf_counter()
        applied = self.scheduler.schedule(w, bytes_reserved=bytes_reserved,
                                          files_reserved=files_reserved)
        if len(applied):
            # Vectorized plan application — one gather per column.  The
            # fault path still walks the (budget-bounded) admitted moves:
            # placement mutation per file is stateful by design.
            fi = applied.file_index
            self.current_rf[fi] = applied.rf_new
            self.current_cat[fi] = applied.cat_new
            if self._cluster_state is None:
                self._installed_cat[fi] = applied.cat_new
            elif self._storage is None:
                cs = self._cluster_state
                for f, rf_new in zip(fi.tolist(),
                                     applied.rf_new.tolist()):
                    cs.apply_rf_target(f, rf_new)
                self._installed_cat[fi] = applied.cat_new
            else:
                for m in applied:
                    # The move may convert the file between strategies
                    # (replicate <-> EC stripe): apply_strategy_target
                    # re-encodes when the shape changes (or defers if
                    # the file is unreadable right now — the reconcile
                    # pass below retries) and degenerates to
                    # apply_rf_target when it does not.
                    cs = self._cluster_state
                    want = self._file_strategy(int(m.cat_new),
                                               m.file_index)
                    cs.apply_strategy_target(
                        m.file_index, want[0], want[1], want[2],
                        m.rf_new, want[3])
                    installed = (
                        int(cs.min_live[m.file_index]) == want[0]
                        and int(cs.shard_bytes[m.file_index]) == want[1]
                        and int(cs.ec_k[m.file_index]) == want[2]
                        and bool(cs.region_local[m.file_index])
                        == want[3])
                    if installed:
                        self._installed_cat[m.file_index] = m.cat_new
        seconds["schedule"] = time.perf_counter() - t0
        plan_seconds += seconds["schedule"]
        if len(applied):
            # Provenance: admitted migrations carry the cause their plan
            # was submitted under (hysteresis can admit a move windows
            # after its re-cluster — the tag rides the backlog and the
            # checkpoint, so the story survives both).
            cc = self._move_cause[applied.file_index]
            for code, name in sorted(MOVE_CAUSES.items()):
                m = cc == code
                if m.any():
                    self._lineage.append(
                        (name, applied.file_index[m].copy(),
                         int(applied.bytes_moved[m].sum())))
        rec["moves_applied"] = len(applied)
        rec["bytes_migrated"] = applied.total_bytes
        rec["backlog_files"] = len(self.scheduler.backlog)
        rec["backlog_bytes"] = int(self.scheduler.backlog_bytes)
        rec["deferred_hysteresis"] = self.scheduler.last_deferred_hysteresis
        rec["deferred_budget"] = self.scheduler.last_deferred_budget

        if self._cluster_state is not None:
            rec["durability"] = self._cluster_state.durability(
                self.current_rf, self.current_cat, CATEGORIES)
            if len(events):
                # Reads the outage actually refused this window: reads of
                # files with zero REACHABLE replicas (lost outright, or
                # wholly stranded behind a partition).
                unreadable = self._cluster_state.unreadable_mask()
                keep = events.path_id >= 0
                pid = events.path_id[keep]
                reads = np.asarray(events.op)[keep] == 0
                # The denominator that makes the count comparable across
                # run lengths (unavailable_read_fraction in the digests).
                rec["n_reads"] = int(reads.sum())
                rec["unavailable_reads"] = int(unreadable[pid[reads]].sum())
            else:
                rec["n_reads"] = 0
                rec["unavailable_reads"] = 0

        if self._storage is not None:
            # Byte/cost accounting of the applied strategies, post
            # repair + migration (the end-of-window convention) — the
            # observable the cost-vs-durability frontier is built on.
            rec["storage"] = self._storage_record()

        read_detect_copies = 0
        if self._router is not None and read_pid is not None:
            # Route the window's reads against the END-of-window placement
            # (post repair + migration — the locality_after convention):
            # reachability masks and straggler factors become service-time
            # multipliers, and every read gets an exact FIFO-queue latency
            # sample (serve/router.py).
            t0 = time.perf_counter()
            from ..serve import read_view

            reads_shed = 0
            if self.serve_shed is not None and read_pid.shape[0]:
                # Brownout load shedding: reject a seeded, bounded
                # fraction of the window's reads with an explicit shed
                # status BEFORE they queue — the Tail-at-Scale move of
                # bounding p99 by refusing work, made reproducible by
                # drawing from ``[shed_seed, window]`` exactly like the
                # router's own arrival jitter.
                frac, shed_seed = self.serve_shed
                srng = np.random.default_rng([int(shed_seed), int(w)])
                keep_r = srng.random(read_pid.shape[0]) >= float(frac)
                if keep_r.any() and not keep_r.all():
                    reads_shed = int(read_pid.shape[0]
                                     - int(keep_r.sum()))
                    read_pid = read_pid[keep_r]
                    read_ts = read_ts[keep_r]
                    read_client = read_client[keep_r]
            if self._cluster_state is not None:
                view = read_view(read_pid, state=self._cluster_state)
                if not self._integrity_on:
                    # The PR-9 contract: runs whose schedule never
                    # injects corruption (and don't scrub) keep
                    # byte-identical records even if a resumed snapshot
                    # carries stale rot bits — the router must not
                    # react to them.
                    view.slot_corrupt = None
                if self._storage is not None:
                    # An EC stripe below k reachable shards cannot serve
                    # a read from ANY surviving slot — mask the whole
                    # row so the router counts it unavailable, agreeing
                    # with unreadable_mask()/unavailable_reads in the
                    # same window record.
                    readable = ~self._cluster_state.unreadable_mask()
                    if view.file_ids is not None:   # compacted view
                        readable = readable[view.file_ids]
                    view.slot_ok = view.slot_ok & readable[:, None]
            elif (cfg.placement_mode == "functional"
                    and self._storage is None):
                # The O(1)-memory router: resolve ONLY this window's
                # files through the functional chooser instead of
                # materializing the full map (routing is bit-identical —
                # the router only ever indexes replica_map[pid]).
                view = read_view(read_pid, resolver=self._fn_static_rows,
                                 n_nodes=len(self._serve_topology.nodes))
            else:
                view = read_view(
                    read_pid,
                    placement=self._placement_for(self.current_rf))
            extra_ms = None
            if self._storage is not None:
                if view.file_ids is not None:   # compacted (lowmem) view
                    extra_ms = self._serve_penalty_ms(
                        view.slot_ok, fids=view.file_ids)[view.pid]
                else:
                    extra_ms = self._serve_penalty_ms(
                        view.slot_ok)[read_pid]
            res = self._router.route(
                view.replica_map, view.slot_ok, view.node_throughput,
                ts=read_ts, pid=view.pid,
                client=read_client, window_seconds=cfg.window_seconds,
                rng=np.random.default_rng([int(cfg.serve.seed), int(w)]),
                extra_ms=extra_ms, edge_ms=self._edge_ms,
                slot_corrupt=view.slot_corrupt)
            rec.update(res.record_fields())
            if self.serve_shed is not None:
                # Conditional key: only brownout-enabled daemon runs
                # carry it, so every pinned batch record stays
                # byte-identical.
                rec["reads_shed"] = reads_shed
            if res.corrupt_pairs is not None and len(res.corrupt_pairs):
                # Detect-on-read feedback: quarantine the rotten copies
                # the window's reads tripped over, and hint the scrubber
                # at those files (their surviving copies are now
                # suspect).  A compacted view's pairs carry ROW ids —
                # map them back to population file ids first.
                pair_fids = res.corrupt_pairs[:, 0]
                if view.file_ids is not None:
                    pair_fids = view.file_ids[pair_fids]
                for fid, node in zip(pair_fids,
                                     res.corrupt_pairs[:, 1]):
                    self._cluster_state.quarantine(int(fid), int(node))
                read_detect_copies = len(res.corrupt_pairs)
                if self._scrub is not None:
                    self._scrub.add_hints(pair_fids)
            self._last_latency_ms = res.latency_ms
            seconds["serve"] = time.perf_counter() - t0
            if self._elastic is not None:
                # The decision inputs of NEXT window's scale step.
                self._elastic.last_burn = float(rec.get("slo_burn", 0.0))
                self._elastic.last_util = float(
                    rec.get("utilization_max", 0.0))

        if self._integrity_on:
            # Ground-truth integrity digest AFTER the window's detections
            # (scrub, repairs, reads) quarantined what they found: the
            # rot still latent, and the true losses the blind durability
            # tiers cannot see yet.
            integ = self._cluster_state.integrity()
            integ["detected_scrub"] = (rec.get("scrub") or {}).get(
                "corrupt_found", 0)
            integ["detected_repair"] = rec.get("repair_corrupt_sources", 0)
            # Unique COPIES the read path exposed (record_fields'
            # reads_corrupt_detected counts reads — a hot rotten copy can
            # be hit thousands of times in one batch; the per-path
            # detection totals must share one unit).
            integ["detected_read"] = read_detect_copies
            rec["integrity"] = integ

        t0 = time.perf_counter()
        rec["locality_before"] = rec["locality_after"] = None
        rec["balance_before"] = rec["balance_after"] = None
        if want_eval:
            if self._cluster_state is not None:
                from ..cluster import evaluate_placement

                mb = evaluate_placement(self.manifest, events, view_before,
                                        seed=0)
                rec["locality_before"] = float(mb.read_locality)
                rec["balance_before"] = float(mb.load_balance)
                if self._cluster_state.version != ver_before:
                    ma = evaluate_placement(
                        self.manifest, events,
                        self._cluster_state.placement_view(), seed=0)
                    rec["locality_after"] = float(ma.read_locality)
                    rec["balance_after"] = float(ma.load_balance)
                else:
                    rec["locality_after"] = rec["locality_before"]
                    rec["balance_after"] = rec["balance_before"]
            else:
                rec["locality_before"], rec["balance_before"] = \
                    self._evaluate(events, rf_before)
                if applied:
                    rec["locality_after"], rec["balance_after"] = \
                        self._evaluate(events, self.current_rf)
                else:
                    rec["locality_after"] = rec["locality_before"]
                    rec["balance_after"] = rec["balance_before"]
        seconds["evaluate"] = time.perf_counter() - t0

        if self._hash_placement:
            # The positive-engagement stamp of the placement axis (the
            # scenario matrix's functional_engaged invariant reads it).
            # Pre-placement-mode runs carry no key: records stay
            # byte-identical.  ``exceptions`` is the EXACT deviation
            # count from the computed base — deterministic across
            # kill/resume (exception_fids prunes to the verified set).
            pl: dict = {"mode": cfg.placement_mode, "epoch": 0}
            if self._cluster_state is not None:
                pl["epoch"] = int(getattr(self._cluster_state,
                                          "_fn_epoch", 0))
                if cfg.placement_mode == "functional":
                    pl["exceptions"] = int(
                        self._cluster_state.exception_fids().size)
            rec["placement"] = pl

        if self._lineage:
            # The per-window provenance digest: what traffic each cause
            # consumed of the shared churn budget (`cdrs explain window`
            # ranks these; the id-level batches flow out as ``lineage``
            # telemetry events in _instrument_window).
            causes: dict[str, dict] = {}
            for name, fids, b in self._lineage:
                c = causes.setdefault(name, {"files": 0, "bytes": 0})
                c["files"] += int(fids.size)
                c["bytes"] += int(b)
            rec["causes"] = causes

        rec["plan_hash"] = _plan_hash(self.current_rf, self.current_cat)
        # ``plan`` = the host-side planning slice (plan diff/submit +
        # repair pass + budgeted admission) — the control-plane cost the
        # SoA planners exist to shrink, and what the overlap schedule
        # hides under the next window's device step.  ``total`` is host
        # wall-clock attributable to this window (both phases); under
        # overlap the phases interleave with other windows' device time,
        # so totals measure host work, not latency.
        seconds["plan"] = plan_seconds
        seconds["total"] = seconds.pop("host_a") \
            + (time.perf_counter() - t_b)
        rec["seconds"] = {k: round(v, 6) for k, v in seconds.items()}
        self._instrument_window(rec, seconds, X)
        return rec

    def _instrument_window(self, rec: dict, seconds: dict,
                           X: np.ndarray | None = None) -> None:
        """Route the window's observations through the active telemetry
        instrument (obs/), when one is installed: migration counters
        (bytes/files moved, hysteresis/budget deferrals), re-cluster
        counters, per-stage wall-clock histograms (p50/p95 in
        ``cdrs metrics summarize``), and — unless ``Telemetry(audit=False)``
        — the per-window decision-quality audit (obs/audit.py: silhouette/
        Davies-Bouldin proxies over the window's feature snapshot ``X``
        when the loop already computed one, population entropy/TV,
        replication byte cost, anomaly flags).  No-op without an
        instrument; the audit observes and never mutates the plan."""
        from ..obs import current as _obs_current

        tel = _obs_current()
        if tel is None:
            return
        if getattr(tel, "audit", False):
            if self._auditor is None:
                from ..obs.audit import DecisionAuditor

                self._auditor = DecisionAuditor(self._sizes, len(CATEGORIES))
            self._auditor.audit_window(
                tel, window=rec["window"], rec=rec, X=X,
                centroids=self._accepted_centroids,
                rf=self.current_rf, cat=self.current_cat)
        tel.counter_inc("controller.windows")
        if rec["n_events"]:
            tel.counter_inc("controller.events_folded", rec["n_events"])
        if rec["recluster"]:
            tel.counter_inc(f"controller.reclusters.{rec['recluster_mode']}")
        if rec["moves_applied"]:
            tel.counter_inc("migrate.files_moved", rec["moves_applied"])
        if rec["bytes_migrated"]:
            tel.counter_inc("migrate.bytes_moved", rec["bytes_migrated"])
        if rec["deferred_hysteresis"]:
            tel.counter_inc("migrate.deferred_hysteresis",
                            rec["deferred_hysteresis"])
        if rec["deferred_budget"]:
            tel.counter_inc("migrate.deferred_budget",
                            rec["deferred_budget"])
        # Planner depth gauges: how much admitted work is still queued —
        # with the SoA backlog both are O(1)/O(columns) reads, so they are
        # safe to emit every window at any scale.
        tel.gauge("planner.backlog_files", rec["backlog_files"])
        tel.gauge("planner.backlog_bytes", rec["backlog_bytes"])
        mesh = rec.get("mesh")
        if mesh is not None:
            ndata = max(1, int((self.cfg.mesh_shape or {}).get("data", 1)))
            tel.gauge("mesh.devices", mesh["devices"])
            tel.gauge("mesh.rows_per_device",
                      -(-len(self.manifest) // ndata))
            tel.gauge("mesh.collective_bytes_per_iter",
                      mesh["collective_bytes_per_iter"])
        if rec.get("fault_events"):
            tel.counter_inc("fault.events", len(rec["fault_events"]))
            n_part_ev = sum(1 for s in rec["fault_events"]
                            if s.startswith(("partition:", "heal:")))
            if n_part_ev:
                tel.counter_inc("fault.partition.events", n_part_ev)
        dur = rec.get("durability")
        if dur is not None:
            tel.gauge("durability.under_replicated",
                      dur["under_replicated"])
            tel.gauge("durability.at_risk", dur["at_risk"])
            tel.gauge("durability.lost", dur["lost"])
            tel.gauge("durability.nodes_up", dur["nodes_up"])
            tel.gauge("durability.correlated.files",
                      dur.get("correlated_risk", 0))
            tel.gauge("durability.correlated.domains_reachable",
                      dur.get("domains_reachable", 1))
            tel.gauge("fault.partition.nodes",
                      dur.get("nodes_partitioned", 0))
            tel.gauge("fault.partition.unreachable_files",
                      dur.get("unreachable", 0))
            if rec.get("unavailable_reads"):
                tel.counter_inc("fault.unavailable_reads",
                                rec["unavailable_reads"])
        if rec.get("repair_moves"):
            tel.counter_inc("repair.files_replicated", rec["repair_moves"])
        if rec.get("repair_bytes"):
            tel.counter_inc("repair.bytes", rec["repair_bytes"])
        if rec.get("repair_failed"):
            tel.counter_inc("repair.failed", rec["repair_failed"])
        if rec.get("repair_deferred_budget"):
            tel.counter_inc("repair.deferred_budget",
                            rec["repair_deferred_budget"])
        if rec.get("repair_deferred_no_source"):
            tel.counter_inc("repair.deferred_no_source",
                            rec["repair_deferred_no_source"])
        if rec.get("repair_deferred_no_target"):
            tel.counter_inc("repair.deferred_no_target",
                            rec["repair_deferred_no_target"])
        if rec.get("repair_deferred_partition"):
            tel.counter_inc("fault.partition.stalled_repairs",
                            rec["repair_deferred_partition"])
        if rec.get("repair_rebalanced"):
            tel.counter_inc("repair.rebalanced_domain",
                            rec["repair_rebalanced"])
        if rec.get("repair_corrupt_sources"):
            tel.counter_inc("repair.corrupt_sources",
                            rec["repair_corrupt_sources"])
        sc = rec.get("scrub")
        if sc is not None:
            if sc["bytes"]:
                tel.counter_inc("scrub.bytes", sc["bytes"])
            if sc["copies_verified"]:
                tel.counter_inc("scrub.copies_verified",
                                sc["copies_verified"])
            if sc["corrupt_found"]:
                tel.counter_inc("scrub.corrupt_found", sc["corrupt_found"])
            if sc["starved"]:
                tel.counter_inc("scrub.starved_windows")
            tel.gauge("scrub.cursor", sc["cursor"])
        integ = rec.get("integrity")
        if integ is not None:
            tel.gauge("integrity.corrupt_copies", integ["corrupt_copies"])
            tel.gauge("integrity.files_corrupt", integ["files_corrupt"])
            tel.gauge("integrity.true_lost", integ["true_lost"])
        st = rec.get("storage")
        if st is not None:
            tel.gauge("storage.bytes_stored", st["bytes_stored"])
            tel.gauge("storage.overhead_ratio", st["overhead_ratio"])
            tel.gauge("storage.cost_units", st["cost_units"])
            tel.gauge("storage.ec_files", st["ec_files"])
            for t, b in st["per_tier_bytes"].items():
                tel.gauge(f"storage.tier.{t}.bytes", b)
        if self._router is not None:
            from ..serve import emit_window_telemetry

            # The shared serve.* emission path (serve/router.py) — `cdrs
            # serve` streams through the same helper, so the two surfaces
            # cannot drift apart.
            emit_window_telemetry(tel, rec, self._last_latency_ms)
        self._last_latency_ms = None
        for name, fids, b in self._lineage:
            # Decision provenance: one ``lineage`` event per admitted
            # batch — cause, exact file/byte totals, and the id list
            # (capped: a 100M-row epoch diff must not become a 100M-int
            # JSON line; counts stay exact either way).
            ev = {"kind": "lineage", "window": rec["window"],
                  "cause": name, "files": int(fids.size),
                  "bytes": int(b),
                  "file_ids": [int(x) for x in fids[:LINEAGE_ID_CAP]]}
            if fids.size > LINEAGE_ID_CAP:
                ev["truncated"] = True
            tel._emit(ev)
            if fids.size:
                tel.counter_inc(f"lineage.{name}.files", int(fids.size))
            if b:
                tel.counter_inc(f"lineage.{name}.bytes", int(b))
        for stage, secs in seconds.items():
            tel.histogram(f"controller.{stage}.seconds", secs)
        tid = getattr(self, "_trace_id", None)
        if tid is not None:
            # Decision tracing (obs/trace.py): the daemon set a trace
            # context around this window, so each already-measured stage
            # joins the live span stream as a retrospective child of the
            # enclosing ``daemon.decision`` span.  Batch runs never set
            # ``_trace_id`` — their telemetry output is unchanged.
            parent = tel.current_span_id()
            for stage, secs in seconds.items():
                if stage == "total":
                    continue
                tel.emit_span(f"controller.{stage}", secs,
                              parent=parent, trace=tid,
                              window=rec["window"])

    def _degraded_recluster(self, warm: bool, X, init, err: Exception):
        """Degraded mode: the jax kernel path failed (device lost, OOM,
        compile error) — re-cluster on the numpy backend instead of
        crashing the control loop.  The decision is equivalent in kind
        (same Lloyd/scoring semantics, ops/kmeans_np.py is the golden
        model) if not bit-identical; the ``degraded.kernel_fallback``
        counter and a one-time warning record that it happened."""
        import warnings

        if not self._kernel_fallback_warned:
            self._kernel_fallback_warned = True
            warnings.warn(
                f"jax kernel failed ({type(err).__name__}: {err}); "
                f"falling back to the numpy backend for re-clustering",
                RuntimeWarning, stacklevel=2)
        from ..obs import current as _obs_current

        tel = _obs_current()
        if tel is not None:
            tel.counter_inc("degraded.kernel_fallback")
        if warm not in self._fallback_models:
            self._fallback_models[warm] = self._make_model(
                warm, backend="numpy")
        X64 = np.asarray(X, dtype=np.float64)
        init64 = None if init is None else np.asarray(init,
                                                      dtype=np.float64)
        return self._fallback_models[warm].run(X64, init_centroids=init64)

    def _ensure_accepted(self) -> None:
        """Materialize the pending accepted decision into the host-side
        model arrays (centroids, category map, population fractions).
        With ``overlap_windows`` + jax this is where the host finally
        blocks on the previous window's device step; serial runs hit it
        immediately after dispatch, reproducing the historical timing."""
        decision = self._pending_accept
        if decision is None:
            return
        self._pending_accept = None
        cfg = self.cfg
        self._accepted_centroids = np.asarray(
            decision.centroids,
            dtype=np.float64 if cfg.backend == "numpy" else np.float32)
        cat_idx = np.asarray(decision.category_idx).astype(np.int64)
        self._accepted_category_idx = cat_idx
        labels = np.asarray(decision.labels)
        new_cat = cat_idx[labels].astype(np.int64)
        self._accepted_file_cat = new_cat
        frac = np.bincount(new_cat, minlength=len(CATEGORIES)).astype(
            np.float64)
        self._accepted_fractions = frac / max(len(labels), 1)

    def _accept_plan(self, decision, trigger: str | None = None) -> None:
        """Adopt an accepted decision's PLAN: diff against the APPLIED
        plan, rebuild the scheduler backlog (newest plan supersedes
        pending moves).  ``trigger`` (the window's re-cluster trigger)
        cause-tags the submitted moves: hotspot-triggered plans tag
        ``hotspot``, everything else ``drift`` (a cold start is the
        first drift decision), and storage-strategy re-encodes override
        to ``conversion`` per file."""
        cfg = self.cfg
        labels = np.asarray(decision.labels)
        # The model was materialized from THIS decision before planning
        # (phase B materializes a still-pending one; under overlap the
        # next window's phase A already did), so the O(n) per-file
        # category gather can be reused instead of recomputed.
        new_cat = self._accepted_file_cat
        if new_cat is None:
            new_cat = np.asarray(
                decision.category_idx).astype(np.int64)[labels]
        # With a storage config the target "rf" is the strategy's shard
        # count (rf for replicate, k+m for EC) — the one generalization
        # the whole downstream plan/placement/repair machinery needs.
        if self._storage is not None:
            rf_vec = self._storage.n_shards.astype(np.int64)
        else:
            rf_vec = np.asarray(cfg.scoring.rf_vector(), dtype=np.int64)
        new_rf = rf_vec[new_cat]

        # Priority: the new category's scoring margin over the file's
        # currently applied category (unplanned files: margin over the
        # cluster's worst category) — "most misplaced first".
        scores = np.asarray(decision.scores, dtype=np.float64)  # (k, n_cat)
        file_scores = scores[labels]                            # (n, n_cat)
        new_score = np.take_along_axis(
            file_scores, new_cat[:, None], axis=1)[:, 0]
        old_cat = self.current_cat.astype(np.int64)
        old_ref = np.where(old_cat >= 0, old_cat, 0)
        old_score = np.take_along_axis(
            file_scores, old_ref[:, None], axis=1)[:, 0]
        old_score = np.where(old_cat >= 0, old_score,
                             file_scores.min(axis=1))
        priority = new_score - old_score

        move_bytes = None
        convert = None
        if self._storage is not None:
            # A strategy re-encode (shape change: replicate <-> EC, or a
            # different k) drops every old copy and writes rf_new NEW
            # shards — charge those written bytes, not an rf delta of
            # full-size copies (which is 0 for an equal-shard-count
            # conversion and a several-fold over-charge for rf=2 ->
            # ec(6,3)).  Same-shape moves keep the historical formula at
            # the (shared) shard size.
            sv = self._storage
            old_cat = self.current_cat
            shard_old = sv.file_shard_bytes(old_cat, self._sizes)
            shard_new = sv.file_shard_bytes(new_cat, self._sizes)
            convert = ((sv.file_min_live(old_cat)
                        != sv.file_min_live(new_cat))
                       | (shard_old != shard_new)
                       | (sv.file_ec_k(old_cat) != sv.file_ec_k(new_cat))
                       | (sv.file_region_local(old_cat)
                          != sv.file_region_local(new_cat)))
            move_bytes = np.where(
                convert, new_rf * shard_new,
                shard_new * np.maximum(new_rf - self.current_rf, 0))
        moves = plan_diff(self.current_rf, new_rf, self.current_cat, new_cat,
                          self._sizes, priority=priority,
                          move_bytes=move_bytes)
        if len(moves):
            codes = np.full(len(moves),
                            2 if trigger == "hotspot" else 1,
                            dtype=np.int8)
            if convert is not None:
                codes[convert[moves.file_index]] = 3
            self._move_cause[moves.file_index] = codes
        self.scheduler.submit(moves)

    def _edge_latency_ms(self, topology) -> np.ndarray | None:
        """(n_nodes, n_nodes) cross-hierarchy propagation delay for the
        router (``edge_latency`` multipliers x service_ms, zero on the
        diagonal classes) — None for flat-latency topologies, keeping
        their routing byte-identical."""
        if not getattr(topology, "edge_latency", ()):
            return None
        return (float(self.cfg.serve.service_ms)
                * (topology.latency_matrix() - 1.0))

    # -- elastic capacity (control/elastic.py) -----------------------------
    def _elastic_step(self, w: int, rec: dict) -> list:
        """One window's autoscale decision.  Reads LAST window's serving
        telemetry, updates the hot/cool streaks, fires scale-out (grow +
        epoch diff -> rebalance queue) or lays down the rolling drain,
        and returns the drain decommissions due THIS window.  Stamps the
        ``elastic`` record (the black-friday cell's engagement
        invariant)."""
        from ..faults.schedule import FaultEvent

        es = self._elastic
        pol = es.policy
        info: dict = {"active": len(es.active),
                      "queue": int(es.queue.size)}
        if es.last_burn is not None:
            hot = (es.last_burn > pol.burn_hot
                   or es.last_util > pol.util_hot)
            cool = (es.last_burn <= pol.burn_hot
                    and es.last_util < pol.util_cool)
            es.hot = es.hot + 1 if hot else 0
            es.cool = es.cool + 1 if cool else 0
        info["hot_streak"] = es.hot
        info["cool_streak"] = es.cool
        if not es.scaled and es.hot >= pol.hot_windows:
            names = pol.next_activation(es.active)
            if not names:
                # Pool consumed (drained nodes are decommissioned and
                # never reused): a later crowd has nothing to activate.
                # Stamp it — a silent no-op while burn keeps violating
                # would read as a dead autoscaler.
                info["pool_exhausted"] = True
            else:
                moved = self._elastic_grow(names)
                es.active = es.active + tuple(names)
                es.scaled = True
                es.hot = 0
                es.cool = 0
                es.moved_total += int(moved.size)
                info["added"] = list(names)
                info["moved"] = int(moved.size)
                info["active"] = len(es.active)
                info["queue"] = int(es.queue.size)
        elif (es.scaled and not es.drains and es.queue.size == 0
                and es.cool >= pol.cool_windows and es.active):
            es.drains = [(w + 1 + i * pol.drain_spacing, nm)
                         for i, nm in enumerate(es.active)]
            info["drains_scheduled"] = [[int(a), b]
                                        for a, b in es.drains]
            es.scaled = False
            es.cool = 0
        due: list = []
        still: list = []
        for dw, nm in es.drains:
            if dw <= w:
                due.append(FaultEvent(w, "decommission", nm))
            else:
                still.append((dw, nm))
        es.drains = still
        if due:
            info["drained"] = [e.node for e in due]
        rec["elastic"] = info
        return due

    def _elastic_grow(self, names) -> np.ndarray:
        """Activate standby nodes: pin + grow the cluster state, rebuild
        the serve plane on the wider topology, and return the
        addition-pruned epoch-diff moved set (the rebalance queue)."""
        from ..placement_fn.epoch import addition_moved
        from ..serve import ReadRouter

        cs = self._cluster_state
        topo_old = cs.topology
        topo_new = self._elastic.policy.grown_topology(topo_old, names)
        local = None
        if getattr(topo_old, "n_levels", 0) > 0 \
                and cs.region_local.any():
            local = cs.region_local
        moved = addition_moved(topo_old, topo_new, cs.installed_shards,
                               cs._fn_primary, cs._fn_seed,
                               local_mask=local)
        cs.pin_rows(moved)
        cs.grow(topo_new)
        es = self._elastic
        es.queue = (np.concatenate([es.queue, moved])
                    if es.queue.size else moved)
        # Provenance: the moved set IS the addition-pruned epoch diff —
        # tagged now (bytes 0: traffic bills when the queue drains as
        # elastic_rebalance).
        if moved.size:
            self._lineage.append(("epoch_diff", moved.copy(), 0))
        self._serve_topology = topo_new
        self._router = ReadRouter(len(topo_new.nodes), self.cfg.serve)
        self._edge_ms = self._edge_latency_ms(topo_new)
        self._fn_static_primary = None
        return moved

    def _elastic_rebalance(self, bytes_reserved: int) -> tuple[int, int]:
        """Drain the rebalance queue within the remaining churn budget:
        each file retargets to its new computed row (bytes charged = one
        shard per NEWLY holding node — exactly the hash-twice moved
        set's traffic, nothing else).  The repair planner's
        largest-first-op rule applies: when nothing else moved bytes
        this window, the head of the queue is admitted regardless."""
        cs = self._cluster_state
        es = self._elastic
        q = es.queue
        max_bytes = self.cfg.max_bytes_per_window
        used = 0
        done = 0
        for i in range(q.size):
            fid = int(q[i])
            new_row = cs._fn_base_rows(
                np.asarray([fid], dtype=np.int64))[0]
            cur = cs.row(fid)
            new_only = ({int(x) for x in new_row[new_row >= 0]}
                        - {int(x) for x in cur[cur >= 0]})
            charge = int(cs.shard_bytes[fid]) * len(new_only)
            if max_bytes is not None \
                    and bytes_reserved + used + charge > max_bytes \
                    and bytes_reserved + used > 0:
                break
            used += cs.retarget_row(fid, new_row)
            done += 1
        es.queue = q[done:]
        if done:
            self._lineage.append(
                ("elastic_rebalance", q[:done].copy(), int(used)))
        return used, done

    # -- storage strategies (storage/) -------------------------------------
    def _file_strategy(self, cat: int,
                       fid: int) -> tuple[int, int, int, bool]:
        """(min_live, shard_bytes, ec_k, region_local) of one file
        under ``cat``."""
        sv = self._storage
        if cat < 0:
            return 1, int(self._sizes[fid]), 0, False
        return (int(sv.min_live[cat]),
                -(-int(self._sizes[fid]) // int(sv.shard_div[cat])),
                int(sv.ec_k[cat]),
                bool(sv.region_local[cat]))

    def _reconcile_strategies(self) -> tuple[int, np.ndarray]:
        """Retry deferred strategy conversions (apply_strategy_target
        refused a re-encode while the file was unreadable): once the
        partition heals or a holder recovers, the file converts to the
        strategy its applied category wants.  The original migration
        already paid the churn budget when it was scheduled, so the
        retry is the same move landing late, not new traffic.  Returns
        (converted count, file ids STILL deferred) — the repair pass
        needs the latter to maintain those files' installed form."""
        cs = self._cluster_state
        sv = self._storage
        cat = self.current_cat
        want_min = sv.file_min_live(cat)
        want_shard = sv.file_shard_bytes(cat, self._sizes)
        want_k = sv.file_ec_k(cat)
        want_local = sv.file_region_local(cat)
        fids = cs.strategy_mismatch(want_min, want_shard, want_k,
                                    region_local=want_local)
        converted = 0
        still = []
        for fid in fids:
            f = int(fid)
            cs.apply_strategy_target(
                f, int(want_min[f]), int(want_shard[f]),
                int(want_k[f]), int(self.current_rf[f]),
                bool(want_local[f]))
            # Success = the strategy now matches (the shard-count DELTA
            # can legitimately be 0, e.g. replicate(3) -> ec(2,1)).
            if (int(cs.min_live[f]) == int(want_min[f])
                    and int(cs.shard_bytes[f]) == int(want_shard[f])
                    and int(cs.ec_k[f]) == int(want_k[f])
                    and bool(cs.region_local[f]) == bool(want_local[f])):
                converted += 1
                self._installed_cat[f] = int(cat[f])
            else:
                still.append(f)
        return converted, np.asarray(still, dtype=np.int64)

    def _storage_record(self) -> dict:
        """Vectorized byte/cost digest of the APPLIED storage strategies:
        stored vs raw bytes, tier split, cost units (stored bytes x tier
        byte cost), EC stripe count.  Fault runs count the ACTUAL
        assigned slots at the INSTALLED shard size (mid-outage a stripe
        may be short, and a deferred conversion still holds full-size
        replicate copies — the bytes truly on disk); plain runs count
        the target shards capped at the node count (the placement
        cap).  Tier and byte cost likewise follow the INSTALLED
        category (_installed_cat): a deferred rf->EC conversion's
        full-size copies bill at their current hot tier, not the cold
        tier they have not reached yet."""
        sv = self._storage
        cat = self.current_cat
        planned = cat >= 0
        icat = self._installed_cat
        isafe = np.clip(icat, 0, None)
        if self._cluster_state is not None:
            cs = self._cluster_state
            counts = cs.assigned_counts()
            shard_b = cs.shard_bytes
            ec_files = int(((cs.ec_k > 0) & planned).sum())
        else:
            counts = np.minimum(self.current_rf, len(self.manifest.nodes))
            shard_b = sv.file_shard_bytes(cat, self._sizes)
            ec_files = int(((sv.file_ec_k(cat) > 0) & planned).sum())
        stored = counts.astype(np.int64) * shard_b
        raw = int(self._sizes.sum())
        cost_file = np.where(icat >= 0, sv.byte_cost[isafe],
                             sv.default_byte_cost)
        tier_file = np.where(icat >= 0, sv.tier_idx[isafe],
                             sv.default_tier_idx)
        per_tier = np.bincount(tier_file, weights=stored,
                               minlength=len(sv.tier_names))
        names = list(sv.categories) + ["Unplanned"]
        bucket = np.where(planned, cat, len(sv.categories))
        per_cat = np.bincount(bucket, weights=stored, minlength=len(names))
        total = int(stored.sum())
        return {
            "bytes_raw": raw,
            "bytes_stored": total,
            "overhead_ratio": round(total / raw, 6) if raw else 0.0,
            "cost_units": round(float((stored * cost_file).sum()), 3),
            "ec_files": ec_files,
            "per_tier_bytes": {t: int(per_tier[i])
                               for i, t in enumerate(sv.tier_names)
                               if per_tier[i]},
            "per_category_bytes": {c: int(per_cat[i])
                                   for i, c in enumerate(names)
                                   if per_cat[i]},
        }

    def _serve_penalty_ms(self, slot_ok: np.ndarray,
                          fids: np.ndarray | None = None) -> np.ndarray:
        """(n_files,) additive read latency from the storage layer: the
        tier penalty (a cold read is ``1/throughput`` x slower than the
        hot-tier service time) plus the degraded-read penalty — a read
        of an EC file whose PRIMARY shard is unreachable must gather k
        shards from the surviving stripe before it can answer.  Reads
        hit whatever encoding is actually on disk, so the penalty
        follows the INSTALLED category (deferred conversions are still
        plain hot-tier copies).  ``fids`` restricts to a compacted
        view's rows (the lowmem serve path) — the result is then
        per-row, not per-population-file."""
        sv = self._storage
        cat = (self._installed_cat if fids is None
               else self._installed_cat[fids])
        safe = np.clip(cat, 0, None)
        pen = np.where(cat >= 0, sv.read_penalty[safe],
                       sv.default_read_penalty)
        k_file = sv.file_ec_k(cat)
        primary_down = ~slot_ok[:, 0] if slot_ok.shape[1] else \
            np.ones(cat.shape[0], dtype=bool)
        base = float(self.cfg.serve.service_ms)
        return base * (pen - 1.0) + np.where(
            (k_file > 0) & primary_down,
            base * (k_file - 1) * pen, 0.0)

    def _fn_static_rows(self, uniq: np.ndarray) -> np.ndarray:
        """(k, R) computed slot rows of a file subset against the CURRENT
        rf vector — the functional serve path's resolver (no fault state:
        the static placement is a pure function, so there is no exception
        overlay to consult)."""
        from ..placement_fn import compute_placement, primary_on_topology

        topology = self._serve_topology
        if self._fn_static_primary is None:
            self._fn_static_primary = primary_on_topology(
                self.manifest.nodes, self.manifest.primary_node_id,
                topology)
        slots, _ = compute_placement(
            uniq, self.current_rf[uniq], self._fn_static_primary[uniq],
            topology, 0)
        return slots

    def _placement_for(self, rf: np.ndarray):
        """Placement for an rf vector — a pure seeded function, cached so
        move-free windows (the common steady state), the before/after
        evaluation pair, and the read router don't redo the O(n x nodes)
        priority sort.  Serve mode routes against the serve topology
        (``cfg.topology`` or flat); without serve this is the historical
        flat topology bit-for-bit."""
        key = rf.tobytes()
        if self._storage is not None:
            # Two categories can share a shard count but differ in
            # shard SIZE (replicate vs EC) — the storage accounting of
            # the cached placement depends on the category vector too.
            key += self.current_cat.tobytes()
        if self._placement_key != key:
            from ..cluster import (
                ClusterTopology,
                place_replicas,
                place_stripes,
            )

            topology = self._serve_topology or ClusterTopology(
                nodes=tuple(self.manifest.nodes))
            if self._storage is not None:
                # Shard-aware placement: an EC slot holds size/k bytes,
                # not the full file (all-replicate shard_bytes == sizes
                # and this is place_replicas bit-for-bit).  Region-local
                # categories pin to the primary's top-level domain on a
                # hierarchical topology (no-op otherwise).
                self._placement = place_stripes(
                    self.manifest, rf.copy(), topology, seed=0,
                    shard_bytes=self._storage.file_shard_bytes(
                        self.current_cat, self._sizes),
                    method=self._placement_method,
                    local_mask=self._storage.file_region_local(
                        self.current_cat))
            else:
                self._placement = place_replicas(
                    self.manifest, rf.copy(), topology, seed=0,
                    method=self._placement_method)
            self._placement_key = key
        return self._placement

    def _evaluate(self, events: EventLog, rf: np.ndarray):
        from ..cluster import evaluate_placement

        m = evaluate_placement(self.manifest, events,
                               self._placement_for(rf), seed=0)
        return float(m.read_locality), float(m.load_balance)

    # -- checkpoint --------------------------------------------------------
    def save_checkpoint(self, path: str,
                        extra_meta: dict | None = None) -> None:
        """Atomic npz snapshot of the full controller state.

        ``extra_meta`` rides along in the JSON meta blob under the
        caller's own keys (the streaming daemon stores its ingest
        cursor there, so ONE atomic file carries both the controller
        state and the resume position — no torn two-file checkpoint);
        ``load_checkpoint`` hands the full meta back via
        ``last_checkpoint_meta``."""
        from ..utils.checkpoint import save_state

        # A lazily accepted decision must land in host arrays before it
        # can be serialized (no-op unless a recluster just dispatched).
        self._ensure_accepted()
        arrays = {k: np.asarray(getattr(self._state, k))
                  for k in self._NP_STATE}
        if self._dec is not None:
            for k, v in self._dec.items():
                arrays["dec_" + k] = v
        arrays["current_rf"] = self.current_rf
        arrays["current_cat"] = self.current_cat
        arrays["installed_cat"] = self._installed_cat
        # Provenance causes, SPARSE over the scheduler backlog: admitted
        # moves are the only reader of the cause vector and they always
        # come from the backlog, so O(pending moves) rows restore the
        # full story — an O(n_files) dense dump would break the
        # functional mode's O(exceptions) checkpoint claim.
        bl_fids = self.scheduler.backlog.file_index
        arrays["move_cause_fids"] = bl_fids.copy()
        arrays["move_cause_vals"] = self._move_cause[bl_fids]
        if self._accepted_centroids is not None:
            arrays["accepted_centroids"] = self._accepted_centroids
            arrays["accepted_category_idx"] = self._accepted_category_idx
            arrays["accepted_fractions"] = self._accepted_fractions
        arrays.update(self.scheduler.state_arrays())
        if self._cluster_state is not None:
            if self.cfg.placement_mode == "functional":
                # Sparse placement snapshot: exceptions over the
                # computed base, with the shard-intent reconstruction
                # anchored at current_rf (also in this checkpoint).
                arrays.update(self._cluster_state.state_arrays(
                    rf_hint=self.current_rf))
            else:
                arrays.update(self._cluster_state.state_arrays())
            arrays.update(self._repairs.state_arrays())
        if self._hotspot is not None:
            arrays.update(self._hotspot.state_arrays())
        if self._scrub is not None:
            arrays.update(self._scrub.state_arrays())
        if self._elastic is not None:
            arrays["elastic_queue"] = self._elastic.queue.copy()
        meta = {
            "window_index": self.window_index,
            "last_window_events": self._last_window_events,
            "t0": self._t0,
            "events_total": self._events_total,
            "sec_base": self._state.sec_base,
            "observation_end": self._state.observation_end,
            "state_n_events": self._state.n_events,
            "dec_obs_end": self._dec_obs_end if self._dec is not None
            else None,
            "decay": self.cfg.decay,
            "window_seconds": self.cfg.window_seconds,
            "k": int(self.cfg.kmeans.k),
            "backend": self.cfg.backend,
            "n_files": len(self.manifest),
            "faults": self._cluster_state is not None,
            "serve": self._router is not None,
            "storage": self._storage is not None,
            "scrub": self._scrub is not None,
            "placement": self.cfg.placement_mode,
        }
        if self._elastic is not None:
            es = self._elastic
            meta["elastic"] = {
                "hot": es.hot, "cool": es.cool,
                "active": list(es.active),
                "moved_total": es.moved_total,
                "drains": [[int(a), str(b)] for a, b in es.drains],
                "scaled": bool(es.scaled),
                "last_burn": es.last_burn,
                "last_util": es.last_util,
            }
        if self.cfg.backend == "jax":
            meta["pad_events"] = self._state.pad_events
        if extra_meta:
            meta.update(extra_meta)
        stats = save_state(path, arrays, meta=meta)
        # Per-save record (window-stamped): the checkpoint-size artifact
        # the functional placement mode is measured by.
        self.checkpoint_log.append(
            {"window": int(self.window_index), **stats})

    def load_checkpoint(self, path: str) -> None:
        from ..utils.checkpoint import load_state

        arrays, meta = load_state(path)
        for key, want in (("n_files", len(self.manifest)),
                          ("k", int(self.cfg.kmeans.k)),
                          ("backend", self.cfg.backend),
                          ("decay", self.cfg.decay),
                          ("window_seconds", self.cfg.window_seconds)):
            if meta.get(key) != want:
                raise ValueError(
                    f"checkpoint {path!r} has {key}={meta.get(key)!r} but "
                    f"the controller expects {want!r} — stale checkpoint? "
                    f"delete it to start over")
        # Fault-mode flag checked separately: pre-fault checkpoints carry
        # no "faults" key and must keep loading in non-fault controllers.
        if bool(meta.get("faults", False)) != (self._cluster_state
                                               is not None):
            raise ValueError(
                f"checkpoint {path!r} has faults="
                f"{bool(meta.get('faults', False))} but the controller "
                f"expects {self._cluster_state is not None} — stale "
                f"checkpoint? delete it to start over")
        # Serve-mode flag likewise checked separately: pre-serve
        # checkpoints carry no "serve" key and keep loading in serve-less
        # controllers; a serve-enabled controller cannot resume bit-
        # identically without the hotspot EWMA baseline.
        if bool(meta.get("serve", False)) != (self._router is not None):
            raise ValueError(
                f"checkpoint {path!r} has serve="
                f"{bool(meta.get('serve', False))} but the controller "
                f"expects {self._router is not None} — stale checkpoint? "
                f"delete it to start over")
        # Storage-strategy flag, same posture: pre-storage checkpoints
        # carry no "storage" key and keep loading in storage-less
        # controllers; a storage-enabled controller must not resume from
        # a snapshot whose targets meant plain rf.
        if bool(meta.get("storage", False)) != (self._storage is not None):
            raise ValueError(
                f"checkpoint {path!r} has storage="
                f"{bool(meta.get('storage', False))} but the controller "
                f"expects {self._storage is not None} — stale "
                f"checkpoint? delete it to start over")
        # Scrub flag, same posture: a scrubbing controller cannot resume
        # bit-identically without its cursor/hint state.
        if bool(meta.get("scrub", False)) != (self._scrub is not None):
            raise ValueError(
                f"checkpoint {path!r} has scrub="
                f"{bool(meta.get('scrub', False))} but the controller "
                f"expects {self._scrub is not None} — stale "
                f"checkpoint? delete it to start over")
        # Placement mode, same posture: pre-placement-mode checkpoints
        # carry no key and keep loading in materialized controllers; a
        # sparse functional snapshot cannot restore a dense state (or
        # vice versa) and the base chooser must match.
        ck_mode = meta.get("placement", "materialized")
        if ck_mode != self.cfg.placement_mode:
            raise ValueError(
                f"checkpoint {path!r} has placement={ck_mode!r} but the "
                f"controller expects {self.cfg.placement_mode!r} — stale "
                f"checkpoint? delete it to start over")
        if self.cfg.backend == "jax":
            import jax.numpy as jnp

            from ..features.streaming import StreamFeatureState

            self._state = StreamFeatureState(
                **{k: jnp.asarray(arrays[k]) for k in self._NP_STATE},
                sec_base=meta.get("sec_base"),
                observation_end=meta.get("observation_end"),
                n_events=int(meta.get("state_n_events", 0)),
                pad_events=int(meta.get("pad_events", 0)))
        else:
            for k in self._NP_STATE:
                setattr(self._state, k, arrays[k].copy())
            self._state.sec_base = meta.get("sec_base")
            self._state.observation_end = meta.get("observation_end")
            self._state.n_events = int(meta.get("state_n_events", 0))
        if self._dec is not None:
            for k in self._dec:
                self._dec[k] = arrays["dec_" + k].copy()
            self._dec_obs_end = meta.get("dec_obs_end")
        self.current_rf = arrays["current_rf"].astype(np.int32)
        self.current_cat = arrays["current_cat"].astype(np.int32)
        # Pre-PR-7 checkpoints have no installed_cat: nothing was ever
        # deferred, so installed == target.
        self._installed_cat = (arrays["installed_cat"].astype(np.int32)
                               if "installed_cat" in arrays
                               else self.current_cat.copy())
        # Pre-provenance checkpoints carry no cause rows: the resumed
        # backlog's moves report cause "unknown" (MOVE_CAUSES code 0).
        self._move_cause = np.zeros(len(self.manifest), dtype=np.int8)
        if "move_cause_fids" in arrays:
            self._move_cause[arrays["move_cause_fids"]] = \
                arrays["move_cause_vals"].astype(np.int8)
        if "accepted_centroids" in arrays:
            self._accepted_centroids = arrays["accepted_centroids"]
            self._accepted_category_idx = arrays["accepted_category_idx"]
            self._accepted_fractions = arrays["accepted_fractions"]
        # The stash is not checkpointed; a restored controller recomputes
        # it on the next materialize (stale values must never survive a
        # load).
        self._accepted_file_cat = None
        self.scheduler.load_state_arrays(arrays)
        if self._elastic is not None and meta.get("elastic"):
            # Elastic growth must be REPLAYED before the state arrays
            # load: a post-scale-out snapshot's arrays are sized for the
            # grown topology, and the serve plane must route on it too.
            em = meta["elastic"]
            es = self._elastic
            es.hot = int(em["hot"])
            es.cool = int(em["cool"])
            es.active = tuple(em["active"])
            es.moved_total = int(em["moved_total"])
            es.drains = [(int(a), str(b)) for a, b in em["drains"]]
            es.scaled = bool(em["scaled"])
            es.last_burn = em["last_burn"]
            es.last_util = em["last_util"]
            es.queue = np.asarray(
                arrays.get("elastic_queue", np.zeros(0, np.int64)),
                dtype=np.int64).copy()
            if es.active:
                from ..serve import ReadRouter

                topo_new = es.policy.grown_topology(
                    self._cluster_state.topology, es.active)
                self._cluster_state.grow(topo_new)
                self._serve_topology = topo_new
                self._router = ReadRouter(len(topo_new.nodes),
                                          self.cfg.serve)
                self._edge_ms = self._edge_latency_ms(topo_new)
                self._fn_static_primary = None
        if self._cluster_state is not None:
            self._cluster_state.load_state_arrays(arrays)
            self._repairs.load_state_arrays(arrays)
        if self._hotspot is not None:
            self._hotspot.load_state_arrays(arrays)
        if self._scrub is not None:
            self._scrub.load_state_arrays(arrays)
        self.window_index = int(meta["window_index"])
        self._last_window_events = int(meta.get("last_window_events", 0))
        self._t0 = meta.get("t0")
        self._events_total = int(meta.get("events_total", 0))
        #: Full meta blob of the snapshot just loaded — callers that
        #: stored ``extra_meta`` via ``save_checkpoint`` (the streaming
        #: daemon's ingest cursor) read it back from here.
        self.last_checkpoint_meta = meta

    def _load_checkpoint_with_fallback(self, path: str) -> None:
        """Resume from ``path``; a corrupt/truncated snapshot (power cut
        mid-write, disk fault) degrades to the retained last-good
        ``<path>.prev`` copy (utils/checkpoint.save_state) instead of
        crashing — the fallback window is one checkpoint interval older,
        and the deterministic loop re-processes forward from it to the
        identical state.  Config-mismatch ValueErrors still raise: a
        *stale* checkpoint is an operator error, not a fault."""
        import warnings

        from ..utils.checkpoint import CheckpointError

        prev = path + ".prev"
        if not os.path.exists(path):
            # A deleted checkpoint always means "start over" — save_state
            # retains .prev by hardlink, so path only vanishes by hand.
            return
        try:
            self.load_checkpoint(path)
            return
        except CheckpointError as e:
            if not os.path.exists(prev):
                raise
            warnings.warn(
                f"{e}; falling back to the retained last-good "
                f"snapshot {prev!r}", RuntimeWarning, stacklevel=2)
        from ..obs import current as _obs_current

        tel = _obs_current()
        if tel is not None:
            tel.counter_inc("degraded.checkpoint_fallback")
        self.load_checkpoint(prev)
        # Promote the good snapshot back over the corrupt ``path``:
        # otherwise the next save_state would retain the corrupt file as
        # the new ``.prev``, destroying the very snapshot just resumed
        # from.  Prefer a link so ``.prev`` survives too.
        tmp = prev + ".promote"
        try:
            if os.path.exists(tmp):  # leftover from a crashed promotion
                os.unlink(tmp)
            os.link(prev, tmp)
            os.replace(tmp, path)
        except OSError:
            import shutil

            # No hardlinks: promote by copy so ``.prev`` survives too.
            shutil.copyfile(prev, tmp)
            os.replace(tmp, path)

    # -- the loop ----------------------------------------------------------
    def run(self, source, *, metrics_path: str | None = None,
            metrics_max_bytes: int | None = None,
            checkpoint_path: str | None = None, checkpoint_every: int = 1,
            max_windows: int | None = None,
            batch_size: int = 1_000_000) -> ControllerResult:
        """Drive the controller over a log (path, EventLog, or batch iter).

        ``checkpoint_path``: resume from an existing snapshot (windows
        before its ``window_index`` are skipped without folding — the log is
        re-read from the start, so the window grid is identical) and
        snapshot every ``checkpoint_every`` processed windows plus once at
        exit.  Unlike the streaming fold's checkpoint, the snapshot is NOT
        deleted on completion — a controller is a long-running process and
        a later run over a longer APPEND-ONLY log continues from it:
        events that arrived inside the previously-final partial window's
        time span are folded into the feature state on resume (that
        window's migration tick already ran, so they inform the NEXT
        windows' drift/plans; rewriting history earlier in the log is not
        detected).  Resume re-reads the log from byte 0 and skips processed
        windows — O(history) per restart; checkpointing the byte offset of
        the last completed window (the read_csv_batches
        ``start_offset``/``with_offsets`` hooks fold_stream already uses)
        is the known follow-up that would make it O(new data).

        ``metrics_path``: append one JSON line per window through the
        telemetry layer's thread-safe sink (obs/sink.JsonlSink: one
        ``write()`` + flush per line, atomic from a tailing reader's view).
        The stream is append-only; after a crash the tail may repeat the
        windows between the last snapshot and the crash — consumers take
        the last record per window index.  Each line is the window record
        with ``"kind": "window"`` stamped, so ``cdrs metrics summarize``
        digests the stream alongside full telemetry output.  When an
        ``obs.Telemetry`` is additionally active (``with Telemetry(...)``),
        migration/re-cluster counters and per-stage histograms flow through
        it as well.

        ``max_windows`` stops after that many windows are PROCESSED this
        call (resume-skipped windows don't count) — the kill/resume test
        hook, also useful for stepping a live controller.
        """
        if checkpoint_path:
            self._load_checkpoint_with_fallback(checkpoint_path)
        records: list[dict] = []
        sink = None
        own_sink = False
        if metrics_path:
            from ..obs import JsonlSink
            from ..obs import current as _obs_current

            # One stream, ONE writer: when an active Telemetry already
            # owns a sink on this very path (the `cdrs control --metrics`
            # wiring), share it — two independent JsonlSink instances on
            # one file would each track their own size and, under
            # max_bytes rotation, rotate the file out from under each
            # other.  The shared sink's lifetime belongs to the
            # Telemetry context; a private sink is closed here.
            tel = _obs_current()
            if (tel is not None and tel.sink is not None
                    and getattr(tel.sink, "path", None) == metrics_path):
                sink = tel.sink
            else:
                sink = JsonlSink(metrics_path,
                                 max_bytes=metrics_max_bytes)
                own_sink = True
        processed = 0
        since_ckpt = 0
        t0_box: dict = {}
        every = max(1, checkpoint_every)
        overlap = bool(self.cfg.overlap_windows)
        #: Window context dispatched (phase A) but not yet planned (phase
        #: B) — the one-deep pipeline of the overlap schedule.
        pending: dict | None = None

        def finish(ctx: dict) -> None:
            nonlocal processed, since_ckpt
            rec = self._window_phase_b(ctx)
            self.window_index = ctx["w"] + 1
            self._last_window_events = len(ctx["events"])
            records.append(rec)
            if sink:
                sink.emit({"kind": "window", **rec})
            processed += 1
            since_ckpt += 1

        try:
            for w, events in iter_windows(source, self.manifest,
                                          self.cfg.window_seconds,
                                          batch_size=batch_size,
                                          t0=self._t0, t0_out=t0_box):
                # BEFORE processing: max_windows=0 mutates nothing, and a
                # held window counts as soon as it would complete — the
                # next window must not even fold past the limit.
                if max_windows is not None and processed \
                        + (1 if pending is not None else 0) >= max_windows:
                    break
                if self._t0 is None:
                    # iter_windows derived the grid origin from the first
                    # event; checkpoint it so resume replays the same grid.
                    self._t0 = t0_box.get("t0")
                if w < self.window_index:
                    # Resume: already folded + planned.  The final processed
                    # window can have GROWN since the snapshot (append-only
                    # log): fold its late tail so no event is lost.
                    if (w == self.window_index - 1
                            and len(events) > self._last_window_events):
                        from .windows import _slice

                        self._fold_window(
                            _slice(events, self._last_window_events,
                                   len(events)), new_window=False)
                        self._last_window_events = len(events)
                        since_ckpt += 1  # state changed: snapshot at exit
                    continue
                # A window is only ever held when completing it cannot
                # trigger a snapshot (the hold guard below), so no
                # flush-before-fold is needed here: a checkpoint can never
                # contain a dispatched-but-unplanned window's state.
                ctx = self._window_phase_a(w, events)
                if pending is not None:
                    # The overlap: last window's host planning runs while
                    # the device chews on this window's cluster step.
                    finish(pending)
                    pending = None
                if overlap and not (checkpoint_path
                                    and since_ckpt + 1 >= every):
                    pending = ctx
                else:
                    finish(ctx)
                    if checkpoint_path and since_ckpt >= every:
                        self.save_checkpoint(checkpoint_path)
                        since_ckpt = 0
            if pending is not None:
                finish(pending)
                pending = None
        finally:
            if sink and own_sink:
                sink.close()
        # Snapshot only on CLEAN exit: an exception can land mid-window
        # (events folded, window_index not yet advanced) and a snapshot of
        # that torn state would double-fold the window on resume.  A crash
        # instead resumes from the last per-window snapshot and
        # deterministically re-processes — bit-identical by construction.
        if checkpoint_path and since_ckpt:
            self.save_checkpoint(checkpoint_path)
        return ControllerResult(records=records, rf=self.current_rf.copy(),
                                category_idx=self.current_cat.copy(),
                                manifest=self.manifest,
                                checkpoints=list(self.checkpoint_log))
