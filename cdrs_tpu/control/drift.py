"""Cheap windowed drift detection against the last accepted model.

The controller must not pay a full re-cluster per window; this detector
answers "did the feature distribution move?" with two O(n·k·d) quantities
computed from the current feature snapshot and the last ACCEPTED model
(centroids + per-category population fractions):

* **centroid shift** — one Lloyd step from the accepted centroids (assign,
  then per-cluster means; empty clusters do not move) and the RMS L2 norm of
  the centroid movement.  Features are min-max normalized to [0, 1]
  (features/streaming_np.finalize_counters), so the magnitude is comparable
  across workloads.
* **population delta** — total-variation distance between the per-category
  population fractions under the accepted model's (centroid -> category)
  mapping and the fractions recorded when the model was accepted.

``score = max(centroid_shift, population_delta)``: either signal alone is
grounds to re-cluster (a category flip can move populations with little
centroid motion and vice versa).  Everything is plain NumPy — the detector
runs every window, on host, regardless of the clustering backend.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

__all__ = ["DriftReport", "detect_drift"]


@dataclass(frozen=True)
class DriftReport:
    score: float             # max(centroid_shift, population_delta)
    centroid_shift: float    # RMS L2 centroid movement of one Lloyd step
    population_delta: float  # total-variation distance of category fractions
    fractions: np.ndarray    # (n_categories,) current category fractions


def detect_drift(
    X: np.ndarray,
    centroids: np.ndarray,
    category_idx: np.ndarray,
    accepted_fractions: np.ndarray,
    n_categories: int,
) -> DriftReport:
    """Drift of the feature snapshot ``X`` against the accepted model."""
    from ..ops.kmeans_np import assign_labels

    X = np.asarray(X, dtype=np.float64)
    c = np.asarray(centroids, dtype=np.float64)
    k = c.shape[0]
    # The clustering path's own tiled assignment kernel — one tie-break/
    # tiling implementation for both the model and its drift detector.
    labels = assign_labels(X, c)

    counts = np.bincount(labels, minlength=k).astype(np.float64)
    sums = np.stack([np.bincount(labels, weights=X[:, j], minlength=k)
                     for j in range(X.shape[1])], axis=1)
    nonempty = counts > 0
    means = np.where(nonempty[:, None], sums / np.maximum(counts, 1.0)[:, None], c)
    shift = float(np.sqrt((((means - c) ** 2).sum(axis=1)).mean()))

    cat_per_file = np.asarray(category_idx)[labels]
    frac = np.bincount(cat_per_file, minlength=n_categories).astype(np.float64)
    frac /= max(len(labels), 1)
    pop_delta = float(0.5 * np.abs(frac - np.asarray(accepted_fractions)).sum())

    return DriftReport(score=max(shift, pop_delta), centroid_shift=shift,
                       population_delta=pop_delta, fractions=frac)
