"""Cheap windowed drift detection against the last accepted model.

The controller must not pay a full re-cluster per window; this detector
answers "did the feature distribution move?" with two O(n·k·d) quantities
computed from the current feature snapshot and the last ACCEPTED model
(centroids + per-category population fractions):

* **centroid shift** — one Lloyd step from the accepted centroids (assign,
  then per-cluster means; empty clusters do not move) and the RMS L2 norm of
  the centroid movement.  Features are min-max normalized to [0, 1]
  (features/streaming_np.finalize_counters), so the magnitude is comparable
  across workloads.
* **population delta** — total-variation distance between the per-category
  population fractions under the accepted model's (centroid -> category)
  mapping and the fractions recorded when the model was accepted.

``score = max(centroid_shift, population_delta)``: either signal alone is
grounds to re-cluster (a category flip can move populations with little
centroid motion and vice versa).  Two implementations:

* :func:`detect_drift` — plain NumPy on host (float64), the oracle; runs
  every window regardless of the clustering backend.
* :func:`detect_drift_jax` — the same one-Lloyd-step math inside a
  ``shard_map`` body data-parallel over files: each shard assigns its rows
  and reduces local per-cluster (sum, count); ONE ``psum`` of the
  ``(k, d+1)`` sufficient statistics per call merges them — the feature
  table never gathers to one device, and the category fractions fall out
  of the already-psum'd counts (no second data pass).  Float32 on device,
  so scores agree with the oracle to fp tolerance while re-cluster/plan
  decisions are identical (tests/test_mesh_control.py).  Used by the
  controller when ``ControllerConfig.mesh_shape`` is set.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import numpy as np

__all__ = ["DriftReport", "detect_drift", "detect_drift_jax"]


@dataclass(frozen=True)
class DriftReport:
    score: float             # max(centroid_shift, population_delta)
    centroid_shift: float    # RMS L2 centroid movement of one Lloyd step
    population_delta: float  # total-variation distance of category fractions
    fractions: np.ndarray    # (n_categories,) current category fractions


def detect_drift(
    X: np.ndarray,
    centroids: np.ndarray,
    category_idx: np.ndarray,
    accepted_fractions: np.ndarray,
    n_categories: int,
) -> DriftReport:
    """Drift of the feature snapshot ``X`` against the accepted model."""
    from ..ops.kmeans_np import assign_labels

    X = np.asarray(X, dtype=np.float64)
    c = np.asarray(centroids, dtype=np.float64)
    k = c.shape[0]
    # The clustering path's own tiled assignment kernel — one tie-break/
    # tiling implementation for both the model and its drift detector.
    labels = assign_labels(X, c)

    counts = np.bincount(labels, minlength=k).astype(np.float64)
    sums = np.stack([np.bincount(labels, weights=X[:, j], minlength=k)
                     for j in range(X.shape[1])], axis=1)
    nonempty = counts > 0
    means = np.where(nonempty[:, None], sums / np.maximum(counts, 1.0)[:, None], c)
    shift = float(np.sqrt((((means - c) ** 2).sum(axis=1)).mean()))

    cat_per_file = np.asarray(category_idx)[labels]
    frac = np.bincount(cat_per_file, minlength=n_categories).astype(np.float64)
    frac /= max(len(labels), 1)
    pop_delta = float(0.5 * np.abs(frac - np.asarray(accepted_fractions)).sum())

    return DriftReport(score=max(shift, pop_delta), centroid_shift=shift,
                       population_delta=pop_delta, fractions=frac)


@functools.lru_cache(maxsize=32)
def _build_drift(n_valid: int, d: int, k: int, ncat: int, ndata: int):
    """Compile the sharded drift kernel for one (shape, mesh) point.

    ``ndata == 1`` compiles the same body under plain jit with the
    collectives elided (the streaming fold's one-device-bypass pattern) —
    the ``mesh_shape={"data": 1}`` path the overhead bench holds against
    the host oracle.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..ops.kmeans_jax import (_weighted_cluster_stats, assign_labels_jax)
    from ..parallel.mesh import (DATA_AXIS, make_mesh, prefix_mask,
                                 shard_map_compat)

    sharded = ndata > 1

    def local_fn(x, c, cat_idx, acc_frac):
        w = prefix_mask(x, n_valid, sharded=sharded)
        labels = assign_labels_jax(x, c)
        # ``scatter`` (segment_sum) matches numpy bincount accumulation
        # order, keeping the shard-local partials as close to the oracle
        # as f32 allows.
        sums, counts = _weighted_cluster_stats(x, w, labels, k, "scatter")
        if sharded:
            # THE one collective: (k, d+1) sufficient statistics — the
            # same sums/counts identity the Lloyd update psums.
            stats = lax.psum(
                jnp.concatenate([sums, counts[:, None]], axis=1), DATA_AXIS)
            sums, counts = stats[:, :d], stats[:, d]
        means = jnp.where(counts[:, None] > 0,
                          sums / jnp.maximum(counts, 1.0)[:, None], c)
        shift = jnp.sqrt(jnp.mean(jnp.sum((means - c) ** 2, axis=1)))
        # Category fractions fall out of the psum'd per-cluster counts —
        # no per-file gather, no second pass.
        frac = jnp.zeros((ncat,), sums.dtype).at[cat_idx].add(counts) \
            / n_valid
        pop_delta = 0.5 * jnp.sum(jnp.abs(frac - acc_frac))
        return shift, pop_delta, frac

    if not sharded:
        return jax.jit(local_fn)
    mesh = make_mesh(n_data=ndata)
    return jax.jit(shard_map_compat(
        local_fn, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(), P(), P()),
        out_specs=(P(), P(), P()),
        check_vma=False,
    ))


def detect_drift_jax(
    X: np.ndarray,
    centroids: np.ndarray,
    category_idx: np.ndarray,
    accepted_fractions: np.ndarray,
    n_categories: int,
    mesh_shape: dict[str, int] | None = None,
) -> DriftReport:
    """Mesh-sharded drift of ``X`` against the accepted model.

    Same report as :func:`detect_drift` with the one-Lloyd-step statistics
    reduced across the ``data`` mesh axis (see module docstring).  Rows
    pad to a shard multiple with weight-0 tails (``pad_rows`` +
    ``prefix_mask``); the centroid table is replicated (a model axis would
    buy nothing at k·d drift scale, so only ``data`` is honored).
    """
    import jax.numpy as jnp

    from ..parallel.mesh import DATA_AXIS, pad_rows, validate_mesh_shape

    ndata = int(validate_mesh_shape(mesh_shape).get(DATA_AXIS, 1))
    X = np.asarray(X, dtype=np.float32)
    c = np.asarray(centroids, dtype=np.float32)
    Xp, n_valid = pad_rows(X, ndata)
    fn = _build_drift(n_valid, X.shape[1], c.shape[0], int(n_categories),
                      ndata)
    shift, pop_delta, frac = fn(
        jnp.asarray(Xp), jnp.asarray(c),
        jnp.asarray(np.asarray(category_idx), jnp.int32),
        jnp.asarray(np.asarray(accepted_fractions), jnp.float32))
    shift = float(shift)
    pop_delta = float(pop_delta)
    return DriftReport(score=max(shift, pop_delta), centroid_shift=shift,
                       population_delta=pop_delta,
                       fractions=np.asarray(frac, dtype=np.float64))
