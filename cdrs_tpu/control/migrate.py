"""Plan diffing and bounded-churn migration scheduling.

A re-cluster produces a NEW target plan (per-file category + replication
factor); applying it wholesale is exactly the churn storm dynamic replication
exists to avoid.  This module turns a plan delta into *moves* and meters them
out:

* ``plan_diff`` — per-file rf up/down moves with their byte-move cost
  (``size_bytes * max(0, rf_new - rf_old)``: new replicas are copied over the
  network; dropping a replica is a metadata delete and moves no bytes) and a
  caller-supplied priority (the controller uses the scoring margin of the new
  category over the currently applied one).
* ``MigrationScheduler`` — a backlog keyed by file.  ``submit`` replaces the
  backlog with the newest plan's moves (a newer plan supersedes pending moves
  for the same file, and files that no longer differ drop out — this is the
  anti-flap property a FIFO queue lacks).  ``schedule`` pops up to the
  per-window churn budget (bytes moved and/or files touched), highest
  priority first, and enforces **hysteresis**: a file migrated at window w is
  frozen until ``w + 1 + hysteresis_windows``, so a borderline file cannot
  oscillate between categories every window.

Everything is deterministic: ties break on file index, and the scheduler's
whole state round-trips through ``state_arrays``/``load_state_arrays`` for
the controller's checkpoint.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

__all__ = ["PlanMove", "plan_diff", "MigrationScheduler"]

#: ``last_moved`` sentinel: "never moved" must stay eligible at window 0
#: for any hysteresis setting.
_NEVER = -(2 ** 40)


@dataclass(frozen=True)
class PlanMove:
    """One per-file replication change: rf_old -> rf_new (and its category)."""

    file_index: int
    rf_old: int
    rf_new: int
    cat_old: int      # index into config.CATEGORIES; -1 = not yet planned
    cat_new: int
    bytes_moved: int  # default size_bytes * max(0, rf_new - rf_old)
    priority: float   # larger = applied earlier


def plan_diff(rf_old, rf_new, cat_old, cat_new, size_bytes,
              priority=None, move_bytes=None) -> list[PlanMove]:
    """Moves for every file whose (rf, category) changed between two plans.

    All inputs are (n,) arrays; ``priority`` defaults to zero, so callers
    that don't score moves get stable file-index ordering.  ``move_bytes``
    overrides the per-file byte cost (the storage layer charges a
    strategy re-encode as the new shards written, not an rf delta of
    full copies); default is the historical
    ``size_bytes * max(0, rf_new - rf_old)``.
    """
    rf_old = np.asarray(rf_old, dtype=np.int64)
    rf_new = np.asarray(rf_new, dtype=np.int64)
    cat_old = np.asarray(cat_old, dtype=np.int64)
    cat_new = np.asarray(cat_new, dtype=np.int64)
    size_bytes = np.asarray(size_bytes, dtype=np.int64)
    n = rf_old.shape[0]
    for name, a in (("rf_new", rf_new), ("cat_old", cat_old),
                    ("cat_new", cat_new), ("size_bytes", size_bytes)):
        if a.shape != (n,):
            raise ValueError(f"{name} shape {a.shape} != ({n},)")
    prio = np.zeros(n) if priority is None else np.asarray(priority,
                                                           dtype=np.float64)
    changed = np.flatnonzero((rf_new != rf_old) | (cat_new != cat_old))
    if move_bytes is None:
        bytes_moved = size_bytes * np.maximum(rf_new - rf_old, 0)
    else:
        bytes_moved = np.asarray(move_bytes, dtype=np.int64)
        if bytes_moved.shape != (n,):
            raise ValueError(
                f"move_bytes shape {bytes_moved.shape} != ({n},)")
    return [PlanMove(file_index=int(i), rf_old=int(rf_old[i]),
                     rf_new=int(rf_new[i]), cat_old=int(cat_old[i]),
                     cat_new=int(cat_new[i]), bytes_moved=int(bytes_moved[i]),
                     priority=float(prio[i]))
            for i in changed]


class MigrationScheduler:
    """Backlog + churn budget + hysteresis (see module docstring)."""

    def __init__(self, n_files: int, max_bytes_per_window: int | None = None,
                 max_files_per_window: int | None = None,
                 hysteresis_windows: int = 0):
        if max_bytes_per_window is not None and max_bytes_per_window < 0:
            raise ValueError("max_bytes_per_window must be >= 0 or None")
        if max_files_per_window is not None and max_files_per_window < 1:
            raise ValueError("max_files_per_window must be >= 1 or None")
        self.n_files = int(n_files)
        self.max_bytes = max_bytes_per_window
        self.max_files = max_files_per_window
        self.hysteresis = int(hysteresis_windows)
        self.backlog: dict[int, PlanMove] = {}
        self.last_moved = np.full(n_files, _NEVER, dtype=np.int64)
        #: Telemetry of the most recent ``schedule`` call: moves skipped by
        #: the hysteresis freeze vs by the byte budget.  Plain attributes —
        #: per-window observations, deliberately NOT checkpointed state.
        self.last_deferred_hysteresis = 0
        self.last_deferred_budget = 0

    def submit(self, moves: list[PlanMove]) -> None:
        """Replace the backlog with the newest plan's pending moves."""
        self.backlog = {m.file_index: m for m in moves}

    def schedule(self, window_index: int, *, bytes_reserved: int = 0,
                 files_reserved: int = 0) -> list[PlanMove]:
        """Pop this window's moves (budgeted, prioritized, hysteresis-gated).

        Byte budget: a byte-moving move is admitted while ``bytes_used +
        move.bytes <= max_bytes`` — except that a single move larger than
        the whole budget is admitted as the window's first byte-moving move
        (otherwise the largest file would starve forever; churn stays
        bounded by one oversized move per window).  ``max_bytes == 0`` is a
        true freeze: no byte-moving move is admitted at all (the oversized
        allowance needs a positive budget).  Zero-byte moves (replica
        drops, category-only changes) are metadata operations the byte
        budget never blocks; the file cap still counts them and is strict.
        Scheduled moves leave the backlog and stamp ``last_moved``.

        ``bytes_reserved``/``files_reserved`` pre-charge the window's
        budget with traffic another producer already spent — the
        controller's repair pass (faults/repair.py) runs first and hands
        its consumption here, so repair and drift-migration traffic
        compete for ONE churn allowance.  A nonzero reservation also
        disables the oversized-move allowance for this window (the first
        byte-moving operation was the reserver's).
        """
        order = sorted(self.backlog.values(),
                       key=lambda m: (-m.priority, m.file_index))
        applied: list[PlanMove] = []
        bytes_used = int(bytes_reserved)
        self.last_deferred_hysteresis = 0
        self.last_deferred_budget = 0
        for m in order:
            if self.max_files is not None \
                    and len(applied) + int(files_reserved) >= self.max_files:
                break
            if window_index < int(self.last_moved[m.file_index]) \
                    + 1 + self.hysteresis:
                self.last_deferred_hysteresis += 1
                continue
            if self.max_bytes is not None and m.bytes_moved > 0:
                over = bytes_used + m.bytes_moved > self.max_bytes
                first = bytes_used == 0 and self.max_bytes > 0
                if over and not first:
                    self.last_deferred_budget += 1
                    continue
            applied.append(m)
            bytes_used += m.bytes_moved
        for m in applied:
            del self.backlog[m.file_index]
            self.last_moved[m.file_index] = window_index
        return applied

    @property
    def backlog_bytes(self) -> int:
        return sum(m.bytes_moved for m in self.backlog.values())

    # -- checkpoint (controller snapshots ride utils/checkpoint npz) -------
    _MOVE_COLS = ("file_index", "rf_old", "rf_new", "cat_old", "cat_new",
                  "bytes_moved")

    def state_arrays(self) -> dict[str, np.ndarray]:
        moves = sorted(self.backlog.values(), key=lambda m: m.file_index)
        out = {"sched_" + c: np.asarray([getattr(m, c) for m in moves],
                                        dtype=np.int64)
               for c in self._MOVE_COLS}
        out["sched_priority"] = np.asarray([m.priority for m in moves],
                                           dtype=np.float64)
        out["sched_last_moved"] = self.last_moved.copy()
        return out

    def load_state_arrays(self, arrays: dict) -> None:
        """Restore the backlog + freeze stamps, validating shapes/dtypes
        against ``n_files`` up front — a truncated or foreign checkpoint
        must fail here with a message, not later with an opaque
        IndexError deep in ``schedule``."""
        missing = [k for k in ("sched_last_moved", "sched_priority",
                               *("sched_" + c for c in self._MOVE_COLS))
                   if k not in arrays]
        if missing:
            raise ValueError(
                f"checkpoint is missing scheduler arrays {missing} — "
                f"not a controller snapshot?")
        lm = np.asarray(arrays["sched_last_moved"])
        if lm.shape != (self.n_files,):
            raise ValueError(
                f"checkpoint covers {lm.shape[0] if lm.ndim == 1 else lm.shape} "
                f"files, scheduler has {self.n_files}")
        if not np.issubdtype(lm.dtype, np.integer):
            raise ValueError(
                f"sched_last_moved dtype {lm.dtype} is not integral")
        self.last_moved = lm.astype(np.int64).copy()
        cols = [np.asarray(arrays["sched_" + c]) for c in self._MOVE_COLS]
        prio = np.asarray(arrays["sched_priority"], dtype=np.float64)
        n_moves = cols[0].shape[0] if cols[0].ndim == 1 else -1
        for name, a in zip((*self._MOVE_COLS, "priority"), (*cols, prio)):
            if a.ndim != 1 or a.shape[0] != n_moves:
                raise ValueError(
                    f"scheduler backlog column sched_{name} has shape "
                    f"{a.shape}, expected ({n_moves},)")
            if name != "priority" and not np.issubdtype(a.dtype,
                                                        np.integer):
                raise ValueError(
                    f"scheduler backlog column sched_{name} dtype "
                    f"{a.dtype} is not integral")
        if n_moves and ((cols[0] < 0) | (cols[0] >= self.n_files)).any():
            bad = cols[0][(cols[0] < 0) | (cols[0] >= self.n_files)]
            raise ValueError(
                f"scheduler backlog names file indices outside "
                f"[0, {self.n_files}): {bad[:5].tolist()}")
        self.backlog = {
            int(cols[0][i]): PlanMove(
                file_index=int(cols[0][i]), rf_old=int(cols[1][i]),
                rf_new=int(cols[2][i]), cat_old=int(cols[3][i]),
                cat_new=int(cols[4][i]), bytes_moved=int(cols[5][i]),
                priority=float(prio[i]))
            for i in range(cols[0].shape[0])
        }
