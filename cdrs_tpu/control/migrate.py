"""Plan diffing and bounded-churn migration scheduling (structure-of-arrays).

A re-cluster produces a NEW target plan (per-file category + replication
factor); applying it wholesale is exactly the churn storm dynamic replication
exists to avoid.  This module turns a plan delta into *moves* and meters them
out:

* ``plan_diff`` — per-file rf up/down moves with their byte-move cost
  (``size_bytes * max(0, rf_new - rf_old)``: new replicas are copied over the
  network; dropping a replica is a metadata delete and moves no bytes) and a
  caller-supplied priority (the controller uses the scoring margin of the new
  category over the currently applied one).
* ``MigrationScheduler`` — a backlog keyed by file.  ``submit`` replaces the
  backlog with the newest plan's moves (a newer plan supersedes pending moves
  for the same file, and files that no longer differ drop out — this is the
  anti-flap property a FIFO queue lacks).  ``schedule`` pops up to the
  per-window churn budget (bytes moved and/or files touched), highest
  priority first, and enforces **hysteresis**: a file migrated at window w is
  frozen until ``w + 1 + hysteresis_windows``, so a borderline file cannot
  oscillate between categories every window.

The control plane is **structure-of-arrays end to end**: ``plan_diff``
returns a ``MoveSet`` (seven parallel numpy columns), the scheduler's backlog
IS a MoveSet held in admission order, and the per-window admission runs as a
lexsort + cumsum + ``searchsorted`` threshold scan instead of a Python loop
over move objects — decision-identical to the historical object path
(``cdrs_tpu/compat/reference_planners.py`` keeps that path for the
equivalence tests and ``benchmarks/plan_bench.py``), including:

* the **oversized-move allowance** — when nothing was reserved and the
  budget is positive, the first byte-moving move is admitted even if it
  alone exceeds the budget (the largest file must not starve);
* **reservation semantics** — ``bytes_reserved``/``files_reserved``
  pre-charge the window (the repair pass runs first) and a nonzero byte
  reservation disables the oversized allowance;
* zero-byte moves (replica drops, category-only changes) bypassing the byte
  budget entirely while the file cap still counts them;
* deferral telemetry (hysteresis vs budget) counted only up to the point
  the legacy loop would have ``break``-ed on the file cap.

Everything is deterministic: ties break on file index, and the scheduler's
whole state round-trips through ``state_arrays``/``load_state_arrays`` for
the controller's checkpoint — the backlog columns are dumped as-is (no
re-sort, no per-move object resurrection), and load re-canonicalizes the
admission order with one lexsort, so legacy file-index-ordered checkpoints
keep loading bit-identically.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

__all__ = ["PlanMove", "MoveSet", "plan_diff", "MigrationScheduler"]

#: ``last_moved`` sentinel: "never moved" must stay eligible at window 0
#: for any hysteresis setting.
_NEVER = -(2 ** 40)


@dataclass(frozen=True)
class PlanMove:
    """One per-file replication change: rf_old -> rf_new (and its category).

    The scalar row view of a ``MoveSet`` — kept for tests and small-scale
    callers; the planner itself never materializes these per file.
    """

    file_index: int
    rf_old: int
    rf_new: int
    cat_old: int      # index into config.CATEGORIES; -1 = not yet planned
    cat_new: int
    bytes_moved: int  # default size_bytes * max(0, rf_new - rf_old)
    priority: float   # larger = applied earlier


#: (column name, dtype) of the six integer MoveSet columns (priority is the
#: seventh, float64) — also the checkpoint schema (``sched_<col>``).
_MOVE_COLS = ("file_index", "rf_old", "rf_new", "cat_old", "cat_new",
              "bytes_moved")


class MoveSet:
    """Parallel arrays of plan moves — the planner's native currency.

    Rows are moves; column order is whatever the producer chose (the
    scheduler keeps its backlog in admission order).  Supports ``len``,
    file-id membership (``fid in ms``), iteration as ``PlanMove`` rows
    (small result sets only — the compat/test surface), and fancy-indexed
    ``take``.
    """

    __slots__ = ("file_index", "rf_old", "rf_new", "cat_old", "cat_new",
                 "bytes_moved", "priority")

    def __init__(self, file_index, rf_old, rf_new, cat_old, cat_new,
                 bytes_moved, priority):
        self.file_index = np.asarray(file_index, dtype=np.int64)
        self.rf_old = np.asarray(rf_old, dtype=np.int64)
        self.rf_new = np.asarray(rf_new, dtype=np.int64)
        self.cat_old = np.asarray(cat_old, dtype=np.int64)
        self.cat_new = np.asarray(cat_new, dtype=np.int64)
        self.bytes_moved = np.asarray(bytes_moved, dtype=np.int64)
        self.priority = np.asarray(priority, dtype=np.float64)
        n = self.file_index.shape[0]
        for name in self.__slots__:
            a = getattr(self, name)
            if a.ndim != 1 or a.shape[0] != n:
                raise ValueError(
                    f"MoveSet column {name} has shape {a.shape}, "
                    f"expected ({n},)")

    @classmethod
    def empty(cls) -> "MoveSet":
        z = np.zeros(0, dtype=np.int64)
        return cls(z, z, z, z, z, z, np.zeros(0))

    @classmethod
    def from_moves(cls, moves) -> "MoveSet":
        """From an iterable of ``PlanMove`` (tests, legacy callers)."""
        moves = list(moves)
        return cls(
            [m.file_index for m in moves], [m.rf_old for m in moves],
            [m.rf_new for m in moves], [m.cat_old for m in moves],
            [m.cat_new for m in moves], [m.bytes_moved for m in moves],
            [m.priority for m in moves])

    def __len__(self) -> int:
        return int(self.file_index.shape[0])

    def __contains__(self, fid) -> bool:
        return bool((self.file_index == int(fid)).any())

    def __iter__(self):
        for i in range(len(self)):
            yield PlanMove(
                file_index=int(self.file_index[i]),
                rf_old=int(self.rf_old[i]), rf_new=int(self.rf_new[i]),
                cat_old=int(self.cat_old[i]), cat_new=int(self.cat_new[i]),
                bytes_moved=int(self.bytes_moved[i]),
                priority=float(self.priority[i]))

    def take(self, idx) -> "MoveSet":
        """Row subset/reorder by integer indices or boolean mask."""
        return MoveSet(*(getattr(self, c)[idx] for c in self.__slots__))

    @property
    def total_bytes(self) -> int:
        return int(self.bytes_moved.sum())


def _as_move_set(moves) -> MoveSet:
    return moves if isinstance(moves, MoveSet) else MoveSet.from_moves(moves)


def plan_diff(rf_old, rf_new, cat_old, cat_new, size_bytes,
              priority=None, move_bytes=None) -> MoveSet:
    """Moves for every file whose (rf, category) changed between two plans.

    All inputs are (n,) arrays; ``priority`` defaults to zero, so callers
    that don't score moves get stable file-index ordering.  ``move_bytes``
    overrides the per-file byte cost (the storage layer charges a
    strategy re-encode as the new shards written, not an rf delta of
    full copies); default is the historical
    ``size_bytes * max(0, rf_new - rf_old)``.

    Returns a ``MoveSet`` in ascending file-index order — one gather per
    column, no per-file Python objects.
    """
    rf_old = np.asarray(rf_old, dtype=np.int64)
    rf_new = np.asarray(rf_new, dtype=np.int64)
    cat_old = np.asarray(cat_old, dtype=np.int64)
    cat_new = np.asarray(cat_new, dtype=np.int64)
    size_bytes = np.asarray(size_bytes, dtype=np.int64)
    n = rf_old.shape[0]
    for name, a in (("rf_new", rf_new), ("cat_old", cat_old),
                    ("cat_new", cat_new), ("size_bytes", size_bytes)):
        if a.shape != (n,):
            raise ValueError(f"{name} shape {a.shape} != ({n},)")
    prio = np.zeros(n) if priority is None else np.asarray(priority,
                                                           dtype=np.float64)
    changed = np.flatnonzero((rf_new != rf_old) | (cat_new != cat_old))
    if move_bytes is None:
        bytes_moved = size_bytes[changed] * np.maximum(
            rf_new[changed] - rf_old[changed], 0)
    else:
        move_bytes = np.asarray(move_bytes, dtype=np.int64)
        if move_bytes.shape != (n,):
            raise ValueError(
                f"move_bytes shape {move_bytes.shape} != ({n},)")
        bytes_moved = move_bytes[changed]
    return MoveSet(changed, rf_old[changed], rf_new[changed],
                   cat_old[changed], cat_new[changed], bytes_moved,
                   prio[changed])


def _next_le(values: np.ndarray, start: int, limit) -> int:
    """First index >= ``start`` with ``values[i] <= limit``, or -1.

    Chunked scan: dense hits cost O(distance to the hit), a dry suffix
    costs one vectorized pass — the budget scan stays O(n) overall
    instead of O(n) per admission round.
    """
    n = values.shape[0]
    chunk = 4096
    i = start
    while i < n:
        j = min(n, i + chunk)
        hit = np.flatnonzero(values[i:j] <= limit)
        if hit.size:
            return i + int(hit[0])
        i = j
        chunk = min(chunk * 4, 1 << 20)
    return -1


def _greedy_admit(bp: np.ndarray, max_bytes: int, reserved: int
                  ) -> np.ndarray:
    """Byte-budget admission flags over positive byte costs ``bp`` in
    admission order — the vectorized equivalent of the legacy sequential
    scan (admit while ``used + b <= max_bytes``; skipped moves keep the
    scan going; the first byte-moving move of an unreserved positive-budget
    window is admitted unconditionally).

    Runs in O(n + admissions * log n): maximal admitted runs come from one
    ``searchsorted`` on the cumulative sum; deferred runs are skipped with
    a chunked next-fitting-move scan.
    """
    n = bp.shape[0]
    admit = np.zeros(n, dtype=bool)
    if n == 0:
        return admit
    used = int(reserved)
    j = 0
    if used == 0 and max_bytes > 0:
        # Oversized-move allowance: the window's first byte-moving move.
        admit[0] = True
        used += int(bp[0])
        j = 1
    cum = np.cumsum(bp)
    while j < n:
        base = int(cum[j - 1]) if j > 0 else 0
        # Admit the maximal run [j, e): used + (cum[i] - base) <= max_bytes.
        e = int(np.searchsorted(cum, max_bytes - used + base,
                                side="right"))
        if e > j:
            admit[j:e] = True
            used += int(cum[e - 1]) - base
        e = max(e, j)
        if e >= n:
            break
        # Move at e is over budget; skip it and every following move too
        # big for what is left.
        nxt = _next_le(bp, e + 1, max_bytes - used)
        if nxt < 0:
            break
        j = nxt
    return admit


class MigrationScheduler:
    """Backlog + churn budget + hysteresis (see module docstring).

    The backlog is a ``MoveSet`` kept in **admission order**
    (priority descending, file index ascending) — sorted once per
    ``submit``, scanned (never re-sorted) by ``schedule`` and dumped
    as-is by ``state_arrays``.
    """

    def __init__(self, n_files: int, max_bytes_per_window: int | None = None,
                 max_files_per_window: int | None = None,
                 hysteresis_windows: int = 0):
        if max_bytes_per_window is not None and max_bytes_per_window < 0:
            raise ValueError("max_bytes_per_window must be >= 0 or None")
        if max_files_per_window is not None and max_files_per_window < 1:
            raise ValueError("max_files_per_window must be >= 1 or None")
        self.n_files = int(n_files)
        self.max_bytes = max_bytes_per_window
        self.max_files = max_files_per_window
        self.hysteresis = int(hysteresis_windows)
        self.backlog: MoveSet = MoveSet.empty()
        self.last_moved = np.full(n_files, _NEVER, dtype=np.int64)
        #: Telemetry of the most recent ``schedule`` call: moves skipped by
        #: the hysteresis freeze vs by the byte budget.  Plain attributes —
        #: per-window observations, deliberately NOT checkpointed state.
        self.last_deferred_hysteresis = 0
        self.last_deferred_budget = 0

    @staticmethod
    def _admission_order(moves: MoveSet) -> MoveSet:
        """Rows re-ordered by (-priority, file_index) — the legacy
        ``sorted`` key, as one stable lexsort."""
        order = np.lexsort((moves.file_index, -moves.priority))
        return moves.take(order)

    def submit(self, moves) -> None:
        """Replace the backlog with the newest plan's pending moves."""
        ms = _as_move_set(moves)
        fi = ms.file_index
        if fi.size and np.unique(fi).size != fi.size:
            # Legacy dict-backlog semantics: a later move for the same
            # file overwrites an earlier one.  ``plan_diff`` emits unique
            # files, so this pays only for hand-built move lists.
            keep = fi.size - 1 - np.unique(fi[::-1], return_index=True)[1]
            ms = ms.take(np.sort(keep))
        self.backlog = self._admission_order(ms)

    def schedule(self, window_index: int, *, bytes_reserved: int = 0,
                 files_reserved: int = 0) -> MoveSet:
        """Pop this window's moves (budgeted, prioritized, hysteresis-gated).

        Byte budget: a byte-moving move is admitted while ``bytes_used +
        move.bytes <= max_bytes`` — except that a single move larger than
        the whole budget is admitted as the window's first byte-moving move
        (otherwise the largest file would starve forever; churn stays
        bounded by one oversized move per window).  ``max_bytes == 0`` is a
        true freeze: no byte-moving move is admitted at all (the oversized
        allowance needs a positive budget).  Zero-byte moves (replica
        drops, category-only changes) are metadata operations the byte
        budget never blocks; the file cap still counts them and is strict.
        Scheduled moves leave the backlog and stamp ``last_moved``.

        ``bytes_reserved``/``files_reserved`` pre-charge the window's
        budget with traffic another producer already spent — the
        controller's repair pass (faults/repair.py) runs first and hands
        its consumption here, so repair and drift-migration traffic
        compete for ONE churn allowance.  A nonzero reservation also
        disables the oversized-move allowance for this window (the first
        byte-moving operation was the reserver's).

        Returns the admitted moves as a ``MoveSet`` in admission order.
        """
        bl = self.backlog
        self.last_deferred_hysteresis = 0
        self.last_deferred_budget = 0
        m = len(bl)
        if m == 0:
            return MoveSet.empty()
        cap = None
        if self.max_files is not None:
            cap = self.max_files - int(files_reserved)
            if cap <= 0:
                # The legacy loop breaks before touching its first move:
                # nothing is processed, nothing is counted.
                return MoveSet.empty()

        hyst_ok = np.asarray(window_index, dtype=np.int64) >= \
            self.last_moved[bl.file_index] + 1 + self.hysteresis
        admit = np.zeros(m, dtype=bool)
        if self.max_bytes is None:
            admit[hyst_ok] = True
        else:
            cand = np.flatnonzero(hyst_ok)
            b = bl.bytes_moved[cand]
            adm_c = b == 0          # metadata moves: never byte-blocked
            pos = np.flatnonzero(b > 0)
            if pos.size:
                adm_c = adm_c.copy()
                adm_c[pos[_greedy_admit(b[pos], int(self.max_bytes),
                                        int(bytes_reserved))]] = True
            admit[cand[adm_c]] = True

        # File cap: the legacy loop breaks at the first move once the
        # admitted count (plus the reservation) reaches the cap — moves
        # past that point are unprocessed and uncounted.  Everything the
        # byte scan decided before that point is unaffected by the cap,
        # so truncation after the fact reproduces the loop exactly.
        limit = m
        hits = np.flatnonzero(admit)
        if cap is not None:
            if hits.size > cap:
                limit = int(hits[cap - 1]) + 1 if cap > 0 else 0
                admit[limit:] = False
                hits = hits[:cap]
            elif hits.size == cap:
                limit = int(hits[-1]) + 1

        self.last_deferred_hysteresis = int((~hyst_ok[:limit]).sum())
        self.last_deferred_budget = int(
            (hyst_ok[:limit] & ~admit[:limit]).sum())

        if hits.size == 0:
            return MoveSet.empty()
        # Integer-index takes: a boolean mask re-scans all seven columns
        # end to end, index gathers cost only the rows they move.
        applied = bl.take(hits)
        self.last_moved[applied.file_index] = window_index
        self.backlog = bl.take(np.flatnonzero(~admit))
        return applied

    @property
    def backlog_bytes(self) -> int:
        return self.backlog.total_bytes

    # -- checkpoint (controller snapshots ride utils/checkpoint npz) -------
    _MOVE_COLS = _MOVE_COLS

    def state_arrays(self) -> dict[str, np.ndarray]:
        """Checkpoint columns — the SoA backlog verbatim (admission
        order), plus the hysteresis stamps.  O(columns) array copies: no
        re-sort, no per-move objects."""
        out = {"sched_" + c: getattr(self.backlog, c).copy()
               for c in self._MOVE_COLS}
        out["sched_priority"] = self.backlog.priority.copy()
        out["sched_last_moved"] = self.last_moved.copy()
        return out

    def load_state_arrays(self, arrays: dict) -> None:
        """Restore the backlog + freeze stamps, validating shapes/dtypes
        against ``n_files`` up front — a truncated or foreign checkpoint
        must fail here with a message, not later with an opaque
        IndexError deep in ``schedule``.  The stored row order is
        irrelevant: admission order is re-derived with one lexsort, so
        legacy (file-index-ordered) checkpoints resume bit-identically."""
        missing = [k for k in ("sched_last_moved", "sched_priority",
                               *("sched_" + c for c in self._MOVE_COLS))
                   if k not in arrays]
        if missing:
            raise ValueError(
                f"checkpoint is missing scheduler arrays {missing} — "
                f"not a controller snapshot?")
        lm = np.asarray(arrays["sched_last_moved"])
        if lm.shape != (self.n_files,):
            raise ValueError(
                f"checkpoint covers {lm.shape[0] if lm.ndim == 1 else lm.shape} "
                f"files, scheduler has {self.n_files}")
        if not np.issubdtype(lm.dtype, np.integer):
            raise ValueError(
                f"sched_last_moved dtype {lm.dtype} is not integral")
        self.last_moved = lm.astype(np.int64).copy()
        cols = [np.asarray(arrays["sched_" + c]) for c in self._MOVE_COLS]
        prio = np.asarray(arrays["sched_priority"], dtype=np.float64)
        n_moves = cols[0].shape[0] if cols[0].ndim == 1 else -1
        for name, a in zip((*self._MOVE_COLS, "priority"), (*cols, prio)):
            if a.ndim != 1 or a.shape[0] != n_moves:
                raise ValueError(
                    f"scheduler backlog column sched_{name} has shape "
                    f"{a.shape}, expected ({n_moves},)")
            if name != "priority" and not np.issubdtype(a.dtype,
                                                        np.integer):
                raise ValueError(
                    f"scheduler backlog column sched_{name} dtype "
                    f"{a.dtype} is not integral")
        if n_moves and ((cols[0] < 0) | (cols[0] >= self.n_files)).any():
            bad = cols[0][(cols[0] < 0) | (cols[0] >= self.n_files)]
            raise ValueError(
                f"scheduler backlog names file indices outside "
                f"[0, {self.n_files}): {bad[:5].tolist()}")
        self.backlog = self._admission_order(MoveSet(*cols, prio))
