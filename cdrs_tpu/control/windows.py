"""Time-window carving over a globally sorted event stream.

The online controller (control/controller.py) consumes the access log as a
sequence of fixed-width time windows, independent of how the log is batched
on disk: a window may span several read batches and one batch may span
several windows.  ``iter_windows`` re-slices any batch stream onto the
window grid ``[t0 + w*W, t0 + (w+1)*W)`` (``t0`` = floor of the first event
second, the same origin every replay of the same log derives), yielding
EMPTY windows too — the controller's migration scheduler drains its backlog
on every tick, events or not.

Sources accepted: a log path (CSV access.log or binary ``.cdrsb`` — the
readers auto-detect), an in-memory EventLog, or any iterable of EventLog
batches.  The stream must be globally time-sorted (the simulator's contract,
sim/access.py; verified batchwise here) — window carving on an unsorted log
would silently split seconds across windows.
"""

from __future__ import annotations

import os

import numpy as np

from ..io.events import EventLog, Manifest

__all__ = ["iter_windows"]


def _slice(ev: EventLog, lo: int, hi: int) -> EventLog:
    return EventLog(ts=ev.ts[lo:hi], path_id=ev.path_id[lo:hi],
                    op=ev.op[lo:hi], client_id=ev.client_id[lo:hi],
                    clients=ev.clients)


def _concat(parts: list[EventLog], manifest: Manifest) -> EventLog:
    if not parts:
        return EventLog(ts=np.zeros(0), path_id=np.zeros(0, dtype=np.int32),
                        op=np.zeros(0, dtype=np.int8),
                        client_id=np.zeros(0, dtype=np.int32),
                        clients=list(manifest.nodes))
    return EventLog.concat(parts)


def iter_windows(source, manifest: Manifest, window_seconds: float, *,
                 batch_size: int = 1_000_000, t0: float | None = None,
                 t0_out: dict | None = None):
    """Yield ``(window_index, EventLog)`` for consecutive time windows.

    Windows are ``[t0 + w*W, t0 + (w+1)*W)``; empty intermediate windows are
    yielded (with zero-row EventLogs) so every downstream per-window action
    ticks at a fixed cadence.  The final partial window is yielded; windows
    after the last event are not.  Deterministic for a given (source, W, t0)
    regardless of ``batch_size``.

    ``t0_out``, when given, receives the grid origin under key ``"t0"`` as
    soon as it is known (derived from the stream's first event when ``t0``
    is None) — the controller checkpoints it so a resumed run replays the
    identical window grid.
    """
    W = float(window_seconds)
    if W <= 0:
        raise ValueError(f"window_seconds must be > 0, got {window_seconds}")

    if isinstance(source, EventLog):
        batches = iter([source])
    elif isinstance(source, (str, bytes, os.PathLike)):
        batches = EventLog.read_csv_batches(source, manifest,
                                            batch_size=batch_size)
    else:
        batches = iter(source)

    w = 0
    parts: list[EventLog] = []
    last_ts = -np.inf
    if t0 is not None and t0_out is not None:
        t0_out["t0"] = float(t0)
    for ev in batches:
        n = len(ev)
        if n == 0:
            continue
        if float(ev.ts[0]) < last_ts or not bool(np.all(np.diff(ev.ts) >= 0)):
            raise ValueError(
                "window carving requires a globally time-sorted log "
                "(the simulator's output contract, sim/access.py)")
        last_ts = float(ev.ts[-1])
        if t0 is None:
            t0 = float(np.floor(ev.ts[0]))
            if t0_out is not None:
                t0_out["t0"] = t0
        pos = 0
        while pos < n:
            w_end = t0 + (w + 1) * W
            hi = int(np.searchsorted(ev.ts, w_end, side="left"))
            if hi >= n:
                parts.append(_slice(ev, pos, n))
                pos = n
            else:
                if hi > pos:
                    parts.append(_slice(ev, pos, hi))
                yield w, _concat(parts, manifest)
                parts = []
                w += 1
                pos = hi
    if parts:
        yield w, _concat(parts, manifest)
