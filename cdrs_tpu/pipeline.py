"""End-to-end pipeline: generator -> simulator -> features -> cluster -> scoring.

Replaces reference run_pipeline.sh + the manual ``python src/main.py`` step
(the reference never wires main.py into its pipeline — SURVEY.md §3.1 note).
All stage boundaries remain durable files when ``outdir`` is given (the
reference's accidental checkpointing property, SURVEY.md §5), but stages hand
off in memory so nothing forces a round-trip through CSV.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np

from .config import CATEGORIES, PLANTED_TO_CATEGORY, PipelineConfig
from .models.replication import ClusterDecision, ReplicationPolicyModel
from .utils.logging import MetricsLog

__all__ = ["PipelineResult", "run_pipeline", "recovery_accuracy"]


@dataclass
class PipelineResult:
    decision: ClusterDecision
    metrics: MetricsLog
    n_files: int
    n_events: int
    planted_accuracy: float | None
    evaluation: dict | None = None

    def summary(self) -> dict:
        out = {
            "n_files": self.n_files,
            "n_events": self.n_events,
            "categories": {f"C{j}": c for j, c in enumerate(self.decision.categories)},
            "planted_accuracy": self.planted_accuracy,
            **self.metrics.records,
        }
        if self.evaluation is not None:
            out["evaluation"] = self.evaluation
        return out


def recovery_accuracy(decision: ClusterDecision, planted: list[str]) -> float | None:
    """Fraction of files whose recovered category matches the planted one.

    The reference plants ground truth (generator.py:45) and drives the
    simulator from it (access_simulator.py:42-47) but never closes the loop
    (SURVEY.md §4.2); this makes the implicit validation executable.
    Returns None when the manifest plants categories outside the canonical
    four (custom category mixes have no ground-truth mapping).
    """
    if any(c not in PLANTED_TO_CATEGORY for c in planted):
        return None
    predicted = np.asarray(decision.category_idx)[np.asarray(decision.labels)]
    truth = np.asarray(
        [CATEGORIES.index(PLANTED_TO_CATEGORY[c]) for c in planted], dtype=np.int64)
    return float((predicted == truth).mean())


def run_pipeline(cfg: PipelineConfig, outdir: str | None = None) -> PipelineResult:
    from .io.events import EventLog, Manifest  # noqa: F401  (types)
    from .sim.access import simulate_access
    from .sim.generator import generate_population

    metrics = MetricsLog()

    with metrics.timer("gen"):
        manifest = generate_population(cfg.generator)
    with metrics.timer("simulate"):
        events = simulate_access(manifest, cfg.simulator)
    metrics.record("n_events", len(events))

    if cfg.backend == "jax":
        import functools

        from .features import get_jax_backend

        # The feature kernel shards the event stream over the mesh's data
        # axis (features/jax_backend.py); model-axis entries are ignored.
        # as_device keeps the table in HBM so features -> clustering never
        # round-trips through host memory (VERDICT r1 #4; at 100M x 128 the
        # host copy alone would be ~51 GB).
        compute = functools.partial(get_jax_backend(), mesh_shape=cfg.mesh_shape,
                                    as_device=True)
    else:
        from .features.numpy_backend import compute_features as compute
    with metrics.timer("features"):
        table = compute(manifest, events)

    model = ReplicationPolicyModel(
        kmeans_cfg=cfg.kmeans, scoring_cfg=cfg.scoring,
        backend=cfg.backend, mesh_shape=cfg.mesh_shape,
    )
    with metrics.timer("cluster"):
        decision = model.run(table.norm)

    accuracy = recovery_accuracy(decision, manifest.category)
    metrics.record("planted_accuracy", accuracy)

    evaluation = None
    if cfg.evaluate:
        from .cluster import ClusterTopology, compare_policies

        with metrics.timer("evaluate"):
            rf = decision.replication_factor_per_file(cfg.scoring)
            evaluation = compare_policies(
                manifest, events, rf,
                topology=ClusterTopology(nodes=tuple(manifest.nodes)),
            )

    if outdir:
        os.makedirs(outdir, exist_ok=True)
        with metrics.timer("io"):
            manifest.write_csv(os.path.join(outdir, "metadata.csv"))
            events.write_csv(os.path.join(outdir, "access.log"), manifest)
            table.write_csv(os.path.join(outdir, "part-00000-features.csv"))
            decision.write_csv(os.path.join(outdir, "final_categories.csv"))
            decision.write_assignments_csv(
                os.path.join(outdir, "assignments.csv"), table.paths)

    return PipelineResult(
        decision=decision, metrics=metrics,
        n_files=len(manifest), n_events=len(events),
        planted_accuracy=accuracy, evaluation=evaluation,
    )
