"""Vectorized Poisson access-pattern simulator.

Capability parity with reference src/access_simulator.py:16-64: each file emits
a homogeneous Poisson event stream over a fixed window, with per-category rate
profiles jittered per file, read/write mix, and a locality-biased client
choice.  Exact distributional semantics preserved:

* per-file rates: category profile (hot/shared/moderate/archival,
  access_simulator.py:42-47) with Gaussian jitter
  read ~ N(mu, max(1e-4, 0.2 mu)) clamped >= 0, write ~ N(mu, max(1e-4, 0.5 mu))
  clamped >= 0, locality_bias ~ N(mu, 0.2) clipped to [0, 1]
  (access_simulator.py:55-57)
* event count per file ~ Poisson(lambda * duration) with event times uniform
  on [0, duration) — the standard order-statistics equivalence with the
  reference's expovariate inter-arrival loop (access_simulator.py:24-28)
* op = READ with probability read_rate / (lambda + 1e-12)  (l.30-31)
* client = primary node w.p. locality_bias, else uniform over clients (l.33-36)
* events globally time-sorted (l.60)

The reference's per-event Python loop is O(total events) interpreter time; this
implementation is O(E) vectorized NumPy and generates ~10M events/s on host —
the 1B-event streaming config additionally has a C++ generator
(native/, runtime/native.py) and an on-device jax.random path.
"""

from __future__ import annotations

import numpy as np

from ..config import SimulatorConfig
from ..io.events import EventLog, Manifest

__all__ = ["simulate_access", "simulate_access_with_shift",
           "simulate_access_phased", "simulate_diurnal",
           "simulate_flash_crowd", "jittered_rates"]


def jittered_rates(
    manifest: Manifest, cfg: SimulatorConfig, rng: np.random.Generator
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Per-file (read_rate, write_rate, locality_bias) with the reference's jitter."""
    n = len(manifest)
    read_mu = np.empty(n)
    write_mu = np.empty(n)
    loc_mu = np.empty(n)
    default = cfg.rate_profiles.get("moderate", {"read_rate": 0.1, "write_rate": 0.01,
                                                 "locality_bias": 0.5})
    for i, cat in enumerate(manifest.category):
        prof = cfg.rate_profiles.get(cat, default)
        read_mu[i] = prof["read_rate"]
        write_mu[i] = prof["write_rate"]
        loc_mu[i] = prof["locality_bias"]

    read = np.maximum(
        0.0, rng.normal(read_mu, np.maximum(1e-4, read_mu * cfg.read_rate_jitter)))
    write = np.maximum(
        0.0, rng.normal(write_mu, np.maximum(1e-4, write_mu * cfg.write_rate_jitter)))
    loc = np.clip(rng.normal(loc_mu, cfg.locality_jitter_std), 0.0, 1.0)
    return read, write, loc


def _poisson_stream(manifest: Manifest, cfg: SimulatorConfig, rng,
                    sim_start: float, time_of_u) -> EventLog:
    """The one vectorized draw core behind every numpy workload curve:
    jittered rates -> Poisson counts -> op mix -> locality-biased client
    -> global time sort.  ``time_of_u`` places the per-event uniform
    draws on the time axis (flat curve: ``u * duration``; diurnal:
    the intensity curve's inverse CDF over the SAME uniforms) — curves
    that differ only here share every other draw by construction, which
    is what makes ``simulate_diurnal``'s count mass bit-identical to the
    flat stream's."""
    n = len(manifest)
    read, write, loc = jittered_rates(manifest, cfg, rng)
    lam = read + write
    counts = rng.poisson(lam * cfg.duration_seconds)
    total = int(counts.sum())

    path_id = np.repeat(np.arange(n, dtype=np.int32), counts)
    ts = sim_start + time_of_u(rng.random(total))

    p_read = read / (lam + 1e-12)
    op = (rng.random(total) >= p_read[path_id]).astype(np.int8)  # 1 = WRITE

    from ..io.events import client_vocabulary

    clients, client_pool = client_vocabulary(manifest, cfg.clients)
    n_clients = len(cfg.clients)

    use_primary = rng.random(total) < loc[path_id]
    random_client = client_pool[rng.integers(0, n_clients, size=total)]
    client_id = np.where(use_primary, manifest.primary_node_id[path_id], random_client)

    order = np.argsort(ts, kind="stable")  # global time sort (reference l.60)
    return EventLog(
        ts=ts[order],
        path_id=path_id[order],
        op=op[order],
        client_id=client_id[order].astype(np.int32),
        clients=clients,
    )


def simulate_access(
    manifest: Manifest,
    cfg: SimulatorConfig,
    sim_start: float | None = None,
    engine: str = "numpy",
) -> EventLog:
    """``engine='native'`` runs the threaded C++ generator (runtime/native.py)
    — same distributional semantics, its own deterministic RNG stream; for
    the 1B-event scale where even vectorized NumPy becomes the bottleneck."""
    rng = np.random.default_rng(cfg.seed)
    if sim_start is None:
        # Anchor to the *manifest's* timebase (latest creation timestamp):
        # deterministic whenever the manifest is (see utils/params
        # .SEEDED_EPOCH) and always just after every file exists.  This also
        # holds when a seeded manifest (anchored to SEEDED_EPOCH, ~2023) is
        # simulated without a seed — the reference's wall clock
        # (src/access_simulator.py:21) would put the window years after
        # creation and flatten every age_seconds to the epoch gap.  For
        # unseeded manifests creation is within the past year of wall clock,
        # so this matches the reference's behavior up to that year.
        sim_start = float(np.ceil(manifest.creation_ts.max())) + 1.0

    if engine == "native":
        from ..io.events import client_vocabulary
        from ..runtime.native import simulate_events_native

        read, write, loc = jittered_rates(manifest, cfg, rng)
        clients, pool = client_vocabulary(manifest, cfg.clients)
        # Unseeded runs must stay independent: derive a fresh 64-bit seed from
        # the (entropy-seeded) numpy generator instead of pinning 0.
        seed = int(cfg.seed) if cfg.seed is not None else int(
            rng.integers(0, 2**63 - 1))
        ts, pid, op, client = simulate_events_native(
            read, write, loc, manifest.primary_node_id, pool,
            cfg.duration_seconds, sim_start, seed=seed,
        )
        return EventLog(ts=ts, path_id=pid, op=op, client_id=client,
                        clients=clients)
    if engine != "numpy":
        raise ValueError(f"unknown simulator engine {engine!r}")
    return _poisson_stream(manifest, cfg, rng, sim_start,
                           lambda u: u * cfg.duration_seconds)


def simulate_diurnal(
    manifest: Manifest,
    cfg: SimulatorConfig,
    *,
    period: float | None = None,
    amplitude: float = 0.8,
    phase: float = 0.0,
    sim_start: float | None = None,
) -> EventLog:
    """Diurnal workload: the Poisson stream with a sinusoidal time-of-day
    intensity curve ``f(t) = 1 + amplitude * sin(2*pi*t/period + phase)``.

    Per-file event COUNTS are drawn exactly as ``simulate_access`` draws
    them (same rng stream, same Poisson(lambda * duration)) — the curve
    conserves total mass bit-for-bit and only re-times the events through
    the curve's inverse CDF (the order-statistics view of an
    inhomogeneous Poisson process conditioned on its count).  The
    controller therefore sees the same cumulative features by the end of
    the log, but per-window event volume swings ``1 +- amplitude`` — the
    load shape a per-window churn budget and the serving queue model must
    absorb.  ``period`` defaults to the full duration (one day == one
    log); deterministic in ``cfg.seed``.
    """
    if not 0.0 <= float(amplitude) < 1.0:
        raise ValueError(
            f"amplitude must be in [0, 1) (the intensity must stay "
            f"positive), got {amplitude}")
    duration = float(cfg.duration_seconds)
    period = duration if period is None else float(period)
    if period <= 0:
        raise ValueError(f"period must be > 0, got {period}")
    rng = np.random.default_rng(cfg.seed)
    if sim_start is None:
        sim_start = float(np.ceil(manifest.creation_ts.max())) + 1.0

    # Inverse-CDF time warp: uniform u -> t with density proportional to
    # the curve (grid CDF exact up to interpolation, 4096 knots).  The
    # shared draw core hands this warp the SAME uniforms simulate_access
    # turns into times, so amplitude=0 degenerates to the flat stream
    # bit-for-bit and the count mass is conserved by construction.
    grid = np.linspace(0.0, duration, 4097)
    dens = 1.0 + float(amplitude) * np.sin(
        2.0 * np.pi * grid / period + float(phase))
    cdf = np.concatenate([[0.0], np.cumsum((dens[1:] + dens[:-1]) * 0.5
                                           * np.diff(grid))])
    cdf /= cdf[-1]
    return _poisson_stream(manifest, cfg, rng, sim_start,
                           lambda u: np.interp(u, cdf, grid))


def simulate_access_phased(
    manifest: Manifest,
    cfg: SimulatorConfig,
    shifts,
    *,
    sim_start: float | None = None,
    engine: str = "numpy",
) -> tuple[EventLog, np.ndarray]:
    """N-phase workload: CUMULATIVE category flips at successive times.

    ``shifts`` is a sequence of ``(shift_at, category_flip, cohort)``
    tuples (cohort None = every file whose current category is a key),
    strictly increasing in time inside ``(0, duration)``; each flip
    applies on top of the previous phase's categories, so an oscillating
    ``{hot: archival, archival: hot}`` flip models ADVERSARIAL drift
    (flip, revert, flip again — the anti-flap hysteresis scenario) and a
    sequence of disjoint-cohort flips models GRADUAL drift (the
    population migrates in waves rather than one step).  Phase ``i``
    draws from seed ``cfg.seed + i * 0x5F17``, making the single-shift
    case bit-identical to ``simulate_access_with_shift`` (which
    delegates here).

    Returns ``(events, changed)``: the concatenated globally time-sorted
    log and the bool mask of files whose FINAL category differs from the
    planted one (empty-handed for a fully reverted adversarial cycle —
    by design: the workload really is back to normal).
    """
    import dataclasses

    duration = float(cfg.duration_seconds)
    shifts = list(shifts)
    times = [float(s[0]) for s in shifts]
    for t in times:
        if not 0.0 < t < duration:
            raise ValueError(
                f"shift_at must fall inside (0, {duration}), got {t}")
    if any(b <= a for a, b in zip(times, times[1:])):
        raise ValueError(
            f"shift times must be strictly increasing, got {times}")
    for _, flip, _ in shifts:
        unknown = (set(flip) | set(flip.values())) - set(cfg.rate_profiles)
        if unknown:
            raise ValueError(
                f"category_flip names categories without a rate profile: "
                f"{sorted(unknown)}")
    if sim_start is None:
        sim_start = float(np.ceil(manifest.creation_ts.max())) + 1.0

    cats = list(manifest.category)
    bounds = [0.0] + times + [duration]
    logs: list[EventLog] = []
    cur_manifest = manifest
    for i in range(len(bounds) - 1):
        if i > 0:
            _, flip, cohort = shifts[i - 1]
            in_cohort = np.ones(len(manifest), dtype=bool) if cohort is None \
                else np.asarray(cohort, dtype=bool)
            if in_cohort.shape != (len(manifest),):
                raise ValueError(
                    f"cohort mask shape {in_cohort.shape} != "
                    f"({len(manifest)},)")
            cats = [flip[c] if in_cohort[j] and c in flip and flip[c] != c
                    else c for j, c in enumerate(cats)]
            cur_manifest = dataclasses.replace(manifest, category=cats)
        seed_i = None if cfg.seed is None else int(cfg.seed) + i * 0x5F17
        cfg_i = dataclasses.replace(
            cfg, duration_seconds=bounds[i + 1] - bounds[i], seed=seed_i)
        ev = simulate_access(cur_manifest, cfg_i,
                             sim_start=sim_start + bounds[i], engine=engine)
        if logs and ev.clients != logs[0].clients:  # pragma: no cover
            raise AssertionError("phase client vocabularies diverged")
        logs.append(ev)
    # Every phase interns clients against the same (manifest nodes, cfg
    # clients) vocabulary and phase i+1 starts where phase i ends, so the
    # concatenation is globally time-sorted.
    changed = np.asarray([a != b for a, b in zip(manifest.category, cats)])
    return EventLog.concat(logs), changed


def simulate_flash_crowd(
    manifest: Manifest,
    cfg: SimulatorConfig,
    *,
    cohort: np.ndarray,
    start: float,
    duration: float,
    boost: float,
    sim_start: float | None = None,
    engine: str = "numpy",
) -> tuple[EventLog, np.ndarray]:
    """Base Poisson workload plus a read BURST on a cohort: flash crowd.

    The serving-layer scenario ``simulate_access_with_shift`` cannot
    express: the category flip changes the cohort's rates for the whole
    remaining stream, so the CUMULATIVE feature fold eventually drifts.
    A flash crowd is a transient — over ``[start, start + duration)``
    seconds of the simulated span, each cohort file emits EXTRA reads at
    ``boost`` x its category's mean read rate (clients drawn with the
    same locality bias), then traffic returns to baseline.  Late in a
    long log the burst is diluted by history and the drift detector never
    fires; the per-window hotspot detector (serve/hotspot.py) fires the
    window it lands — exactly the gap the serving feedback closes.

    Returns ``(events, cohort_mask)``: the merged, globally time-sorted
    log and the bool mask of burst files.  Deterministic in ``cfg.seed``
    (the burst draws from a derived independent stream).
    """
    dur_total = float(cfg.duration_seconds)
    if not 0.0 <= float(start) < dur_total:
        raise ValueError(
            f"start must fall inside [0, {dur_total}), got {start}")
    if duration <= 0 or float(start) + float(duration) > dur_total:
        raise ValueError(
            f"burst [{start}, {start + duration}) must fit inside the "
            f"{dur_total}s simulation span")
    if boost <= 0:
        raise ValueError(f"boost must be > 0, got {boost}")
    in_cohort = np.asarray(cohort, dtype=bool)
    if in_cohort.shape != (len(manifest),):
        raise ValueError(
            f"cohort mask shape {in_cohort.shape} != ({len(manifest)},)")
    if sim_start is None:
        sim_start = float(np.ceil(manifest.creation_ts.max())) + 1.0

    base = simulate_access(manifest, cfg, sim_start=sim_start,
                           engine=engine)

    seed_b = None if cfg.seed is None else int(cfg.seed) + 0x9E37
    rng = np.random.default_rng(seed_b)
    ids = np.flatnonzero(in_cohort)
    default = cfg.rate_profiles.get("moderate", {"read_rate": 0.1,
                                                 "locality_bias": 0.5})
    read_mu = np.asarray([
        cfg.rate_profiles.get(manifest.category[i], default)["read_rate"]
        for i in ids])
    loc_mu = np.asarray([
        cfg.rate_profiles.get(manifest.category[i],
                              default)["locality_bias"] for i in ids])
    counts = rng.poisson(boost * read_mu * float(duration))
    total = int(counts.sum())
    pid = np.repeat(ids.astype(np.int32), counts)
    ts = sim_start + float(start) + rng.random(total) * float(duration)

    from ..io.events import client_vocabulary

    clients, client_pool = client_vocabulary(manifest, cfg.clients)
    use_primary = rng.random(total) < np.repeat(loc_mu, counts)
    random_client = client_pool[rng.integers(0, len(cfg.clients),
                                             size=total)]
    client_id = np.where(use_primary, manifest.primary_node_id[pid],
                         random_client).astype(np.int32)
    burst = EventLog(ts=ts, path_id=pid,
                     op=np.zeros(total, dtype=np.int8),  # all reads
                     client_id=client_id, clients=clients)

    merged = EventLog.concat([base, burst])
    order = np.argsort(merged.ts, kind="stable")
    return EventLog(ts=merged.ts[order], path_id=merged.path_id[order],
                    op=merged.op[order], client_id=merged.client_id[order],
                    clients=merged.clients), in_cohort


def simulate_access_with_shift(
    manifest: Manifest,
    cfg: SimulatorConfig,
    shift_at: float,
    category_flip: dict[str, str],
    cohort: np.ndarray | None = None,
    sim_start: float | None = None,
    engine: str = "numpy",
) -> tuple[EventLog, np.ndarray]:
    """Two-phase workload: planted categories flip mid-stream for a cohort.

    The online-controller benchmark scenario: the first ``shift_at`` seconds
    are simulated from the manifest's planted categories, the remaining
    ``duration_seconds - shift_at`` from a manifest whose cohort categories
    were remapped through ``category_flip`` (e.g. ``{"hot": "archival",
    "archival": "hot"}``).  ``cohort`` (bool mask over files) restricts the
    flip; None flips every file whose planted category is a key.  Each phase
    is one ``simulate_access`` call (identical distributional semantics);
    phase B draws from an independent seed derived from ``cfg.seed`` so the
    phases are decorrelated yet the whole log stays deterministic.

    Returns ``(events, flipped)``: the concatenated, globally time-sorted log
    (phase B starts exactly at ``sim_start + shift_at``) and the bool mask of
    files whose planted category actually changed.  The single-shift case of
    ``simulate_access_phased`` (to which this delegates, bit-identically —
    phase B's seed is ``cfg.seed + 0x5F17`` either way).
    """
    return simulate_access_phased(
        manifest, cfg, [(float(shift_at), category_flip, cohort)],
        sim_start=sim_start, engine=engine)
