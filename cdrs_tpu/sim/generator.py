"""Synthetic file-population generator.

Capability parity with reference src/generator.py:16-67: produces a manifest of
``n`` files with random sizes, ages, primary nodes and planted ground-truth
categories.  Distributional semantics preserved:

* size ~ uniform integer [min_size, max_size]            (generator.py:34)
* creation_ts = now − U(0, age_days_max) days            (generator.py:41-42)
* primary_node ~ uniform over nodes                      (generator.py:44)
* category ~ {hot .10, shared .20, moderate .50, archival .20}  (generator.py:45)

Differences (documented per SURVEY.md §6.1 policy):

* Fully vectorized NumPy instead of a per-file Python loop, so generating
  10M-file populations is seconds, not hours.
* The HDFS ``hdfs dfs -put`` of os.urandom payloads (generator.py:9-14, 39)
  is optional (``write_payloads``) and writes to a local/simulated DFS
  directory instead — the analytics pipeline only ever reads the manifest.
* Seeded via a single ``numpy`` Generator (the reference uses the global
  ``random`` module unseeded).
"""

from __future__ import annotations

import os
import time

import numpy as np

from ..config import GeneratorConfig
from ..io.events import Manifest

__all__ = ["generate_population"]


def generate_population(
    cfg: GeneratorConfig, now: float | None = None
) -> Manifest:
    rng = np.random.default_rng(cfg.seed)
    n = cfg.n_files
    if now is None:
        # Seeded runs anchor to a fixed epoch so the seed fully determines the
        # workload — wall-clock anchoring would shift the simulator's 1-second
        # concurrency buckets every run (utils/params.SEEDED_EPOCH rationale).
        from ..utils.params import SEEDED_EPOCH

        now = SEEDED_EPOCH if cfg.seed is not None else time.time()

    sizes = rng.integers(cfg.min_size, cfg.max_size + 1, size=n, dtype=np.int64)
    age_days = rng.random(n) * cfg.age_days_max
    creation = now - age_days * 86400.0
    primary = rng.integers(0, len(cfg.nodes), size=n).astype(np.int32)

    cats = list(cfg.category_mix.keys())
    probs = np.asarray(list(cfg.category_mix.values()), dtype=np.float64)
    probs = probs / probs.sum()
    cat_idx = rng.choice(len(cats), size=n, p=probs)
    category = [cats[i] for i in cat_idx]

    paths = [f"{cfg.base_dir}/synth_{i}.bin" for i in range(n)]

    if cfg.write_payloads:
        root = cfg.base_dir.lstrip("/")
        os.makedirs(root, exist_ok=True)
        for i in range(n):
            with open(os.path.join(root, f"synth_{i}.bin"), "wb") as f:
                f.write(os.urandom(int(sizes[i])))

    return Manifest(
        paths=paths,
        creation_ts=np.floor(creation),
        primary_node_id=primary,
        size_bytes=sizes,
        category=category,
        nodes=list(cfg.nodes),
    )
