"""Crash supervision: ``cdrs daemon --supervise``.

The crash-anywhere contract (daemon/core.py: a kill -9 mid-window
resumes bit-identically from the last durable cursor) makes restarting
the daemon *safe*; this module makes it *automatic*.  The supervisor is
a tiny parent process that re-execs the real daemon command as a child,
forwards SIGTERM/SIGINT for a graceful drain, and restarts the child on
any abnormal exit with capped exponential backoff:

* delay = ``backoff_base * 2**(consecutive_failures - 1)``, capped at
  ``backoff_cap`` — the standard crash-loop damper.
* a child that exits 0 (clean drain / ``--max_seconds`` reached) ends
  supervision: done means done.
* a child that *ran healthily* for at least ``healthy_after`` seconds
  before dying resets the consecutive-failure counter — a daemon that
  crashes once a day is not a crash loop.
* after ``max_restarts`` CONSECUTIVE failures the supervisor gives up
  and exits with the child's last exit code: a deterministic bug
  (config error, corrupt checkpoint) must page a human, not burn CPU
  forever.

Deliberately dependency-free (subprocess + signal only) and policy-only:
all state the child needs to resume lives in its own checkpoint; the
supervisor holds nothing but the restart counter.
"""

from __future__ import annotations

import signal
import subprocess
import sys
import time

__all__ = ["supervise"]


def supervise(child_argv: list[str], *, max_restarts: int = 5,
              backoff_base: float = 0.5, backoff_cap: float = 30.0,
              healthy_after: float = 30.0,
              log=None) -> int:
    """Run ``child_argv`` under restart supervision; returns the exit
    code to propagate (0 on clean child exit, the child's last code
    after giving up).

    ``log`` is a ``print``-like callable for supervisor lines (defaults
    to stderr); tests inject a capture.
    """
    if max_restarts < 1:
        raise ValueError(f"max_restarts must be >= 1, got {max_restarts}")
    if backoff_base <= 0 or backoff_cap < backoff_base:
        raise ValueError(
            f"need 0 < backoff_base <= backoff_cap, got "
            f"{backoff_base}/{backoff_cap}")
    emit = log if log is not None else (
        lambda msg: print(msg, file=sys.stderr, flush=True))

    failures = 0
    attempt = 0
    stop = {"sig": None}

    def _forward(signum, frame):  # noqa: ARG001
        # Remember the signal so the wait loop knows a drain was asked
        # for; actual forwarding happens against the live child below.
        stop["sig"] = signum

    old_term = signal.signal(signal.SIGTERM, _forward)
    old_int = signal.signal(signal.SIGINT, _forward)
    try:
        while True:
            attempt += 1
            started = time.monotonic()
            emit(f"supervise: starting child (attempt {attempt}): "
                 + " ".join(child_argv))
            child = subprocess.Popen(child_argv)
            while True:
                if stop["sig"] is not None and child.poll() is None:
                    child.send_signal(signal.SIGTERM)
                    stop["sig"] = "sent"
                try:
                    rc = child.wait(timeout=0.2)
                    break
                except subprocess.TimeoutExpired:
                    continue
            ran = time.monotonic() - started
            if rc == 0:
                emit(f"supervise: child exited cleanly after {ran:.1f}s")
                return 0
            if stop["sig"] == "sent":
                # We asked it to stop; a drain cut short by SIGTERM is
                # not a crash to restart.
                emit(f"supervise: child stopped on forwarded signal "
                     f"(exit {rc})")
                return 0
            if ran >= healthy_after:
                failures = 0
            failures += 1
            emit(f"supervise: child died (exit {rc}) after {ran:.1f}s "
                 f"— consecutive failure {failures}/{max_restarts}")
            if failures >= max_restarts:
                emit("supervise: giving up (crash loop); checkpoint is "
                     "durable, rerun to resume")
                return int(rc) if rc else 1
            delay = min(backoff_base * (2.0 ** (failures - 1)),
                        backoff_cap)
            emit(f"supervise: restarting in {delay:.1f}s")
            deadline = time.monotonic() + delay
            while time.monotonic() < deadline:
                if stop["sig"] is not None:
                    emit("supervise: stop requested during backoff")
                    return 0
                time.sleep(0.05)
    finally:
        signal.signal(signal.SIGTERM, old_term)
        signal.signal(signal.SIGINT, old_int)
