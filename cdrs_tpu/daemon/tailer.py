"""Follow-mode tailer over the growing binary event log (.cdrsb).

``EventLog.read_binary_batches`` reads a COMPLETE log: a block whose
bytes run past EOF is corruption and raises.  A live log being appended
to looks exactly like that corruption from a reader's point of view —
the writer's last block is mid-flight — so the tailer re-interprets the
torn tail as "wait for more bytes" and only ever surfaces whole blocks.
Semantics mirror ``obs/sink.iter_events`` (the jsonl follow reader):

* a missing file is waited for under ``follow`` (the daemon may start
  before the simulator), and a clean one-line error otherwise;
* the torn tail (incomplete final block, or a header still being
  written) is buffered by NOT consuming it until the bytes land;
* rotation (``path`` -> ``path + ".1"``) is detected by the file
  shrinking below the read offset; the rotated predecessor is drained
  from that offset before the new file is followed from its header;
* an optional ``stop`` predicate is checked once per poll round, so a
  shutdown request interrupts the sleep cadence, not just the yields.

Yields :class:`TailBatch` — the block's events plus the block-boundary
byte offsets the daemon's resume cursor is built from.
"""

from __future__ import annotations

import os
import time
from typing import NamedTuple

import numpy as np

from ..io.events import EventLog, Manifest

__all__ = ["TailBatch", "tail_binary_log"]


class TailBatch(NamedTuple):
    """One whole block from the log: events + its byte extent."""

    events: EventLog
    offset: int        # byte offset of the block's first byte
    next_offset: int   # byte just past the block — a valid later start


def _wait(poll: float, stop) -> bool:
    """One poll round; True = the stop predicate asked us to return."""
    if stop is not None and stop():
        return True
    time.sleep(poll)
    return False


def tail_binary_log(path: str, manifest: Manifest, *,
                    follow: bool = False, poll: float = 0.5,
                    stop=None, start_offset: int = 0,
                    ingest_box: dict | None = None):
    """Yield :class:`TailBatch` per complete block of a ``.cdrsb`` log.

    ``follow=False`` reads to the current end of file and returns,
    raising the reader's canonical one-line errors on a torn tail (a
    static file ending mid-block IS corruption).  ``follow=True`` keeps
    polling every ``poll`` seconds for appended blocks, waiting out
    missing files and torn tails, until ``stop()`` returns truthy.
    ``start_offset`` resumes from a block boundary previously reported
    via ``TailBatch.next_offset`` (0 = from the first block).
    ``ingest_box``, when given, is stamped ``{"ns": perf_counter_ns}``
    as each block is parsed — the decision tracer's ingest origin,
    taken HERE (at the read, before any downstream slicing) so the
    trace's ``tail`` segment starts where the data actually arrived.
    """
    header = None
    while header is None:
        try:
            header = EventLog._try_read_binary_header(path)
        except FileNotFoundError:
            if not follow:
                raise
            header = None
        if header is None:
            if not follow:
                raise ValueError(
                    f"truncated/corrupt header of {path!r}: file ends "
                    f"inside the header/vocabulary tables")
            if _wait(poll, stop):
                return
    file_clients, file_paths, first_block = header
    plut, clut, clients = EventLog._binary_luts(file_clients, file_paths,
                                                manifest)
    pos = int(start_offset) if start_offset else first_block
    if pos < first_block:
        raise ValueError(
            f"start_offset {pos} outside the block region of {path!r} "
            f"(first block at byte {first_block})")

    while True:
        try:
            size = os.path.getsize(path)
        except OSError:
            # Deleted/rotated away mid-follow: wait for it to reappear.
            if not follow:
                raise FileNotFoundError(
                    f"missing event log {path!r}: no such file") from None
            if _wait(poll, stop):
                return
            continue
        if size < pos:
            # The log rotated under us (sink.iter_events semantics):
            # drain the predecessor from our offset, then restart on the
            # new file from ITS header.  Offsets yielded for the drained
            # blocks refer to the rotated file — a resume cursor taken
            # across a rotation is only valid against ``path + ".1"``.
            prev = path + ".1"
            if os.path.exists(prev) and os.path.getsize(prev) >= pos:
                psize = os.path.getsize(prev)
                with open(prev, "rb") as f:
                    f.seek(pos)
                    while pos < psize:
                        blk = pos
                        ts, pid, op, cid, pos = EventLog._read_block(
                            f, pos, psize, prev, len(file_paths),
                            len(file_clients))
                        if ts is None:
                            continue
                        if ingest_box is not None:
                            ingest_box["ns"] = time.perf_counter_ns()
                        yield TailBatch(_remap(ts, pid, op, cid, plut,
                                               clut, clients), blk, pos)
            header = EventLog._try_read_binary_header(path)
            if header is None:
                if _wait(poll, stop):
                    return
                continue
            file_clients, file_paths, first_block = header
            plut, clut, clients = EventLog._binary_luts(
                file_clients, file_paths, manifest)
            pos = first_block
            continue

        progressed = False
        with open(path, "rb") as f:
            f.seek(pos)
            while pos < size:
                # Complete-block probe BEFORE parsing: a count field or
                # column run past ``size`` is the writer's in-flight
                # tail, not corruption — leave it unconsumed.
                head = f.read(8)
                if len(head) < 8:
                    break
                bn = int(np.frombuffer(head, dtype=np.int64)[0])
                if bn < 0:
                    raise ValueError(
                        f"truncated/corrupt block at byte {pos} of "
                        f"{path!r}")
                need = 8 + bn * (8 + 4 + 1 + 4)
                if pos + need > size:
                    break  # torn tail — wait for the rest
                f.seek(pos)
                blk = pos
                ts, pid, op, cid, pos = EventLog._read_block(
                    f, pos, size, path, len(file_paths),
                    len(file_clients))
                progressed = True
                if ts is None:
                    continue  # legal empty block
                if ingest_box is not None:
                    ingest_box["ns"] = time.perf_counter_ns()
                yield TailBatch(_remap(ts, pid, op, cid, plut, clut,
                                       clients), blk, pos)
        if not follow:
            if pos < size:
                # Static file ending mid-block: the canonical error.
                raise ValueError(
                    f"truncated/corrupt block at byte {pos} of {path!r}")
            return
        if not progressed and _wait(poll, stop):
            return
        if progressed and stop is not None and stop():
            return


def _remap(ts, pid, op, cid, plut, clut, clients) -> EventLog:
    """Raw block columns -> caller-manifest EventLog (reader contract)."""
    if plut is not None:
        pid = plut[pid]
    return EventLog(ts=ts, path_id=pid, op=op, client_id=clut[cid],
                    clients=list(clients))
