"""Always-on streaming controller daemon (ROADMAP open item 3).

Everything else in the repo is one batch ``ReplicationController.run()``
over a pre-materialized log; this package is the process that never
stops.  Three pieces:

* ``tailer`` — follow-mode batch reader over the growing binary event
  log (``io/events`` ``.cdrsb``), mirroring ``obs/sink.iter_events``
  semantics: wait for a missing file, buffer the torn tail, drain a
  rotated predecessor, honor a stop predicate per poll round.
* ``epochs`` — immutable :class:`PlacementEpoch` snapshots published
  through an atomic single-reference :class:`EpochPublisher`; readers
  pin ONE epoch per request batch, so a routed read never observes a
  torn placement while the controller recomputes underneath (the CRUSH
  cluster-map posture, PAPERS.md).
* ``core`` — :class:`StreamDaemon`, the loop: ingest -> carve windows
  on the controller's grid -> ``process_window`` (decision-identical to
  the batch loop by construction) -> publish an epoch -> evaluate the
  live alert rules -> checkpoint.  SIGTERM lands a cursor-carrying
  checkpoint and a resumed daemon continues bit-identically, reading
  O(new data) instead of re-reading history.
"""

from .brownout import RUNGS, BrownoutConfig, BrownoutLadder
from .core import DaemonConfig, StreamDaemon
from .epochs import EpochPublisher, PlacementEpoch
from .supervise import supervise
from .tailer import TailBatch, tail_binary_log

__all__ = ["DaemonConfig", "StreamDaemon", "EpochPublisher",
           "PlacementEpoch", "TailBatch", "tail_binary_log",
           "RUNGS", "BrownoutConfig", "BrownoutLadder", "supervise"]
