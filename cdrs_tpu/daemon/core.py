"""The always-on streaming controller loop.

:class:`StreamDaemon` wraps a :class:`~cdrs_tpu.control.ReplicationController`
and drives it window by window over a LIVE event stream — a growing
binary log (tailer) or an in-process batch feed — instead of a finished
file.  The windows themselves come from the batch loop's own carver
(``control.windows.iter_windows`` consumes any batch iterable lazily),
and each window runs through the controller's public
``process_window``: the daemon therefore makes *exactly* the decisions
the batch ``run()`` loop would make on the same stream — the
equivalence oracle ``benchmarks/daemon_bench.py`` gates on.

What the daemon adds around that loop:

* **Epoch publication** — every processed window's admitted plan
  freezes into a :class:`~cdrs_tpu.daemon.epochs.PlacementEpoch` backed
  by a new ``placement_fn.EpochMap`` revision and lands via one atomic
  reference swap; readers pin per request batch (see ``epochs``).
* **Live alerting** — the window record feeds ``obs/alerts.AlertEngine``
  as it is produced; a firing page-severity alert (``files_lost`` /
  ``true_lost``) triggers an immediate protective checkpoint — the
  alert engine is the daemon's control surface, not a post-hoc report.
* **Cursor checkpoints** — the controller snapshot carries the ingest
  cursor ``(byte offset of the block holding the first unprocessed
  event, events to skip within it)`` in its meta blob, making resume
  O(new data): the batch loop's documented O(history) re-read from byte
  0 is exactly the follow-up this daemon implements.
* **Graceful shutdown** — SIGTERM sets a flag; the loop finishes the
  window in flight, checkpoints, and returns.  Buffered events of the
  next (incomplete) window are NOT folded — the cursor re-reads them on
  resume, so an interrupted-and-resumed daemon produces bit-identical
  records and plans to an uninterrupted one (Yuan et al.'s warning:
  the shutdown path is tested, not assumed).
* **Incremental re-cluster tracking** (``recluster="minibatch"``) — a
  warm-started ``ops/kmeans_stream.MiniBatchKMeans`` advances one
  mini-batch Lloyd step per window on the decayed feature snapshot,
  maintaining live centroids/inertia between the controller's admitted
  full plans.  Observability only: plan decisions stay the
  controller's, so the equivalence oracle holds with it on or off.

Backpressure is pull-based by construction: the tailer is only read
when the loop is ready for the next window, so a fast writer fills the
log (bounded by disk), never the daemon's memory — in-flight residency
is one window plus one block.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from ..control.windows import _concat, _slice, iter_windows
from ..io.events import EventLog, is_binary_log
from ..obs.alerts import SEVERE_ALERTS, AlertEngine, default_rules
from ..obs.telemetry import HIST_RAW_CAP
from ..obs.trace import STAGE_ORDER, build_span_tree, decision_trace_id
from .brownout import RUNGS, BrownoutConfig, BrownoutLadder
from .epochs import EpochPublisher, PlacementEpoch
from .tailer import tail_binary_log

__all__ = ["DaemonConfig", "StreamDaemon"]

_RECLUSTER_MODES = ("controller", "minibatch")


@dataclass
class DaemonConfig:
    """Daemon-side knobs (everything decision-relevant lives in the
    wrapped controller's ``ControllerConfig``)."""

    #: Tail the log for appended blocks (False = process to EOF, once).
    follow: bool = False
    #: Poll cadence of the follow-mode tailer, seconds.
    poll: float = 0.5
    #: Snapshot every N processed windows (plus once at exit/SIGTERM).
    checkpoint_every: int = 1
    #: Stop after this many windows processed THIS run (None = no cap).
    max_windows: int | None = None
    #: Stop after this much wall clock, seconds (None = no cap).
    max_seconds: float | None = None
    #: "controller" = plans re-cluster exactly as the batch loop does;
    #: "minibatch" additionally advances a warm-started mini-batch
    #: Lloyd step per window (live centroids/inertia telemetry).
    recluster: str = "controller"
    #: Rows sampled from the feature snapshot per mini-batch step.
    minibatch_rows: int = 2048
    #: Seed of the daemon's EpochMap hash placement.
    placement_seed: int = 0
    #: Tail-sampled exemplars: the N slowest decisions seen so far keep
    #: a FULL span tree embedded in their ``decision_trace`` event; the
    #: rest keep stage sums only (the 1.05x telemetry-budget contract —
    #: obs/trace.py).  0 disables exemplar trees; tracing itself rides
    #: the metrics sink, not this knob.
    trace_exemplars: int = 8
    #: Overload brownout ladder (daemon/brownout.py): when set, decision
    #: lag drives the degraded-mode state machine — minibatch skip,
    #: scrub deferral, trace capping, deterministic window coalescing,
    #: seeded serve-path shedding.  None (the default) keeps every
    #: existing run bit-identical: lag is still measured and exposed,
    #: but nothing is ever shed or coalesced.
    brownout: BrownoutConfig | None = None

    def __post_init__(self):
        if self.recluster not in _RECLUSTER_MODES:
            raise ValueError(
                f"unknown recluster mode {self.recluster!r} "
                f"(want one of {_RECLUSTER_MODES})")
        if self.poll <= 0:
            raise ValueError(f"poll must be > 0, got {self.poll}")
        if self.trace_exemplars < 0:
            raise ValueError(
                f"trace_exemplars must be >= 0, "
                f"got {self.trace_exemplars}")


@dataclass
class _Inflight:
    """Cursor bookkeeping for one ingested batch still overlapping an
    unprocessed window: where its first event lives in the log."""

    offset: int        # block-boundary byte offset (0 for feeds)
    base: int          # events to skip at ``offset`` before this batch
    ts: np.ndarray     # the batch's timestamps (window membership)


class StreamDaemon:
    """Drive a ReplicationController continuously over a live stream.

    ``source`` accepted by :meth:`run`: a ``.cdrsb`` binary-log path
    (tailed; CSV logs are rejected — the live fast path is columnar),
    an in-memory ``EventLog``, or any iterable of ``EventLog`` batches
    (the in-process generator feed).  For feeds the resume cursor is an
    event COUNT — the feed must be replayable from its start (the
    scenario harness replays the seeded simulator).
    """

    def __init__(self, controller, cfg: DaemonConfig | None = None, *,
                 rules=None):
        self.controller = controller
        self.cfg = cfg or DaemonConfig()
        self.publisher = EpochPublisher()
        self.engine = AlertEngine(rules if rules is not None
                                  else default_rules())
        self.records: list[dict] = []
        self.alert_log: list[dict] = []
        self.decision_seconds: list[float] = []
        self.minibatch: dict | None = None
        self.alert_checkpoints = 0
        self.checkpoint_count = 0
        self.windows_processed = 0
        self.events_ingested = 0
        self._stop = threading.Event()
        self._stop_reason: str | None = None
        self._cursor = {"offset": 0, "skip": 0}
        self._inflight: list[_Inflight] = []
        self._tail = (0, 0)       # cursor when nothing is in flight
        self._emap = None
        self._flat_topo = None
        self._mbk = None
        # Bounded decision-latency reservoir (HIST_RAW_CAP decimation):
        # sample i is kept iff i % stride == 0; the stride doubles each
        # time the list fills, so ``decision_seconds`` stays a uniform
        # subsample with stable memory in a true always-on run.
        self._dec_seen = 0
        self._dec_stride = 1
        # Decision tracing (obs/trace.py; active iff a metrics sink is).
        self.traced_decisions = 0
        self._ingest_box: dict = {"ns": 0}
        self._batch_cursor = (0, 0)   # (offset, skip) of the last mint
        self._prev_end_ns = 0
        # Exemplar retention: a min-heap of (total_ns, window, event) —
        # the event dicts of the ``trace_exemplars`` slowest decisions
        # stay resident so the live /debug/trace endpoint can serve them
        # without re-reading the sink.  (total_ns, window) orders the
        # heap; window indices are unique within a run, so the dict is
        # never compared.
        self._exemplar_heap: list[tuple[int, int, dict]] = []
        self._publish_info: dict[int, tuple[int, int, str]] = {}
        self._pins_seen: set[int] = set()
        self._last_epoch_id = 0
        # Live operational plane (obs/httpz.py), attached via
        # ``attach_http``: one immutable snapshot published per
        # processed window + readiness/health bits.
        self._obs = None
        self._reclusters = 0
        self._bytes_migrated = 0
        self._stage_ns: dict[str, int] = {}
        self._source_path: str | None = None
        # Lag accounting (first-class overload signal): how far the
        # resume cursor trails the log head, re-measured after every
        # processed window — bytes are exact, blocks/seconds are
        # estimated from the consumed log's own block-size and
        # timestamp-density averages, so the whole vector is a
        # deterministic function of (log contents, cursor).
        self._lag = {"bytes": 0, "blocks": 0.0, "seconds": 0.0,
                     "windows": 0.0}
        self._bytes_ingested = 0
        self._blocks_ingested = 0
        self._counted_upto = 0
        self._ts_first: float | None = None
        self._ts_last: float | None = None
        # Brownout ladder (cfg.brownout): built here so its level/calm
        # state can be restored from the checkpoint before run().
        self._ladder = (None if self.cfg.brownout is None
                        else BrownoutLadder(self.cfg.brownout))
        self._degraded: frozenset = frozenset()
        self._exemplar_cap = int(self.cfg.trace_exemplars)
        self.brownout_log: list[dict] = []
        self.windows_coalesced = 0
        self.reads_shed_total = 0

    # -- lifecycle ---------------------------------------------------------
    def attach_http(self, server) -> None:
        """Attach the live operational plane (obs/httpz.ObsServer):
        the daemon publishes one immutable :class:`ObsSnapshot` per
        processed window and drives the readiness/health bits.  Call
        before :meth:`run`; the caller owns the server's lifecycle."""
        self._obs = server
        server.heartbeat()

    def request_stop(self, reason: str = "requested") -> None:
        """Ask the loop to stop after the window in flight (thread- and
        signal-safe)."""
        if self._stop_reason is None:
            self._stop_reason = reason
        self._stop.set()
        obs = self._obs
        if obs is not None:
            # Drain begins NOW: readiness drops before the in-flight
            # window finishes (attribute stores only — signal-safe).
            obs.set_draining(True)

    def install_signal_handlers(self,
                                signals=(signal.SIGTERM,
                                         signal.SIGINT)) -> None:
        """Graceful shutdown: SIGTERM/SIGINT -> finish the current
        window, checkpoint, return (main thread only)."""
        for s in signals:
            signal.signal(
                s, lambda signum, frame: self.request_stop(
                    signal.Signals(signum).name))

    # -- ingest ------------------------------------------------------------
    def _ingest_stop(self) -> bool:
        """The tailer's stop probe doubles as the liveness heartbeat:
        it runs at every poll/batch boundary — exactly when ingest is
        making progress (or actively waiting on an empty log, which is
        healthy idling, not a wedge)."""
        obs = self._obs
        if obs is not None:
            obs.heartbeat()
        return self._stop.is_set()

    def _batches(self, source, batch_size: int):
        """Normalize any source into EventLog batches WITH cursor
        bookkeeping: every yielded batch is registered in
        ``_inflight`` so a checkpoint can name the byte/count position
        of the first unprocessed event."""
        skip = int(self._cursor["skip"])
        if isinstance(source, (str, bytes, os.PathLike)):
            if os.path.exists(source) and not is_binary_log(source):
                raise ValueError(
                    f"daemon ingest needs the binary event log "
                    f"(.cdrsb), got a CSV/unknown file: {source!r} — "
                    f"produce one with `cdrs simulate --format binary` "
                    f"or EventLog.write_binary")
            stream = tail_binary_log(
                str(source), self.controller.manifest,
                follow=self.cfg.follow, poll=self.cfg.poll,
                stop=self._ingest_stop,
                start_offset=int(self._cursor["offset"]),
                ingest_box=self._ingest_box)
            for ev, off, nxt in stream:
                # Lag calibration: block size and timestamp density of
                # everything CONSUMED (skipped blocks included — they
                # are log mass too), before any slicing below.  The
                # high-water mark keeps a resumed daemon (which re-reads
                # inflight blocks past the cursor) from double-counting
                # calibration mass — the estimator must equal the
                # uninterrupted run's at every decision point, or the
                # brownout ladder would diverge on resume.
                if int(off) >= self._counted_upto:
                    self._bytes_ingested += int(nxt - off)
                    self._blocks_ingested += 1
                    self._counted_upto = int(nxt)
                    if len(ev):
                        if self._ts_first is None:
                            self._ts_first = float(ev.ts[0])
                        self._ts_last = float(ev.ts[-1])
                base = 0
                if skip:
                    take = min(skip, len(ev))
                    skip -= take
                    if take == len(ev):
                        self._tail = (nxt, 0)
                        continue
                    ev = _slice(ev, take, len(ev))
                    base = take
                self._inflight.append(_Inflight(off, base, ev.ts))
                # The per-batch trace mint: the tailer already stamped
                # the ingest instant into ``_ingest_box`` at the read.
                self._batch_cursor = (off, base)
                self._tail = (nxt, 0)
                self.events_ingested += len(ev)
                yield ev
            return
        if int(self._cursor["offset"]):
            raise ValueError(
                "resume cursor carries a byte offset but the source is "
                "an in-process feed — the checkpoint belongs to a "
                "binary-log daemon")
        feed = iter([source]) if isinstance(source, EventLog) \
            else iter(source)
        gidx = 0
        for ev in feed:
            if self._ingest_stop():
                return
            n = len(ev)
            if n:
                if self._ts_first is None:
                    self._ts_first = float(ev.ts[0])
                self._ts_last = float(ev.ts[-1])
            if skip:
                take = min(skip, n)
                skip -= take
                gidx += take
                if take == n:
                    self._tail = (0, gidx)
                    continue
                ev = _slice(ev, take, n)
            self._inflight.append(_Inflight(0, gidx, ev.ts))
            # Feed-path mint: no tailer to stamp the read, so the batch
            # arrival IS the yield instant.
            self._ingest_box["ns"] = time.perf_counter_ns()
            self._batch_cursor = (0, gidx)
            gidx += len(ev)
            self._tail = (0, gidx)
            self.events_ingested += len(ev)
            yield ev

    def _advance_cursor(self, w: int) -> None:
        """After window ``w`` closed: the cursor is the position of the
        first event belonging to window ``w+1`` (block boundary + skip
        count), or the ingest tail when nothing is buffered."""
        w_end = self.controller._t0 \
            + (w + 1) * float(self.controller.cfg.window_seconds)
        keep: list[_Inflight] = []
        cursor = None
        for fl in self._inflight:
            cut = int(np.searchsorted(fl.ts, w_end, side="left"))
            if cut < len(fl.ts):
                if cursor is None:
                    cursor = (fl.offset, fl.base + cut)
                keep.append(fl)
        self._inflight = keep
        off, sk = cursor if cursor is not None else self._tail
        self._cursor = {"offset": int(off), "skip": int(sk)}

    # -- overload: lag + brownout ------------------------------------------
    def _update_lag(self, w: int) -> None:
        """Decision lag after window ``w`` closed: how far the log head
        is ahead of the resume cursor — bytes (exact), blocks and
        stream-seconds (estimated from the consumed log's own block
        size / timestamp density averages), and windows (seconds over
        the grid).  Every input is a function of (log contents, cursor),
        never of wall clock: the determinism the coalescing contract
        (same log + same lag profile => same merged windows) rests on.
        Feed sources have no byte head; their lag comes from the
        buffered-but-unprocessed timestamp span only."""
        ctl = self.controller
        W = float(ctl.cfg.window_seconds)
        w_end = float(ctl._t0) + (w + 1) * W
        lag_bytes = 0
        if self._source_path is not None:
            try:
                lag_bytes = max(
                    0, os.path.getsize(self._source_path)
                    - int(self._cursor["offset"]))
            except OSError:
                pass
        lag_blocks = 0.0
        lag_seconds = 0.0
        if lag_bytes and self._blocks_ingested and self._bytes_ingested:
            lag_blocks = lag_bytes / max(
                self._bytes_ingested / self._blocks_ingested, 1.0)
            if self._ts_first is not None \
                    and self._ts_last > self._ts_first:
                lag_seconds = lag_bytes * (
                    (self._ts_last - self._ts_first)
                    / self._bytes_ingested)
        # Buffered-but-unprocessed events trail the head too — on short
        # logs their exact span beats the byte-rate estimate.
        buf_last = None
        for fl in self._inflight:
            if len(fl.ts):
                t = float(fl.ts[-1])
                buf_last = t if buf_last is None else max(buf_last, t)
        if buf_last is not None and buf_last > w_end:
            lag_seconds = max(lag_seconds, buf_last - w_end)
        self._lag = {"bytes": int(lag_bytes),
                     "blocks": float(lag_blocks),
                     "seconds": float(lag_seconds),
                     "windows": max(0.0, lag_seconds / W)}

    def _apply_brownout(self) -> None:
        """Install the ladder's engaged modes into the levers they pull:
        the controller's degraded set + serve shed, and the daemon's own
        exemplar cap.  Idempotent — called after every ladder step and
        after a checkpoint restore."""
        modes = self._ladder.modes()
        self._degraded = modes
        ctl = self.controller
        ctl.degraded_modes = modes
        bcfg = self.cfg.brownout
        ctl.serve_shed = ((float(bcfg.shed_fraction),
                          int(bcfg.shed_seed))
                          if "shed_reads" in modes else None)
        self._exemplar_cap = 0 if "cap_trace" in modes \
            else int(self.cfg.trace_exemplars)

    def _step_ladder(self, w: int, rec: dict, sink) -> None:
        """One ladder step per processed window (AFTER the decision, so
        a rung engaged here first affects the NEXT window — the modes a
        window ran under are the ones its record reports)."""
        for t in self._ladder.step(w, self._lag["windows"],
                                   slo_burn=float(
                                       rec.get("slo_burn") or 0.0)):
            ev = {"kind": f"degraded.brownout.{t['state']}", **t}
            self.brownout_log.append(ev)
            if sink is not None:
                sink.emit(ev)
        self._apply_brownout()

    def _coalesce(self, win_iter, w: int, events):
        """Backpressure coalescing: merge up to ``coalesce_max``
        consecutive pending windows onto the LAST window of the group
        and decide once over the union — mass-conserving (every event
        is folded exactly once; ``_advance_cursor`` on the last window
        keeps the resume contract) and deterministic (group size is a
        function of the lag vector; the merge is ``_concat`` in grid
        order).  A window carrying fault-schedule events is never
        merged at all: the controller applies ``for_window`` at the
        GROUP'S last index only, so every member must be fault-free —
        faulted windows always run alone, at their own index.  Returns
        ``(last_w, merged_events, n_merged, pending)`` where
        ``pending`` is a pulled-but-unmerged window for the caller to
        process next."""
        ctl = self.controller
        group = min(int(self.cfg.brownout.coalesce_max),
                    1 + int(self._lag["windows"]))
        sched = getattr(ctl, "_fault_schedule", None)
        if group <= 1 or (sched is not None
                          and len(sched.for_window(w))):
            return w, events, 1, None
        parts = [events]
        last = w
        pending = None
        while last - w + 1 < group:
            if sched is not None and len(sched.for_window(last + 1)):
                break   # fault boundary: never merge across it
            try:
                nw, nev = next(win_iter)
            except StopIteration:
                break
            if nw != last + 1:   # defensive: the carver is consecutive
                pending = (nw, nev)
                break
            parts.append(nev)
            last = nw
        if last == w:
            return w, events, 1, pending
        return last, _concat(parts, ctl.manifest), last - w + 1, pending

    # -- per-window actions ------------------------------------------------
    def _publish(self, w: int, rec: dict,
                 trace_id: str | None = None) -> PlacementEpoch:
        ctl = self.controller
        topo = None
        if getattr(ctl, "_cluster_state", None) is not None:
            topo = ctl._cluster_state.topology
        elif getattr(ctl.cfg, "topology", None) is not None:
            topo = ctl.cfg.topology
        if topo is None:
            if self._flat_topo is None:
                from ..cluster import ClusterTopology

                self._flat_topo = ClusterTopology(
                    nodes=tuple(ctl.manifest.nodes))
            topo = self._flat_topo
        if self._emap is None:
            from ..placement_fn import EpochMap

            self._emap = EpochMap(ctl.manifest.nodes, topo,
                                  seed=self.cfg.placement_seed)
        # Every admitted plan IS a new cluster-map revision (an
        # unchanged topology diffs to zero moves by construction).
        map_ep = self._emap.advance(topo)
        rf = ctl.current_rf.copy()
        cat = ctl.current_cat.copy()
        emap, prim = self._emap, ctl.manifest.primary_node_id

        def resolver(uniq, _eid=map_ep.epoch_id, _rf=rf):
            slots, _ = emap.placement(_eid, np.asarray(uniq),
                                      _rf[uniq], prim[uniq])
            return slots

        epoch = PlacementEpoch(
            epoch_id=self.publisher.published_total + 1,
            window=int(w), plan_hash=str(rec.get("plan_hash", "")),
            rf=rf, category_idx=cat, n_nodes=len(topo.nodes),
            map_epoch_id=map_ep.epoch_id, resolver=resolver,
            trace_id=trace_id)
        epoch = self.publisher.publish(epoch)
        self._last_epoch_id = int(epoch.epoch_id)
        if trace_id is not None:
            # Publish instant + provenance, kept until the epoch's first
            # serve-path pin closes the loop (``_drain_pins``).  Bounded:
            # an epoch nobody ever pins is dropped once it falls 256
            # publications behind.
            self._publish_info[int(epoch.epoch_id)] = (
                time.perf_counter_ns(), int(w), trace_id)
            stale = epoch.epoch_id - 256
            for eid in [e for e in self._publish_info if e < stale]:
                del self._publish_info[eid]
        if self._obs is not None and not self._stop.is_set():
            # The epoch-pinned serving contract as a probe: an epoch
            # exists to pin, so the daemon is ready for traffic.
            self._obs.set_ready(True)
        return epoch

    def _observe_alerts(self, rec: dict, sink,
                        checkpoint_path: str | None) -> None:
        for t in self.engine.observe({"kind": "window", **rec}):
            self.alert_log.append(t)
            if sink is not None:
                sink.emit({"kind": "alert", **t})
            if (t.get("state") == "firing"
                    and t.get("alert") in SEVERE_ALERTS
                    and checkpoint_path):
                # A page-severity alert is the control surface acting:
                # land a protective snapshot immediately so the state
                # that first saw the loss is durable for post-mortem
                # and restart.
                self._save(checkpoint_path)
                self.alert_checkpoints += 1

    def _minibatch_step(self) -> None:
        from ..ops.kmeans_stream import MiniBatchKMeans  # needs jax

        ctl = self.controller
        X = np.asarray(ctl._feature_snapshot(), dtype=np.float32)
        k = int(ctl.cfg.kmeans.k)
        if self._mbk is None:
            self._mbk = MiniBatchKMeans(k=k, seed=ctl.cfg.kmeans.seed)
        n_b = self._mbk.state.n_batches if self._mbk.state else 0
        rng = np.random.default_rng(
            (int(ctl.cfg.kmeans.seed or 0) << 16) ^ n_b)
        rows = min(max(int(self.cfg.minibatch_rows), k), len(X))
        idx = np.sort(rng.choice(len(X), size=rows, replace=False))
        sample = X[idx]
        self._mbk.partial_fit(sample)
        d = sample[:, None, :] - self._mbk.centroids[None, :, :]
        inertia = float(np.mean(np.min((d * d).sum(-1), axis=1)))
        self.minibatch = {
            "n_batches": int(self._mbk.state.n_batches),
            "inertia": inertia,
        }

    def _record_decision(self, seconds: float) -> None:
        """Bounded decision-latency reservoir: uniform 2:1 decimation
        past ``HIST_RAW_CAP`` (the ``obs.telemetry.histogram``
        contract), so a true always-on run keeps stable memory and
        ``digest()``'s p50/p99 stay those of a uniform subsample."""
        if self._dec_seen % self._dec_stride == 0:
            lst = self.decision_seconds
            lst.append(float(seconds))
            if len(lst) >= HIST_RAW_CAP:
                del lst[1::2]
                self._dec_stride *= 2
        self._dec_seen += 1

    def _emit_decision_trace(self, sink, w: int, trace_id: str,
                             rec: dict, epoch: PlacementEpoch,
                             segments_ns: dict, total_ns: int,
                             ref_ns: int, n_events: int) -> None:
        """One compact ``decision_trace`` event per decision — segments
        are integer-ns deltas of ONE clock, so their sum equals
        ``total_ns`` bit-for-bit (the reconciliation every consumer
        asserts).  The ``trace_exemplars`` slowest decisions seen so far
        additionally embed the full span tree."""
        ev = {
            "kind": "decision_trace", "trace": trace_id,
            "window": int(w), "total_ns": int(total_ns),
            "segments_ns": {k: int(v) for k, v in segments_ns.items()},
            "ref_ns": int(ref_ns), "n_events": int(n_events),
            "epoch_id": int(epoch.epoch_id),
            "map_epoch_id": int(epoch.map_epoch_id),
            "plan_hash": epoch.plan_hash,
            "batch": {"offset": int(self._batch_cursor[0]),
                      "skip": int(self._batch_cursor[1])},
        }
        # The live cap, not the configured one: the brownout ladder's
        # ``cap_trace`` rung zeroes it while engaged (span trees are
        # optional work; stage sums survive).
        cap = int(self._exemplar_cap)
        exemplar = False
        if cap > 0:
            if len(self._exemplar_heap) < cap:
                exemplar = True
            elif (int(total_ns), int(w)) > self._exemplar_heap[0][:2]:
                exemplar = True
        ev["exemplar"] = exemplar
        if exemplar:
            import heapq

            ev["spans"] = build_span_tree(ev, rec)
            item = (int(total_ns), int(w), ev)
            if len(self._exemplar_heap) < cap:
                heapq.heappush(self._exemplar_heap, item)
            else:
                heapq.heapreplace(self._exemplar_heap, item)
        if sink is not None:
            sink.emit(ev)
            self.traced_decisions += 1

    def _drain_pins(self, sink) -> None:
        """Surface first serve-path pins as ``epoch_pin`` events closing
        the publish->pin causal gap.  Entries for epochs older than the
        latest publication can never be stamped again (``pin`` only sees
        the current epoch), so they are pruned once emitted — bounded
        state in an always-on run."""
        fp = self.publisher.first_pins
        for eid in sorted(fp):
            if eid not in self._pins_seen:
                self._pins_seen.add(eid)
                ev = {"kind": "epoch_pin", "epoch_id": int(eid)}
                info = self._publish_info.get(eid)
                if info is not None:
                    pub_ns, win, tid = info
                    ev["window"] = win
                    ev["trace"] = tid
                    ev["publish_to_pin_ns"] = int(fp[eid] - pub_ns)
                sink.emit(ev)
            if eid < self._last_epoch_id:
                fp.pop(eid, None)
                self._pins_seen.discard(eid)
                self._publish_info.pop(eid, None)

    def _publish_snapshot(self, w: int, rec: dict, segments_ns: dict,
                          total_ns: int) -> None:
        """Build ONE immutable ObsSnapshot and install it with a single
        reference swap (obs/httpz.py snapshot-swap contract).  Runs
        after the decision's segment clocks close — the endpoint is
        strictly off the decision path; this method is the only
        daemon->server data flow."""
        from ..obs.httpz import ObsSnapshot

        # Critical-path stage attribution, incrementally: the
        # aggregate.critical_path_digest math — the decide segment
        # expands into the controller's per-stage breakdown scaled to
        # the segment's integer-ns span.
        secs = rec.get("seconds") or {}
        decide_ns = int(segments_ns.get("decide", 0))
        stage_sum = sum(float(secs[k]) for k in STAGE_ORDER if k in secs)
        for name, ns in segments_ns.items():
            if name == "decide" and decide_ns > 0 and stage_sum > 0:
                for k in STAGE_ORDER:
                    if k in secs:
                        self._stage_ns[k] = self._stage_ns.get(k, 0) \
                            + int(round(float(secs[k]) / stage_sum
                                        * decide_ns))
                continue
            self._stage_ns[name] = self._stage_ns.get(name, 0) + int(ns)
        total_stage = sum(self._stage_ns.values()) or 1
        order = ("tail",) + STAGE_ORDER + ("decide", "observe",
                                           "publish", "minibatch")
        stages = tuple(
            (name, self._stage_ns[name] / 1e9,
             self._stage_ns[name] / total_stage)
            for name in order if name in self._stage_ns)
        self._reclusters += 1 if rec.get("recluster") else 0
        self._bytes_migrated += int(rec.get("bytes_migrated", 0) or 0)
        backlog_bytes = 0
        if self._source_path is not None:
            try:
                # Block-granular: bytes of log at/after the resume
                # cursor — what a restart would re-read.
                backlog_bytes = max(
                    0, os.path.getsize(self._source_path)
                    - int(self._cursor["offset"]))
            except OSError:
                pass
        # Buffered-but-unprocessed events: inflight batches keep their
        # FULL ts arrays (a batch can span many windows), so count only
        # events past the just-closed window's end.
        w_end = self.controller._t0 \
            + (w + 1) * float(self.controller.cfg.window_seconds)
        backlog_events = int(sum(
            len(fl.ts) - int(np.searchsorted(fl.ts, w_end, side="left"))
            for fl in self._inflight))
        lat = self.decision_seconds
        arr = np.asarray(lat, dtype=np.float64)
        alerts = tuple(
            {"name": r["name"], "severity": r["severity"],
             "kind": r["kind"], "firing": r["firing"],
             "fired": r["fired"], "since": r["since"],
             "streak": r["streak"]}
            for r in self.engine.results())
        self._obs.publish(ObsSnapshot(
            seq=int(self.windows_processed),
            epoch_id=int(self._last_epoch_id) or None,
            window=int(w),
            windows_processed=int(self.windows_processed),
            events_ingested=int(self.events_ingested),
            epochs_published=int(self.publisher.published_total),
            checkpoints_written=int(self.checkpoint_count),
            reclusters=int(self._reclusters),
            bytes_migrated=int(self._bytes_migrated),
            traced_decisions=int(self.traced_decisions),
            backlog_events=backlog_events,
            backlog_bytes=int(backlog_bytes),
            lag_bytes=int(self._lag["bytes"]),
            lag_blocks=round(float(self._lag["blocks"]), 3),
            lag_seconds=round(float(self._lag["seconds"]), 3),
            lag_windows=round(float(self._lag["windows"]), 3),
            brownout_level=(0 if self._ladder is None
                            else int(self._ladder.level)),
            brownout_rungs=(() if self._ladder is None
                            else tuple(RUNGS[:self._ladder.level])),
            reads_shed=int(self.reads_shed_total),
            windows_coalesced=int(self.windows_coalesced),
            decision_seconds=tuple(lat),
            decision_p50_seconds=(
                None if arr.size == 0
                else round(float(np.quantile(arr, 0.5)), 6)),
            decision_p99_seconds=(
                None if arr.size == 0
                else round(float(np.quantile(arr, 0.99)), 6)),
            stages=stages,
            alerts=alerts,
            exemplars=tuple(ev for _t, _w, ev in sorted(
                self._exemplar_heap, key=lambda it: it[1])),
        ))

    def _save(self, path: str) -> None:
        dmeta = {
            "offset": int(self._cursor["offset"]),
            "skip": int(self._cursor["skip"]),
            "epochs_published": int(self.publisher.published_total),
            # Lag-estimator calibration (block size / timestamp density
            # averages): decision-relevant under brownout — a resumed
            # ladder stepping on a freshly-zeroed estimator would see
            # different lag than the uninterrupted run did.
            "lag_est": {
                "bytes": int(self._bytes_ingested),
                "blocks": int(self._blocks_ingested),
                "upto": int(self._counted_upto),
                "ts_first": self._ts_first,
                "ts_last": self._ts_last,
            },
            # The last computed lag vector: the NEXT decision's coalesce
            # group size reads it before any window closes, so a resume
            # must see what the uninterrupted run saw.
            "lag": dict(self._lag),
        }
        if self._ladder is not None:
            # The ladder is decision-relevant state (it gates sheds and
            # coalescing): its level/calm pair must survive restart, or
            # a resumed daemon would re-climb from rung 0 and make
            # different decisions than the uninterrupted run.
            dmeta["brownout"] = self._ladder.state_dict()
            dmeta["windows_coalesced"] = int(self.windows_coalesced)
            dmeta["reads_shed"] = int(self.reads_shed_total)
        self.controller.save_checkpoint(path,
                                        extra_meta={"daemon": dmeta})
        self.checkpoint_count += 1

    # -- the loop ----------------------------------------------------------
    def run(self, source, *, metrics_path: str | None = None,
            metrics_max_bytes: int | None = None,
            checkpoint_path: str | None = None,
            batch_size: int = 1_000_000) -> dict:
        """Ingest -> carve -> decide -> publish, until the stream ends
        (non-follow), a cap is hit, or a stop/SIGTERM arrives.  Returns
        the digest (:meth:`digest`)."""
        ctl = self.controller
        cfg = self.cfg
        if isinstance(source, (str, bytes, os.PathLike)):
            self._source_path = os.fspath(source)  # backlog accounting
        if checkpoint_path:
            ctl._load_checkpoint_with_fallback(checkpoint_path)
            dmeta = (getattr(ctl, "last_checkpoint_meta", None)
                     or {}).get("daemon") or {}
            self._cursor = {"offset": int(dmeta.get("offset", 0)),
                            "skip": int(dmeta.get("skip", 0))}
            self._tail = (self._cursor["offset"], self._cursor["skip"])
            self.publisher.published_total = int(
                dmeta.get("epochs_published", 0))
            est = dmeta.get("lag_est") or {}
            self._bytes_ingested = int(est.get("bytes", 0))
            self._blocks_ingested = int(est.get("blocks", 0))
            self._counted_upto = int(est.get("upto", 0))
            self._ts_first = est.get("ts_first")
            self._ts_last = est.get("ts_last")
            if dmeta.get("lag"):
                self._lag = {k: dmeta["lag"].get(k, 0)
                             for k in ("bytes", "blocks", "seconds",
                                       "windows")}
            if self._ladder is not None:
                self._ladder.load_state_dict(
                    dmeta.get("brownout") or {})
                self.windows_coalesced = int(
                    dmeta.get("windows_coalesced", 0))
                self.reads_shed_total = int(dmeta.get("reads_shed", 0))
                self._apply_brownout()
        sink = None
        own_sink = False
        tel = None
        if metrics_path:
            from ..obs import JsonlSink
            from ..obs import current as _obs_current

            # One stream, ONE writer (controller.run's sharing rule).
            tel = _obs_current()
            if (tel is not None and tel.sink is not None
                    and getattr(tel.sink, "path", None) == metrics_path):
                sink = tel.sink
            else:
                sink = JsonlSink(metrics_path,
                                 max_bytes=metrics_max_bytes)
                own_sink = True
                tel = None   # ambient instrument writes elsewhere
        # Decision tracing rides the metrics sink: a sink means every
        # decision gets a trace context and a ``decision_trace`` event;
        # live ``daemon.decision``/``controller.*`` spans additionally
        # flow when the ambient telemetry shares that sink.
        trace_on = sink is not None
        if trace_on:
            self.publisher.record_pins = True

        deadline = (time.monotonic() + float(cfg.max_seconds)
                    if cfg.max_seconds is not None else None)
        every = max(1, int(cfg.checkpoint_every))
        since_ckpt = 0
        t0_box: dict = {}
        win_iter = iter_windows(
            self._batches(source, batch_size), ctl.manifest,
            ctl.cfg.window_seconds, batch_size=batch_size,
            t0=ctl._t0, t0_out=t0_box)
        #: A window the coalescer pulled but could not merge (fault
        #: boundary / group full): processed on the next iteration.
        pending: tuple | None = None
        try:
            while True:
                if pending is not None:
                    w, events = pending
                    pending = None
                else:
                    try:
                        w, events = next(win_iter)
                    except StopIteration:
                        if self._stop_reason is None:
                            self._stop_reason = "end_of_stream"
                        break
                if self._stop.is_set():
                    # Includes the carver's trailing partial-window
                    # flush after a stop-interrupted tail: those events
                    # stay unprocessed, the cursor re-reads them.
                    break
                if ctl._t0 is None:
                    ctl._t0 = t0_box.get("t0")
                if w < ctl.window_index:
                    # Already processed before the checkpoint.  Any
                    # events here re-read past the cursor are a late
                    # tail appended after the snapshot, inside an
                    # already-planned window's span: fold them so no
                    # event is lost (batch resume's contract).
                    if len(events):
                        ctl._fold_window(events, new_window=False)
                        ctl._last_window_events += len(events)
                        self._advance_cursor(w)
                        since_ckpt += 1
                    continue
                coalesced = 1
                if self._ladder is not None \
                        and "coalesce" in self._degraded:
                    w, events, coalesced, pending = self._coalesce(
                        win_iter, w, events)
                # Segment clocks: consecutive ``perf_counter_ns`` reads
                # of ONE clock, so the per-stage deltas telescope to the
                # measured total EXACTLY (integer equality — the
                # reconciliation obs/trace.py asserts).  ``ref`` is the
                # decision's causal origin: the ingest instant of the
                # closing batch, or the previous decision's end when the
                # loop itself is the bottleneck (a backlog replay must
                # not double-charge earlier decisions' service time to
                # later windows' tails).
                t_start = time.perf_counter_ns()
                ref = max(self._ingest_box["ns"], self._prev_end_ns)
                if ref == 0 or ref > t_start:
                    ref = t_start
                tid = decision_trace_id(w)
                if tel is not None:
                    ctl._trace_id = tid
                    try:
                        with tel.span("daemon.decision", trace=tid,
                                      window=int(w)):
                            rec = ctl.process_window(w, events)
                    finally:
                        ctl._trace_id = None
                else:
                    rec = ctl.process_window(w, events)
                t1 = time.perf_counter_ns()
                ctl.window_index = w + 1
                ctl._last_window_events = len(events)
                # Crash-anywhere contract: the cursor advances WITH the
                # window index, before anything below can land a
                # checkpoint (the alert path's protective save runs
                # next).  A snapshot carrying window_index = w+1 with a
                # cursor still parked on window w's first event would
                # double-fold window w on resume — the exact torn state
                # an uncoordinated kill -9 used to be able to persist.
                self._advance_cursor(w)
                self._update_lag(w)
                if self._ladder is not None:
                    # First-class overload signal in the record stream
                    # (daemon_lagging alert + post-hoc analysis).  Keyed
                    # into the record ONLY under a brownout config, so
                    # the batch-equivalence oracle's records stay
                    # byte-identical.
                    rec["daemon"] = {
                        "lag_bytes": int(self._lag["bytes"]),
                        "lag_blocks": round(self._lag["blocks"], 3),
                        "lag_seconds": round(self._lag["seconds"], 3),
                        "lag_windows": round(self._lag["windows"], 3),
                        "brownout_level": int(self._ladder.level),
                        "coalesced": int(coalesced),
                    }
                    if coalesced > 1:
                        self.windows_coalesced += coalesced - 1
                    self.reads_shed_total += int(
                        rec.get("reads_shed") or 0)
                self.records.append(rec)
                if sink is not None:
                    sink.emit({"kind": "window", **rec})
                self._observe_alerts(rec, sink, checkpoint_path)
                t2 = time.perf_counter_ns()
                epoch = self._publish(
                    w, rec, trace_id=tid if trace_on else None)
                t3 = time.perf_counter_ns()
                t4 = t3
                did_minibatch = (cfg.recluster == "minibatch"
                                 and "skip_minibatch"
                                 not in self._degraded)
                if did_minibatch:
                    self._minibatch_step()
                    t4 = time.perf_counter_ns()
                segments = {"tail": t_start - ref,
                            "decide": t1 - t_start,
                            "observe": t2 - t1,
                            "publish": t3 - t2}
                if did_minibatch:
                    segments["minibatch"] = t4 - t3
                self._record_decision((t4 - t_start) / 1e9)
                if trace_on or self._obs is not None:
                    # Exemplar retention also feeds the live
                    # /debug/trace endpoint, so it runs whenever the
                    # operational plane is attached — sink-less runs
                    # build the events without emitting them.
                    self._emit_decision_trace(
                        sink if trace_on else None, w, tid, rec, epoch,
                        segments, t4 - ref, ref, len(events))
                if trace_on:
                    self._drain_pins(sink)
                self._prev_end_ns = t4
                self.windows_processed += 1
                since_ckpt += 1
                if self._ladder is not None:
                    # Ladder steps AFTER the decision: the rung set a
                    # window ran under is what its record reports; a
                    # transition here first bites the NEXT window.
                    self._step_ladder(w, rec, sink)
                if self._obs is not None:
                    self._publish_snapshot(w, rec, segments, t4 - ref)
                if checkpoint_path and since_ckpt >= every:
                    self._save(checkpoint_path)
                    since_ckpt = 0
                if (cfg.max_windows is not None
                        and self.windows_processed
                        >= int(cfg.max_windows)):
                    self.request_stop("max_windows")
                if deadline is not None and time.monotonic() > deadline:
                    self.request_stop("max_seconds")
        finally:
            if sink is not None and own_sink:
                sink.close()
            if self._obs is not None:
                # The loop is over (drain, cap, or end of stream):
                # whatever epoch is pinned stays served by its holders,
                # but no new work should be routed here.
                self._obs.set_ready(False)
        if checkpoint_path and since_ckpt:
            self._save(checkpoint_path)
        return self.digest()

    # -- reporting ---------------------------------------------------------
    def digest(self) -> dict:
        """One JSON-able summary of the daemon's run (the CLI prints
        it; CI asserts on it)."""
        lat = np.asarray(self.decision_seconds, dtype=np.float64)
        # NOT ``pin()``: a digest is reporting, not serving — it must
        # never register as an epoch's first serve-path pin.
        cur = self.publisher.peek()
        out = {
            "windows_processed": int(self.windows_processed),
            "window_index": int(self.controller.window_index),
            "events_ingested": int(self.events_ingested),
            "epochs_published": int(self.publisher.published_total),
            "current_epoch": None if cur is None else int(cur.epoch_id),
            "plan_hash": None if cur is None else cur.plan_hash,
            "alerts_fired": sorted({t["alert"] for t in self.alert_log
                                    if t.get("state") == "firing"}),
            "alert_checkpoints": int(self.alert_checkpoints),
            "checkpoints": int(self.checkpoint_count),
            "decision_p50_seconds": (
                None if lat.size == 0
                else round(float(np.quantile(lat, 0.5)), 6)),
            "decision_p99_seconds": (
                None if lat.size == 0
                else round(float(np.quantile(lat, 0.99)), 6)),
            "traced_decisions": int(self.traced_decisions),
            "stop_reason": self._stop_reason,
            "cursor": dict(self._cursor),
        }
        if self.minibatch is not None:
            out["minibatch"] = dict(self.minibatch)
        if self._ladder is not None:
            out["lag"] = {
                "bytes": int(self._lag["bytes"]),
                "blocks": round(float(self._lag["blocks"]), 3),
                "seconds": round(float(self._lag["seconds"]), 3),
                "windows": round(float(self._lag["windows"]), 3),
            }
            out["brownout"] = {
                "level": int(self._ladder.level),
                "rungs": list(RUNGS[:self._ladder.level]),
                "transitions": len(self.brownout_log),
                "windows_coalesced": int(self.windows_coalesced),
                "reads_shed": int(self.reads_shed_total),
            }
        return out
