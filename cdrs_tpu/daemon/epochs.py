"""Atomically published placement epochs — the daemon's serve contract.

The CRUSH posture (Weil et al., PAPERS.md): serving resolves against a
*published cluster-map epoch*, never a mutable table.  Each admitted
plan freezes into a :class:`PlacementEpoch` — immutable rf/category
vectors, the plan hash, and a functional resolver over the backing
``placement_fn.EpochMap`` revision — and lands via one atomic reference
swap in :class:`EpochPublisher`.  Readers ``pin()`` ONCE per request
batch and route every read of that batch against the pinned epoch; a
concurrent ``publish()`` is invisible to them until their next pin, so
no batch ever observes a mix of epoch N and N+1 (property-tested in
tests/test_daemon.py under concurrent publication).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

import numpy as np

__all__ = ["PlacementEpoch", "EpochPublisher"]


@dataclass(frozen=True)
class PlacementEpoch:
    """One immutable admitted plan, as served.

    ``epoch_id`` is the daemon-lifetime publication sequence (continuous
    across checkpoint/resume); ``map_epoch_id`` names the backing
    ``placement_fn.EpochMap`` revision INSIDE the current process (the
    map is rebuilt on resume, so its ids restart while ``epoch_id`` does
    not).  ``resolver(unique_file_ids) -> (k, R) int32 slot rows`` is
    the ``serve.read_view(resolver=...)`` plug — the O(unique pids)
    functional resolution, frozen over this epoch's rf vector and map
    revision.
    """

    epoch_id: int
    window: int                  # window index whose plan this is
    plan_hash: str
    rf: np.ndarray               # (n,) int32, read-only
    category_idx: np.ndarray     # (n,) int32, read-only
    n_nodes: int
    map_epoch_id: int = 0
    resolver: object | None = field(default=None, repr=False)
    #: Trace id of the decision that published this epoch (obs/trace.py)
    #: — the causal link from an ingested event batch through the
    #: decision to the plan a reader pins.  None on epochs published
    #: outside a traced daemon run (tests, ad-hoc publication).
    trace_id: str | None = None

    def __post_init__(self):
        # An epoch is a snapshot, not a view: freeze the arrays so a
        # later controller window can never mutate a pinned plan.
        self.rf.setflags(write=False)
        self.category_idx.setflags(write=False)

    def read_view(self, pid: np.ndarray):
        """The router inputs for one request batch, pinned to THIS
        epoch (``serve.read_view`` resolver path)."""
        from ..serve import read_view

        if self.resolver is None:
            raise ValueError(
                f"epoch {self.epoch_id} carries no resolver (published "
                f"without a topology)")
        return read_view(pid, resolver=self.resolver,
                         n_nodes=self.n_nodes)


class EpochPublisher:
    """Single-slot atomic epoch publication.

    ``publish`` swaps one reference under a lock (writers are the
    daemon's window loop — rare); ``pin`` is ONE unlocked attribute
    read, atomic by construction, so readers never block the publisher
    and vice versa.  Epoch ids must grow monotonically — a republish of
    an older epoch is a torn-history bug and raises.
    """

    def __init__(self, published_total: int = 0):
        self._lock = threading.Lock()
        self._current: PlacementEpoch | None = None
        #: Epochs ever published across the daemon's LIFETIME, including
        #: before a checkpoint/resume (restored from daemon meta).
        self.published_total = int(published_total)
        #: Decision tracing: when on, ``pin`` records the FIRST pin of
        #: each epoch (``perf_counter_ns``) so the publish-to-first-pin
        #: latency joins the decision's trace.  Off by default — the
        #: untraced pin path stays one attribute read.
        self.record_pins = False
        #: epoch_id -> perf_counter_ns of its first observed pin.  Two
        #: racing request batches may both stamp "first" within
        #: nanoseconds of each other; either value is the honest first
        #: pin at trace resolution, so no lock is taken on the pin path.
        self.first_pins: dict[int, int] = {}

    def publish(self, epoch: PlacementEpoch) -> PlacementEpoch:
        with self._lock:
            cur = self._current
            if cur is not None and epoch.epoch_id <= cur.epoch_id:
                raise ValueError(
                    f"epoch ids must grow: {epoch.epoch_id} after "
                    f"{cur.epoch_id}")
            self._current = epoch
            self.published_total += 1
        return epoch

    def peek(self) -> PlacementEpoch | None:
        """The current epoch WITHOUT pin semantics: reporting surfaces
        (``digest()``, the /statusz snapshot) read state but must never
        register as an epoch's first serve-path pin."""
        return self._current

    def pin(self) -> PlacementEpoch | None:
        """The current epoch, pinned: callers hold the returned object
        for their WHOLE request batch and never re-read mid-batch.
        With ``record_pins`` on, the first pin of each epoch is
        timestamped (one dict probe per batch — never per read)."""
        ep = self._current
        if self.record_pins and ep is not None \
                and ep.epoch_id not in self.first_pins:
            self.first_pins[ep.epoch_id] = time.perf_counter_ns()
        return ep
