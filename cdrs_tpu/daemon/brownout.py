"""Brownout ladder: declarative degraded-mode state machine under load.

Dean & Barroso (PAPERS.md, *The Tail at Scale*) argue tail tolerance is
*designed* degradation: a system that cannot keep up must shed optional
work in a deliberate order, not let queues (and p99) run away.  The
daemon's load signal is **decision lag** — how far the window loop has
fallen behind the log head, measured in windows (daemon/core.py
``_update_lag``) — and this module turns it into a five-rung ladder of
progressively harsher sheds, engaged in fixed order as lag crosses each
rung's threshold and released **hysteretically** in reverse order:

====================  =====================================================
rung                  what it sheds
====================  =====================================================
``skip_minibatch``    the observability-only mini-batch Lloyd step
``defer_scrub``       background verification reads (known damage still
                      heals: repair keeps its budget priority)
``cap_trace``         span-tree exemplar retention (stage sums survive)
``coalesce``          window granularity: pending blocks merge onto the
                      grid, one decision per ``coalesce_max`` windows
``shed_reads``        serve-path load shedding: a bounded, seeded
                      fraction of reads rejected with an explicit
                      ``shed`` status instead of queueing
====================  =====================================================

The ladder is deliberately boring: pure function of the lag series
(plus the optional SLO-burn trip wire for the serve rung), no wall
clock, no RNG beyond the seeded shed draw the controller makes — so the
same log replays the same rung transitions, and the level/calm pair
checkpoints in the daemon's meta blob for bit-identical resume.

Hysteresis: rung *i* engages at ``engage[i]`` lag-windows and is only
released after ``hold`` consecutive windows at/below ``release[i]``
(strictly below the engage threshold), top rung first — the standard
two-threshold + dwell-time guard against flapping at a boundary.
"""

from __future__ import annotations

from dataclasses import dataclass, field

__all__ = ["RUNGS", "BrownoutConfig", "BrownoutLadder"]

#: Shed order, mildest first.  ``modes()`` returns the engaged prefix.
RUNGS = ("skip_minibatch", "defer_scrub", "cap_trace", "coalesce",
         "shed_reads")


@dataclass(frozen=True)
class BrownoutConfig:
    """Thresholds of the ladder, in decision-lag windows per rung."""

    #: Lag (windows behind the log head) at which each rung engages.
    engage: tuple = (2.0, 3.0, 4.0, 6.0, 8.0)
    #: Lag at/below which a rung counts a calm window toward release.
    #: Must sit strictly below the matching engage threshold.
    release: tuple = (1.0, 1.5, 2.0, 3.0, 4.0)
    #: Consecutive calm windows before ONE rung releases (dwell time).
    hold: int = 2
    #: Serve-path shed: fraction of the window's reads rejected while
    #: the ``shed_reads`` rung is engaged (seeded, bounded).
    shed_fraction: float = 0.2
    #: Seed of the per-window shed draw (controller's rng stream is
    #: ``[shed_seed, window]`` — decision-reproducible).
    shed_seed: int = 0
    #: Max consecutive windows merged per decision under ``coalesce``.
    coalesce_max: int = 4
    #: Optional serve trip wire: SLO burn at/above this also engages
    #: the ladder through ``shed_reads`` (None = lag-only).
    burn_engage: float | None = None

    def __post_init__(self):
        n = len(RUNGS)
        if len(self.engage) != n or len(self.release) != n:
            raise ValueError(
                f"brownout thresholds must cover all {n} rungs, got "
                f"engage={len(self.engage)} release={len(self.release)}")
        if any(e2 < e1 for e1, e2 in zip(self.engage, self.engage[1:])):
            raise ValueError(
                f"engage thresholds must be non-decreasing (the ladder "
                f"engages in rung order), got {self.engage}")
        if any(r >= e for r, e in zip(self.release, self.engage)):
            raise ValueError(
                f"each release threshold must sit strictly below its "
                f"engage threshold (hysteresis), got "
                f"release={self.release} engage={self.engage}")
        if self.hold < 1:
            raise ValueError(f"hold must be >= 1, got {self.hold}")
        if not 0.0 < self.shed_fraction < 1.0:
            raise ValueError(
                f"shed_fraction must be in (0, 1), got "
                f"{self.shed_fraction}")
        if self.coalesce_max < 2:
            raise ValueError(
                f"coalesce_max must be >= 2 (1 is no coalescing), got "
                f"{self.coalesce_max}")


@dataclass
class BrownoutLadder:
    """The live state machine: one :meth:`step` per processed window."""

    cfg: BrownoutConfig = field(default_factory=BrownoutConfig)
    #: Engaged rung count (0 = fully healthy; modes() = RUNGS[:level]).
    level: int = 0
    #: Consecutive calm windows toward the next release.
    calm: int = 0

    def modes(self) -> frozenset:
        """The engaged degraded modes (prefix of :data:`RUNGS`)."""
        return frozenset(RUNGS[:self.level])

    def step(self, window: int, lag_windows: float,
             slo_burn: float = 0.0) -> list[dict]:
        """Advance one window; returns the rung transitions it caused
        (``{"rung", "level", "state": "engage"|"release", "window",
        "lag_windows"}`` dicts, engage-order)."""
        cfg = self.cfg
        lag = float(lag_windows)
        out: list[dict] = []
        want = 0
        for i, thr in enumerate(cfg.engage):
            if lag >= thr:
                want = i + 1
        if cfg.burn_engage is not None \
                and float(slo_burn) >= float(cfg.burn_engage):
            # The serve trip wire engages the WHOLE ladder: if p99 is
            # burning the error budget, every milder shed is already
            # justified.
            want = len(RUNGS)
        if want > self.level:
            # Engage upward, possibly several rungs in one window (a
            # lag spike does not wait for one-rung-per-window manners).
            for lv in range(self.level + 1, want + 1):
                out.append({"rung": RUNGS[lv - 1], "level": lv,
                            "state": "engage", "window": int(window),
                            "lag_windows": round(lag, 3)})
            self.level = want
            self.calm = 0
            return out
        # Release path: hysteretic, ONE rung per dwell period, reverse
        # order — recovery is deliberately slower than degradation.
        if self.level and lag <= cfg.release[self.level - 1] \
                and (cfg.burn_engage is None
                     or float(slo_burn) < float(cfg.burn_engage)):
            self.calm += 1
            if self.calm >= cfg.hold:
                self.level -= 1
                self.calm = 0
                out.append({"rung": RUNGS[self.level],
                            "level": self.level, "state": "release",
                            "window": int(window),
                            "lag_windows": round(lag, 3)})
        else:
            self.calm = 0
        return out

    # -- checkpoint (rides the daemon's meta blob) --------------------------
    def state_dict(self) -> dict:
        return {"level": int(self.level), "calm": int(self.calm)}

    def load_state_dict(self, d: dict) -> None:
        self.level = min(max(int(d.get("level", 0)), 0), len(RUNGS))
        self.calm = max(int(d.get("calm", 0)), 0)
