"""Storage strategies: category -> {replicate(rf) | ec(k, m)} x tier.

The paper maps each category to one integer replication factor (Hot=3,
Shared=2, Moderate=1, Archival=4).  Production systems never quadruple-
replicate cold data — they erasure-code it and push it down a storage
tier: HDFS Erasure Coding stores an RS(6,3) stripe at 1.5x raw bytes
where rf=3 costs 3x, and Ceph's CRUSH places EC chunks across failure
domains exactly like replicas (PAPERS.md).  This module generalizes the
decision layer's output from "category -> rf" to "category -> strategy":

* ``replicate(rf)`` — rf full copies on rf distinct nodes.  One live
  copy suffices to read or re-replicate.
* ``ec(k, m)``      — the file splits into ``k`` data shards plus ``m``
  parity shards, each ``ceil(size/k)`` bytes, on ``k+m`` distinct nodes
  (domain-spread like replicas).  ANY ``k`` live shards reconstruct the
  file, so the stripe is **lost** when fewer than ``k`` shards survive
  and **at risk** when exactly ``k`` are reachable.  Stored bytes are
  ``(k+m)/k`` x raw — EC(6,3) stores 1.5x where rf=3 stores 3x — but
  repairing ONE shard must read ``k`` surviving shards (``k x
  shard_bytes`` of reconstruction traffic vs one plain copy), and a
  read whose primary shard is down degrades to a k-shard gather.

Every strategy carries a **storage tier** (hot/warm/cold) with a
relative per-byte cost and a throughput factor: cold media are cheap
and slow, which is why EC-on-cold is the production Archival shape.

The unifying arithmetic (``StrategyVectors``) is three per-category
integers the whole stack consumes vectorized:

=============  ==============  =========================
               replicate(rf)   ec(k, m)
=============  ==============  =========================
n_shards       rf              k + m
min_live       1               k
shard_div      1               k   (shard = ceil(size/div))
=============  ==============  =========================

``replicate(rf)`` is exactly ``n_shards=rf, min_live=1, shard_div=1`` —
so a config with only replicate strategies degenerates BIT-FOR-BIT to
the historical rf semantics through placement, durability tiers, repair
scheduling and byte accounting, and ``ec(1, m)`` is provably identical
to ``replicate(m+1)`` (tests/test_storage.py pins both).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

import numpy as np

__all__ = ["StorageTier", "Strategy", "StorageConfig", "StrategyVectors",
           "DEFAULT_TIERS", "storage_config_from_dict",
           "load_storage_config", "resolve_storage_config"]


@dataclass(frozen=True)
class StorageTier:
    """One storage medium class: relative cost and speed."""

    name: str
    #: Relative cost per stored byte (hot disk/flash = 1.0).  The cost
    #: digest multiplies stored bytes by this — a dimensionless "cost
    #: unit" that makes EC-cold vs replicate-hot comparable.
    byte_cost: float = 1.0
    #: Throughput factor in (0, 1] relative to the hot tier: reads of a
    #: file on this tier are served ``1/throughput`` x slower (the
    #: serve router's tier penalty).
    throughput: float = 1.0

    def __post_init__(self):
        if not self.name:
            raise ValueError("storage tier needs a name")
        if self.byte_cost <= 0:
            raise ValueError(
                f"tier {self.name!r}: byte_cost must be > 0, got "
                f"{self.byte_cost}")
        if not 0.0 < self.throughput <= 1.0:
            raise ValueError(
                f"tier {self.name!r}: throughput must be in (0, 1], got "
                f"{self.throughput}")


def _default_tiers() -> dict[str, StorageTier]:
    return {t.name: t for t in (
        StorageTier("hot", byte_cost=1.0, throughput=1.0),
        StorageTier("warm", byte_cost=0.6, throughput=0.6),
        StorageTier("cold", byte_cost=0.35, throughput=0.25),
    )}


#: The built-in tier schema (hot flash/disk, warm disk, cold archive).
DEFAULT_TIERS: dict[str, StorageTier] = _default_tiers()

_SPEC_RE = re.compile(
    r"^\s*(?:(?:replicate|rf)\((?P<rf>-?\d+)\)"
    r"|ec\((?P<k>-?\d+)\s*,\s*(?P<m>-?\d+)\))"
    r"\s*(?::(?P<tier>\w+))?(?::(?P<loc>region|spread))?\s*$")


@dataclass(frozen=True)
class Strategy:
    """One category's storage strategy (module-docstring arithmetic)."""

    kind: str = "replicate"   # "replicate" | "ec"
    rf: int = 1               # replicate only
    k: int = 1                # ec: data shards
    m: int = 0                # ec: parity shards
    tier: str = "hot"
    #: Placement locality on a geo-hierarchical topology: ``"spread"``
    #: (default) lets copies/shards cross top-level domains — the
    #: region-loss survival posture; ``"region"`` pins the whole file to
    #: its primary's top-level domain — zero WAN bytes for data whose
    #: durability target is satisfied in-region (stripes stay local; a
    #: WAN partition strands them rather than losing them).  Ignored by
    #: non-hierarchical topologies.
    locality: str = "spread"

    def __post_init__(self):
        if self.locality not in ("spread", "region"):
            raise ValueError(
                f"unknown strategy locality {self.locality!r} "
                f"(want 'spread' or 'region')")
        if self.kind not in ("replicate", "ec"):
            raise ValueError(
                f"unknown strategy kind {self.kind!r} (want 'replicate' "
                f"or 'ec')")
        if self.kind == "replicate" and self.rf < 1:
            raise ValueError(
                f"replicate rf must be >= 1, got {self.rf}")
        if self.kind == "ec":
            if self.k < 1:
                raise ValueError(f"ec k must be >= 1, got {self.k}")
            if self.m < 0:
                raise ValueError(f"ec m must be >= 0, got {self.m}")

    # -- the three integers everything consumes --------------------------
    @property
    def n_shards(self) -> int:
        """Distinct nodes the strategy occupies per file."""
        return self.rf if self.kind == "replicate" else self.k + self.m

    @property
    def min_live(self) -> int:
        """Live shards below which the file is LOST (cannot be read or
        reconstructed): 1 full copy, or k EC shards."""
        return 1 if self.kind == "replicate" else self.k

    @property
    def shard_div(self) -> int:
        """Per-shard bytes = ceil(size / shard_div)."""
        return 1 if self.kind == "replicate" else self.k

    @property
    def overhead(self) -> float:
        """Stored bytes / raw bytes at full strength (rf, or (k+m)/k)."""
        return float(self.n_shards) / float(self.shard_div)

    @property
    def repair_read_shards(self) -> int:
        """Shards read over the wire to rebuild ONE shard: a replicate
        repair copies one replica; an EC repair reconstructs from k."""
        return 1 if self.kind == "replicate" else self.k

    def spec(self) -> str:
        body = (f"replicate({self.rf})" if self.kind == "replicate"
                else f"ec({self.k},{self.m})")
        out = f"{body}:{self.tier}"
        if self.locality != "spread":
            out += f":{self.locality}"
        return out

    @classmethod
    def from_spec(cls, spec, tier: str | None = None) -> "Strategy":
        """Parse ``replicate(3)``, ``rf(3)``, ``ec(6,3)``, each with an
        optional ``:tier`` suffix; a bare int is ``replicate(n)``."""
        if isinstance(spec, Strategy):
            return spec
        if isinstance(spec, int):
            return cls(kind="replicate", rf=spec, tier=tier or "hot")
        if isinstance(spec, dict):
            d = dict(spec)
            kind = d.pop("kind", None)
            allowed = {"rf", "k", "m", "tier", "locality"}
            unknown = set(d) - allowed
            if unknown:
                raise ValueError(
                    f"unknown strategy keys {sorted(unknown)} in {spec!r}")
            if kind is None:
                kind = ("replicate" if "rf" in d
                        else "ec" if "k" in d else None)
            # A dict must size itself explicitly: a tier-only dict would
            # otherwise silently default to ec(1, 0) — ONE copy.
            if kind is None or (kind == "replicate" and "rf" not in d) \
                    or (kind == "ec" and "k" not in d):
                raise ValueError(
                    f"strategy dict {spec!r} needs 'rf' (replicate) or "
                    f"'k' + optional 'm' (ec)")
            if kind == "replicate" and ("k" in d or "m" in d):
                raise ValueError(
                    f"replicate strategy dict {spec!r} must not carry "
                    f"ec keys 'k'/'m'")
            if kind == "ec" and "rf" in d:
                raise ValueError(
                    f"ec strategy dict {spec!r} must not carry 'rf'")
            if tier is not None:
                d.setdefault("tier", tier)
            return cls(kind=kind,
                       **{k: (str(v) if k in ("tier", "locality")
                              else int(v))
                          for k, v in d.items()})
        m = _SPEC_RE.match(str(spec))
        if not m:
            raise ValueError(
                f"bad strategy spec {spec!r} (want 'replicate(3)', "
                f"'ec(6,3)', optionally ':tier' e.g. 'ec(6,3):cold')")
        t = m.group("tier")
        loc = m.group("loc")
        if loc is None and t in ("region", "spread"):
            # 'ec(2,1):region' omits the tier: the greedy tier group
            # must not swallow the locality keyword (those two words
            # are reserved — no tier may use them).
            t, loc = None, t
        t = t or tier or "hot"
        loc = loc or "spread"
        if m.group("rf") is not None:
            return cls(kind="replicate", rf=int(m.group("rf")), tier=t,
                       locality=loc)
        return cls(kind="ec", k=int(m.group("k")), m=int(m.group("m")),
                   tier=t, locality=loc)


@dataclass
class StrategyVectors:
    """Per-CATEGORY-index arrays of the strategy arithmetic, the form the
    controller, faults layer and serve router consume vectorized.  Index
    with a category vector (``vec[cat]``); files with ``cat == -1``
    (not yet planned) use the replicate defaults."""

    categories: tuple[str, ...]
    n_shards: np.ndarray      # (n_cat,) int32
    min_live: np.ndarray      # (n_cat,) int32
    shard_div: np.ndarray     # (n_cat,) int64
    ec_k: np.ndarray          # (n_cat,) int32 — k for ec, 0 for replicate
    tier_idx: np.ndarray      # (n_cat,) int32 into tier_names
    tier_names: tuple[str, ...]
    byte_cost: np.ndarray     # (n_cat,) float64 per stored byte
    read_penalty: np.ndarray  # (n_cat,) float64 = 1/tier.throughput
    #: (n_cat,) bool — category pins its files to the primary's
    #: top-level hierarchy domain (``locality: region``).
    region_local: np.ndarray = None
    #: Defaults for files with ``cat == -1`` (not yet planned): the
    #: config's default tier.
    default_tier_idx: int = 0
    default_byte_cost: float = 1.0
    default_read_penalty: float = 1.0

    def file_min_live(self, cat: np.ndarray) -> np.ndarray:
        """(n,) int32 min live shards per file (-1-cat files: 1)."""
        c = np.asarray(cat)
        return np.where(c >= 0, self.min_live[np.clip(c, 0, None)],
                        1).astype(np.int32)

    def file_shard_bytes(self, cat: np.ndarray,
                         sizes: np.ndarray) -> np.ndarray:
        """(n,) int64 per-shard bytes (``ceil(size / shard_div)``;
        -1-cat files: the full size — a replicate shard IS the file)."""
        c = np.asarray(cat)
        div = np.where(c >= 0, self.shard_div[np.clip(c, 0, None)], 1)
        return -(-np.asarray(sizes, dtype=np.int64) // div)

    def file_region_local(self, cat: np.ndarray) -> np.ndarray:
        """(n,) bool region-locality per file (-1-cat files: spread)."""
        c = np.asarray(cat)
        return np.where(c >= 0,
                        self.region_local[np.clip(c, 0, None)], False)

    def file_ec_k(self, cat: np.ndarray) -> np.ndarray:
        """(n,) int32 EC data-shard count per file (0 = replicate)."""
        c = np.asarray(cat)
        return np.where(c >= 0, self.ec_k[np.clip(c, 0, None)],
                        0).astype(np.int32)

    def file_n_shards(self, cat: np.ndarray,
                      default_rf: int = 1) -> np.ndarray:
        """(n,) int32 target shard count per file (the rf vector's
        generalization; -1-cat files keep ``default_rf``)."""
        c = np.asarray(cat)
        return np.where(c >= 0, self.n_shards[np.clip(c, 0, None)],
                        int(default_rf)).astype(np.int32)


@dataclass
class StorageConfig:
    """category -> Strategy mapping plus the tier schema.

    ``strategies`` may cover a subset of categories; missing categories
    fall back to ``replicate(scoring rf)`` on the ``default_tier`` when
    resolved (``vectors``/``resolve_storage_config``)."""

    strategies: dict[str, Strategy] = field(default_factory=dict)
    tiers: dict[str, StorageTier] = field(default_factory=_default_tiers)
    default_tier: str = "hot"

    def __post_init__(self):
        parsed = {}
        for c, s in self.strategies.items():
            try:
                parsed[c] = Strategy.from_spec(s)
            except ValueError as e:
                raise ValueError(
                    f"storage strategy for category {c!r}: {e}") from None
        self.strategies = parsed
        self.tiers = {n: (t if isinstance(t, StorageTier)
                          else StorageTier(name=n, **dict(t)))
                      for n, t in self.tiers.items()}
        if self.default_tier not in self.tiers:
            raise ValueError(
                f"default_tier {self.default_tier!r} is not a defined "
                f"tier {sorted(self.tiers)}")
        for c, s in self.strategies.items():
            if s.tier not in self.tiers:
                raise ValueError(
                    f"storage strategy for category {c!r} names unknown "
                    f"tier {s.tier!r} (defined: {sorted(self.tiers)})")

    @property
    def pure_replication(self) -> bool:
        """True when no category erasure-codes (the degenerate config)."""
        return all(s.kind == "replicate" for s in self.strategies.values())

    def strategy_for(self, category: str,
                     scoring_rf: int | None = None) -> Strategy:
        s = self.strategies.get(category)
        if s is not None:
            return s
        if scoring_rf is None:
            raise ValueError(
                f"no storage strategy for category {category!r} and no "
                f"scoring rf to fall back on")
        return Strategy(kind="replicate", rf=int(scoring_rf),
                        tier=self.default_tier)

    def vectors(self, categories, scoring_rf=None) -> StrategyVectors:
        """Resolve every category to its strategy arithmetic.

        ``scoring_rf`` (per-category rf mapping or vector) backs the
        replicate fallback for unmapped categories; categories in
        ``strategies`` that are not in ``categories`` are rejected — a
        typo'd category name must not silently become a no-op."""
        categories = tuple(categories)
        unknown = sorted(set(self.strategies) - set(categories))
        if unknown:
            raise ValueError(
                f"storage strategies name unknown categories {unknown} "
                f"(want a subset of {categories})")
        if scoring_rf is None:
            rf_by_cat = {}
        elif isinstance(scoring_rf, dict):
            rf_by_cat = scoring_rf
        else:
            rf_by_cat = dict(zip(categories, scoring_rf))
        resolved = [self.strategy_for(c, rf_by_cat.get(c))
                    for c in categories]
        tier_names = tuple(sorted(self.tiers))
        tidx = {t: i for i, t in enumerate(tier_names)}
        return StrategyVectors(
            categories=categories,
            n_shards=np.asarray([s.n_shards for s in resolved], np.int32),
            min_live=np.asarray([s.min_live for s in resolved], np.int32),
            shard_div=np.asarray([s.shard_div for s in resolved],
                                 np.int64),
            # ec(1, m) IS replication (a 1-shard "stripe" is a full
            # copy; reconstruction fan-in 1 is a plain copy), so it
            # normalizes to ec_k=0 — this is what makes the
            # ec(1, m) == replicate(m+1) identity exact end to end.
            ec_k=np.asarray([s.k if s.kind == "ec" and s.k > 1 else 0
                             for s in resolved], np.int32),
            tier_idx=np.asarray([tidx[s.tier] for s in resolved],
                                np.int32),
            tier_names=tier_names,
            region_local=np.asarray(
                [s.locality == "region" for s in resolved], bool),
            byte_cost=np.asarray([self.tiers[s.tier].byte_cost
                                  for s in resolved], np.float64),
            read_penalty=np.asarray(
                [1.0 / self.tiers[s.tier].throughput for s in resolved],
                np.float64),
            default_tier_idx=tidx[self.default_tier],
            default_byte_cost=self.tiers[self.default_tier].byte_cost,
            default_read_penalty=1.0
            / self.tiers[self.default_tier].throughput,
        )

    def describe(self, categories, scoring_rf=None) -> list[dict]:
        """Per-category resolution table (the ``cdrs storage show``
        payload): strategy, tier, overhead, loss threshold, repair read
        amplification."""
        rf_by_cat = (scoring_rf if isinstance(scoring_rf, dict)
                     else dict(zip(categories, scoring_rf))
                     if scoring_rf is not None else {})
        rows = []
        for c in categories:
            s = self.strategy_for(c, rf_by_cat.get(c))
            t = self.tiers[s.tier]
            rows.append({
                "category": c,
                "strategy": s.spec(),
                "kind": s.kind,
                "n_shards": s.n_shards,
                "min_live": s.min_live,
                "storage_overhead": round(s.overhead, 4),
                "tier": s.tier,
                "tier_byte_cost": t.byte_cost,
                "tier_throughput": t.throughput,
                "cost_per_raw_byte": round(s.overhead * t.byte_cost, 4),
                "repair_read_shards": s.repair_read_shards,
                "locality": s.locality,
            })
        return rows

    def to_dict(self) -> dict:
        return {
            "default_tier": self.default_tier,
            "tiers": {n: {"byte_cost": t.byte_cost,
                          "throughput": t.throughput}
                      for n, t in sorted(self.tiers.items())},
            "strategies": {c: s.spec()
                           for c, s in sorted(self.strategies.items())},
        }

    # -- constructors ----------------------------------------------------
    @classmethod
    def from_scoring(cls, scoring, tier: str = "hot") -> "StorageConfig":
        """The degenerate config: every category ``replicate(scoring
        rf)`` on one tier — bit-for-bit the historical behaviour."""
        return cls(strategies={
            c: Strategy(kind="replicate", rf=int(r), tier=tier)
            for c, r in scoring.replication_factors.items()},
            default_tier=tier)

    @classmethod
    def ec_archival(cls, scoring=None, k: int = 6, m: int = 3,
                    tier: str = "cold") -> "StorageConfig":
        """The production Archival shape: cold data erasure-codes down a
        tier (HDFS EC's RS(6,3) default), everything else replicates at
        its scoring rf on the hot tier."""
        strategies: dict[str, Strategy] = {
            "Archival": Strategy(kind="ec", k=k, m=m, tier=tier)}
        if scoring is not None:
            for c, r in scoring.replication_factors.items():
                if c != "Archival":
                    strategies[c] = Strategy(kind="replicate", rf=int(r))
        return cls(strategies=strategies)


def storage_config_from_dict(d) -> StorageConfig:
    """Build a StorageConfig from a plain dict (parsed JSON).

    Unknown keys are rejected — a typo'd table must not silently fall
    back to defaults (the scoring_config_from_dict contract)."""
    allowed = {"strategies", "tiers", "default_tier"}
    unknown = set(d) - allowed
    if unknown:
        raise ValueError(f"unknown storage config keys: {sorted(unknown)}")
    kwargs = dict(d)
    if "tiers" in kwargs:
        tiers = dict(_default_tiers())
        for name, spec in kwargs["tiers"].items():
            extra = set(spec) - {"byte_cost", "throughput"}
            if extra:
                raise ValueError(
                    f"unknown tier keys for {name!r}: {sorted(extra)}")
            tiers[name] = StorageTier(name=name, **spec)
        kwargs["tiers"] = tiers
    return StorageConfig(**kwargs)


def load_storage_config(path: str) -> StorageConfig:
    """Load a StorageConfig from a JSON file."""
    import json

    with open(path, encoding="utf-8") as f:
        return storage_config_from_dict(json.load(f))


def resolve_storage_config(spec: str | None, scoring) -> StorageConfig | None:
    """The CLI contract for ``--storage_config``: None passes through
    (no storage subsystem — historical behaviour), ``replicate`` is the
    explicit degenerate config, ``ec_archival`` the built-in EC preset,
    anything else a JSON file path."""
    if not spec:
        return None
    if spec == "replicate":
        return StorageConfig.from_scoring(scoring)
    if spec == "ec_archival":
        return StorageConfig.ec_archival(scoring)
    return load_storage_config(spec)
