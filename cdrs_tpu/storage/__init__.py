"""Tiered storage & erasure coding as first-class replication strategies.

Generalizes the paper's "category -> replication factor" into
"category -> strategy", where a strategy is ``replicate(rf)`` or
``ec(k, m)`` (k data + m parity shards on k+m distinct nodes), each on a
storage tier (hot/warm/cold) with per-tier byte cost and throughput
(ROADMAP item 4; HDFS Erasure Coding and Ceph CRUSH in PAPERS.md).

The arithmetic lives in ``strategy.py`` (n_shards / min_live /
shard_div per category); the consumers are spread across the stack:
``cluster.place_stripes`` (vectorized stripe placement),
``faults.ClusterState`` (shard-aware durability tiers + reconstruction
repair charging), ``control.ControllerConfig.storage`` (end-to-end
wiring with checkpointed strategy state), ``serve`` (degraded-read
penalty), ``cdrs storage`` (CLI) and ``benchmarks/storage_bench.py``
(the cost-vs-durability frontier).  A config with only ``replicate``
strategies degenerates bit-for-bit to the historical rf semantics.
"""

from .strategy import (
    DEFAULT_TIERS,
    StorageConfig,
    StorageTier,
    Strategy,
    StrategyVectors,
    load_storage_config,
    resolve_storage_config,
    storage_config_from_dict,
)

__all__ = [
    "DEFAULT_TIERS",
    "StorageConfig",
    "StorageTier",
    "Strategy",
    "StrategyVectors",
    "load_storage_config",
    "resolve_storage_config",
    "storage_config_from_dict",
]
