"""Declarative scenario cells: the matrix axes as one JSON-able spec.

A ``ScenarioSpec`` names one point in the robustness matrix — workload
curve x drift pattern x fault schedule x topology x storage strategy x
scale x serve config — and nothing else: no cell owns simulation or
controller code.  ONE harness (scenarios/harness.py) consumes every
spec, so a new axis value (a new drift pattern, a new fault template)
is instantly crossable with every other axis instead of waiting for a
bench author to hand-wire the combination.

Every field is a plain scalar/dict/list, so a spec round-trips through
JSON (``to_dict``/``from_dict``) — the repro contract: a failing sweep
cell prints one line that reruns exactly that cell.

Axes
----
* ``workload`` — the base traffic curve:
  ``{"kind": "poisson"}`` (the reference's homogeneous stream),
  ``{"kind": "diurnal", "amplitude": 0.8, "period_frac": 1.0,
  "phase": 0.0}`` (sinusoidal time-of-day intensity, total mass
  conserved — sim/access.simulate_diurnal), or
  ``{"kind": "flash_crowd", "start_frac": 0.5, "duration_frac": 0.1,
  "boost": 40.0, "cohort": "archival"}`` (transient read burst on a
  planted-category cohort — sim/access.simulate_flash_crowd).
* ``drift`` — how the category ground truth moves (poisson base only;
  sim/access.simulate_access_phased):
  ``{"kind": "flip", "at_frac": 0.5, "flip": {...}}`` (the classic
  one-step shift), ``{"kind": "gradual", "steps": 3, ...}`` (the
  cohort migrates in waves), or ``{"kind": "adversarial", "cycles": 3,
  ...}`` (the flip oscillates — the anti-flap hysteresis scenario).
* ``faults`` — any of ``specs`` (faults/schedule spec strings),
  ``template`` (``cascade`` / ``rolling_decommission`` with their
  parameters), and ``random`` (the seeded chaos generator), merged
  into one window-keyed FaultSchedule.
* ``racks`` — failure-domain topology (the ``cdrs chaos --racks``
  spec string); None = flat.
* ``storage`` — ``replicate`` / ``ec_archival`` / JSON path; None =
  historical rf semantics.
* ``serve`` — read-router config dict (policy/slo_ms/...); None = no
  serving.
* ``scrub`` — background-scrubber bytes/window; None = off.
* ``alerts`` — alerting expectations (obs/alerts.py default rules):
  ``{"expect": [...], "forbid": [...] | "others"}`` — expected alerts
  must fire, forbidden ones must stay silent (the alerting-regression
  axis); None = no alert gating.
* scale — ``n_files`` / ``duration`` / ``n_windows`` / ``k`` / ``mesh``
  (``{"data": N}`` runs the whole per-window device computation —
  cluster step, scoring medians, feature fold, drift one-Lloyd-step —
  data-parallel over an N-device mesh; requires ``backend: "jax"`` and,
  on CPU, ``XLA_FLAGS=--xla_force_host_platform_device_count=N``).

Controller knobs (budget fraction, scoring table, decay, thresholds)
ride along so a legacy bench scenario is exactly re-expressible: the
``control-shift`` and ``chaos-kill`` presets (scenarios/presets.py)
reproduce data/control_bench.json and data/chaos_bench.json
bit-identically on the same seeds.
"""

from __future__ import annotations

import copy
import dataclasses
from dataclasses import dataclass, field

__all__ = ["ScenarioSpec"]

_WORKLOAD_KINDS = ("poisson", "diurnal", "flash_crowd")
_DRIFT_KINDS = ("flip", "gradual", "adversarial")
_SCORINGS = ("default", "validated", "min_rf2")


@dataclass
class ScenarioSpec:
    """One matrix cell (see module docstring for the axes)."""

    name: str
    # -- scale -------------------------------------------------------------
    n_files: int = 300
    seed: int = 0
    duration: float = 1800.0
    n_windows: int = 15
    k: int = 12
    nodes: tuple[str, ...] = ("dn1", "dn2", "dn3", "dn4", "dn5")
    #: Device mesh for the per-window device computation
    #: (ControllerConfig.mesh_shape); requires ``backend == "jax"``.
    mesh: dict | None = None
    # -- axes --------------------------------------------------------------
    workload: dict = field(default_factory=lambda: {"kind": "poisson"})
    drift: dict | None = None
    faults: dict | None = None
    racks: str | None = None
    #: Geo-hierarchical topology spec (the ``ClusterTopology.
    #: from_hierarchy`` dict: levels, per-level group maps, optional
    #: edge_bytes/edge_latency multipliers).  Mutually exclusive with
    #: ``racks``; fault specs may then use domain scopes
    #: (``crash:region:eu@5-9``).
    topology: dict | None = None
    #: ``replicate`` / ``ec_archival`` / JSON path, or an inline
    #: StorageConfig dict (storage_config_from_dict — the form that can
    #: carry per-category ``locality`` rules).
    storage: str | dict | None = None
    serve: dict | None = None
    scrub: int | None = None
    #: Alerting expectations (obs/alerts.py, default ruleset):
    #: ``{"expect": [names...], "forbid": [names...] | "others"}`` — the
    #: named alerts must FIRE during the cell (``alerts_expected``
    #: invariant) and the forbidden ones must stay silent
    #: (``alerts_silent``); ``"forbid": "others"`` means any alert
    #: outside ``expect`` failing silent fails the cell.  None = no
    #: alert gating (e.g. random cells, whose transient fault storms
    #: legitimately trip loss alerts that heal by the end).
    alerts: dict | None = None
    #: Elastic capacity (control/elastic.ElasticPolicy dict: standby
    #: pool + hot/cool thresholds).  Requires ``serve`` (the telemetry
    #: source) and a hash ``placement`` mode (the epoch-diff rebalance).
    elastic: dict | None = None
    # -- controller knobs --------------------------------------------------
    #: Per-window churn budget as a fraction of the population's total
    #: bytes (None = unbounded) — repair + migration + scrub share it.
    budget_frac: float | None = 0.25
    max_files: int | None = None
    default_rf: int = 2
    scoring: str = "min_rf2"
    decay: float = 1.0
    drift_threshold: float = 0.05
    full_recluster_drift: float = 0.30
    hysteresis: int = 1
    backend: str = "numpy"
    #: Placement representation (ControllerConfig.placement_mode):
    #: "materialized" (historical), "functional" (CRUSH-style hash
    #: chooser + exception-overlay checkpoints + on-the-fly serve
    #: resolution), or "materialized_hash" (the equivalence oracle).
    placement: str = "materialized"
    #: Mid-cell kill/resume bit-identity check: kill after this window and
    #: resume from the checkpoint, asserting the stitched record stream
    #: equals the uninterrupted run's.  None = not sampled for this cell.
    resume_window: int | None = None
    #: Streaming-daemon axis: run the cell's event stream through the
    #: always-on controller daemon (daemon/core.StreamDaemon) tailing a
    #: binary event log, and gate the daemon invariants — decisions
    #: bit-identical to the windowed batch run, >= 2 placement epochs
    #: published (``daemon_engaged``), the pinned epoch frozen and equal
    #: to the admitted plan, and SIGTERM-path stop/checkpoint/resume
    #: stitching bit-identical to the uninterrupted daemon run.
    daemon: bool = False

    def __post_init__(self):
        kind = (self.workload or {}).get("kind", "poisson")
        if kind not in _WORKLOAD_KINDS:
            raise ValueError(
                f"cell {self.name!r}: unknown workload kind {kind!r} "
                f"(want one of {_WORKLOAD_KINDS})")
        if self.drift is not None:
            dk = self.drift.get("kind")
            if dk not in _DRIFT_KINDS:
                raise ValueError(
                    f"cell {self.name!r}: unknown drift kind {dk!r} "
                    f"(want one of {_DRIFT_KINDS})")
            if kind != "poisson":
                raise ValueError(
                    f"cell {self.name!r}: drift patterns compose with the "
                    f"poisson workload only (got workload {kind!r})")
        if self.scoring not in _SCORINGS:
            raise ValueError(
                f"cell {self.name!r}: unknown scoring {self.scoring!r} "
                f"(want one of {_SCORINGS})")
        if self.n_windows < 1:
            raise ValueError(
                f"cell {self.name!r}: n_windows must be >= 1")
        if self.budget_frac is not None and self.budget_frac <= 0:
            raise ValueError(
                f"cell {self.name!r}: budget_frac must be > 0 or None")
        if self.scrub is not None and self.faults is None:
            raise ValueError(
                f"cell {self.name!r}: scrub requires a faults axis (the "
                f"scrubber verifies the fault path's cluster state)")
        if self.placement not in ("materialized", "functional",
                                  "materialized_hash"):
            raise ValueError(
                f"cell {self.name!r}: unknown placement "
                f"{self.placement!r} (want 'materialized', 'functional' "
                f"or 'materialized_hash')")
        if self.topology is not None:
            if self.racks is not None:
                raise ValueError(
                    f"cell {self.name!r}: topology and racks are "
                    f"mutually exclusive (the hierarchy spec subsumes "
                    f"the rack map)")
            if not isinstance(self.topology, dict):
                raise ValueError(
                    f"cell {self.name!r}: topology must be a hierarchy "
                    f"spec dict (ClusterTopology.from_hierarchy)")
            from ..cluster.placement import ClusterTopology

            try:
                topo = ClusterTopology.from_hierarchy(self.topology)
            except ValueError as e:
                raise ValueError(
                    f"cell {self.name!r}: bad topology spec: {e}"
                ) from None
            if set(topo.nodes) != set(self.nodes):
                raise ValueError(
                    f"cell {self.name!r}: topology nodes "
                    f"{sorted(topo.nodes)} != cell nodes "
                    f"{sorted(self.nodes)}")
        if self.elastic is not None:
            if self.serve is None:
                raise ValueError(
                    f"cell {self.name!r}: elastic requires a serve axis "
                    f"(the SLO-burn/utilization telemetry that drives "
                    f"the scale decisions)")
            if self.placement == "materialized":
                raise ValueError(
                    f"cell {self.name!r}: elastic requires a hash "
                    f"placement mode ('functional'/'materialized_hash')"
                    f" — scale-out rebalances by epoch diff")
        if self.alerts is not None:
            from ..obs.alerts import DEFAULT_RULE_NAMES

            unknown_keys = set(self.alerts) - {"expect", "forbid"}
            if unknown_keys:
                raise ValueError(
                    f"cell {self.name!r}: unknown alerts keys "
                    f"{sorted(unknown_keys)} (want 'expect'/'forbid')")
            names = list(self.alerts.get("expect") or [])
            forbid = self.alerts.get("forbid")
            if forbid != "others":
                names += list(forbid or [])
            bad = sorted(set(names) - DEFAULT_RULE_NAMES)
            if bad:
                raise ValueError(
                    f"cell {self.name!r}: unknown alert names {bad} "
                    f"(known: {sorted(DEFAULT_RULE_NAMES)})")
        if self.mesh is not None:
            # Kept jax-import-free (specs parse anywhere): the full axis
            # validation re-runs in ControllerConfig/validate_mesh_shape.
            unknown = set(self.mesh) - {"data", "model"}
            if unknown:
                raise ValueError(
                    f"cell {self.name!r}: unknown mesh axis "
                    f"{sorted(unknown)} (want 'data'/'model')")
            if any(int(v) < 1 for v in self.mesh.values()):
                raise ValueError(
                    f"cell {self.name!r}: mesh axis sizes must be >= 1, "
                    f"got {self.mesh}")
            if self.backend != "jax":
                raise ValueError(
                    f"cell {self.name!r}: a mesh axis requires "
                    f"backend 'jax' (got {self.backend!r})")

    @property
    def window_seconds(self) -> float:
        return float(self.duration) / int(self.n_windows)

    # -- JSON round trip (the repro contract) ------------------------------
    def to_dict(self) -> dict:
        """Spec as plain JSON, omitting fields that equal their DEFAULT
        (not fields that are None: ``budget_frac=None`` means an
        unbounded budget and must survive the round trip — dropping
        Nones would silently rebuild a budgeted cell from a repro
        line).  ``from_dict`` refills omitted fields with the same
        defaults, so the round trip is exact for every field."""
        out: dict = {}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if f.name != "name":
                if f.default is not dataclasses.MISSING \
                        and v == f.default:
                    continue
                if f.default_factory is not dataclasses.MISSING \
                        and v == f.default_factory():
                    continue
            if f.name == "nodes":
                v = list(v)
            elif isinstance(v, dict):
                v = copy.deepcopy(v)  # never hand out live axis dicts
            out[f.name] = v
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "ScenarioSpec":
        allowed = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - allowed
        if unknown:
            raise ValueError(
                f"unknown scenario spec keys: {sorted(unknown)}")
        kwargs = dict(d)
        if "nodes" in kwargs:
            kwargs["nodes"] = tuple(kwargs["nodes"])
        return cls(**kwargs)

    def replace(self, **kw) -> "ScenarioSpec":
        return dataclasses.replace(self, **kw)
