"""Declarative scenario matrix: invariant-gated chaos sweeps.

The fault machinery grown in PRs 4-9 (crashes, partitions, stragglers,
silent rot) was only ever exercised in the exact combinations a bench
author thought of.  This package replaces that posture with a matrix: a
declarative spec (spec.py) over workload curve x drift pattern x fault
schedule x topology x storage strategy x scale x serve config, ONE
harness (harness.py) that runs any cell end to end and checks
invariants — zero silent loss, churn-budget conservation, placement
domain diversity, SLO bounds, sampled kill/resume bit-identity — and
named presets + seeded random cells (presets.py) swept by ``cdrs
scenarios sweep``.  On top of the matrix, search.py grows the cell set
itself: a seeded coverage-guided mutator (``cdrs scenarios search``)
that keeps mutants lighting up new coverage-fingerprint bits and
delta-debugs any invariant violation down to a minimal-event repro.

Why a matrix and not more hand-picked configs: CRUSH (Weil et al., SC
2006 — PAPERS.md) argues placement properties must hold across the
space of cluster maps, not at sampled points; and Yuan et al., "Simple
Testing Can Prevent Most Critical Failures" (OSDI 2014 — PAPERS.md)
found that the majority of catastrophic distributed-system failures
stem from error-handling code that was never exercised — systematic,
not incidental, coverage of the failure paths is exactly what the
invariant-gated sweep provides.  Every cell is seeded and every failing
cell prints a one-line repro command.
"""

from .harness import coverage_bits, run_cell
from .presets import PRESETS, SUITES, preset, random_cell, suite_cells
from .search import (
    distill_corpus,
    mutate_spec,
    run_search,
    shrink_cell,
)
from .spec import ScenarioSpec
from .sweep import run_sweep

__all__ = [
    "PRESETS",
    "SUITES",
    "ScenarioSpec",
    "coverage_bits",
    "distill_corpus",
    "mutate_spec",
    "preset",
    "random_cell",
    "run_cell",
    "run_search",
    "run_sweep",
    "shrink_cell",
    "suite_cells",
]
