"""Matrix sweep driver: run cells, gate on invariants, feed the history.

``run_sweep`` runs a suite's cells through the one harness and returns
the sweep artifact: per-cell invariant verdicts + headline metrics, the
flattened ``bench_records`` list ``cdrs metrics regress`` bands per
cell, and an ``ok`` flag the CLI turns into the exit code.  Failing
cells carry their one-line seeded repro command — the sweep output
alone is enough to rerun exactly the failing point of the matrix.

When ``history`` is given, each cell's records append to
``data/bench_history.jsonl`` through ``benchmarks/regress.append_history``
— append-only, deduplicated on (round, metric, platform), so re-running
a sweep (or CI re-running it) never double-appends rows.
"""

from __future__ import annotations

import json
import time

from .harness import run_cell
from .presets import suite_cells
from .spec import ScenarioSpec

__all__ = ["run_sweep", "format_cell_line", "load_extra_cells"]


def format_cell_line(cell: dict) -> str:
    """One human line per cell: verdict, name, failed invariants, repro."""
    inv = cell["invariants"]
    if cell["ok"]:
        checked = len(inv)
        return (f"  [ok  ] {cell['cell']:<22} {checked} invariants, "
                f"{cell['metrics']['windows']} windows, "
                f"{cell['seconds']:.1f}s")
    failed = sorted(k for k, v in inv.items() if not v)
    return (f"  [FAIL] {cell['cell']:<22} {','.join(failed)}\n"
            f"         repro: {cell['repro']}")


def load_extra_cells(paths) -> list[ScenarioSpec]:
    """Corpus cell files -> validated specs riding along with a suite.

    Each path is a ``{"cells": [spec dicts], "names": [...]}`` document
    — the exact shape ``distill_corpus`` (distilled.json) and
    ``triage_corpus`` (triage.json) emit — so the search's curated
    frontier and the regression-locked violation reruns plug into the
    CI sweep without a second driver.  Stored names are re-applied so
    regress/history keys stay stable (``search-*`` / ``triage-*``
    prefixes are reserved and can never alias a preset)."""
    specs: list[ScenarioSpec] = []
    for path in paths:
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except OSError as e:
            raise ValueError(
                f"cannot read extra-cells file {path}: "
                f"{e.strerror or e}") from e
        if not isinstance(doc, dict) or not isinstance(
                doc.get("cells"), list):
            raise ValueError(
                f"extra-cells file {path} must be a JSON object with a "
                f"'cells' list (distilled.json / triage.json shape)")
        names = doc.get("names") or []
        for i, c in enumerate(doc["cells"]):
            d = dict(c)
            if i < len(names):
                d["name"] = str(names[i])
            specs.append(ScenarioSpec.from_dict(d))
    return specs


def run_sweep(suite: str, *, seed: int = 0, round_no: int | None = None,
              history: str | None = None, extra=None,
              progress=None) -> dict:
    """Run every cell of ``suite`` (plus any ``extra`` corpus cell
    files — see :func:`load_extra_cells`); returns the sweep artifact
    dict.  Extra cells are PINNED repros: the suite seed shifts preset
    workloads but never touches them."""
    cells = list(suite_cells(suite, seed))
    if extra:
        cells += load_extra_cells(extra)
    return run_cells(cells, suite=suite, seed=seed, round_no=round_no,
                     history=history, progress=progress)


def run_cells(cells: list[ScenarioSpec], *, suite: str | None = None,
              seed: int = 0, round_no: int | None = None,
              history: str | None = None, progress=None) -> dict:
    # Validate the history combination BEFORE any cell runs: per-cell
    # baseline keys are defined at suite seed 0 (a shifted sweep
    # re-seeds every workload, so its records would alias them), and
    # failing after the multi-second sweep would discard every result.
    if history and round_no is not None and seed:
        raise ValueError(
            "history append (--round) is only valid at suite seed 0 "
            "— non-zero seeds shift every cell's workload, so their "
            "records would alias the seed-0 baseline keys")
    t0 = time.perf_counter()
    results = []
    for spec in cells:
        cell = run_cell(spec, suite=suite, suite_seed=seed)
        results.append(cell)
        if progress is not None:
            progress(format_cell_line(cell))
    ok = all(c["ok"] for c in results)
    bench_records = [r for c in results for r in c["bench_records"]]
    out = {
        "suite": suite,
        "seed": seed,
        "cells": results,
        "n_cells": len(results),
        "n_failed": sum(1 for c in results if not c["ok"]),
        "invariants_checked": sum(len(c["invariants"]) for c in results),
        "ok": ok,
        "seconds": round(time.perf_counter() - t0, 3),
        "bench_records": bench_records,
    }
    if round_no is not None:
        out["round"] = int(round_no)
    if history and round_no is not None:
        from ..benchmarks.regress import append_history, extract_records

        appended = append_history(
            history, extract_records(out, f"scenarios_{suite or 'cells'}"))
        out["history_appended"] = appended
    return out
